"""CTL7xx — trace-context propagation closure (ClusterTelemetry).

Cross-process tracing only works if every hop carries the trace
context forward: the objecter/AsyncObjecter chokepoints stamp
``(trace_id, span_id)`` into every request they send (``tctx`` in
the typed meta of MSG_REQ / MSG_REQ_SG frames), and daemons link
their stage spans under it.  The failure mode is SILENT: a wire send
or dispatch fan-out site that builds its own request dict and ships
it through a raw connection never propagates the context, the trace
simply has a hole where that hop's spans should be, and nobody
notices until a slow op's flame trace dead-ends mid-cluster — the
silent-trace-gap bug class (the v1 sweep found 11 real gaps: the
client's snapset/digest/recovery sends and every daemon peer_req).

  CTL701  a raw wire send (``<conn>.call({...})`` / ``_peer_req(n,
          {...})``) in cluster//client/ whose request names a
          DATA-PATH command but neither passed through
          ``tracer.stamp(...)`` nor carries a ``tctx`` key

CTLint v2 promotes the check to the whole-program graph; three send
shapes are covered:

  * the dict literal passed directly to the raw send (v1);
  * a dict literal bound to a LOCAL NAME first and sent later in the
    same function (``req = {...}; conn.call(req)``) — clean when the
    function stamps the name in between (``req = stamp(req)`` /
    ``req["tctx"] = ...``);
  * a dict literal handed to a WRAPPER function that forwards its
    parameter to a raw send (resolved through the import-aware call
    graph, wrapper-of-wrapper included) — the hop that v1 could not
    see because the send lives one module away.

Sends through the stamping chokepoints (``osd_call`` /
``call_async`` / ``aio_osd_call``) are exempt — AsyncObjecter.
call_async stamps centrally — as is any wrapper that itself stamps
(calls ``*.stamp(...)`` or assigns a ``tctx`` key) before sending.
Control traffic (maps, pings, boots, mon commands) is exempt: only
the tracked data-path commands carry op traces.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import astutil
from .core import Finding, ParsedModule, Rule

# the tracked wire data-path commands (cluster/daemon.py
# _TRACKED_CMDS): the ops whose traces an operator hunts
_DATA_CMDS = frozenset((
    "put_shard", "get_shard", "delete_shard", "setattr_shard",
    "getattr_shard", "stat_shard", "digest_shard", "copy_from",
    "put_object", "delete_object", "exec_cls"))

# raw send callables that do NOT stamp centrally; osd_call /
# call_async route through AsyncObjecter's stamping and are exempt
_RAW_SENDS = frozenset(("call", "_peer_req"))

# chokepoint names that must never be treated as gap wrappers even
# though their bodies forward to a raw send: they stamp centrally
# (call_async) or route through something that does (osd_call ->
# aio.call -> call_async)
_CHOKEPOINT_FNS = frozenset(("osd_call", "aio_osd_call",
                             "call_async", "mon_call"))

_SCOPE_DIRS = frozenset(("cluster", "client"))


def _in_scope(mod: ParsedModule) -> bool:
    parts = mod.relpath.replace("\\", "/").split("/")[:-1]
    return any(p in _SCOPE_DIRS for p in parts)


def _data_cmd_of(node: ast.AST):
    """The constant data-path command name of a dict-literal request,
    or None (non-dict, computed cmd, control command)."""
    if not isinstance(node, ast.Dict):
        return None
    cmd = None
    has_tctx = False
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and
                isinstance(k.value, str)):
            continue
        if k.value == "cmd" and isinstance(v, ast.Constant) and \
                isinstance(v.value, str):
            cmd = v.value
        elif k.value == "tctx":
            has_tctx = True
    if cmd in _DATA_CMDS and not has_tctx:
        return cmd
    return None


def _send_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _fn_stamps(fn: ast.AST) -> bool:
    """Does this function stamp a request itself?  True for a
    ``*.stamp(...)`` call or a ``x["tctx"] = ...`` assignment
    anywhere in the body — the AsyncObjecter.call_async shape."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = _send_name(node)
            if name == "stamp":
                return True
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.slice, ast.Constant) and \
                        tgt.slice.value == "tctx":
                    return True
    return False


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


class TraceGapRule(Rule):
    rule_id = "CTL701"
    name = "wire-send-without-trace-context"
    description = ("raw wire send / dispatch fan-out builds a "
                   "data-path request without propagating the active "
                   "trace context (the silent-trace-gap bug class): "
                   "wrap the request in tracer.stamp(...) or route "
                   "through the stamping chokepoints — checked over "
                   "the whole-program graph (wrapper sends included)")

    def __init__(self) -> None:
        super().__init__()
        self.mods: List[ParsedModule] = []

    # ------------------------------------------------------- wrappers --
    def _raw_wrappers(self, graph) -> Dict[ast.AST, Set[int]]:
        """fn -> positions of parameters forwarded (transitively) to
        a raw send.  A function that stamps internally, or bears a
        chokepoint name, is never a gap wrapper."""
        wrappers: Dict[ast.AST, Set[int]] = {}
        candidates = []
        for fn, mod in ((f, graph.mod_of[f]) for f in graph.mod_of):
            if mod.evidence or not _in_scope(mod):
                continue
            if fn.name in _CHOKEPOINT_FNS or \
                    not isinstance(fn, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                continue
            if _fn_stamps(fn):
                continue
            candidates.append(fn)
        changed = True
        while changed:
            changed = False
            for fn in candidates:
                mod = graph.mod_of[fn]
                cls = graph.cls_of[fn]
                params = _param_names(fn)
                fwd: Set[int] = set(wrappers.get(fn, set()))
                for call in ast.walk(fn):
                    if not isinstance(call, ast.Call):
                        continue
                    positions: Set[int] = set()
                    name = _send_name(call)
                    if name in _RAW_SENDS:
                        positions = set(range(len(call.args)))
                    else:
                        for tgt in graph.resolve_call(mod, cls, call):
                            for i in wrappers.get(tgt, ()):
                                # account for the bound self arg of
                                # method calls: wrapper param i is
                                # caller arg i-1 when the target is a
                                # method invoked via attribute access
                                off = 1 if (graph.cls_of[tgt] and
                                            isinstance(call.func,
                                                       ast.Attribute)
                                            ) else 0
                                positions.add(i - off)
                    for pos in positions:
                        if not 0 <= pos < len(call.args):
                            continue
                        a = call.args[pos]
                        if isinstance(a, ast.Name) and \
                                a.id in params:
                            idx = params.index(a.id)
                            if idx not in fwd:
                                fwd.add(idx)
                                changed = True
                if fwd:
                    wrappers[fn] = fwd
        return wrappers

    # ------------------------------------------------------ collection --
    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if not mod.evidence and _in_scope(mod):
            self.mods.append(mod)
        return ()

    def finish(self) -> Iterable[Finding]:
        graph = astutil.program_graph(self.program)
        wrappers = self._raw_wrappers(graph)
        out: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()

        def emit(mod: ParsedModule, node: ast.AST, cmd: str,
                 how: str) -> None:
            if (mod.relpath, node.lineno) in seen:
                return
            seen.add((mod.relpath, node.lineno))
            out.append(self.finding(
                mod, node.lineno,
                f"data-path request {cmd!r} {how} without trace "
                f"propagation — wrap it in tracer.stamp(...) (or "
                f"carry 'tctx') so the receiving daemon's spans "
                f"link into the op's trace instead of leaving a "
                f"silent gap"))

        for mod in self.mods:
            for fn, cls in astutil.walk_functions(mod.tree):
                # local names bound to an unstamped data-cmd dict,
                # minus names the function later stamps
                bound: Dict[str, Tuple[ast.AST, str]] = {}
                stamped: Set[str] = set()
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign) and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0], ast.Name):
                        cmd = _data_cmd_of(node.value)
                        if cmd is not None:
                            bound[node.targets[0].id] = \
                                (node.value, cmd)
                        elif isinstance(node.value, ast.Call):
                            # req = stamp(req) / req = dict(req, ...)
                            stamped.add(node.targets[0].id)
                    elif isinstance(node, ast.Assign):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Subscript) and \
                                    isinstance(tgt.value, ast.Name) \
                                    and isinstance(tgt.slice,
                                                   ast.Constant) \
                                    and tgt.slice.value == "tctx":
                                stamped.add(tgt.value.id)
                    elif isinstance(node, ast.Call) and \
                            _send_name(node) == "stamp":
                        for a in node.args:
                            if isinstance(a, ast.Name):
                                stamped.add(a.id)
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    name = _send_name(node)
                    if name in _RAW_SENDS:
                        for arg in node.args:
                            cmd = _data_cmd_of(arg)
                            if cmd is not None:
                                emit(mod, arg, cmd,
                                     "sent over a raw connection")
                            elif isinstance(arg, ast.Name) and \
                                    arg.id in bound and \
                                    arg.id not in stamped:
                                emit(mod, node,
                                     bound[arg.id][1],
                                     "sent over a raw connection")
                        continue
                    # wrapper send: the dict rides a parameter that
                    # the callee (possibly in another module)
                    # forwards to a raw send
                    for tgt in graph.resolve_call(mod, cls, node):
                        fwd = wrappers.get(tgt)
                        if not fwd:
                            continue
                        off = 1 if (graph.cls_of[tgt] and
                                    isinstance(node.func,
                                               ast.Attribute)) else 0
                        for i in fwd:
                            pos = i - off
                            if not 0 <= pos < len(node.args):
                                continue
                            arg = node.args[pos]
                            cmd = _data_cmd_of(arg)
                            if cmd is not None:
                                emit(mod, arg, cmd,
                                     f"handed to raw-send wrapper "
                                     f"{tgt.name!r}")
        return out


_PERF_FACTORIES = frozenset(("perf", "_perf"))
_PC_MUTATORS = frozenset(("inc", "set", "tinc", "hinc"))
_HISTORY_MODULE = "mgr/metrics_history.py"


def _perf_group_of(node: ast.AST) -> Optional[str]:
    """The constant group name when ``node`` is ``perf("g")`` /
    ``_perf("g")``, else None."""
    if isinstance(node, ast.Call) and \
            _send_name(node) in _PERF_FACTORIES and node.args and \
            isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


class RateCounterMonotonicRule(Rule):
    rule_id = "CTL702"
    name = "rate-counter-not-monotonic"
    description = ("a perf counter the metrics-history rate layer "
                   "queries (mgr/metrics_history.py RATE_COUNTERS) "
                   "must be MONOTONIC at its declaration site: only "
                   "``.inc()`` may ever touch it — a ``.set()`` "
                   "retype feeds a gauge into the delta pipeline and "
                   "every derived rate is silently garbage; each "
                   "listed counter also needs at least one inc site "
                   "(the declaration), or the history ring records "
                   "nothing")

    # ----------------------------------------------------- contract --
    def _rate_pairs(self) -> Tuple[List[Tuple[str, str]],
                                   Optional[ParsedModule], int]:
        """The (group, key) pairs of the RATE_COUNTERS literal in
        mgr/metrics_history.py, plus the module and the literal's
        line (anchor for missing-inc findings)."""
        for mod in self.program.modules.values():
            if not mod.relpath.replace("\\", "/") \
                    .endswith(_HISTORY_MODULE):
                continue
            for node in ast.walk(mod.tree):
                if not (isinstance(node, ast.Assign) and
                        len(node.targets) == 1 and
                        isinstance(node.targets[0], ast.Name) and
                        node.targets[0].id == "RATE_COUNTERS"):
                    continue
                pairs: List[Tuple[str, str]] = []
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    for el in node.value.elts:
                        if isinstance(el, (ast.Tuple, ast.List)) and \
                                len(el.elts) == 2 and all(
                                    isinstance(c, ast.Constant) and
                                    isinstance(c.value, str)
                                    for c in el.elts):
                            pairs.append((el.elts[0].value,
                                          el.elts[1].value))
                return pairs, mod, node.lineno
        return [], None, 0

    # ------------------------------------------------------- bindings --
    @staticmethod
    def _attr_groups(mods: Iterable[ParsedModule]) -> Dict[str, str]:
        """``self.X = _perf("g")`` sites across the tree: attribute
        name -> group (the class-attr receiver shape, e.g. daemon.py
        ``self._pc_io = _perf("osd.io")``)."""
        out: Dict[str, str] = {}
        for mod in mods:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Attribute):
                    g = _perf_group_of(node.value)
                    if g is not None:
                        out[node.targets[0].attr] = g
        return out

    def finish(self) -> Iterable[Finding]:
        pairs, hist_mod, decl_line = self._rate_pairs()
        if not pairs:
            return ()
        rate_set = set(pairs)
        mods = [m for m in self.program.lint_modules()]
        attr_groups = self._attr_groups(mods)
        inc_seen: Set[Tuple[str, str]] = set()
        out: List[Finding] = []
        for mod in mods:
            for fn, _cls in astutil.walk_functions(mod.tree):
                local: Dict[str, str] = {}
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign) and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0], ast.Name):
                        g = _perf_group_of(node.value)
                        if g is not None:
                            local[node.targets[0].id] = g
                for node in ast.walk(fn):
                    if not (isinstance(node, ast.Call) and
                            isinstance(node.func, ast.Attribute) and
                            node.func.attr in _PC_MUTATORS and
                            node.args and
                            isinstance(node.args[0], ast.Constant) and
                            isinstance(node.args[0].value, str)):
                        continue
                    recv = node.func.value
                    group = _perf_group_of(recv)
                    if group is None and isinstance(recv, ast.Name):
                        group = local.get(recv.id)
                    if group is None and \
                            isinstance(recv, ast.Attribute):
                        group = attr_groups.get(recv.attr)
                    if group is None:
                        continue
                    key = node.args[0].value
                    if (group, key) not in rate_set:
                        continue
                    if node.func.attr == "inc":
                        inc_seen.add((group, key))
                    else:
                        out.append(self.finding(
                            mod, node.lineno,
                            f"history rate counter "
                            f"{group}.{key} updated via "
                            f".{node.func.attr}() — RATE_COUNTERS "
                            f"entries must be monotonic (inc-only); "
                            f"a gauge in the delta pipeline yields "
                            f"garbage rates silently"))
        if hist_mod is not None:
            for group, key in pairs:
                if (group, key) not in inc_seen:
                    out.append(self.finding(
                        hist_mod, decl_line,
                        f"RATE_COUNTERS lists {group}.{key} but no "
                        f".inc() declaration site exists in the "
                        f"tree — the history/rate layer would query "
                        f"a counter nothing increments"))
        return out


def register(reg) -> None:
    reg.add("CTL701", TraceGapRule)
    reg.add("CTL702", RateCounterMonotonicRule)
