"""CTL7xx — trace-context propagation closure (ClusterTelemetry).

Cross-process tracing only works if every hop carries the trace
context forward: the objecter/AsyncObjecter chokepoints stamp
``(trace_id, span_id)`` into every request they send (``tctx`` in
the typed meta of MSG_REQ / MSG_REQ_SG frames), and daemons link
their stage spans under it.  The failure mode is SILENT: a wire send
or dispatch fan-out site that builds its own request dict and ships
it through a raw connection never propagates the context, the trace
simply has a hole where that hop's spans should be, and nobody
notices until a slow op's flame trace dead-ends mid-cluster — the
silent-trace-gap bug class (this sweep found 11 real gaps: the
client's snapset/digest/recovery sends and every daemon peer_req).

  CTL701  a raw wire send (``<conn>.call({...})`` / ``_peer_req(n,
          {...})``) in cluster//client/ whose dict-literal request
          names a DATA-PATH command but neither passed through
          ``tracer.stamp(...)`` nor carries a ``tctx`` key

Sends through the stamping chokepoints (``osd_call`` /
``call_async`` / ``aio_osd_call``) are exempt — AsyncObjecter.
call_async stamps centrally.  Control traffic (maps, pings, boots,
mon commands) is exempt: only the tracked data-path commands carry
op traces.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from .core import Finding, ParsedModule, Rule

# the tracked wire data-path commands (cluster/daemon.py
# _TRACKED_CMDS): the ops whose traces an operator hunts
_DATA_CMDS = frozenset((
    "put_shard", "get_shard", "delete_shard", "setattr_shard",
    "getattr_shard", "stat_shard", "digest_shard", "copy_from",
    "put_object", "delete_object", "exec_cls"))

# raw send callables that do NOT stamp centrally; osd_call /
# call_async route through AsyncObjecter's stamping and are exempt
_RAW_SENDS = frozenset(("call", "_peer_req"))

_SCOPE_DIRS = frozenset(("cluster", "client"))


def _data_cmd_of(node: ast.AST):
    """The constant data-path command name of a dict-literal request,
    or None (non-dict, computed cmd, control command)."""
    if not isinstance(node, ast.Dict):
        return None
    cmd = None
    has_tctx = False
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and
                isinstance(k.value, str)):
            continue
        if k.value == "cmd" and isinstance(v, ast.Constant) and \
                isinstance(v.value, str):
            cmd = v.value
        elif k.value == "tctx":
            has_tctx = True
    if cmd in _DATA_CMDS and not has_tctx:
        return cmd
    return None


class TraceGapRule(Rule):
    rule_id = "CTL701"
    name = "wire-send-without-trace-context"
    description = ("raw wire send / dispatch fan-out builds a "
                   "data-path request without propagating the active "
                   "trace context (the silent-trace-gap bug class): "
                   "wrap the request in tracer.stamp(...) or route "
                   "through the stamping chokepoints")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        parts = mod.relpath.replace("\\", "/").split("/")[:-1]
        if not any(p in _SCOPE_DIRS for p in parts):
            return ()
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute):
                name = fn.attr
            elif isinstance(fn, ast.Name):
                name = fn.id
            else:
                continue
            if name not in _RAW_SENDS:
                continue
            for arg in node.args:
                cmd = _data_cmd_of(arg)
                if cmd is None:
                    continue
                # a stamp(...)-wrapped dict is not a direct arg of
                # the send, so reaching here means the context was
                # dropped on the floor
                out.append(self.finding(
                    mod, arg.lineno,
                    f"data-path request {cmd!r} sent over a raw "
                    f"connection without trace propagation — wrap "
                    f"it in tracer.stamp(...) (or carry 'tctx') so "
                    f"the receiving daemon's spans link into the "
                    f"op's trace instead of leaving a silent gap"))
        return out


def register(reg) -> None:
    reg.add("CTL701", TraceGapRule)
