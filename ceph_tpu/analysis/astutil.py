"""Shared AST analyses: import-alias-aware name resolution, the
whole-program interprocedural call graph, and the jit-reachability
("hot") set the CTL1xx/CTL6xx rules key off.

Two resolution tiers coexist (CTLint v2):

  * **Precise, cross-module** — ``ProgramGraph`` resolves
    ``from x import f`` / ``import pkg.mod as m`` (absolute AND
    relative forms), ``self._method`` against the enclosing class,
    and ``module.func`` attribute calls against the imported module's
    top-level functions, across every file of the run.  Built once
    per run and cached on the ``Program``, shared by all rules.
  * **Module-local, name-based fallback** — when resolution is
    ambiguous (an attribute call on an arbitrary object,
    ``dt.bucket_row(...)``), the graph falls back to today's idiom:
    every same-module function NAMED ``bucket_row`` is a candidate
    callee.  Cheap, deterministic, and right for this codebase's
    helpers-next-to-entry-points layout — and it means the widened
    graph can only ADD reachability, never silently lose it.

A module parsed outside a run (no ``Program``) keeps the pure
module-local behavior.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

# canonical (post-alias) spellings
JIT_NAMES = {"jax.jit", "jax.pjit"}
# combinators whose function arguments are traced (treated as hot but
# NOT as directly-jitted roots: their params may be static Python)
TRACE_COMBINATORS = {
    "jax.vmap", "jax.pmap", "jax.checkpoint", "jax.remat",
    "jax.grad", "jax.value_and_grad",
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.fori_loop",
    "jax.lax.switch", "jax.lax.cond", "jax.lax.map",
    "jax.lax.associative_scan",
}
PARTIAL_NAMES = {"functools.partial", "partial"}
# shard_map wraps its FIRST argument as a per-device traced body; the
# remaining arguments are mesh/spec pytrees, never callables — so only
# args[0] joins the hot set (treated like a combinator target, not a
# directly-jitted root: the body's params are per-shard views)
SHARD_MAP_NAMES = {
    "jax.experimental.shard_map.shard_map",
    "jax.experimental.shard_map",
    "jax.shard_map",
}


def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """local name -> canonical dotted prefix, e.g. {'_jax': 'jax',
    'jnp': 'jax.numpy', 'lax': 'jax.lax', 'np': 'numpy',
    'jit': 'jax.jit'}."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Alias-normalized dotted name ('_jax.jit' -> 'jax.jit')."""
    d = dotted(node)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    head = aliases.get(head, head)
    return f"{head}.{rest}" if rest else head


def is_jit_expr(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """True for ``jax.jit`` / ``functools.partial(jax.jit, ...)``."""
    if resolve(node, aliases) in JIT_NAMES:
        return True
    if isinstance(node, ast.Call) and \
            resolve(node.func, aliases) in PARTIAL_NAMES and node.args:
        return resolve(node.args[0], aliases) in JIT_NAMES
    return False


def _tail(name: str) -> str:
    return name.rsplit(".", 1)[-1]


class HotInfo:
    """jit-reachability result for one module.

    ``hot``     — FunctionDefs traced under jit (roots + combinator
                  targets + everything they reach in-module).
    ``direct``  — FunctionDefs whose PARAMETERS are traced values
                  (jit-decorated / jax.jit(f) targets), mapped to the
                  set of their statically-marked parameter names (None
                  when the static spec could not be resolved).
    """

    def __init__(self) -> None:
        self.hot: Set[ast.AST] = set()
        self.direct: Dict[ast.AST, Optional[Set[str]]] = {}


def _static_params(fn: ast.AST, spec: ast.Call) -> Optional[Set[str]]:
    """Parameter names marked static by a jit call/partial ``spec``;
    None when not statically resolvable (conservative: skip checks)."""
    names: Set[str] = set()
    args = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in spec.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) \
                else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, str):
                    names.add(e.value)
                else:
                    return None
        elif kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) \
                else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, int) and \
                        0 <= e.value < len(args):
                    names.add(args[e.value])
                else:
                    return None
    return names


def aliases_of(mod) -> Dict[str, str]:
    """Per-module ``import_aliases``, computed once and cached —
    every rule shares one pass instead of re-walking the imports."""
    cached = mod._cache.get("aliases")
    if cached is None:
        cached = mod._cache["aliases"] = import_aliases(mod.tree)
    return cached


def module_dotted(relpath: str) -> str:
    """'ceph_tpu/cluster/daemon.py' -> 'ceph_tpu.cluster.daemon';
    a package ``__init__.py`` maps to the package itself."""
    parts = relpath.rsplit(".", 1)[0].split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def program_aliases_of(mod) -> Dict[str, str]:
    """local name -> ABSOLUTE dotted import target, with relative
    imports (``from .objectstore import T`` / ``from ..common import
    tracer as _trace``) anchored at the module's package path.  The
    cross-module half of name resolution; ``import_aliases`` stays
    the canonical-spelling half (jax/np normalization)."""
    cached = mod._cache.get("prog_aliases")
    if cached is not None:
        return cached
    mparts = [p for p in module_dotted(mod.relpath).split(".") if p]
    is_pkg = mod.relpath.endswith("__init__.py")
    pkg = mparts if is_pkg else mparts[:-1]
    out: Dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                drop = node.level - 1
                if drop > len(pkg):
                    continue                  # beyond the lint root
                anchor = pkg[:len(pkg) - drop] if drop else pkg
                base = ".".join(anchor + ([node.module]
                                          if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = \
                    f"{base}.{a.name}" if base else a.name
    mod._cache["prog_aliases"] = out
    return out


def _partial_aliases(mod) -> Dict[str, str]:
    """name -> callee name for ``g = functools.partial(f, ...)``."""
    cached = mod._cache.get("partial_aliases")
    if cached is not None:
        return cached
    aliases = aliases_of(mod)
    out: Dict[str, str] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and resolve(node.value.func, aliases) in PARTIAL_NAMES \
                and node.value.args:
            base = dotted(node.value.args[0])
            if base:
                out[node.targets[0].id] = _tail(base)
    mod._cache["partial_aliases"] = out
    return out


class ProgramGraph:
    """The whole-tree, import-resolving call graph (CTLint v2).

    Resolution order for a call / function reference in module M,
    enclosing class C:

      1. ``self.x`` / ``cls.x``     -> method ``x`` of C in M (precise);
                                       no such method: module-local
                                       name fallback
      2. bare ``f``                 -> function named ``f`` in M, else
                                       the ``from x import f`` target's
                                       top-level ``f`` (cross-module)
      3. ``m.f`` / ``pkg.m.f``      -> top-level ``f`` of the imported
                                       in-tree module ``m`` (precise;
                                       a resolved module WITHOUT such a
                                       function is a miss, not a
                                       fallback — it is a class or
                                       dynamic attribute)
      4. anything else (``obj.f``)  -> module-local name fallback:
                                       every function in M named ``f``

    ``functools.partial`` rebindings resolve through their base
    callable first.  Evidence modules participate in the indexes (so
    --graph can answer questions about them) but never in the hot
    set.
    """

    def __init__(self, program) -> None:
        self.program = program
        self.mod_of: Dict[ast.AST, object] = {}
        self.cls_of: Dict[ast.AST, Optional[str]] = {}
        # (relpath, name) -> fns; (relpath, cls, name) -> methods;
        # (dotted module, name) -> top-level fns
        self.local: Dict[Tuple[str, str], List[ast.AST]] = {}
        self.methods: Dict[Tuple[str, Optional[str], str],
                           List[ast.AST]] = {}
        self.top: Dict[Tuple[str, str], List[ast.AST]] = {}
        self.mod_dotted: Dict[str, object] = {}
        self._edges: Dict[ast.AST, Set[ast.AST]] = {}
        self._redges: Optional[Dict[ast.AST, Set[ast.AST]]] = None
        for mod in program.modules.values():
            dn = module_dotted(mod.relpath)
            self.mod_dotted.setdefault(dn, mod)
            for fn, cls in walk_functions(mod.tree):
                self.mod_of[fn] = mod
                self.cls_of[fn] = cls
                self.local.setdefault((mod.relpath, fn.name),
                                      []).append(fn)
                self.methods.setdefault((mod.relpath, cls, fn.name),
                                        []).append(fn)
                if cls is None:
                    self.top.setdefault((dn, fn.name), []).append(fn)

    # --------------------------------------------------------- naming --
    def qualname(self, fn: ast.AST) -> str:
        mod = self.mod_of[fn]
        cls = self.cls_of[fn]
        dn = module_dotted(mod.relpath)
        mid = f"{cls}." if cls else ""
        return f"{dn}.{mid}{fn.name}"

    def find(self, pattern: str) -> List[ast.AST]:
        """Functions matching a dotted pattern: the last part names
        the function, the rest must appear in the qualified name in
        order — so 'daemon._recover_pg' matches
        'ceph_tpu.cluster.daemon.OSDDaemon._recover_pg' without the
        caller knowing the class."""
        pat = pattern.split(".")
        out = []
        for fn in self.mod_of:
            q = self.qualname(fn).split(".")
            if q[-1] != pat[-1]:
                continue
            i = 0
            for part in q[:-1]:
                if i < len(pat) - 1 and part == pat[i]:
                    i += 1
            if i == len(pat) - 1:
                out.append(fn)
        return sorted(out, key=self.qualname)

    # ----------------------------------------------------- resolution --
    def resolve_call(self, mod, cls: Optional[str], call: ast.Call,
                     precise: bool = False) -> List[ast.AST]:
        """Callee candidates.  ``precise=True`` drops the ambiguous
        module-local name fallback (an attribute call on an arbitrary
        object resolves to NOTHING instead of every same-named local
        function) — for traversals where an over-approximate edge is
        worse than a missed one."""
        d = dotted(call.func)
        if d is None:
            return []
        return self._resolve(mod, cls, d, precise)

    def resolve_ref(self, mod, cls: Optional[str],
                    node: ast.AST) -> List[ast.AST]:
        """A function-OBJECT reference (jit(f) argument, ``cb=f``
        registration) rather than a call."""
        d = dotted(node)
        if d is None:
            return []
        return self._resolve(mod, cls, d, False)

    def _resolve(self, mod, cls: Optional[str], d: str,
                 precise: bool) -> List[ast.AST]:
        parts = d.split(".")
        pa = _partial_aliases(mod)
        if len(parts) == 2 and parts[0] in ("self", "cls"):
            name = pa.get(parts[1], parts[1])
            if cls is not None:
                hit = self.methods.get((mod.relpath, cls, name))
                if hit:
                    return list(hit)
            if precise:
                return []
            return list(self.local.get((mod.relpath, name), ()))
        if len(parts) == 1:
            name = pa.get(parts[0], parts[0])
            hit = self.local.get((mod.relpath, name))
            if hit:
                return list(hit)
            tgt = program_aliases_of(mod).get(name)
            if tgt and "." in tgt:
                mn, _, fname = tgt.rpartition(".")
                if mn in self.mod_dotted:
                    return list(self.top.get((mn, fname), ()))
            return []
        head = program_aliases_of(mod).get(parts[0])
        if head:
            mn = ".".join([head] + parts[1:-1])
            if mn in self.mod_dotted:
                return list(self.top.get((mn, parts[-1]), ()))
        if precise:
            return []
        name = pa.get(parts[-1], parts[-1])
        return list(self.local.get((mod.relpath, name), ()))

    # ----------------------------------------------------------- edges --
    def callees(self, fn: ast.AST) -> Set[ast.AST]:
        """Resolved direct callees of ``fn`` (cached)."""
        cached = self._edges.get(fn)
        if cached is not None:
            return cached
        mod = self.mod_of[fn]
        cls = self.cls_of[fn]
        out: Set[ast.AST] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                out.update(self.resolve_call(mod, cls, node))
        out.discard(fn)
        self._edges[fn] = out
        return out

    def callers_of(self, fn: ast.AST) -> Set[ast.AST]:
        if self._redges is None:
            redges: Dict[ast.AST, Set[ast.AST]] = {}
            for f in self.mod_of:
                for tgt in self.callees(f):
                    redges.setdefault(tgt, set()).add(f)
            self._redges = redges
        return self._redges.get(fn, set())

    def reachable(self, roots, forward: bool = True) -> Set[ast.AST]:
        """Transitive closure from ``roots`` (roots excluded unless
        re-reached) over resolved call edges."""
        step = self.callees if forward else self.callers_of
        seen: Set[ast.AST] = set()
        work = list(roots)
        while work:
            for nxt in step(work.pop()):
                if nxt not in seen:
                    seen.add(nxt)
                    work.append(nxt)
        return seen


def program_graph(program) -> ProgramGraph:
    """The per-run shared graph (built once, cached on Program)."""
    g = program._cache.get("graph")
    if g is None:
        g = program._cache["graph"] = ProgramGraph(program)
    return g


def _program_hot(program) -> HotInfo:
    """Whole-program jit-reachable set: roots collected from every
    lint module (jit decorators, jit/combinator call forms — the
    argument may be imported from another module), then propagated
    to a fixed point over the resolved cross-module call graph.
    Evidence modules contribute neither roots nor members."""
    cached = program._cache.get("hot")
    if cached is not None:
        return cached
    g = program_graph(program)
    info = HotInfo()

    def mark_direct(fn: ast.AST, spec: Optional[ast.Call]) -> None:
        info.hot.add(fn)
        statics = _static_params(fn, spec) if spec is not None \
            else set()
        info.direct.setdefault(fn, statics)

    for mod in program.modules.values():
        if mod.evidence:
            continue
        aliases = aliases_of(mod)
        for fn, _cls in walk_functions(mod.tree):
            for dec in fn.decorator_list:
                if is_jit_expr(dec, aliases):
                    spec = dec if isinstance(dec, ast.Call) else None
                    mark_direct(fn, spec)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = resolve(node.func, aliases)
            if cn in JIT_NAMES:
                for a in node.args[:1]:
                    for fn in g.resolve_ref(mod, None, a):
                        if not g.mod_of[fn].evidence:
                            mark_direct(fn, node)
            elif cn in TRACE_COMBINATORS:
                for a in node.args:
                    for fn in g.resolve_ref(mod, None, a):
                        if not g.mod_of[fn].evidence:
                            info.hot.add(fn)
            elif cn in SHARD_MAP_NAMES:
                for a in node.args[:1]:
                    for fn in g.resolve_ref(mod, None, a):
                        if not g.mod_of[fn].evidence:
                            info.hot.add(fn)

    work = list(info.hot)
    while work:
        fn = work.pop()
        for tgt in g.callees(fn):
            if tgt not in info.hot and not g.mod_of[tgt].evidence:
                info.hot.add(tgt)
                work.append(tgt)
    program._cache["hot"] = info
    return info


def hot_functions(mod) -> HotInfo:
    """The jit-reachable set, per module (cached).  Inside a run the
    module belongs to a Program and the set is the PER-MODULE SLICE
    of the whole-program reachability closure; a standalone module
    keeps the original module-local computation."""
    cached = mod._cache.get("hot")
    if cached is not None:
        return cached
    program = getattr(mod, "program", None)
    if program is not None:
        g = program_graph(program)
        ph = _program_hot(program)
        info = HotInfo()
        info.hot = {fn for fn in ph.hot if g.mod_of.get(fn) is mod}
        info.direct = {fn: s for fn, s in ph.direct.items()
                       if g.mod_of.get(fn) is mod}
        mod._cache["hot"] = info
        return info
    tree = mod.tree
    aliases = import_aliases(tree)
    info = HotInfo()

    funcs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, []).append(node)

    # name -> callee name, for `g = functools.partial(f, ...)`
    partial_alias: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and resolve(node.value.func, aliases) in PARTIAL_NAMES \
                and node.value.args:
            base = dotted(node.value.args[0])
            if base:
                partial_alias[node.targets[0].id] = _tail(base)

    def mark_direct(fn: ast.AST, spec: Optional[ast.Call]) -> None:
        info.hot.add(fn)
        statics = _static_params(fn, spec) if spec is not None \
            else set()
        info.direct.setdefault(fn, statics)

    # roots: decorated functions
    for flist in funcs.values():
        for fn in flist:
            for dec in fn.decorator_list:
                if is_jit_expr(dec, aliases):
                    spec = dec if isinstance(dec, ast.Call) else None
                    mark_direct(fn, spec)

    # roots: jax.jit(f, ...) / combinator(f, ...) call forms
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        cn = resolve(node.func, aliases)
        if cn in JIT_NAMES:
            for a in node.args[:1]:
                base = dotted(a)
                if base:
                    for fn in funcs.get(_tail(base), ()):
                        mark_direct(fn, node)
        elif cn in TRACE_COMBINATORS:
            for a in node.args:
                base = dotted(a)
                if base:
                    for fn in funcs.get(_tail(base), ()):
                        info.hot.add(fn)
        elif cn in SHARD_MAP_NAMES:
            for a in node.args[:1]:
                base = dotted(a)
                if base:
                    for fn in funcs.get(_tail(base), ()):
                        info.hot.add(fn)

    # propagate through the in-module call graph to a fixed point
    changed = True
    while changed:
        changed = False
        for fn in list(info.hot):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                base = dotted(node.func)
                if base is None:
                    continue
                callee = partial_alias.get(_tail(base), _tail(base))
                for target in funcs.get(callee, ()):
                    if target not in info.hot:
                        info.hot.add(target)
                        changed = True

    mod._cache["hot"] = info
    return info


def walk_functions(tree: ast.AST
                   ) -> Iterator[Tuple[ast.AST, Optional[str]]]:
    """Yield (FunctionDef, enclosing class name) pairs."""
    def visit(node: ast.AST, cls: Optional[str]) -> Iterator:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from visit(child, child.name)
            elif isinstance(child,
                            (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from visit(child, cls)
            else:
                yield from visit(child, cls)
    yield from visit(tree, None)
