"""Shared AST analyses: import-alias-aware name resolution and the
jit-reachability ("hot") call graph the CTL1xx/CTL2xx rules key off.

Everything here is intentionally module-local and name-based: a call
``dt.bucket_row(...)`` marks every same-module function NAMED
``bucket_row`` — an over-approximation that is cheap, deterministic,
and right for this codebase's idiom (helpers live next to the jitted
entry points that trace them).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

# canonical (post-alias) spellings
JIT_NAMES = {"jax.jit", "jax.pjit"}
# combinators whose function arguments are traced (treated as hot but
# NOT as directly-jitted roots: their params may be static Python)
TRACE_COMBINATORS = {
    "jax.vmap", "jax.pmap", "jax.checkpoint", "jax.remat",
    "jax.grad", "jax.value_and_grad",
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.fori_loop",
    "jax.lax.switch", "jax.lax.cond", "jax.lax.map",
    "jax.lax.associative_scan",
}
PARTIAL_NAMES = {"functools.partial", "partial"}


def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """local name -> canonical dotted prefix, e.g. {'_jax': 'jax',
    'jnp': 'jax.numpy', 'lax': 'jax.lax', 'np': 'numpy',
    'jit': 'jax.jit'}."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Alias-normalized dotted name ('_jax.jit' -> 'jax.jit')."""
    d = dotted(node)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    head = aliases.get(head, head)
    return f"{head}.{rest}" if rest else head


def is_jit_expr(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """True for ``jax.jit`` / ``functools.partial(jax.jit, ...)``."""
    if resolve(node, aliases) in JIT_NAMES:
        return True
    if isinstance(node, ast.Call) and \
            resolve(node.func, aliases) in PARTIAL_NAMES and node.args:
        return resolve(node.args[0], aliases) in JIT_NAMES
    return False


def _tail(name: str) -> str:
    return name.rsplit(".", 1)[-1]


class HotInfo:
    """jit-reachability result for one module.

    ``hot``     — FunctionDefs traced under jit (roots + combinator
                  targets + everything they reach in-module).
    ``direct``  — FunctionDefs whose PARAMETERS are traced values
                  (jit-decorated / jax.jit(f) targets), mapped to the
                  set of their statically-marked parameter names (None
                  when the static spec could not be resolved).
    """

    def __init__(self) -> None:
        self.hot: Set[ast.AST] = set()
        self.direct: Dict[ast.AST, Optional[Set[str]]] = {}


def _static_params(fn: ast.AST, spec: ast.Call) -> Optional[Set[str]]:
    """Parameter names marked static by a jit call/partial ``spec``;
    None when not statically resolvable (conservative: skip checks)."""
    names: Set[str] = set()
    args = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in spec.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) \
                else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, str):
                    names.add(e.value)
                else:
                    return None
        elif kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) \
                else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, int) and \
                        0 <= e.value < len(args):
                    names.add(args[e.value])
                else:
                    return None
    return names


def hot_functions(mod) -> HotInfo:
    """Compute (and cache on the module) the jit-reachable set."""
    cached = mod._cache.get("hot")
    if cached is not None:
        return cached
    tree = mod.tree
    aliases = import_aliases(tree)
    info = HotInfo()

    funcs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, []).append(node)

    # name -> callee name, for `g = functools.partial(f, ...)`
    partial_alias: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and resolve(node.value.func, aliases) in PARTIAL_NAMES \
                and node.value.args:
            base = dotted(node.value.args[0])
            if base:
                partial_alias[node.targets[0].id] = _tail(base)

    def mark_direct(fn: ast.AST, spec: Optional[ast.Call]) -> None:
        info.hot.add(fn)
        statics = _static_params(fn, spec) if spec is not None \
            else set()
        info.direct.setdefault(fn, statics)

    # roots: decorated functions
    for flist in funcs.values():
        for fn in flist:
            for dec in fn.decorator_list:
                if is_jit_expr(dec, aliases):
                    spec = dec if isinstance(dec, ast.Call) else None
                    mark_direct(fn, spec)

    # roots: jax.jit(f, ...) / combinator(f, ...) call forms
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        cn = resolve(node.func, aliases)
        if cn in JIT_NAMES:
            for a in node.args[:1]:
                base = dotted(a)
                if base:
                    for fn in funcs.get(_tail(base), ()):
                        mark_direct(fn, node)
        elif cn in TRACE_COMBINATORS:
            for a in node.args:
                base = dotted(a)
                if base:
                    for fn in funcs.get(_tail(base), ()):
                        info.hot.add(fn)

    # propagate through the in-module call graph to a fixed point
    changed = True
    while changed:
        changed = False
        for fn in list(info.hot):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                base = dotted(node.func)
                if base is None:
                    continue
                callee = partial_alias.get(_tail(base), _tail(base))
                for target in funcs.get(callee, ()):
                    if target not in info.hot:
                        info.hot.add(target)
                        changed = True

    mod._cache["hot"] = info
    return info


def walk_functions(tree: ast.AST
                   ) -> Iterator[Tuple[ast.AST, Optional[str]]]:
    """Yield (FunctionDef, enclosing class name) pairs."""
    def visit(node: ast.AST, cls: Optional[str]) -> Iterator:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from visit(child, child.name)
            elif isinstance(child,
                            (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from visit(child, cls)
            else:
                yield from visit(child, cls)
    yield from visit(tree, None)
