"""cephtpu-lint — AST-based static analysis for the framework's own
invariants.

PR 1 added *runtime* guards (lockdep cycle detection, immutable
perf-counter types); this package is the *static* counterpart: the
properties the two TPU inner loops (CRUSH mapping, GF(2^8) EC) and the
daemon plane depend on are checked at lint time, before any test
runs.  Since CTLint v2 the reachability-based rules run on a
WHOLE-PROGRAM, import-resolving call graph (astutil.ProgramGraph,
built once per run and shared by every rule), so a violation one
module away from its root is no longer invisible.  Eight rule
families (ids are stable and suppressable via ``# noqa: CTL###`` or
the checked-in baseline):

  CTL1xx  hot-path hygiene: JAX (host syncs / tracer branches /
          per-call jit inside jit-reachable code, cross-module),
          the messenger (110: blocking calls reachable from
          completion-callback context) and recovery loops (120:
          per-shard blocking round trips, helpers included)
  CTL2xx  GF(2^8)/CRUSH dtype invariants (implicit dtypes that drift
          under jax_enable_x64; unpinned array ingestion in ops/)
  CTL3xx  concurrency (static lock-order inversions against the same
          edge model common/lockdep.py enforces at runtime; raw
          threading locks in daemon-plane modules)
  CTL4xx  perf-counter / config registry hygiene
  CTL5xx  admin-command registry (dispatched vs registered)
  CTL6xx  faultpoint registry closure (fires declared; fires outside
          jit; swallowed IO errors; store writes off the barrier API)
  CTL7xx  trace-context propagation closure (stamped wire sends —
          direct, var-flow, and cross-module wrapper shapes)
  CTL8xx  wire-protocol contract closure (sent cmds handled, arms
          exercised, mutations (session,seq)-stamped, sender keys
          cover handler reads, faultpoint grammar single-declare) —
          the ceph-dencoder / ceph-object-corpus role, statically

Entry points: ``scripts/lint.py`` (CI driver), ``ceph_tpu.tools.
ceph_cli lint`` (operator surface; ``--rule`` family filter,
``--graph module.fn`` call-graph dump), ``ceph_tpu.analysis.runner.
run`` (library), ``scripts/check_static.py`` (seeded smoke).
Reference role: src/test/static-analysis + the sanitizer wiring —
regressions caught by machinery, not review.
"""
from .core import Finding, LintError, Rule  # noqa: F401
from .registry import RuleRegistry, instance  # noqa: F401
from .runner import run  # noqa: F401
