"""cephtpu-lint — AST-based static analysis for the framework's own
invariants.

PR 1 added *runtime* guards (lockdep cycle detection, immutable
perf-counter types); this package is the *static* counterpart: the
properties the two TPU inner loops (CRUSH mapping, GF(2^8) EC) and the
daemon plane depend on are checked at lint time, across every module,
before any test runs.  Five rule families (ids are stable and
suppressable via ``# noqa: CTL###`` or the checked-in baseline):

  CTL1xx  hot-path hygiene: JAX (host syncs / tracer branches /
          per-call jit inside jit-reachable code) and the messenger
          (110: blocking calls reachable from completion-callback
          context)
  CTL2xx  GF(2^8)/CRUSH dtype invariants (implicit dtypes that drift
          under jax_enable_x64; unpinned array ingestion in ops/)
  CTL3xx  concurrency (static lock-order inversions against the same
          edge model common/lockdep.py enforces at runtime; raw
          threading locks in daemon-plane modules)
  CTL4xx  perf-counter / config registry hygiene
  CTL5xx  admin-command registry (dispatched vs registered)

Entry points: ``scripts/lint.py`` (CI driver), ``ceph_tpu.tools.
ceph_cli lint`` (operator surface), ``ceph_tpu.analysis.runner.run``
(library).  Reference role: src/test/static-analysis + the sanitizer
wiring — regressions caught by machinery, not review.
"""
from .core import Finding, LintError, Rule  # noqa: F401
from .registry import RuleRegistry, instance  # noqa: F401
from .runner import run  # noqa: F401
