"""CTL6xx — faultpoint registry closure.

The fault-injection registry (common/faults.py) is a string-keyed
dispatch seam like the admin registry: ``faults.declare("name", doc)``
on one side, ``faults.fire("name", ...)`` on the other, and nothing
ties them together until a thrash run arms the point.  A typo'd fire
name silently never fires (the dict-miss fast path eats it), which is
the worst failure mode a fault-injection system can have — the soak
"passes" while injecting nothing.  And a ``faults.fire`` inside
jit-reachable code is a host-side branch in a traced program: it
either burns the compiled path or bakes one outcome in at trace time.

  CTL601  a literal ``faults.fire("name")`` whose name no
          ``faults.declare("name", ...)`` site declares
  CTL602  ``faults.fire`` reachable under jit (reuses the CTL1xx
          jit-reachability graph, analysis/astutil.py)
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import astutil
from .core import Finding, ParsedModule, Rule


def _faults_recv(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """True when the attribute receiver is the faults module/registry
    (``faults.fire``, an aliased import, or ``faults.registry()``)."""
    r = astutil.resolve(node, aliases)
    if r is not None and (r == "faults" or r.endswith(".faults") or
                          r.endswith("faults.registry")):
        return True
    # registry() call receiver: faults.registry().fire(...)
    if isinstance(node, ast.Call):
        rc = astutil.resolve(node.func, aliases)
        return rc is not None and rc.endswith("registry")
    return False


def _collect(mod: ParsedModule):
    """(declared, fired) literal faultpoint names with sites — once
    per module, shared by CTL601/CTL602 (the rules_admin pattern)."""
    cached = mod._cache.get("faultpoints")
    if cached is not None:
        return cached
    aliases = astutil.import_aliases(mod.tree)
    declared: Dict[str, Tuple[str, int]] = {}
    fired: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr not in ("declare", "fire", "arm"):
            continue
        if not _faults_recv(node.func.value, aliases):
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        name = node.args[0].value
        if node.func.attr == "declare":
            declared.setdefault(name, (mod.relpath, node.lineno))
        elif node.func.attr == "fire":
            fired.setdefault(name, (mod.relpath, node.lineno))
    mod._cache["faultpoints"] = (declared, fired)
    return declared, fired


class UndeclaredFireRule(Rule):
    rule_id = "CTL601"
    name = "faultpoint-fire-undeclared"
    description = ("faults.fire() names a faultpoint no "
                   "faults.declare() site declares — the dict-miss "
                   "fast path silently never fires it")

    def __init__(self) -> None:
        self.declared: Set[str] = set()
        self.fired: Dict[str, List[Tuple[str, int]]] = {}

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        declared, fired = _collect(mod)
        self.declared.update(declared)       # evidence declares count
        if not mod.evidence:
            for name, site in fired.items():
                self.fired.setdefault(name, []).append(site)
        return ()

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for name in sorted(set(self.fired) - self.declared):
            for path, line in self.fired[name]:
                out.append(Finding(
                    self.rule_id, path, line,
                    f"faultpoint {name!r} is fired here but no "
                    f"faults.declare() site declares it — arming "
                    f"raises and the fire is a silent no-op"))
        return out


class FireInJitRule(Rule):
    rule_id = "CTL602"
    name = "faultpoint-fire-in-jit"
    description = ("faults.fire() inside jit-reachable code: a host "
                   "branch in a traced program (bakes one outcome in "
                   "at trace time)")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        info = astutil.hot_functions(mod)
        if not info.hot:
            return ()
        aliases = astutil.import_aliases(mod.tree)
        out: List[Finding] = []
        seen: Set[int] = set()               # nested-hot dedup
        for fn in info.hot:
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "fire" and \
                        _faults_recv(node.func.value, aliases) and \
                        node.lineno not in seen:
                    seen.add(node.lineno)
                    out.append(self.finding(
                        mod, node.lineno,
                        f"faults.fire() inside jit-reachable "
                        f"{getattr(fn, 'name', '<fn>')}() — the "
                        f"branch is traced once and baked in; inject "
                        f"at the dispatch boundary instead"))
        return out


def register(reg) -> None:
    reg.add(UndeclaredFireRule.rule_id, UndeclaredFireRule)
    reg.add(FireInJitRule.rule_id, FireInJitRule)
