"""CTL6xx — faultpoint registry closure.

The fault-injection registry (common/faults.py) is a string-keyed
dispatch seam like the admin registry: ``faults.declare("name", doc)``
on one side, ``faults.fire("name", ...)`` on the other, and nothing
ties them together until a thrash run arms the point.  A typo'd fire
name silently never fires (the dict-miss fast path eats it), which is
the worst failure mode a fault-injection system can have — the soak
"passes" while injecting nothing.  And a ``faults.fire`` inside
jit-reachable code is a host-side branch in a traced program: it
either burns the compiled path or bakes one outcome in at trace time.

  CTL601  a literal ``faults.fire("name")`` whose name no
          ``faults.declare("name", ...)`` site declares
  CTL602  ``faults.fire`` reachable under jit (reuses the CTL1xx
          jit-reachability graph, analysis/astutil.py)
  CTL603  catch-and-discard of IOError/OSError into a constant
          default in client//rgw//msg/ — the ``Bucket._read_index``
          lost-object bug class: a transient wire/device error
          swallowed into ``{}`` reads as "object absent" and the
          next metadata WRITE rebuilds from the fabricated default
  CTL604  direct write-capable ``open()`` / ``os.write`` /
          ``os.pwrite`` / ``os.rename`` / ... in a BlockDevice-owned
          store module (cluster/{bluestore,wal_kv,filestore}.py) —
          bytes that bypass the barrier API are invisible to the
          CrashDev recorder, so the crash-state enumeration silently
          proves nothing about them (exactly the bug class that
          invalidates the power-loss harness)
  CTL605  a sync-agent apply path that persists a replication marker
          (advance/commit/save × marker/applied/position/cursor/
          state) while an async submission's completion is still
          unresolved — the acked-then-lost ordering bug: a crash
          between the marker write and the apply's completion makes
          the peer skip an entry it never actually applied.  Marker
          calls resolve through the PR-12 whole-program graph, so a
          one-hop wrapper around the persist helper is still caught
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import astutil, shardspec
from .core import Finding, ParsedModule, Rule


def _faults_recv(node: ast.AST, aliases: Dict[str, str]) -> bool:
    """True when the attribute receiver is the faults module/registry
    (``faults.fire``, an aliased import, or ``faults.registry()``)."""
    r = astutil.resolve(node, aliases)
    if r is not None and (r == "faults" or r.endswith(".faults") or
                          r.endswith("faults.registry")):
        return True
    # registry() call receiver: faults.registry().fire(...)
    if isinstance(node, ast.Call):
        rc = astutil.resolve(node.func, aliases)
        return rc is not None and rc.endswith("registry")
    return False


def _collect(mod: ParsedModule):
    """(declared, fired) literal faultpoint names with sites — once
    per module, shared by CTL601/CTL602 (the rules_admin pattern)."""
    cached = mod._cache.get("faultpoints")
    if cached is not None:
        return cached
    aliases = astutil.aliases_of(mod)
    declared: Dict[str, Tuple[str, int]] = {}
    fired: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr not in ("declare", "fire", "arm"):
            continue
        if not _faults_recv(node.func.value, aliases):
            continue
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        name = node.args[0].value
        if node.func.attr == "declare":
            declared.setdefault(name, (mod.relpath, node.lineno))
        elif node.func.attr == "fire":
            fired.setdefault(name, (mod.relpath, node.lineno))
    mod._cache["faultpoints"] = (declared, fired)
    return declared, fired


class UndeclaredFireRule(Rule):
    rule_id = "CTL601"
    name = "faultpoint-fire-undeclared"
    description = ("faults.fire() names a faultpoint no "
                   "faults.declare() site declares — the dict-miss "
                   "fast path silently never fires it")

    def __init__(self) -> None:
        self.declared: Set[str] = set()
        self.fired: Dict[str, List[Tuple[str, int]]] = {}

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        declared, fired = _collect(mod)
        self.declared.update(declared)       # evidence declares count
        if not mod.evidence:
            for name, site in fired.items():
                self.fired.setdefault(name, []).append(site)
        return ()

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for name in sorted(set(self.fired) - self.declared):
            for path, line in self.fired[name]:
                out.append(Finding(
                    self.rule_id, path, line,
                    f"faultpoint {name!r} is fired here but no "
                    f"faults.declare() site declares it — arming "
                    f"raises and the fire is a silent no-op"))
        return out


class FireInJitRule(Rule):
    rule_id = "CTL602"
    name = "faultpoint-fire-in-jit"
    description = ("faults.fire() inside jit-reachable code: a host "
                   "branch in a traced program (bakes one outcome in "
                   "at trace time)")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        # the ShardCheck DeviceContext and astutil.hot_functions slice
        # the SAME whole-program reachability set (computed once per
        # run); CTL602 reads it through the shared context so the
        # jit/shard_map families cannot disagree on what is traced
        hot = shardspec.device_context(mod.program).hot_in(mod) \
            if mod.program is not None else \
            astutil.hot_functions(mod).hot
        if not hot:
            return ()
        aliases = astutil.aliases_of(mod)
        out: List[Finding] = []
        seen: Set[int] = set()               # nested-hot dedup
        for fn in hot:
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "fire" and \
                        _faults_recv(node.func.value, aliases) and \
                        node.lineno not in seen:
                    seen.add(node.lineno)
                    out.append(self.finding(
                        mod, node.lineno,
                        f"faults.fire() inside jit-reachable "
                        f"{getattr(fn, 'name', '<fn>')}() — the "
                        f"branch is traced once and baked in; inject "
                        f"at the dispatch boundary instead"))
        return out


# directories whose modules face the wire/device error domain: a
# swallowed transient error there is user-visible data loss, not a
# local inconvenience (the scope the ISSUE-6 satellite names)
_IO_DIRS = ("client", "rgw", "msg")

# exception names that cover IOError/OSError when caught
_IO_EXC_NAMES = ("IOError", "OSError", "EnvironmentError",
                 "Exception", "BaseException", "WireError",
                 "WireClosed", "ConnectionError", "TimeoutError")


def _catches_io(handler: ast.ExceptHandler) -> bool:
    """Does this handler swallow IOError/OSError (directly, via a
    tuple, via a broad Exception/BaseException, or bare except)?"""
    t = handler.type
    if t is None:
        return True                           # bare except
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in types:
        name = e.id if isinstance(e, ast.Name) else (
            e.attr if isinstance(e, ast.Attribute) else None)
        if name in _IO_EXC_NAMES:
            return True
    return False


def _const_expr(e: Optional[ast.AST]) -> bool:
    """A literal/constant default: the fabricated value the swallow
    substitutes for real state ({} / [] / None / 0 / "" / ...)."""
    if e is None or isinstance(e, ast.Constant):
        return True
    if isinstance(e, (ast.List, ast.Tuple, ast.Set)):
        return all(_const_expr(x) for x in e.elts)
    if isinstance(e, ast.Dict):
        return all(_const_expr(k) for k in e.keys if k is not None) \
            and all(_const_expr(v) for v in e.values)
    if isinstance(e, ast.UnaryOp):
        return _const_expr(e.operand)
    return False


class SwallowedIOErrorRule(Rule):
    rule_id = "CTL603"
    name = "ioerror-swallowed-to-default"
    description = ("except IOError/OSError handler returns a constant "
                   "default in client//rgw//msg/ — a transient error "
                   "fabricates 'absent' state (the _read_index "
                   "lost-object bug class); retry with backoff, "
                   "re-raise, or suppress with # noqa: CTL603")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        parts = mod.relpath.replace("\\", "/").split("/")[:-1]
        if not any(p in _IO_DIRS for p in parts):
            return ()
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _catches_io(node):
                continue
            body = node.body
            if len(body) == 1 and isinstance(body[0], ast.Return) \
                    and _const_expr(body[0].value):
                out.append(self.finding(
                    mod, node.lineno,
                    "IOError/OSError swallowed into a constant "
                    "default return — a transient wire/device error "
                    "now reads as 'absent' state (the _read_index "
                    "lost-object class); retry with "
                    "common/backoff.ExpBackoff, raise, or justify "
                    "with # noqa: CTL603"))
        return out


# the BlockDevice-owned store modules: every byte they persist must
# cross cluster/blockdev.py's barrier-recording API, or the CrashDev
# crash-state recorder is blind to it.  blockdev.py itself is the
# one place raw I/O is legitimate (it IS the door).
_STORE_MODULES = frozenset(("bluestore.py", "wal_kv.py",
                            "filestore.py"))

# os-level write-capable calls a store module must not make directly
_RAW_OS_WRITERS = frozenset((
    "os.write", "os.pwrite", "os.writev", "os.pwritev",
    "os.rename", "os.replace", "os.truncate", "os.ftruncate",
    "os.unlink", "os.remove", "os.fsync", "os.fdatasync"))


def _open_write_mode(call: ast.Call) -> Optional[str]:
    """The literal mode string of an ``open()`` call when it enables
    writing ('w'/'a'/'x'/'+'), else None.  A read-only or
    mode-omitted open is fine — the recorder only needs WRITES."""
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and any(c in mode for c in "wax+"):
        return mode
    return None


class StoreBypassRule(Rule):
    rule_id = "CTL604"
    name = "store-write-bypasses-blockdev"
    description = ("write-capable open()/os.write/os.pwrite/os.rename"
                   "/... in a BlockDevice-owned store module — bytes "
                   "that bypass the barrier API are invisible to the "
                   "CrashDev crash-state recorder, so power-loss "
                   "enumeration proves nothing about them")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        parts = mod.relpath.replace("\\", "/").split("/")
        if "cluster" not in parts[:-1] or \
                parts[-1] not in _STORE_MODULES:
            return ()
        aliases = astutil.aliases_of(mod)
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Name) and \
                    node.func.id == "open":
                m = _open_write_mode(node)
                if m is not None:
                    out.append(self.finding(
                        mod, node.lineno,
                        f"open(..., {m!r}) in a BlockDevice-owned "
                        f"store module bypasses the barrier API — "
                        f"the crash-state recorder never sees these "
                        f"bytes; use cluster.blockdev.BlockDevice"))
                continue
            r = astutil.resolve(node.func, aliases)
            if r in _RAW_OS_WRITERS:
                out.append(self.finding(
                    mod, node.lineno,
                    f"{r}() in a BlockDevice-owned store module "
                    f"bypasses the barrier API — route it through "
                    f"cluster.blockdev (BlockDevice / replace / "
                    f"unlink) so CrashDev can enumerate its "
                    f"crash states"))
        return out


# the replication-agent layer: modules under rgw/ plus any module
# whose name says it is a sync/replication agent — the only place a
# "persisted marker" means "the peer will never resend this entry"
_SYNC_DIRS = ("rgw",)

# a call persists a replication marker when its name pairs a commit
# verb with a marker noun (_advance_applied, _save_state,
# commit_marker, update_position, ...)
_MARKER_VERBS = ("advance", "commit", "persist", "save", "update",
                 "bump", "store")
_MARKER_NOUNS = ("marker", "applied", "position", "cursor", "state")

# completion-resolving calls: any of these settles outstanding async
# submissions (the AioCompletion surface + concurrent.futures')
_RESOLVERS = ("result", "wait_for_complete", "wait", "gather",
              "as_completed")


def _is_marker_name(name: Optional[str]) -> bool:
    if not name:
        return False
    n = name.lower()
    return any(v in n for v in _MARKER_VERBS) and \
        any(s in n for s in _MARKER_NOUNS)


class MarkerBeforeCompletionRule(Rule):
    rule_id = "CTL605"
    name = "marker-advanced-before-completion"
    description = ("sync-agent apply path persists a replication "
                   "marker while an async submission's completion is "
                   "unresolved — a crash between the marker write and "
                   "the apply's completion loses the entry forever "
                   "(the acked-then-lost ordering bug)")

    def _marker_call(self, mod: ParsedModule, cls: Optional[str],
                     call: ast.Call) -> Optional[str]:
        """The marker-persist name this call reaches, or None.  Direct
        name match first; otherwise resolve one wrapper hop through
        the whole-program graph (a helper whose own name is bland but
        which calls the persist helper is the same commit point)."""
        f = call.func
        direct = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if _is_marker_name(direct):
            return direct
        if mod.program is None:
            return None
        graph = astutil.program_graph(mod.program)
        for fn in graph.resolve_call(mod, cls, call, precise=True):
            if _is_marker_name(getattr(fn, "name", None)):
                return fn.name
            for callee in graph.callees(fn):
                if _is_marker_name(getattr(callee, "name", None)):
                    return f"{fn.name} -> {callee.name}"
        return None

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        rp = mod.relpath.replace("\\", "/")
        parts = rp.split("/")
        if not (any(p in _SYNC_DIRS for p in parts[:-1]) or
                "sync" in parts[-1]):
            return ()
        out: List[Finding] = []
        for fn, cls in astutil.walk_functions(mod.tree):
            out.extend(self._check_fn(mod, cls, fn))
        return out

    def _check_fn(self, mod: ParsedModule, cls: Optional[str],
                  fn: ast.AST) -> Iterable[Finding]:
        """Linearize the function's calls by source line and simulate:
        a ``.submit(...)`` opens a pending completion, any resolver
        call settles ALL pending (gathers are batch-shaped), and a
        marker persist while something is pending is the finding.
        Statement order approximates control flow — exactly right for
        the submit -> persist -> gather loop shape the bug takes."""
        events: List[Tuple[int, str, Optional[str]]] = []
        plain: List[ast.Call] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if attr == "submit" or (attr or "").startswith("aio_"):
                events.append((node.lineno, "submit", attr))
                continue
            if attr in _RESOLVERS:
                events.append((node.lineno, "resolve", attr))
                continue
            plain.append(node)
        if any(k == "submit" for _, k, _ in events):
            # only a function that actually opens completions can
            # order a marker ahead of one — graph-resolve its other
            # calls; everything else skips the whole-program walk
            for node in plain:
                name = self._marker_call(mod, cls, node)
                if name is not None:
                    events.append((node.lineno, "marker", name))
        events.sort()
        pending = 0
        out: List[Finding] = []
        for lineno, kind, name in events:
            if kind == "submit":
                pending += 1
            elif kind == "resolve":
                pending = 0
            elif kind == "marker" and pending:
                out.append(self.finding(
                    mod, lineno,
                    f"{name}() persists a replication marker while "
                    f"{pending} async submission(s) are still "
                    f"unresolved — a crash here acks an entry whose "
                    f"apply never completed (peer will skip it "
                    f"forever); gather/.result() the completions "
                    f"first, then advance the marker"))
        return out


def register(reg) -> None:
    reg.add(UndeclaredFireRule.rule_id, UndeclaredFireRule)
    reg.add(FireInJitRule.rule_id, FireInJitRule)
    reg.add(SwallowedIOErrorRule.rule_id, SwallowedIOErrorRule)
    reg.add(StoreBypassRule.rule_id, StoreBypassRule)
    reg.add(MarkerBeforeCompletionRule.rule_id,
            MarkerBeforeCompletionRule)
