"""CTL1xx — JAX hot-path hygiene.

The CRUSH and GF(2^8) inner loops only stay fast while they remain
single compiled programs: one stray host sync inside a jitted path
serializes the device pipeline, one Python branch on a tracer throws
``TracerBoolConversionError`` at trace time (or silently bakes in one
branch), and one per-call ``jax.jit`` wrapper retraces on every
invocation.  These rules walk the jit-reachable call graph
(analysis/astutil.py) and flag exactly those three classes.

  CTL101  host sync / host-numpy call inside jit-reachable code
  CTL102  Python control flow on a traced parameter of a jitted
          function (statically-marked args are exempt)
  CTL103  jax.jit(...) built and invoked in one expression — a fresh
          executable (and a retrace) per call
  CTL110  blocking socket / wait call reachable from messenger
          CALLBACK context — completion callbacks (``cb=`` /
          ``set_complete_callback`` / ``add_done_callback``) run on
          a stream's reader thread (cluster/async_objecter.py), so a
          callback that blocks on a connect RTT or a future stalls
          every completion pipelined behind it.  Work handed to an
          engine via ``.submit(...)`` is deferred off the callback
          thread and exempt (that is the sanctioned escape hatch).
  CTL120  per-shard blocking wire round trip inside a loop in a
          recovery/backfill function (cluster// client//) — the
          pattern ISSUE 11 retired: a recovery sweep that fetches or
          pushes one shard per blocking ``osd_call``/``_peer_req``/
          ``.call`` pays an RTT per shard, which is the 0.002 GB/s
          wire-recovery floor BENCH r05 measured.  Submit-all-then-
          gather (``call_async`` + ``gather``) and bulk scatter-
          gather frames (``get_objects``/``put_objects``) are the
          sanctioned shapes and exempt.
"""
from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set, Tuple

from . import astutil, shardspec
from .core import Finding, ParsedModule, Rule

# method calls that force a device->host readback on an array
_SYNC_ATTRS = {"item", "tolist", "block_until_ready", "copy_to_host"}


class HostSyncRule(Rule):
    rule_id = "CTL101"
    name = "jax-host-sync"
    description = ("host sync (np.*, .item()/.tolist()/"
                   ".block_until_ready()) inside jit-reachable code")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        info = astutil.hot_functions(mod)
        if not info.hot:
            return ()
        aliases = astutil.aliases_of(mod)
        out: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()   # nested-hot dedup
        for fn in info.hot:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = None
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _SYNC_ATTRS:
                    msg = (f".{node.func.attr}() inside jit-reachable "
                           f"code forces a host sync")
                else:
                    cn = astutil.resolve(node.func, aliases)
                    if cn and cn.split(".")[0] == "numpy":
                        msg = (f"host numpy call {cn}() inside "
                               f"jit-reachable code (host sync / "
                               f"tracer leak)")
                if msg and (node.lineno, msg) not in seen:
                    seen.add((node.lineno, msg))
                    out.append(self.finding(mod, node.lineno, msg))
        return out


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    return {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs} | \
        ({a.vararg.arg} if a.vararg else set()) | \
        ({a.kwarg.arg} if a.kwarg else set())


class TracerBranchRule(Rule):
    rule_id = "CTL102"
    name = "jax-tracer-branch"
    description = ("Python if/while/assert on a traced parameter of a "
                   "jitted function (use jnp.where / lax.cond)")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        info = astutil.hot_functions(mod)
        out: List[Finding] = []
        for fn, statics in info.direct.items():
            if statics is None:
                continue      # unresolvable static spec: stay quiet
            traced = _param_names(fn) - statics
            if not traced:
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    test = node.test
                elif isinstance(node, ast.Assert):
                    test = node.test
                else:
                    continue
                names = {n.id for n in ast.walk(test)
                         if isinstance(n, ast.Name)}
                hits = sorted(names & traced)
                if hits:
                    kind = type(node).__name__.lower()
                    out.append(self.finding(
                        mod, node.lineno,
                        f"Python {kind} on traced value(s) "
                        f"{', '.join(hits)} in jitted "
                        f"{getattr(fn, 'name', '<fn>')}() — branches "
                        f"must be jnp.where/lax.cond (or mark the "
                        f"arg static)"))
        return out


class JitPerCallRule(Rule):
    rule_id = "CTL103"
    name = "jax-jit-per-call"
    description = ("jax.jit(...) constructed and called in one "
                   "expression: a fresh executable per invocation")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        aliases = astutil.aliases_of(mod)
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Call) and \
                    astutil.is_jit_expr(node.func.func, aliases):
                out.append(self.finding(
                    mod, node.lineno,
                    "jax.jit(f)(...) builds a fresh wrapper (and "
                    "retraces) on every call — hoist the jitted "
                    "callable to module/instance scope"))
        return out


# socket / future verbs that park the calling thread; in callback
# context (a stream's reader thread) each one stalls every completion
# pipelined behind it
_BLOCKING_ATTRS = {
    "connect", "accept", "recv", "recv_into", "recvfrom", "sendall",
    "sendmsg", "makefile", "create_connection", "result",
    "wait_for_complete",
}
# deferral verbs: a callable handed to X.submit(...) runs on the
# engine's workers, NOT in callback context
_DEFER_ATTRS = {"submit"}
# registration sites whose callable argument becomes callback-context
# (cb= / set_complete_callback / add_done_callback) are collected by
# the shared ShardCheck DeviceContext in analysis/shardspec.py


class CallbackBlockingRule(Rule):
    rule_id = "CTL110"
    name = "msgr-callback-blocking"
    description = ("blocking socket/wait call reachable from "
                   "messenger callback context (cb= / done-callback "
                   "functions run on stream reader threads) — "
                   "whole-program: the callback may be registered "
                   "in one module and block in another")

    @staticmethod
    def _own_calls(fn: ast.AST) -> List[ast.Call]:
        """Call nodes executed IN ``fn``'s own frame: nested
        def/lambda bodies are excluded (they only run if called or
        registered themselves), and argument subtrees of deferral
        calls (``X.submit(...)``) are excluded — they execute on the
        engine, not in callback context."""
        out: List[ast.Call] = []

        def visit(n: ast.AST) -> None:
            for ch in ast.iter_child_nodes(n):
                if isinstance(ch, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(ch, ast.Call):
                    out.append(ch)
                    if isinstance(ch.func, ast.Attribute) and \
                            ch.func.attr in _DEFER_ATTRS:
                        visit(ch.func)      # receiver still runs here
                        continue            # args are deferred
                visit(ch)

        visit(fn)
        return out

    def finish(self) -> Iterable[Finding]:
        # callback ROOT collection lives on the shared ShardCheck
        # DeviceContext (analysis/shardspec.py) — one tree walk feeds
        # this rule AND the CTL10xx shard_map site collection, so the
        # reachability families share a single per-run computation
        roots = shardspec.device_context(self.program).callback_roots
        if not roots:
            return ()
        graph = astutil.program_graph(self.program)
        # callback-context reachability over the resolved
        # cross-module graph, own-frame calls only (deferred
        # arguments escape callback context by design)
        origin = {fn: name for fn, (name, _m, _c) in roots.items()}
        ctx = {fn: (m, c) for fn, (_n, m, c) in roots.items()}
        work = list(roots)
        while work:
            fn = work.pop()
            mod, cls = ctx[fn]
            for call in self._own_calls(fn):
                for tgt in graph.resolve_call(mod, cls, call):
                    tmod = graph.mod_of[tgt]
                    if tgt not in origin and not tmod.evidence:
                        origin[tgt] = origin[fn]
                        ctx[tgt] = (tmod, graph.cls_of[tgt])
                        work.append(tgt)

        out: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()
        for fn in origin:
            mod, _cls = ctx[fn]
            aliases = astutil.aliases_of(mod)
            for call in self._own_calls(fn):
                msg = None
                if isinstance(call.func, ast.Attribute) and \
                        call.func.attr in _BLOCKING_ATTRS:
                    msg = (f".{call.func.attr}() blocks in messenger "
                           f"callback context (reachable from "
                           f"callback {origin[fn]!r}) — defer it via "
                           f"the completion engine's submit()")
                else:
                    cn = astutil.resolve(call.func, aliases)
                    if cn == "time.sleep":
                        msg = (f"time.sleep() in messenger callback "
                               f"context (reachable from callback "
                               f"{origin[fn]!r}) stalls every "
                               f"completion behind it")
                if msg and (mod.relpath, call.lineno, msg) not in seen:
                    seen.add((mod.relpath, call.lineno, msg))
                    out.append(self.finding(mod, call.lineno, msg))
        return out


# per-shard data-transfer commands: one of these inside a blocking
# loop is an RTT per shard; control-plane commands (pg_info,
# recover_pg, reserve_recovery, ...) and the bulk frames
# (get_objects/put_objects/delete_objects) are per-PG and exempt
_PER_SHARD_CMDS = frozenset((
    "get_shard", "put_shard", "getattr_shard", "setattr_shard",
    "digest_shard", "stat_shard", "delete_shard"))

# blocking senders; the async submission path (call_async) is exempt
_BLOCKING_SEND_ATTRS = frozenset(("call", "osd_call", "_peer_req"))

_RECOVERY_FN_RE = re.compile(r"recover|backfill")


class RecoveryShardLoopRule(Rule):
    rule_id = "CTL120"
    name = "recovery-per-shard-blocking-loop"
    description = ("per-shard blocking wire round trip inside a loop "
                   "on a recovery/backfill path — use submit-all-"
                   "then-gather (call_async + gather) or a bulk "
                   "scatter-gather frame")

    @staticmethod
    def _req_cmd(call: ast.Call):
        """The literal ``cmd`` value of a request dict anywhere in
        the call's argument tree (dicts may ride a tracer.stamp()
        wrapper or a variable is invisible — literal-only, the same
        bar CTL701 sets)."""
        for node in ast.walk(call):
            if not isinstance(node, ast.Dict):
                continue
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and k.value == "cmd" \
                        and isinstance(v, ast.Constant):
                    return v.value
        return None

    def __init__(self) -> None:
        super().__init__()
        # whole-program site dedup: a helper reachable from recovery
        # loops in SEVERAL modules must report once, at one site
        self.seen: Set[Tuple[str, int]] = set()

    @staticmethod
    def _in_scope(mod: ParsedModule) -> bool:
        parts = mod.parts()
        return "cluster" in parts or "client" in parts

    def _direct_hits(self, fn_name: str, root: ast.AST,
                     mod: ParsedModule, out: List[Finding],
                     seen: Set[Tuple[str, int]],
                     via: str = "") -> None:
        for call in ast.walk(root):
            if not isinstance(call, ast.Call) or \
                    not isinstance(call.func, ast.Attribute):
                continue
            if call.func.attr not in _BLOCKING_SEND_ATTRS:
                continue
            cmd = self._req_cmd(call)
            if cmd not in _PER_SHARD_CMDS:
                continue
            if (mod.relpath, call.lineno) in seen:
                continue
            seen.add((mod.relpath, call.lineno))
            out.append(self.finding(
                mod, call.lineno,
                f"blocking {cmd!r} round trip inside a loop "
                f"in recovery path {fn_name!r}{via}: one RTT per "
                f"shard is the wire-recovery floor — submit "
                f"the sweep async (call_async + gather) or "
                f"ship a bulk get_objects/put_objects frame"))

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence or not self._in_scope(mod):
            return ()
        graph = astutil.program_graph(mod.program)
        out: List[Finding] = []
        seen = self.seen
        for fn, cls in astutil.walk_functions(mod.tree):
            if not _RECOVERY_FN_RE.search(fn.name):
                continue
            for loop in ast.walk(fn):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                # sends lexically inside the loop
                self._direct_hits(fn.name, loop, mod, out, seen)
                # whole-program: a helper CALLED from the loop that
                # performs the per-shard blocking send pays the same
                # RTT per iteration — follow PRECISE edges only
                # (self-methods, local names, resolved imports;
                # ambiguous obj.attr fallback edges would drag in
                # every same-named function) and stop at the wire
                # layer itself (the send primitives' own bodies are
                # engine internals, not callers' loop shapes)
                helpers: Set[ast.AST] = set()
                work: List[ast.AST] = []
                for call in ast.walk(loop):
                    if isinstance(call, ast.Call):
                        work.extend(graph.resolve_call(
                            mod, cls, call, precise=True))
                while work:
                    h = work.pop()
                    if h in helpers or \
                            h.name in _BLOCKING_SEND_ATTRS:
                        continue
                    helpers.add(h)
                    for call in ast.walk(h):
                        if isinstance(call, ast.Call):
                            work.extend(graph.resolve_call(
                                graph.mod_of[h], graph.cls_of[h],
                                call, precise=True))
                for h in helpers:
                    hmod = graph.mod_of[h]
                    if hmod.evidence or not self._in_scope(hmod):
                        continue
                    # recovery-named helpers are NOT skipped: their
                    # own check only covers sends inside their own
                    # loops, while a straight-line per-shard send in
                    # a helper called from THIS loop still pays an
                    # RTT per iteration (site dedup prevents double
                    # reports)
                    self._direct_hits(
                        fn.name, h, hmod, out, seen,
                        via=f" (via helper {h.name!r})")
        return out


def register(reg) -> None:
    reg.add(HostSyncRule.rule_id, HostSyncRule)
    reg.add(TracerBranchRule.rule_id, TracerBranchRule)
    reg.add(JitPerCallRule.rule_id, JitPerCallRule)
    reg.add(CallbackBlockingRule.rule_id, CallbackBlockingRule)
    reg.add(RecoveryShardLoopRule.rule_id, RecoveryShardLoopRule)
