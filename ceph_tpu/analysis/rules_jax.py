"""CTL1xx — JAX hot-path hygiene.

The CRUSH and GF(2^8) inner loops only stay fast while they remain
single compiled programs: one stray host sync inside a jitted path
serializes the device pipeline, one Python branch on a tracer throws
``TracerBoolConversionError`` at trace time (or silently bakes in one
branch), and one per-call ``jax.jit`` wrapper retraces on every
invocation.  These rules walk the jit-reachable call graph
(analysis/astutil.py) and flag exactly those three classes.

  CTL101  host sync / host-numpy call inside jit-reachable code
  CTL102  Python control flow on a traced parameter of a jitted
          function (statically-marked args are exempt)
  CTL103  jax.jit(...) built and invoked in one expression — a fresh
          executable (and a retrace) per call
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Set, Tuple

from . import astutil
from .core import Finding, ParsedModule, Rule

# method calls that force a device->host readback on an array
_SYNC_ATTRS = {"item", "tolist", "block_until_ready", "copy_to_host"}


class HostSyncRule(Rule):
    rule_id = "CTL101"
    name = "jax-host-sync"
    description = ("host sync (np.*, .item()/.tolist()/"
                   ".block_until_ready()) inside jit-reachable code")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        info = astutil.hot_functions(mod)
        if not info.hot:
            return ()
        aliases = astutil.import_aliases(mod.tree)
        out: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()   # nested-hot dedup
        for fn in info.hot:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = None
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _SYNC_ATTRS:
                    msg = (f".{node.func.attr}() inside jit-reachable "
                           f"code forces a host sync")
                else:
                    cn = astutil.resolve(node.func, aliases)
                    if cn and cn.split(".")[0] == "numpy":
                        msg = (f"host numpy call {cn}() inside "
                               f"jit-reachable code (host sync / "
                               f"tracer leak)")
                if msg and (node.lineno, msg) not in seen:
                    seen.add((node.lineno, msg))
                    out.append(self.finding(mod, node.lineno, msg))
        return out


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    return {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs} | \
        ({a.vararg.arg} if a.vararg else set()) | \
        ({a.kwarg.arg} if a.kwarg else set())


class TracerBranchRule(Rule):
    rule_id = "CTL102"
    name = "jax-tracer-branch"
    description = ("Python if/while/assert on a traced parameter of a "
                   "jitted function (use jnp.where / lax.cond)")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        info = astutil.hot_functions(mod)
        out: List[Finding] = []
        for fn, statics in info.direct.items():
            if statics is None:
                continue      # unresolvable static spec: stay quiet
            traced = _param_names(fn) - statics
            if not traced:
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    test = node.test
                elif isinstance(node, ast.Assert):
                    test = node.test
                else:
                    continue
                names = {n.id for n in ast.walk(test)
                         if isinstance(n, ast.Name)}
                hits = sorted(names & traced)
                if hits:
                    kind = type(node).__name__.lower()
                    out.append(self.finding(
                        mod, node.lineno,
                        f"Python {kind} on traced value(s) "
                        f"{', '.join(hits)} in jitted "
                        f"{getattr(fn, 'name', '<fn>')}() — branches "
                        f"must be jnp.where/lax.cond (or mark the "
                        f"arg static)"))
        return out


class JitPerCallRule(Rule):
    rule_id = "CTL103"
    name = "jax-jit-per-call"
    description = ("jax.jit(...) constructed and called in one "
                   "expression: a fresh executable per invocation")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        aliases = astutil.import_aliases(mod.tree)
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Call) and \
                    astutil.is_jit_expr(node.func.func, aliases):
                out.append(self.finding(
                    mod, node.lineno,
                    "jax.jit(f)(...) builds a fresh wrapper (and "
                    "retraces) on every call — hoist the jitted "
                    "callable to module/instance scope"))
        return out


def register(reg) -> None:
    reg.add(HostSyncRule.rule_id, HostSyncRule)
    reg.add(TracerBranchRule.rule_id, TracerBranchRule)
    reg.add(JitPerCallRule.rule_id, JitPerCallRule)
