"""CTL1xx — JAX hot-path hygiene.

The CRUSH and GF(2^8) inner loops only stay fast while they remain
single compiled programs: one stray host sync inside a jitted path
serializes the device pipeline, one Python branch on a tracer throws
``TracerBoolConversionError`` at trace time (or silently bakes in one
branch), and one per-call ``jax.jit`` wrapper retraces on every
invocation.  These rules walk the jit-reachable call graph
(analysis/astutil.py) and flag exactly those three classes.

  CTL101  host sync / host-numpy call inside jit-reachable code
  CTL102  Python control flow on a traced parameter of a jitted
          function (statically-marked args are exempt)
  CTL103  jax.jit(...) built and invoked in one expression — a fresh
          executable (and a retrace) per call
  CTL110  blocking socket / wait call reachable from messenger
          CALLBACK context — completion callbacks (``cb=`` /
          ``set_complete_callback`` / ``add_done_callback``) run on
          a stream's reader thread (cluster/async_objecter.py), so a
          callback that blocks on a connect RTT or a future stalls
          every completion pipelined behind it.  Work handed to an
          engine via ``.submit(...)`` is deferred off the callback
          thread and exempt (that is the sanctioned escape hatch).
  CTL120  per-shard blocking wire round trip inside a loop in a
          recovery/backfill function (cluster// client//) — the
          pattern ISSUE 11 retired: a recovery sweep that fetches or
          pushes one shard per blocking ``osd_call``/``_peer_req``/
          ``.call`` pays an RTT per shard, which is the 0.002 GB/s
          wire-recovery floor BENCH r05 measured.  Submit-all-then-
          gather (``call_async`` + ``gather``) and bulk scatter-
          gather frames (``get_objects``/``put_objects``) are the
          sanctioned shapes and exempt.
"""
from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set, Tuple

from . import astutil
from .core import Finding, ParsedModule, Rule

# method calls that force a device->host readback on an array
_SYNC_ATTRS = {"item", "tolist", "block_until_ready", "copy_to_host"}


class HostSyncRule(Rule):
    rule_id = "CTL101"
    name = "jax-host-sync"
    description = ("host sync (np.*, .item()/.tolist()/"
                   ".block_until_ready()) inside jit-reachable code")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        info = astutil.hot_functions(mod)
        if not info.hot:
            return ()
        aliases = astutil.import_aliases(mod.tree)
        out: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()   # nested-hot dedup
        for fn in info.hot:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                msg = None
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _SYNC_ATTRS:
                    msg = (f".{node.func.attr}() inside jit-reachable "
                           f"code forces a host sync")
                else:
                    cn = astutil.resolve(node.func, aliases)
                    if cn and cn.split(".")[0] == "numpy":
                        msg = (f"host numpy call {cn}() inside "
                               f"jit-reachable code (host sync / "
                               f"tracer leak)")
                if msg and (node.lineno, msg) not in seen:
                    seen.add((node.lineno, msg))
                    out.append(self.finding(mod, node.lineno, msg))
        return out


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    return {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs} | \
        ({a.vararg.arg} if a.vararg else set()) | \
        ({a.kwarg.arg} if a.kwarg else set())


class TracerBranchRule(Rule):
    rule_id = "CTL102"
    name = "jax-tracer-branch"
    description = ("Python if/while/assert on a traced parameter of a "
                   "jitted function (use jnp.where / lax.cond)")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        info = astutil.hot_functions(mod)
        out: List[Finding] = []
        for fn, statics in info.direct.items():
            if statics is None:
                continue      # unresolvable static spec: stay quiet
            traced = _param_names(fn) - statics
            if not traced:
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    test = node.test
                elif isinstance(node, ast.Assert):
                    test = node.test
                else:
                    continue
                names = {n.id for n in ast.walk(test)
                         if isinstance(n, ast.Name)}
                hits = sorted(names & traced)
                if hits:
                    kind = type(node).__name__.lower()
                    out.append(self.finding(
                        mod, node.lineno,
                        f"Python {kind} on traced value(s) "
                        f"{', '.join(hits)} in jitted "
                        f"{getattr(fn, 'name', '<fn>')}() — branches "
                        f"must be jnp.where/lax.cond (or mark the "
                        f"arg static)"))
        return out


class JitPerCallRule(Rule):
    rule_id = "CTL103"
    name = "jax-jit-per-call"
    description = ("jax.jit(...) constructed and called in one "
                   "expression: a fresh executable per invocation")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        aliases = astutil.import_aliases(mod.tree)
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Call) and \
                    astutil.is_jit_expr(node.func.func, aliases):
                out.append(self.finding(
                    mod, node.lineno,
                    "jax.jit(f)(...) builds a fresh wrapper (and "
                    "retraces) on every call — hoist the jitted "
                    "callable to module/instance scope"))
        return out


# socket / future verbs that park the calling thread; in callback
# context (a stream's reader thread) each one stalls every completion
# pipelined behind it
_BLOCKING_ATTRS = {
    "connect", "accept", "recv", "recv_into", "recvfrom", "sendall",
    "sendmsg", "makefile", "create_connection", "result",
    "wait_for_complete",
}
# deferral verbs: a callable handed to X.submit(...) runs on the
# engine's workers, NOT in callback context
_DEFER_ATTRS = {"submit"}
# registration sites whose callable argument becomes callback-context
_CB_REG_ATTRS = {"set_complete_callback", "add_done_callback"}


class CallbackBlockingRule(Rule):
    rule_id = "CTL110"
    name = "msgr-callback-blocking"
    description = ("blocking socket/wait call reachable from "
                   "messenger callback context (cb= / done-callback "
                   "functions run on stream reader threads)")

    @staticmethod
    def _own_calls(fn: ast.AST) -> List[ast.Call]:
        """Call nodes executed IN ``fn``'s own frame: nested
        def/lambda bodies are excluded (they only run if called or
        registered themselves), and argument subtrees of deferral
        calls (``X.submit(...)``) are excluded — they execute on the
        engine, not in callback context."""
        out: List[ast.Call] = []

        def visit(n: ast.AST) -> None:
            for ch in ast.iter_child_nodes(n):
                if isinstance(ch, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                if isinstance(ch, ast.Call):
                    out.append(ch)
                    if isinstance(ch.func, ast.Attribute) and \
                            ch.func.attr in _DEFER_ATTRS:
                        visit(ch.func)      # receiver still runs here
                        continue            # args are deferred
                visit(ch)

        visit(fn)
        return out

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        tree = mod.tree
        aliases = astutil.import_aliases(tree)
        funcs = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, []).append(node)

        # roots: callables registered as completion callbacks
        roots: Set[ast.AST] = set()
        root_names: dict = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            cands = [kw.value for kw in node.keywords
                     if kw.arg == "cb"]
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _CB_REG_ATTRS and node.args:
                cands.append(node.args[0])
            for v in cands:
                if isinstance(v, ast.Lambda):
                    roots.add(v)
                    root_names[v] = "<lambda callback>"
                else:
                    base = astutil.dotted(v)
                    if base:
                        for fn in funcs.get(base.rsplit(".", 1)[-1],
                                            ()):
                            roots.add(fn)
                            root_names[fn] = fn.name
        if not roots:
            return ()

        # propagate through the in-module call graph (name-based,
        # the hot_functions idiom) to everything callback-reachable
        reach = set(roots)
        origin = dict((fn, root_names[fn]) for fn in roots)
        changed = True
        while changed:
            changed = False
            for fn in list(reach):
                for call in self._own_calls(fn):
                    base = astutil.dotted(call.func)
                    if base is None:
                        continue
                    for tgt in funcs.get(base.rsplit(".", 1)[-1], ()):
                        if tgt not in reach:
                            reach.add(tgt)
                            origin[tgt] = origin[fn]
                            changed = True

        out: List[Finding] = []
        seen: Set[Tuple[int, str]] = set()
        for fn in reach:
            for call in self._own_calls(fn):
                msg = None
                if isinstance(call.func, ast.Attribute) and \
                        call.func.attr in _BLOCKING_ATTRS:
                    msg = (f".{call.func.attr}() blocks in messenger "
                           f"callback context (reachable from "
                           f"callback {origin[fn]!r}) — defer it via "
                           f"the completion engine's submit()")
                else:
                    cn = astutil.resolve(call.func, aliases)
                    if cn == "time.sleep":
                        msg = (f"time.sleep() in messenger callback "
                               f"context (reachable from callback "
                               f"{origin[fn]!r}) stalls every "
                               f"completion behind it")
                if msg and (call.lineno, msg) not in seen:
                    seen.add((call.lineno, msg))
                    out.append(self.finding(mod, call.lineno, msg))
        return out


# per-shard data-transfer commands: one of these inside a blocking
# loop is an RTT per shard; control-plane commands (pg_info,
# recover_pg, reserve_recovery, ...) and the bulk frames
# (get_objects/put_objects/delete_objects) are per-PG and exempt
_PER_SHARD_CMDS = frozenset((
    "get_shard", "put_shard", "getattr_shard", "setattr_shard",
    "digest_shard", "stat_shard", "delete_shard"))

# blocking senders; the async submission path (call_async) is exempt
_BLOCKING_SEND_ATTRS = frozenset(("call", "osd_call", "_peer_req"))

_RECOVERY_FN_RE = re.compile(r"recover|backfill")


class RecoveryShardLoopRule(Rule):
    rule_id = "CTL120"
    name = "recovery-per-shard-blocking-loop"
    description = ("per-shard blocking wire round trip inside a loop "
                   "on a recovery/backfill path — use submit-all-"
                   "then-gather (call_async + gather) or a bulk "
                   "scatter-gather frame")

    @staticmethod
    def _req_cmd(call: ast.Call):
        """The literal ``cmd`` value of a request dict anywhere in
        the call's argument tree (dicts may ride a tracer.stamp()
        wrapper or a variable is invisible — literal-only, the same
        bar CTL701 sets)."""
        for node in ast.walk(call):
            if not isinstance(node, ast.Dict):
                continue
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and k.value == "cmd" \
                        and isinstance(v, ast.Constant):
                    return v.value
        return None

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence:
            return ()
        parts = mod.parts()
        if "cluster" not in parts and "client" not in parts:
            return ()
        out: List[Finding] = []
        seen: Set[int] = set()
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if not _RECOVERY_FN_RE.search(fn.name):
                continue
            for loop in ast.walk(fn):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                for call in ast.walk(loop):
                    if not isinstance(call, ast.Call) or \
                            not isinstance(call.func, ast.Attribute):
                        continue
                    if call.func.attr not in _BLOCKING_SEND_ATTRS:
                        continue
                    cmd = self._req_cmd(call)
                    if cmd not in _PER_SHARD_CMDS:
                        continue
                    if call.lineno in seen:
                        continue
                    seen.add(call.lineno)
                    out.append(self.finding(
                        mod, call.lineno,
                        f"blocking {cmd!r} round trip inside a loop "
                        f"in recovery path {fn.name!r}: one RTT per "
                        f"shard is the wire-recovery floor — submit "
                        f"the sweep async (call_async + gather) or "
                        f"ship a bulk get_objects/put_objects frame"))
        return out


def register(reg) -> None:
    reg.add(HostSyncRule.rule_id, HostSyncRule)
    reg.add(TracerBranchRule.rule_id, TracerBranchRule)
    reg.add(JitPerCallRule.rule_id, JitPerCallRule)
    reg.add(CallbackBlockingRule.rule_id, CallbackBlockingRule)
    reg.add(RecoveryShardLoopRule.rule_id, RecoveryShardLoopRule)
