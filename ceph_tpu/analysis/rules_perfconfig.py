"""CTL4xx — perf-counter / config registry hygiene.

The runtime halves of these contracts already fail loudly:
``Options.get`` raises OptionError on an unknown key, and PR 1 made
declared perf-counter types immutable (a typo'd ``set()`` on a COUNTER
raises).  But both only fire when the offending line RUNS — a
misspelled config key on an error path or a tinc/hinc type clash
between two modules can sit untested for months.  These rules find the
same contract breaks across the whole tree at lint time.

  CTL401  config key read/set at a call site but absent from the
          Option table (common/options.py or any register site)
  CTL402  one perf counter key driven with conflicting types
          (inc vs tinc vs hinc vs set) across the tree
  CTL403  perf counter key read (.get) but never updated anywhere
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import astutil
from .core import Finding, ParsedModule, Rule

# receivers accepted as "the options registry" at a read site
_CFG_RECV = {"config()", "_config()", "cfg", "self.cfg", "conf"}
_CFG_METHODS = {"get", "set", "observe", "clear"}

# perf handle method -> allowed counter types ('*' = read)
_PC_METHODS = {
    "inc": ("counter", "gauge"),
    "add_counter": ("counter",),
    "set": ("gauge",),
    "add_gauge": ("gauge",),
    "tinc": ("time_avg",),
    "add_time_avg": ("time_avg",),
    "time": ("time_avg",),
    "hinc": ("histogram",),
    "add_histogram": ("histogram",),
}
_PC_READS = {"get", "type_of", "histogram"}


def _str_arg(call: ast.Call, idx: int = 0) -> Optional[str]:
    if len(call.args) > idx and \
            isinstance(call.args[idx], ast.Constant) and \
            isinstance(call.args[idx].value, str):
        return call.args[idx].value
    return None


class ConfigKeyRule(Rule):
    rule_id = "CTL401"
    name = "config-key-undeclared"
    description = ("config key used at a call site but never declared "
                   "in the Option table")

    def __init__(self) -> None:
        self.declared: Set[str] = set()
        # key -> list of (relpath, line)
        self.reads: Dict[str, List[Tuple[str, int]]] = {}

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = astutil.dotted(node.func)
            # declarations: Option("name", ...) anywhere (incl. tests
            # registering scratch options — evidence counts)
            if fname and fname.rsplit(".", 1)[-1] == "Option":
                key = _str_arg(node)
                if key:
                    self.declared.add(key)
                continue
            if mod.evidence:
                continue
            # reads: config().get("k") / cfg.set("k", v) / _cfg("k")
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _CFG_METHODS:
                try:
                    recv = ast.unparse(node.func.value)
                except Exception:      # pragma: no cover
                    recv = ""
                if recv in _CFG_RECV:
                    key = _str_arg(node)
                    if key:
                        self.reads.setdefault(key, []).append(
                            (mod.relpath, node.lineno))
            elif fname in ("_cfg", "cfg"):
                key = _str_arg(node)
                if key:
                    self.reads.setdefault(key, []).append(
                        (mod.relpath, node.lineno))
        return ()

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for key in sorted(set(self.reads) - self.declared):
            for path, line in self.reads[key]:
                out.append(Finding(
                    self.rule_id, path, line,
                    f"config key {key!r} is not declared in the "
                    f"Option table (common/options.py) — "
                    f"Options.get would raise OptionError at "
                    f"runtime"))
        return out


class _PerfUsages(ast.NodeVisitor):
    """Collect (group, key, method) perf-counter usages in a module.

    Handles the tree's three binding idioms::

        pc = _perf("crush.mapper"); pc.inc("lanes")
        self._pc = _perf("osd.service"); ... self._pc.hinc(...)
        _perf("op_tracker").inc("slow_ops")
    """

    def __init__(self, aliases: Dict[str, str]):
        self.aliases = aliases
        self.perf_names = {"perf", "_perf"} | {
            local for local, full in aliases.items()
            if full.endswith("perf_counters.perf")}
        self.cls: Optional[str] = None
        self.binds: Dict[Tuple[str, Optional[str], str], str] = {}
        # (group, key, method, line)
        self.usages: List[Tuple[str, str, str, int]] = []

    @classmethod
    def of(cls, mod: ParsedModule) -> List[Tuple[str, str, str, int]]:
        """Per-module usage list, computed once and shared by the
        CTL402/CTL403 rules (same pattern as astutil.hot_functions)."""
        cached = mod._cache.get("perf_usages")
        if cached is None:
            v = cls(astutil.aliases_of(mod))
            v.visit(mod.tree)
            cached = mod._cache["perf_usages"] = v.usages
        return cached

    def _group_of_call(self, call: ast.Call) -> Optional[str]:
        fname = astutil.dotted(call.func)
        if fname is None:
            return None
        if fname.rsplit(".", 1)[-1] in self.perf_names or \
                fname in self.perf_names:
            return _str_arg(call)
        return None

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self.cls = self.cls, node.name
        self.generic_visit(node)
        self.cls = prev

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            group = self._group_of_call(node.value)
            if group:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.binds[("name", None, tgt.id)] = group
                    elif isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        self.binds[("self", self.cls,
                                    tgt.attr)] = group
        self.generic_visit(node)

    def _resolve_handle(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Call):
            return self._group_of_call(expr)
        if isinstance(expr, ast.Name):
            return self.binds.get(("name", None, expr.id))
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self":
            return self.binds.get(("self", self.cls, expr.attr))
        return None

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute) and \
                (node.func.attr in _PC_METHODS or
                 node.func.attr in _PC_READS):
            group = self._resolve_handle(node.func.value)
            key = _str_arg(node)
            if group and key:
                self.usages.append((group, key, node.func.attr,
                                    node.lineno))
        self.generic_visit(node)


class PerfTypeRule(Rule):
    rule_id = "CTL402"
    name = "perf-counter-type-conflict"
    description = ("perf counter key driven with conflicting types "
                   "across the tree (inc vs tinc vs hinc vs set)")

    def __init__(self) -> None:
        # (group, key) -> {method: first (path, line, evidence)}
        self.writes: Dict[Tuple[str, str],
                          Dict[str, Tuple[str, int, bool]]] = {}

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        for group, key, method, line in _PerfUsages.of(mod):
            if method in _PC_METHODS:
                self.writes.setdefault((group, key), {}).setdefault(
                    method, (mod.relpath, line, mod.evidence))
        return ()

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for (group, key), methods in sorted(self.writes.items()):
            allowed = None
            for m in methods:
                types = set(_PC_METHODS[m])
                allowed = types if allowed is None else \
                    allowed & types
            if allowed:
                continue
            # report at a lint-scope site; a conflict confined to
            # evidence modules (tests driving scratch counters) is
            # theirs to fail at runtime, not this gate's to report
            sites = sorted((p, ln) for p, ln, ev in methods.values()
                           if not ev)
            if not sites:
                continue
            used = sorted(methods)
            path, line = sites[0]
            out.append(Finding(
                self.rule_id, path, line,
                f"perf counter {group}.{key} driven as "
                f"{'+'.join(used)} — no single declared type "
                f"satisfies all call sites (the immutable-type "
                f"guard would raise at runtime)"))
        return out


class PerfReadRule(Rule):
    rule_id = "CTL403"
    name = "perf-counter-read-never-written"
    description = ("perf counter key read via .get() but never "
                   "updated anywhere in the tree")

    def __init__(self) -> None:
        self.written: Set[Tuple[str, str]] = set()
        self.reads: Dict[Tuple[str, str],
                         List[Tuple[str, int]]] = {}

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        for group, key, method, line in _PerfUsages.of(mod):
            gk = (group, key)
            if method in _PC_METHODS:
                self.written.add(gk)
            elif not mod.evidence:
                self.reads.setdefault(gk, []).append(
                    (mod.relpath, line))
        return ()

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for gk in sorted(set(self.reads) - self.written):
            group, key = gk
            for path, line in self.reads[gk]:
                out.append(Finding(
                    self.rule_id, path, line,
                    f"perf counter {group}.{key} is read but no "
                    f"call site ever updates it (stale name after "
                    f"a rename?)"))
        return out


def register(reg) -> None:
    reg.add(ConfigKeyRule.rule_id, ConfigKeyRule)
    reg.add(PerfTypeRule.rule_id, PerfTypeRule)
    reg.add(PerfReadRule.rule_id, PerfReadRule)
