"""CTL2xx — GF(2^8) / CRUSH dtype invariants.

"Accelerating XOR-based Erasure Coding using Program Optimization
Techniques" measures exactly this failure class: XOR/GF throughput is
dominated by keeping the math in the narrow integer domain, and one
silently-widened dtype (uint8 -> int32/int64) multiplies the moved
bytes.  In this tree the hazard is concrete: importing
placement/xla_mapper.py enables ``jax_enable_x64`` process-wide, after
which every ``jnp.arange(n)``-style constructor WITHOUT an explicit
dtype materializes int64/float64 — 64-bit integer ops XLA must emulate
on TPU — and every ``jnp.asarray(caller_data)`` in ops/ inherits
whatever dtype the caller happened to hold.

  CTL201  implicit-dtype jnp constructor (arange/zeros/ones/empty) in
          ops/ or placement/
  CTL202  jnp.asarray/jnp.array of a bare function parameter without a
          pinned dtype in ops/ (GF math ingesting caller-typed data)
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from . import astutil
from .core import Finding, ParsedModule, Rule

# constructor -> positional index of its dtype parameter:
# zeros/ones/empty(shape, dtype), asarray/array(obj, dtype) but
# arange(start, stop, step, dtype) — `jnp.arange(1, n)` has NO dtype
_CTORS = {"jax.numpy.arange": 3, "jax.numpy.zeros": 1,
          "jax.numpy.ones": 1, "jax.numpy.empty": 1}
_INGEST = {"jax.numpy.asarray": 1, "jax.numpy.array": 1}


def _has_dtype(call: ast.Call, dtype_pos: int) -> bool:
    return any(kw.arg == "dtype" for kw in call.keywords) or \
        len(call.args) > dtype_pos


class ImplicitDtypeRule(Rule):
    rule_id = "CTL201"
    name = "gf-implicit-dtype"
    description = ("jnp.arange/zeros/ones/empty without dtype= on the "
                   "GF/CRUSH data path drifts under jax_enable_x64")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        parts = mod.parts()
        if mod.evidence or not ({"ops", "placement"} & set(parts)):
            return ()
        aliases = astutil.aliases_of(mod)
        out: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            cn = astutil.resolve(node.func, aliases)
            if cn in _CTORS and not _has_dtype(node, _CTORS[cn]):
                short = cn.replace("jax.numpy", "jnp")
                out.append(self.finding(
                    mod, node.lineno,
                    f"{short}() without dtype= materializes "
                    f"int64/float64 under jax_enable_x64 (emulated "
                    f"64-bit ops on TPU) — pin the dtype"))
        return out


class UnpinnedIngestRule(Rule):
    rule_id = "CTL202"
    name = "gf-unpinned-ingest"
    description = ("jnp.asarray(param) without dtype in ops/: GF math "
                   "silently runs in the caller's dtype")

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence or "ops" not in mod.parts():
            return ()
        aliases = astutil.aliases_of(mod)
        out: List[Finding] = []
        seen = set()                      # nested-function walk dedup
        for fn, _cls in astutil.walk_functions(mod.tree):
            params = {p.arg for p in fn.args.posonlyargs + fn.args.args}
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                cn = astutil.resolve(node.func, aliases)
                if cn in _INGEST and \
                        not _has_dtype(node, _INGEST[cn]) and \
                        len(node.args) == 1 and \
                        isinstance(node.args[0], ast.Name) and \
                        node.args[0].id in params and \
                        (node.lineno, node.args[0].id) not in seen:
                    seen.add((node.lineno, node.args[0].id))
                    short = cn.replace("jax.numpy", "jnp")
                    out.append(self.finding(
                        mod, node.lineno,
                        f"{short}({node.args[0].id}) without dtype= "
                        f"ingests caller-typed data into GF math "
                        f"(uint8 work silently widens to int32/int64)"
                        f" — pin the contract dtype"))
        return out


def register(reg) -> None:
    reg.add(ImplicitDtypeRule.rule_id, ImplicitDtypeRule)
    reg.add(UnpinnedIngestRule.rule_id, UnpinnedIngestRule)
