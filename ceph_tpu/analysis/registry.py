"""Lint-rule registry — the ErasureCodePlugin registry pattern
(ceph_tpu/ec/registry.py, itself mirroring ErasureCodePlugin.cc)
applied to analysis rules.

Rules register factory callables under their rule id; a version string
is checked at registration so an out-of-tree rule built against a
different framework version fails loudly instead of silently linting
with stale invariants (the __erasure_code_version failure mode).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

from .. import __version__
from .core import LintError, Rule

RuleFactory = Callable[[], Rule]


class RuleRegistry:
    """Thread-safe singleton registry of rule factories."""

    _instance: "RuleRegistry | None" = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._factories: Dict[str, RuleFactory] = {}
        self._meta: Dict[str, Dict[str, str]] = {}

    @classmethod
    def instance(cls) -> "RuleRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                reg = cls()
                reg._load_builtins()
                # publish only after builtins loaded, so a failed
                # bootstrap retries instead of pinning an empty registry
                cls._instance = reg
        return cls._instance

    # ----------------------------------------------------------- registry --
    def add(self, rule_id: str, factory: RuleFactory,
            version: str = __version__) -> None:
        if version != __version__:
            raise LintError(
                f"rule {rule_id!r} version {version!r} != runtime "
                f"{__version__!r}")
        probe = factory()
        if probe.rule_id != rule_id:
            raise LintError(
                f"rule factory id mismatch: registered as {rule_id!r} "
                f"but builds {probe.rule_id!r}")
        with self._lock:
            if rule_id in self._factories:
                raise LintError(f"rule {rule_id!r} already registered")
            self._factories[rule_id] = factory
            self._meta[rule_id] = {"name": probe.name,
                                   "description": probe.description}

    def remove(self, rule_id: str) -> None:
        with self._lock:
            self._factories.pop(rule_id, None)
            self._meta.pop(rule_id, None)

    def has(self, rule_id: str) -> bool:
        with self._lock:
            return rule_id in self._factories

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._factories)

    def describe(self) -> Dict[str, Dict[str, str]]:
        with self._lock:
            return {k: dict(v) for k, v in sorted(self._meta.items())}

    # ------------------------------------------------------------ factory --
    def factory(self, rule_id: str) -> Rule:
        with self._lock:
            fac = self._factories.get(rule_id)
        if fac is None:
            raise LintError(f"unknown lint rule {rule_id!r}; "
                            f"known: {self.names()}")
        return fac()

    def create(self, select: Optional[Sequence[str]] = None
               ) -> List[Rule]:
        """Fresh instances of every (or the selected) rule.  A select
        entry matches an exact id or a family prefix ('CTL3')."""
        rules = []
        for rid in self.names():
            if select and not any(rid.startswith(s.upper())
                                  for s in select):
                continue
            rules.append(self.factory(rid))
        if select and not rules:
            raise LintError(f"no rules match {list(select)!r}; "
                            f"known: {self.names()}")
        return rules

    # ----------------------------------------------------------- builtins --
    def _load_builtins(self) -> None:
        # local imports to avoid cycles; each module exposes
        # register(reg), mirroring the EC plugin seam
        from . import (rules_admin, rules_concurrency, rules_dtype,
                       rules_faults, rules_jax, rules_perfconfig,
                       rules_protocol, rules_serving, rules_shard,
                       rules_trace, rules_wire)
        for mod in (rules_jax, rules_dtype, rules_concurrency,
                    rules_perfconfig, rules_admin, rules_faults,
                    rules_trace, rules_protocol, rules_serving,
                    rules_wire, rules_shard):
            mod.register(self)


def instance() -> RuleRegistry:
    return RuleRegistry.instance()
