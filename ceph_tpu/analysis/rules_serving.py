"""CTL9xx — serving-path rules.

CTL901 polices the hot-bucket serialization class S3Serve's index
sharding retired: a FULL-index read (the ``_read_index``-shaped
whole-object JSON load that merges every shard) on a PER-REQUEST
gateway path in ``rgw/``.  Before sharding, every put/get/delete
deserialized — and every index write re-serialized — the entire
bucket's key table through one RADOS object: one hot bucket
serialized all its writers on a single omap-object RMW and made
per-request cost O(bucket).  After sharding, per-request ops must
touch only the key's shard (``_read_index_shard``); the whole-index
merge is legitimate ONLY on listing / reshard / admin surfaces.

The rule is interprocedural over the PR-12 whole-program graph
(precise edges): a per-request op that reaches ``_read_index``
through a helper is the same bug wearing a wrapper.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Set, Tuple

from . import astutil
from .core import Finding, ParsedModule, Rule

# the per-request gateway surface (RGWOp verbs): listing is excluded
# by design — ListObjects IS the shard-merge
_REQUEST_OPS = frozenset((
    "put_object", "get_object", "head_object", "delete_object",
    "upload_part", "initiate_multipart", "complete_multipart",
    "abort_multipart"))

_FULL_INDEX_READERS = frozenset(("_read_index",))


def _in_rgw(mod: ParsedModule) -> bool:
    parts = mod.relpath.replace("\\", "/").split("/")[:-1]
    return "rgw" in parts


class FullIndexReadRule(Rule):
    rule_id = "CTL901"
    name = "rgw-full-index-read-on-request-path"
    description = ("per-request gateway op reads the FULL bucket "
                   "index (_read_index whole-object load) instead of "
                   "the key's shard — the hot-bucket serialization "
                   "class index sharding exists to retire; merge all "
                   "shards only on listing/reshard/admin surfaces")

    def __init__(self) -> None:
        super().__init__()
        # (mod, fn, cls) request-op definitions found in rgw/
        self._roots: List[Tuple[ParsedModule, ast.AST]] = []

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        if mod.evidence or not _in_rgw(mod):
            return ()
        for fn, _cls in astutil.walk_functions(mod.tree):
            if fn.name in _REQUEST_OPS:
                self._roots.append((mod, fn))
        return ()

    @staticmethod
    def _full_read_call(fn: ast.AST) -> int:
        """Line of a direct ``*._read_index()`` call inside ``fn``,
        or 0."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _FULL_INDEX_READERS:
                return node.lineno
        return 0

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        graph = astutil.program_graph(self.program) \
            if self.program is not None else None
        for mod, fn in self._roots:
            line = self._full_read_call(fn)
            via = ""
            if not line and graph is not None:
                # interprocedural: the request op REACHES a function
                # that does the full-index read (precise edges only —
                # name-fallback edges would drown rgw/ in noise)
                seen: Set[ast.AST] = graph.reachable([fn])
                for g in seen:
                    if g is fn:
                        continue
                    if getattr(g, "name", "") in _FULL_INDEX_READERS:
                        continue       # the reader itself is legal
                    inner = self._full_read_call(g)
                    if inner:
                        line = fn.lineno
                        via = f" (via {getattr(g, 'name', '?')}())"
                        break
            if line:
                out.append(self.finding(
                    mod, line,
                    f"per-request op {fn.name}() loads the FULL "
                    f"bucket index{via} — one hot bucket serializes "
                    f"every writer and pays O(bucket) per request; "
                    f"read only the key's shard "
                    f"(_read_index_shard)"))
        return out


def register(reg) -> None:
    reg.add(FullIndexReadRule.rule_id, FullIndexReadRule)
