"""CTL8xx — wire-protocol contract closure.

Five daemons speak a ~60-command dict protocol over the messenger,
dispatched by ad-hoc ``if cmd == "...":`` arms (cluster/daemon.py)
— a string-keyed seam with NOTHING tying the two ends together, the
exact surface the reference guards with ceph-dencoder +
ceph-object-corpus round-trip checks.  The failure modes are all
silent: a typo'd command earns an IOError (or nothing) at runtime
under exactly the failure scenario nobody tests; a mutating command
that skips the (session, seq) stamping chokepoint silently loses the
PR-5 at-most-once replay guarantee; a sender that omits a field the
handler subscripts is a KeyError INSIDE the daemon, surfaced to the
client as a generic wire error.  These rules close the protocol
statically, whole-program:

  CTL801  protocol surface closure — every literal ``cmd`` sent from
          client//cluster//rgw/ has a dispatch arm somewhere
          (``cmd == "X"`` or a literal membership test), and every
          arm is sent/exercised by SOMETHING (package, tools,
          scripts, or tests) — a handled-but-never-sent arm is dead
          protocol surface
  CTL802  at-most-once closure — every send of a MUTATING command
          (the daemon's ``_REPLAY_CMDS`` contract, read from the
          tree itself) reaches the messenger through a
          (session, seq)-stamping chokepoint (``osd_call`` /
          ``call_async`` / ``aio_osd_call`` / the daemon's
          ``_peer_req``) or carries an explicit ``session`` stamp
  CTL803  typed-encoding field agreement — keys a sender builds into
          a literal cmd dict must cover every key the handler arm
          SUBSCRIPTS (``req["k"]``; ``req.get`` is optional by
          construction): a short send is a silent KeyError inside
          the daemon
  CTL804  faultpoint grammar closure — every faultpoint name armed
          over the asok ``fault_injection`` grammar or
          ``faults.arm()`` is declared, and every name is declared
          EXACTLY once (a second declare site is doc drift waiting
          to collide at runtime); fire-site closure stays CTL601

Senders are anchored on the send callables (``call`` / ``osd_call``
/ ``call_async`` / ``aio_osd_call`` / ``mon_call`` / ``_peer_req`` /
``_peer_call`` / ``_osd_probe``) with a dict-literal request —
directly or through one ``tracer.stamp(...)`` wrapper.  Handlers are
any function assigning ``<var> = <param>["cmd"]`` (the dispatch
idiom).  Tests count as exercise evidence but never carry findings.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, ParsedModule, Rule
from .rules_faults import _faults_recv
from . import astutil

_SEND_ATTRS = frozenset((
    "call", "osd_call", "call_async", "aio_osd_call", "mon_call",
    "_peer_req", "_peer_call", "_osd_probe"))

# chokepoints that stamp (session, seq) centrally on mutating cmds:
# AsyncObjecter.call_async (osd_call/aio_osd_call route through it)
# and OSDDaemon._peer_req (the daemon's peer-send seam)
_STAMP_CHOKEPOINTS = frozenset((
    "osd_call", "call_async", "aio_osd_call", "_peer_req"))

# the at-most-once contract set when the tree declares none (fixture
# trees); a real tree's _REPLAY_CMDS assignments override this
_DEFAULT_MUTATING = frozenset((
    "put_shard", "put_object", "delete_shard", "delete_object",
    "setattr_shard", "copy_from", "exec_cls"))

_SCOPE_DIRS = frozenset(("client", "cluster", "rgw"))


def _in_scope(mod: ParsedModule) -> bool:
    parts = mod.relpath.replace("\\", "/").split("/")[:-1]
    return any(p in _SCOPE_DIRS for p in parts)


class _Send:
    __slots__ = ("attr", "cmd", "keys", "complete", "has_session",
                 "lineno")

    def __init__(self, attr: str, cmd: Optional[str],
                 keys: Set[str], complete: bool,
                 has_session: bool, lineno: int):
        self.attr = attr
        self.cmd = cmd
        self.keys = keys
        self.complete = complete
        self.has_session = has_session
        self.lineno = lineno


class _Arm:
    __slots__ = ("cmd", "lineno", "required", "fn_name")

    def __init__(self, cmd: str, lineno: int,
                 required: Set[str], fn_name: str):
        self.cmd = cmd
        self.lineno = lineno
        self.required = required
        self.fn_name = fn_name


def _req_dict(call: ast.Call) -> Optional[ast.Dict]:
    """The request dict literal of a send call: a direct Dict
    argument, or a Dict inside ONE wrapping call (the
    ``tracer.stamp({...})`` shape)."""
    for arg in call.args:
        if isinstance(arg, ast.Dict):
            return arg
        if isinstance(arg, ast.Call):
            for inner in arg.args:
                if isinstance(inner, ast.Dict):
                    return inner
    return None


def _dict_shape(d: ast.Dict) -> Tuple[Optional[str], Set[str], bool]:
    """(literal cmd, literal keys, keys-complete) of a request dict.
    ``**spread`` entries or computed keys make the key set open
    (complete=False): CTL803 then stays quiet rather than guessing."""
    cmd = None
    keys: Set[str] = set()
    complete = True
    for k, v in zip(d.keys, d.values):
        if k is None or not isinstance(k, ast.Constant) or \
                not isinstance(k.value, str):
            complete = False
            continue
        keys.add(k.value)
        if k.value == "cmd":
            if isinstance(v, ast.Constant) and \
                    isinstance(v.value, str):
                cmd = v.value
    return cmd, keys, complete


def _collect(mod: ParsedModule):
    """Per-module protocol facts, computed once and shared by every
    CTL8xx rule (the rules_admin/_faults pattern)."""
    cached = mod._cache.get("protocol")
    if cached is not None:
        return cached
    sends: List[_Send] = []
    arms: List[_Arm] = []
    handled: Set[str] = set()
    exercised: Set[str] = set()
    mutating: Set[str] = set()
    for node in ast.walk(mod.tree):
        # literal {"cmd": "X"} ANYWHERE is exercise evidence (tests
        # poking handlers directly, faultpoint match filters, ...)
        if isinstance(node, ast.Dict):
            cmd, _keys, _c = _dict_shape(node)
            if cmd is not None:
                exercised.add(cmd)
            continue
        # the tree's own at-most-once contract declaration
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "_REPLAY_CMDS":
            v = node.value
            if isinstance(v, ast.Call) and v.args:
                v = v.args[0]
            if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and \
                            isinstance(e.value, str):
                        mutating.add(e.value)
            continue
        if not isinstance(node, ast.Call):
            continue
        # a string constant passed DIRECTLY as a call argument is
        # exercise evidence too: parameterized request builders
        # (``self._shard0_probe(oid, "stat_shard")``) send cmds the
        # dict-literal scan cannot see.  Container literals (the
        # _TRACKED_CMDS-style frozensets) deliberately do NOT count.
        for a in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(a, ast.Constant) and \
                    isinstance(a.value, str):
                exercised.add(a.value)
        name = node.func.attr if isinstance(node.func, ast.Attribute) \
            else (node.func.id if isinstance(node.func, ast.Name)
                  else None)
        if name in _SEND_ATTRS:
            d = _req_dict(node)
            if d is not None:
                cmd, keys, complete = _dict_shape(d)
                sends.append(_Send(name, cmd, keys, complete,
                                   "session" in keys, d.lineno))
    # handler arms: any function assigning <var> = <param>["cmd"]
    for fn, _cls in astutil.walk_functions(mod.tree):
        params = {a.arg for a in fn.args.posonlyargs + fn.args.args}
        cmd_var = req_var = None
        for node in fn.body:
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    isinstance(node.value, ast.Subscript) and \
                    isinstance(node.value.value, ast.Name) and \
                    node.value.value.id in params and \
                    isinstance(node.value.slice, ast.Constant) and \
                    node.value.slice.value == "cmd":
                cmd_var = node.targets[0].id
                req_var = node.value.value.id
                break
        if cmd_var is None:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.If):
                continue
            for cmp in ast.walk(node.test):
                if not (isinstance(cmp, ast.Compare) and
                        isinstance(cmp.left, ast.Name) and
                        cmp.left.id == cmd_var and
                        len(cmp.ops) == 1):
                    continue
                rhs = cmp.comparators[0]
                if isinstance(cmp.ops[0], ast.Eq) and \
                        isinstance(rhs, ast.Constant) and \
                        isinstance(rhs.value, str):
                    required: Set[str] = set()
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Subscript) and \
                                isinstance(sub.value, ast.Name) and \
                                sub.value.id == req_var and \
                                isinstance(sub.ctx, ast.Load) and \
                                isinstance(sub.slice, ast.Constant) \
                                and isinstance(sub.slice.value, str):
                            required.add(sub.slice.value)
                    required.discard("cmd")
                    arms.append(_Arm(rhs.value, node.lineno,
                                     required, fn.name))
                    handled.add(rhs.value)
                elif isinstance(cmp.ops[0], ast.In) and \
                        isinstance(rhs, (ast.Tuple, ast.List,
                                         ast.Set)):
                    for e in rhs.elts:
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, str):
                            handled.add(e.value)
    cached = (sends, arms, handled, exercised, mutating)
    mod._cache["protocol"] = cached
    return cached


class _ProtocolBase(Rule):
    def __init__(self) -> None:
        super().__init__()
        # (mod, send) for reportable scope; global cross-reference
        self.scope_sends: List[Tuple[ParsedModule, _Send]] = []
        self.arms: List[Tuple[ParsedModule, _Arm]] = []
        self.handled: Set[str] = set()
        self.sent_or_exercised: Set[str] = set()
        self.mutating: Set[str] = set()

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        sends, arms, handled, exercised, mutating = _collect(mod)
        self.handled.update(handled)
        self.sent_or_exercised.update(exercised)
        self.sent_or_exercised.update(
            s.cmd for s in sends if s.cmd is not None)
        self.mutating.update(mutating)
        if not mod.evidence:
            self.arms.extend((mod, a) for a in arms)
            if _in_scope(mod):
                self.scope_sends.extend((mod, s) for s in sends)
        return ()


class ProtocolClosureRule(_ProtocolBase):
    rule_id = "CTL801"
    name = "wire-cmd-closure"
    description = ("wire cmd sent with no dispatch arm anywhere "
                   "(silent 'unknown command' under the one scenario "
                   "nobody tests), or a dispatch arm nothing ever "
                   "sends — dead protocol surface")

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for mod, s in self.scope_sends:
            if s.cmd is not None and s.cmd not in self.handled:
                out.append(self.finding(
                    mod, s.lineno,
                    f"wire cmd {s.cmd!r} is sent here but no daemon/"
                    f"mon dispatch arm handles it — the send can "
                    f"only ever fail"))
        for mod, a in self.arms:
            if a.cmd not in self.sent_or_exercised:
                out.append(self.finding(
                    mod, a.lineno,
                    f"dispatch arm for {a.cmd!r} in {a.fn_name}() is "
                    f"handled but never sent by any client, tool, "
                    f"script, or test — dead protocol surface (or "
                    f"missing coverage)"))
        return out


class MutatingStampRule(_ProtocolBase):
    rule_id = "CTL802"
    name = "wire-mutation-unstamped"
    description = ("mutating wire cmd sent outside the (session, seq)"
                   "-stamping chokepoints (osd_call / call_async / "
                   "aio_osd_call / _peer_req) with no explicit "
                   "session stamp: the at-most-once replay contract "
                   "is silently absent on this path")

    def finish(self) -> Iterable[Finding]:
        mutating = self.mutating or set(_DEFAULT_MUTATING)
        out: List[Finding] = []
        for mod, s in self.scope_sends:
            if s.cmd in mutating and \
                    s.attr not in _STAMP_CHOKEPOINTS and \
                    not s.has_session:
                out.append(self.finding(
                    mod, s.lineno,
                    f"mutating cmd {s.cmd!r} sent through raw "
                    f"{s.attr}() without a (session, seq) stamp — a "
                    f"reconnect retry can apply it twice; route "
                    f"through osd_call/call_async/_peer_req or stamp "
                    f"explicitly"))
        return out


class FieldAgreementRule(_ProtocolBase):
    rule_id = "CTL803"
    name = "wire-field-agreement"
    description = ("literal cmd dict omits a key EVERY handler arm "
                   "of that cmd subscripts (req['k']) — a silent "
                   "KeyError inside the daemon; req.get() keys are "
                   "optional by construction")

    def finish(self) -> Iterable[Finding]:
        by_cmd: Dict[str, List[Set[str]]] = {}
        for _mod, a in self.arms:
            by_cmd.setdefault(a.cmd, []).append(a.required)
        out: List[Finding] = []
        for mod, s in self.scope_sends:
            if s.cmd is None or not s.complete:
                continue
            reqs = by_cmd.get(s.cmd)
            if not reqs:
                continue
            # a send is broken only when EVERY arm of the cmd has a
            # req[...] key the sender omits (multi-daemon cmds:
            # satisfying one daemon's arm is legitimate); report the
            # closest arm's missing keys as the minimal fix
            missings = [r - s.keys for r in reqs]
            if all(missings):
                best = min(missings,
                           key=lambda m: (len(m), sorted(m)))
                out.append(self.finding(
                    mod, s.lineno,
                    f"cmd {s.cmd!r} sent without key(s) "
                    f"{sorted(best)} that the handler arm reads "
                    f"with req[...] — this send is a guaranteed "
                    f"KeyError inside the daemon"))
        return out


class FaultpointGrammarRule(Rule):
    rule_id = "CTL804"
    name = "faultpoint-grammar-closure"
    description = ("faultpoint name armed (faults.arm / asok "
                   "fault_injection grammar) but never declared, or "
                   "declared more than once — the registry contract "
                   "is one declare site per name, where the fire "
                   "lives")

    def __init__(self) -> None:
        super().__init__()
        self.declares: Dict[str, List[Tuple[str, int]]] = {}
        self.armed: Dict[str, List[Tuple[str, int]]] = {}
        self.evidence_declares: Set[str] = set()

    def check_module(self, mod: ParsedModule) -> Iterable[Finding]:
        aliases = astutil.aliases_of(mod)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Dict):
                # asok grammar: {"prefix": "fault_injection",
                #                "name": "X", ...}
                kv = {k.value: v for k, v in zip(node.keys,
                                                 node.values)
                      if isinstance(k, ast.Constant)}
                pref = kv.get("prefix")
                nm = kv.get("name")
                if isinstance(pref, ast.Constant) and \
                        pref.value == "fault_injection" and \
                        isinstance(nm, ast.Constant) and \
                        isinstance(nm.value, str) and \
                        not mod.evidence:
                    self.armed.setdefault(nm.value, []).append(
                        (mod.relpath, node.lineno))
                continue
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr not in ("declare", "arm"):
                continue
            if not _faults_recv(node.func.value, aliases):
                continue
            if not (node.args and
                    isinstance(node.args[0], ast.Constant) and
                    isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            if node.func.attr == "declare":
                if mod.evidence:
                    self.evidence_declares.add(name)
                else:
                    self.declares.setdefault(name, []).append(
                        (mod.relpath, node.lineno))
            elif not mod.evidence:
                self.armed.setdefault(name, []).append(
                    (mod.relpath, node.lineno))
        return ()

    def finish(self) -> Iterable[Finding]:
        out: List[Finding] = []
        for name, sites in sorted(self.declares.items()):
            for path, line in sites[1:]:
                out.append(Finding(
                    self.rule_id, path, line,
                    f"faultpoint {name!r} declared more than once "
                    f"(first at {sites[0][0]}) — one declare site "
                    f"per name, next to its fire"))
        known = set(self.declares) | self.evidence_declares
        for name, sites in sorted(self.armed.items()):
            if name in known:
                continue
            for path, line in sites:
                out.append(Finding(
                    self.rule_id, path, line,
                    f"faultpoint {name!r} is armed here but no "
                    f"faults.declare() site declares it — arming "
                    f"raises FaultError at runtime"))
        return out


def register(reg) -> None:
    reg.add(ProtocolClosureRule.rule_id, ProtocolClosureRule)
    reg.add(MutatingStampRule.rule_id, MutatingStampRule)
    reg.add(FieldAgreementRule.rule_id, FieldAgreementRule)
    reg.add(FaultpointGrammarRule.rule_id, FaultpointGrammarRule)
