"""Socket wire protocol — typed envelopes between daemon processes.

The process-boundary transport of the messenger (the AsyncMessenger /
Protocol V2 role, src/msg/async/ProtocolV2.cc): length-prefixed,
CRC-protected frames carrying the same typed envelopes the in-process
queues move, over unix-domain or TCP sockets.  Kept deliberately small:
banner exchange, an authentication frame (ceph_tpu.common.auth — the
cephx handshake role), then framed request/reply.

Frame:  u32 magic | u32 type | u64 id | i32 shard | u32 len |
        u32 crc(wire_payload) | wire_payload
Secure mode (every frame after the auth handshake, Protocol V2's
crypto_onwire role, src/msg/async/crypto_onwire.cc): the payload is a
SEALED BOX under the session key (PRF-CTR encryption, encrypt-then-MAC
— common/auth.seal), so traffic is unreadable on the socket, plus a
32-byte HMAC-SHA256 trailer over header+ciphertext so the plaintext
header cannot be tampered with either.  Pre-auth frames (banner,
nonce, auth blobs) are plaintext by necessity; secrets inside them are
themselves sealed under entity keys.
"""
from __future__ import annotations

import hmac
import socket
import struct
import threading
import time
import zlib
from typing import Optional

from ..common import crcutil, faults
from .queue import Envelope

# messenger-frame faultpoints (the qa msgr-failures suite axes): armed
# by the thrasher / fault_injection admin command, never in production
faults.declare("wire.drop_frame",
               "drop an outbound frame before any byte hits the "
               "socket (connection torn down, peer sees a clean "
               "close) — the ms_inject_socket_failures send half")
faults.declare("wire.truncate_frame",
               "send only the first half of a frame, then tear the "
               "connection down — the peer's length-prefixed read "
               "unblocks with WireClosed when the socket dies")
faults.declare("wire.flip_bit",
               "flip one bit in the last byte of the assembled frame "
               "(payload crc in plaintext mode, MAC trailer in secure "
               "mode) — the receiver must REJECT the frame, never "
               "deliver corrupt bytes")

MAGIC = 0x43455054        # "CEPT"
BANNER = b"ceph-tpu v1\n"
_FHDR = struct.Struct("<IIQiII")
_U32 = struct.Struct("<I")
_MAC_LEN = 32
# unauthenticated peers control the length field: cap it so a forged
# header cannot make _recv_exact buffer gigabytes pre-auth (the
# Throttle/ms_max_message_size role)
MAX_FRAME = 256 << 20

# message types (the protocol's canonical home; cluster/daemon.py
# aliases these for its handshake/dispatch code)
MSG_AUTH_NONCE = 0x01
MSG_AUTH_SECRET = 0x02       # secret-mode proof
MSG_AUTH_TICKET = 0x03       # ticket-mode (ticket + authorizer)
MSG_AUTH_OK = 0x04
MSG_AUTH_FAIL = 0x05
MSG_REQ = 0x10               # typed-encoded {"cmd": ..., ...}
MSG_REPLY = 0x11
MSG_ERR = 0x12
MSG_REQ_SG = 0x13            # scatter-gather request: u32 metalen |
#                              encoded meta dict | raw data payload —
#                              bulk bytes never pass through the typed
#                              encoder (zero intermediate copies)
MSG_SET_MODE = 0x14          # authenticated per-connection downgrade
#                              to "crc" data mode (the reference's
#                              ms_mode crc vs secure negotiation)
MSG_SHM_ATTACH = 0x15        # same-host shared-memory ring handoff:
#                              the client asks the daemon to map its
#                              ring file; subsequent requests may then
#                              carry payloads out-of-band with only a
#                              doorbell (meta + ring extent + crc)
#                              crossing the socket (msg/shm_ring.py)

# per-connection data modes after the auth handshake (the reference's
# ms_cluster_mode / ms_client_mode values, src/msg/msg_types.h):
#   secure — payload sealed (PRF-CTR + MAC): confidentiality + integrity
#   crc    — payload plaintext but hdr+payload HMAC'd under the session
#            key: integrity/authenticity only, the reference's DEFAULT
#            for intra-cluster traffic (and ~10x cheaper per byte on
#            stdlib-crypto hosts, which is what lets the multi-stream
#            data path reach device-adjacent rates)
MODE_SECURE = "secure"
MODE_CRC = "crc"


class WireError(IOError):
    pass


class WireClosed(WireError):
    pass


# cached ZeroWire config flags (common/crcutil.flag, observer-refreshed
# — the hot path must not pay a layered-options lookup per frame):
# wire_one_pass gates the sub-crc/combine integrity scan, wire_zero_copy
# the buffer-view spine (both default True; the bench's "before" phases
# flip them to price the legacy 3-pass/copying path against the same
# daemons)
_opt = crcutil.flag


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    # recv_into a preallocated buffer: bulk payloads land in place
    # (one allocation, no per-chunk copies) — on the multi-stream
    # data path this is a per-byte cost, not a nicety
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise WireClosed("peer closed")
        got += r
    return bytes(buf)  # noqa: CTL130 — pre-auth handshake frames
    # only (banner/nonce/auth blobs): small and off the data path


_IOV_MAX = 1024      # POSIX sysconf(_SC_IOV_MAX) floor; sendmsg with
                     # more iovecs fails EMSGSIZE, and a greedy batch
                     # drain of a deep window can exceed it


def _sendmsg_all(sock: socket.socket, parts) -> None:
    """sendall over a scatter-gather buffer list: one syscall per
    window, partial sends resumed without re-joining the parts."""
    bufs = [memoryview(p) for p in parts if len(p)]
    while bufs:
        sent = sock.sendmsg(bufs[:_IOV_MAX])
        while bufs and sent >= len(bufs[0]):
            sent -= len(bufs[0])
            bufs.pop(0)
        if sent and bufs:
            bufs[0] = bufs[0][sent:]


def _frame_parts(env_type: int, env_id: int, shard: int, parts,
                 session_key: Optional[bytes],
                 mode: str, data_csums=None) -> list:
    """Assemble one frame as a buffer list: header | payload [| mac].
    Per-byte integrity is mode-priced the way the reference prices
    ms_mode: secure seals and MACs every payload byte; crc mode runs
    one crc32 pass (C speed) and binds the digest into the header,
    whose HMAC is then constant-cost — the payload never feeds SHA256,
    which is the difference between ~150 MiB/s and line rate on a
    syscall-priced host.  Plaintext (no session key) is crc-only.

    ``data_csums`` (a crcutil.Csums for the LAST part — the bulk data
    segment) is the one-pass handoff: its combined sub-crcs are FOLDED
    into the frame crc via crc32_combine, so a payload whose csums are
    already known (device crc kernel, staging digest, content cache)
    crosses the sender with ZERO crc scans.  The wire value is
    bit-identical to a whole-payload zlib.crc32 — receivers cannot
    tell the difference."""
    crc = 0
    if session_key is not None and mode == MODE_SECURE:
        from ..common.auth import seal_parts
        parts = seal_parts(session_key, parts)
    elif data_csums is not None and parts and \
            data_csums.length == len(parts[-1]) and _opt("wire_one_pass"):
        for p in parts[:-1]:
            crc = zlib.crc32(p, crc)
            crcutil.note_scan(len(p), "send")
        crc = crcutil.crc32_combine(crc, data_csums.combined,
                                    data_csums.length)
    else:
        for p in parts:
            crc = zlib.crc32(p, crc)
            crcutil.note_scan(len(p), "send")
    total = sum(len(p) for p in parts)
    hdr = _FHDR.pack(MAGIC, env_type, env_id, shard, total, crc)
    if session_key is None:
        return [hdr] + list(parts)
    mac = hmac.new(session_key, hdr, "sha256")
    if mode == MODE_SECURE:
        for p in parts:
            mac.update(p)
    return [hdr] + list(parts) + [mac.digest()]


def prepare_frame(sock: socket.socket, env_type: int, env_id: int,
                  shard: int, parts,
                  session_key: Optional[bytes], mode: str,
                  src: Optional[str], dst: Optional[str],
                  data_csums=None) -> list:
    """Per-frame assembly with every wire faultpoint applied; returns
    the frame's buffer list WITHOUT sending it, so callers (the
    stream sender, the server's reply batching) can coalesce many
    frames into one sendmsg.  A fired drop/truncate raises exactly as
    the unbatched path did (truncate pushes its half-frame first)."""
    if src is not None and dst is not None and \
            faults.partitioned(src, dst):
        raise WireClosed(f"fault injected: {src} -> {dst} partitioned")
    blobs = _frame_parts(env_type, env_id, shard, parts,
                         session_key, mode, data_csums=data_csums)
    if faults.fire("wire.drop_frame", type=env_type) is not None:
        raise WireClosed("fault injected: frame dropped before send")
    if faults.fire("wire.truncate_frame", type=env_type) is not None:
        whole = b"".join(bytes(p) for p in blobs)  # noqa: CTL130 —
        # fault path only: the half-frame join never runs in production
        sock.sendall(whole[:max(1, len(whole) // 2)])
        raise WireClosed("fault injected: frame truncated mid-send")
    if faults.fire("wire.flip_bit", type=env_type) is not None:
        # last non-empty blob: MAC trailer (MAC'd frames), crc-covered
        # payload tail (plaintext), or the header itself when the
        # plaintext payload is empty — rejection every way
        for bi in range(len(blobs) - 1, -1, -1):
            tail = bytes(blobs[bi])
            if tail:
                blobs[bi] = tail[:-1] + bytes([tail[-1] ^ 0x01])
                break
    return blobs


def _send_parts(sock: socket.socket, env_type: int, env_id: int,
                shard: int, parts,
                session_key: Optional[bytes],
                mode: str,
                src: Optional[str], dst: Optional[str],
                data_csums=None) -> None:
    _sendmsg_all(sock, prepare_frame(sock, env_type, env_id, shard,
                                     parts, session_key, mode,
                                     src, dst, data_csums=data_csums))


def send_frame(sock: socket.socket, env: Envelope,
               session_key: Optional[bytes] = None,
               src: Optional[str] = None,
               dst: Optional[str] = None,
               mode: str = MODE_SECURE) -> None:
    """``src``/``dst`` are the sending/receiving entity names, passed
    by callers that know them (WireClient requests, WireServer
    replies): an armed ``net.partition`` that severs src -> dst drops
    the frame before any byte hits the socket — per-direction, so a
    oneway cut can deliver the request yet drop the reply (the
    half-open-link shape the session-replay machinery must absorb).
    ``mode`` applies only when a session key is present: "secure"
    seals the payload, "crc" sends it plaintext with a crc32 bound
    into the HMAC-authenticated header (constant-cost MAC)."""
    _send_parts(sock, env.type, env.id, env.shard,
                [env.payload or b""], session_key, mode, src, dst)


def send_frame_sg(sock: socket.socket, env_type: int, env_id: int,
                  meta: bytes, data,
                  session_key: Optional[bytes] = None,
                  src: Optional[str] = None,
                  dst: Optional[str] = None,
                  mode: str = MODE_SECURE,
                  data_csums=None) -> None:
    """Scatter-gather frame: typed-encoded ``meta`` plus a raw bulk
    ``data`` buffer shipped as separate segments of ONE frame
    (u32 metalen | meta | data), so multi-MB shard payloads go from
    their staging buffers to the socket without passing through the
    typed encoder or any intermediate join (crc mode: zero copies;
    secure mode: single cipher+MAC pass via auth.seal_parts).
    ``data_csums`` (crcutil.Csums of ``data``) folds precomputed
    sub-crcs into the frame crc instead of re-scanning."""
    _send_parts(sock, env_type, env_id, -1,
                [_U32.pack(len(meta)), meta, data],
                session_key, mode, src, dst, data_csums=data_csums)


def split_sg(payload):
    """Inverse of the SG payload layout: -> (meta_bytes, data).

    ``data`` is a zero-copy memoryview over the received frame buffer
    (the buffer stays alive as long as the view does — Python buffer
    semantics carry the lifetime); the meta prefix is materialized
    because the typed decoder wants bytes and it is ~100 bytes.  With
    ``wire_zero_copy`` off the legacy whole-payload copy runs and is
    COUNTED (copies/MiB in the bench decomposition)."""
    mv = crcutil.as_u8(payload)
    if len(mv) < 4:
        raise WireError("SG frame truncated")
    (mlen,) = _U32.unpack_from(mv, 0)
    if 4 + mlen > len(mv):
        raise WireError("SG meta length exceeds frame")
    data = mv[4 + mlen:]
    if not _opt("wire_zero_copy"):
        crcutil.note_copy(len(data), "split_sg")
        data = bytes(data)  # noqa: CTL130 — the counted legacy path
    return bytes(mv[4:4 + mlen]), data


# bulk payloads at/above this ride a scatter-gather frame: below it
# the typed encoder re-buffers anyway and the SG framing overhead
# dominates.  ONE constant shared by both senders (the async
# objecter's client streams and the daemon's peer client) — the
# zero-copy view contract relies on every sender agreeing on it.
SG_MIN = 1024


def extract_bulk(req, site: str):
    """Split a bulk ``data`` payload (and its precomputed ``_csums``)
    out of a request dict for the scatter-gather frame tail; returns
    (req, data|None, csums|None).  Zero-copy: the payload buffer
    (bytes, bytearray or memoryview — staged numpy shards arrive as
    views) goes to the frame assembly UNTOUCHED; with
    ``wire_zero_copy`` off the legacy materialization runs and is
    COUNTED at ``site``.  Sub-SG_MIN payloads ride the typed encoder
    (memoryviews materialized — tiny by definition) and drop their
    ``_csums`` (not wire-encodable, and the scan saved is tiny)."""
    payload = req.get("data") if isinstance(req, dict) else None
    if isinstance(payload, (bytes, bytearray, memoryview)) and \
            len(payload) >= SG_MIN:
        req = dict(req)
        data = req.pop("data")
        csums = req.pop("_csums", None)
        if not _opt("wire_zero_copy") and not isinstance(data, bytes):
            crcutil.note_copy(len(data), site)
            data = bytes(data)  # noqa: CTL130 — counted legacy path
        return req, data, csums
    if isinstance(req, dict) and ("_csums" in req or
                                  isinstance(payload, memoryview)):
        req = dict(req)
        req.pop("_csums", None)
        if isinstance(payload, memoryview):
            req["data"] = bytes(payload)  # noqa: CTL130 — sub-SG_MIN
            # payloads ride the typed encoder, which re-buffers
            # anyway (tiny by definition)
    return req, None, None


def _parse_frame(hdr: bytes, payload, mac: Optional[bytes],
                 session_key: Optional[bytes],
                 mode: str) -> Envelope:
    """Verify one received frame (crc / MAC / unseal) — shared by the
    raw-socket recv_frame and the buffered SockReader.

    One-pass integrity (ZeroWire): for a scatter-gather request the
    verify scan runs per 4-KiB sub-block of the data segment and the
    sub-crcs are COMBINED (crc32_combine) against the header crc —
    same accept/reject verdict as a whole-payload crc32, but the
    sub-crcs survive the verify as TRUSTED values on the returned
    envelope, which the daemon hands to BlueStore as ready-made blob
    csums: the store never scans payload bytes again."""
    magic, typ, mid, shard, ln, crc = _FHDR.unpack(hdr)
    csums = None
    if crc and typ == MSG_REQ_SG and _opt("wire_one_pass"):
        mv = crcutil.as_u8(payload)
        if len(mv) < 4:
            raise WireError("payload crc mismatch")
        (mlen,) = _U32.unpack_from(mv, 0)
        dstart = 4 + mlen
        if dstart > len(mv):
            raise WireError("payload crc mismatch")
        head_crc = zlib.crc32(mv[:dstart])
        crcutil.note_scan(dstart, "verify")
        csums = crcutil.Csums.scan(mv[dstart:],
                                   block=crcutil.CSUM_BLOCK,
                                   site="verify")
        got = crcutil.crc32_combine(head_crc, csums.combined,
                                    csums.length)
        if got != crc:
            raise WireError("payload crc mismatch")
    elif crc:
        if zlib.crc32(payload) != crc:
            raise WireError("payload crc mismatch")
        crcutil.note_scan(len(payload), "verify")
    if session_key is not None:
        # the MAC covers the header always (which binds the crc field,
        # hence the payload, in crc mode) and the payload bytes only
        # in secure mode — mirror of _frame_parts' pricing
        want = hmac.new(session_key, hdr, "sha256")
        if mode == MODE_SECURE:
            want.update(payload)
        if mac is None or not hmac.compare_digest(mac, want.digest()):
            raise WireError("frame MAC rejected")
        if mode == MODE_SECURE:
            from ..common.auth import AuthError, unseal
            try:
                payload = unseal(session_key, bytes(payload))  # noqa: CTL130
                # — secure mode decrypts into fresh bytes by nature;
                # zero-copy applies to the crc data mode
            except AuthError as e:
                raise WireError(f"secure payload rejected: {e}")
    return Envelope(typ, mid, shard, payload, csums)


def _check_hdr(hdr: bytes) -> int:
    magic, typ, mid, shard, ln, crc = _FHDR.unpack(hdr)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic:#x}")
    if ln > MAX_FRAME:
        raise WireError(f"frame length {ln} exceeds cap {MAX_FRAME}")
    return ln


def recv_frame(sock: socket.socket,
               session_key: Optional[bytes] = None,
               mode: str = MODE_SECURE) -> Envelope:
    hdr = _recv_exact(sock, _FHDR.size)
    ln = _check_hdr(hdr)
    payload = _recv_exact(sock, ln) if ln else b""
    mac = _recv_exact(sock, _MAC_LEN) if session_key is not None \
        else None
    return _parse_frame(hdr, payload, mac, session_key, mode)


class SockReader:
    """Buffered frame reader over one socket.

    On hosts where every syscall is expensive (sandboxed kernels —
    exactly where this repo's daemons run in CI), reading one frame
    as hdr/payload/mac recv calls costs three syscalls per frame;
    under a pipelined stream most of those frames are ALREADY in the
    kernel buffer.  This reader pulls large chunks and parses frames
    out of its own buffer: one recv can yield a whole window of
    pipelined frames (and ``try_frame`` drains them with no syscall
    at all, which is what lets a server batch its replies).

    A socket timeout mid-frame leaves the partial bytes buffered;
    the next read resumes where it stopped (the raw ``_recv_exact``
    path would have dropped them)."""

    # one recv per window, not per frame: sized to the 2 MiB kernel
    # buffers the streams set, so a full bulk frame (or several) lands
    # in ONE syscall — at ~1 ms/syscall a 256 KiB chunk made every
    # 1 MiB frame cost four recvs before any byte was parsed
    CHUNK = 1 << 21

    # payloads at/above this size take the DIRECT path: recv_into a
    # dedicated exact-size buffer handed out as a zero-copy memoryview
    # (no scratch->buf append, no _take materialization — the two
    # avoidable copies the legacy reader charged every bulk byte)
    BIG = 1 << 16

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._buf = bytearray()
        self._pos = 0
        # persistent recv_into target: recv(CHUNK) would allocate (and
        # mmap) CHUNK bytes per call even for a 100-byte reply frame.
        # Starts small so the many control connections don't each pin
        # 2 MiB; the first bulk frame upgrades it to CHUNK for good.
        self._scratch = bytearray(1 << 16)
        # a direct big-frame read interrupted by a socket timeout
        # parks here and resumes on the next read_frame call (the
        # buffered path gets the same resume property from _buf)
        self._partial: Optional[tuple] = None

    def _avail(self) -> int:
        return len(self._buf) - self._pos

    def _fill(self, want: int) -> None:
        """Grow the buffer to at least ``want`` available bytes."""
        while self._avail() < want:
            if self._pos and self._pos >= (1 << 20):
                del self._buf[:self._pos]
                self._pos = 0
            if want - self._avail() > len(self._scratch):
                self._scratch = bytearray(self.CHUNK)
            r = self.sock.recv_into(self._scratch)
            if not r:
                raise WireClosed("peer closed")
            self._buf += memoryview(self._scratch)[:r]

    def _take(self, n: int) -> bytes:
        out = bytes(self._buf[self._pos:self._pos + n])
        self._pos += n
        if self._pos == len(self._buf):
            self._buf.clear()
            self._pos = 0
        return out

    def _take_view(self, n: int):
        """Zero-copy take: hand out a memoryview over the CURRENT
        buffer and retire it (a bytearray with an exported buffer can
        never be resized, so the reader starts a fresh one seeded
        with the few bytes that followed this frame — those would
        have been copied by their own _take anyway)."""
        old = self._buf
        view = memoryview(old)[self._pos:self._pos + n]
        self._buf = bytearray(memoryview(old)[self._pos + n:])
        self._pos = 0
        return view

    def _frame_len(self, with_mac: bool) -> Optional[int]:
        """Total length of the next frame if its header is buffered
        (validates it), else None."""
        if self._avail() < _FHDR.size:
            return None
        hdr = bytes(self._buf[self._pos:self._pos + _FHDR.size])
        ln = _check_hdr(hdr)
        return _FHDR.size + ln + (_MAC_LEN if with_mac else 0)

    def try_frame(self, session_key: Optional[bytes] = None,
                  mode: str = MODE_SECURE) -> Optional[Envelope]:
        """Parse one frame ENTIRELY from the buffer; None when the
        next frame is absent or incomplete (never a syscall)."""
        total = self._frame_len(session_key is not None)
        if total is None or self._avail() < total:
            return None
        return self._consume(session_key, mode)

    def read_frame(self, session_key: Optional[bytes] = None,
                   mode: str = MODE_SECURE) -> Envelope:
        """Blocking read of one frame (buffered; bulk payloads land
        DIRECTLY in a dedicated buffer — one recv-side copy total,
        handed out as a zero-copy view)."""
        if self._partial is not None:
            hdr, buf, got = self._partial
            return self._finish_big(hdr, buf, got, session_key, mode)
        self._fill(_FHDR.size)
        total = self._frame_len(session_key is not None)
        ln = total - _FHDR.size - \
            (_MAC_LEN if session_key is not None else 0)
        if ln >= self.BIG and _opt("wire_zero_copy"):
            hdr = self._take(_FHDR.size)
            buf = bytearray(ln)
            mv = memoryview(buf)
            have = min(self._avail(), ln)
            if have:
                mv[:have] = memoryview(self._buf)[
                    self._pos:self._pos + have]
                self._pos += have
                if self._pos == len(self._buf):
                    self._buf.clear()
                    self._pos = 0
            return self._finish_big(hdr, buf, have, session_key, mode)
        self._fill(total)
        return self._consume(session_key, mode)

    def _finish_big(self, hdr: bytes, buf: bytearray, got: int,
                    session_key: Optional[bytes],
                    mode: str) -> Envelope:
        """Drain the rest of a direct big-frame read; a socket timeout
        parks the partial state for the next call (the stream reader's
        idle/stall loop relies on resumability)."""
        mv = memoryview(buf)
        try:
            while got < len(buf):
                r = self.sock.recv_into(mv[got:])
                if not r:
                    raise WireClosed("peer closed")
                got += r
            mac = None
            if session_key is not None:
                self._fill(_MAC_LEN)
        except socket.timeout:
            self._partial = (hdr, buf, got)
            raise
        self._partial = None
        if session_key is not None:
            mac = self._take(_MAC_LEN)
        return _parse_frame(hdr, mv, mac, session_key, mode)

    def _consume(self, session_key: Optional[bytes],
                 mode: str) -> Envelope:
        hdr = self._take(_FHDR.size)
        ln = _FHDR.unpack(hdr)[4]
        if ln >= self.BIG and _opt("wire_zero_copy"):
            # whole frame already buffered (pipelined window): hand
            # out a view instead of materializing the payload
            payload = self._take_view(ln)
        elif ln:
            payload = self._take(ln)
            if ln >= self.BIG:
                crcutil.note_copy(ln, "reader")
        else:
            payload = b""
        mac = self._take(_MAC_LEN) if session_key is not None \
            else None
        return _parse_frame(hdr, payload, mac, session_key, mode)


def exchange_banners(sock: socket.socket) -> None:
    sock.sendall(BANNER)
    got = _recv_exact(sock, len(BANNER))
    if got != BANNER:
        raise WireError(f"bad banner {got!r}")


def raise_reply_error(payload: bytes) -> None:
    """Re-raise a MSG_ERR payload as the matching client-side
    exception (shared by the blocking WireClient and the async
    streams, so both paths surface identical error types)."""
    from . import encoding
    from ..common import auth as _cx
    name, msg = encoding.loads(payload)
    exc = {"IOError": IOError, "OSError": IOError,
           "KeyError": KeyError,
           "AuthError": _cx.AuthError,
           "PermissionError": PermissionError,
           "ClsError": IOError,
           "ObjectStoreError": IOError}.get(name, RuntimeError)
    raise exc(f"{name}: {msg}")


# ------------------------------------------------------------- streams ---

class Stream:
    """One PIPELINED framed connection — the async half of the
    messenger (AsyncConnection role): a bounded send window feeding a
    sender thread (frame assembly + crypto runs there, so N streams
    give N concurrent crypto lanes off the submitter's thread) and a
    reader thread matching replies to pending completions by frame id.
    Submissions never wait for replies; completions are delivered as
    ``cb(result, exc)`` callbacks from the reader thread.

    Built OVER an authenticated connection (a WireClient that finished
    its handshake): per-stream framing, faultpoints and the
    net.partition src/dst checks are exactly the blocking path's.  If
    ``mode`` is "crc" the stream performs the authenticated
    MSG_SET_MODE downgrade before pipelining begins.
    """

    def __init__(self, conn, mode: str = MODE_SECURE,
                 window: int = 16, ring=None):
        import queue as _queue
        from ..common.lockdep import LockdepLock
        self._conn = conn                  # owns the socket lifetime
        self.sock = conn.sock
        self.key = conn.key
        self.entity = conn.entity
        self.peer = getattr(conn, "peer", None)
        self.mode = MODE_SECURE
        self.ring_ok = False
        self.dead = False
        # True while the sender thread is inside sendmsg: a full
        # window + a socket-blocked sender means the PEER is the
        # bottleneck (the pool must not spill to more streams); a
        # full window with the sender in crypto/assembly means this
        # lane's CPU is, and a second lane genuinely helps
        self.sending = False
        self._id = 0
        self._lock = LockdepLock("wire.stream", recursive=False)
        self._pending = {}                 # id -> (cb, t_submit)
        self._sendq = _queue.Queue(maxsize=max(1, window))
        self._stall_s = (self.sock.gettimeout() or 30.0) * 2.0
        # deep kernel buffers: a pipelined stream must absorb a full
        # window of bulk frames without blocking the sender mid-batch
        for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
            try:
                self.sock.setsockopt(socket.SOL_SOCKET, opt, 1 << 21)
            except OSError:
                pass
        if mode == MODE_CRC:
            self._negotiate_crc()
        if ring is not None:
            self._attach_ring(ring)
        self._sender = threading.Thread(
            target=self._sender_loop, daemon=True,
            name=f"stream-send-{self.peer}")
        self._reader = threading.Thread(
            target=self._reader_loop, daemon=True,
            name=f"stream-recv-{self.peer}")
        self._sender.start()
        self._reader.start()

    # ------------------------------------------------------ handshake --
    def _negotiate_crc(self) -> None:
        """Authenticated downgrade to crc data mode: the request and
        its ack travel sealed+MAC'd, so a middle box cannot forge the
        downgrade; only then do frames switch to crc'd plaintext
        under header-only HMAC."""
        from . import encoding
        send_frame(self.sock, Envelope(
            MSG_SET_MODE, 0, -1,
            encoding.dumps({"mode": MODE_CRC})),
            session_key=self.key, src=self.entity, dst=self.peer)
        env = recv_frame(self.sock, session_key=self.key)
        if env.type != MSG_REPLY:
            raise WireError("mode negotiation rejected")
        self.mode = MODE_CRC

    def _attach_ring(self, ring) -> None:
        """Shared-memory lane negotiation (the session_hello-time
        handoff): ask the daemon to map this client's ring file.  The
        request and ack ride the authenticated connection, so only
        the cephx-verified peer learns the path.  A daemon that
        refuses (shm disabled, foreign path) leaves the stream on the
        pure socket lane — fallback is per-stream and silent."""
        from . import encoding
        send_frame(self.sock, Envelope(
            MSG_SHM_ATTACH, 0, -1,
            encoding.dumps({"path": ring.path, "size": ring.size})),
            session_key=self.key, src=self.entity, dst=self.peer,
            mode=self.mode)
        env = recv_frame(self.sock, session_key=self.key,
                         mode=self.mode)
        self.ring_ok = env.type == MSG_REPLY and \
            bool(encoding.loads(bytes(env.payload)).get("ok"))

    # --------------------------------------------------------- submit --
    def inflight(self) -> int:
        with self._lock:
            return len(self._pending)

    def submit(self, req_meta: bytes, data=None, cb=None,
               csums=None) -> None:
        """Queue one request frame (blocks only on the send window).
        ``req_meta`` is the typed-encoded request dict; ``data``, when
        given, rides the scatter-gather tail (MSG_REQ_SG) straight
        from its buffer; ``csums`` (crcutil.Csums of ``data``) lets
        the sender fold precomputed sub-crcs into the frame crc
        instead of re-scanning.  ``cb(result, exc)`` fires from the
        reader thread on reply, or with the error that killed the
        stream."""
        with self._lock:
            if self.dead:
                raise WireClosed(f"stream to {self.peer} is dead")
            self._id += 1
            rid = self._id
            self._pending[rid] = (cb, time.monotonic())
        # bounded-wait put: a stream that dies with a FULL window has
        # no sender draining it — the pending entry registered above
        # already got its failure callback from _fail_all, but this
        # producer must not block forever on the dead queue
        import queue as _q
        while True:
            try:
                self._sendq.put((rid, req_meta, data, csums),
                                timeout=0.2)
                return
            except _q.Full:
                with self._lock:
                    if self.dead:
                        raise WireClosed(
                            f"stream to {self.peer} died mid-submit")

    def try_submit(self, req_meta: bytes, data=None, cb=None,
                   csums=None) -> bool:
        """Non-blocking submit: False when the send window is full
        (the pool's spill signal — this sender is saturated)."""
        import queue as _q
        with self._lock:
            if self.dead:
                return False
            self._id += 1
            rid = self._id
            self._pending[rid] = (cb, time.monotonic())
        try:
            self._sendq.put_nowait((rid, req_meta, data, csums))
            return True
        except _q.Full:
            with self._lock:
                self._pending.pop(rid, None)
            return False

    # -------------------------------------------------------- threads --
    def _sender_loop(self) -> None:
        import queue as _q
        while True:
            item = self._sendq.get()
            if item is None:
                return
            # greedy drain: every frame already queued rides ONE
            # sendmsg — per-frame thread wakeups and syscalls are
            # what caps small-op throughput on a busy host, and the
            # coalesced write is how "batch i+1 encodes while batch
            # i is on the wire" survives the GIL.  Fault checks
            # (partition, drop/truncate/flip) stay per-frame.
            batch = [item]
            try:
                while True:
                    nxt = self._sendq.get_nowait()
                    if nxt is None:
                        self._sendq.put(None)   # close() sentinel
                        break
                    batch.append(nxt)
            except _q.Empty:
                pass
            try:
                blobs: list = []
                for rid, meta, data, csums in batch:
                    if data is None:
                        typ, parts = MSG_REQ, [meta]
                    else:
                        typ = MSG_REQ_SG
                        parts = [_U32.pack(len(meta)), meta, data]
                    blobs.extend(prepare_frame(
                        self.sock, typ, rid, -1, parts, self.key,
                        self.mode, self.entity, self.peer,
                        data_csums=csums))
                self.sending = True
                try:
                    _sendmsg_all(self.sock, blobs)
                finally:
                    self.sending = False
            except (OSError, IOError) as e:
                self._fail_all(e)
                return

    def _reader_loop(self) -> None:
        rd = SockReader(self.sock)
        while True:
            try:
                env = rd.read_frame(session_key=self.key,
                                    mode=self.mode)
            except socket.timeout:
                # idle is fine; a pending op older than the stall
                # bound means the peer wedged mid-reply — fail the
                # stream so callers retry elsewhere (the blocking
                # client's per-call socket timeout, stream-shaped)
                with self._lock:
                    oldest = min((t for _, t in
                                  self._pending.values()),
                                 default=None)
                if oldest is not None and \
                        time.monotonic() - oldest > self._stall_s:
                    self._fail_all(IOError(
                        f"stream to {self.peer}: reply stalled "
                        f"past {self._stall_s:.0f}s"))
                    return
                continue
            except (OSError, IOError) as e:
                self._fail_all(e)
                return
            with self._lock:
                ent = self._pending.pop(env.id, None)
            if ent is None:
                continue                   # unsolicited/duplicate id
            cb = ent[0]
            if cb is None:
                continue
            result, exc = None, None
            if env.type == MSG_ERR:
                try:
                    raise_reply_error(env.payload)
                except Exception as e:
                    exc = e
            else:
                from . import encoding
                try:
                    result = encoding.loads(env.payload)
                except Exception as e:
                    exc = e
            try:
                cb(result, exc)
            except Exception:
                pass                       # callbacks must not kill IO

    def _fail_all(self, exc: Exception) -> None:
        with self._lock:
            if self.dead:
                pending, self._pending = self._pending, {}
            else:
                self.dead = True
                pending, self._pending = self._pending, {}
        try:
            self.sock.close()
        except OSError:
            pass
        # drain unsent frames so no submitter blocks on a dead window
        try:
            while True:
                self._sendq.get_nowait()
        except Exception:
            pass
        for cb, _t in pending.values():
            if cb is None:
                continue
            try:
                cb(None, exc)
            except Exception:
                pass

    def close(self) -> None:
        self._fail_all(WireClosed("stream closed"))
        try:
            self._sendq.put_nowait(None)
        except Exception:
            pass


class StreamPool:
    """N parallel pipelined streams to ONE daemon: a logical op's
    shard fan-out (and whole batches of ops) stripe across the
    streams, so frame crypto and socket writes run concurrently while
    the daemon's per-connection threads handle them in parallel.
    Streams are built lazily from ``factory`` (an authenticated
    connection constructor — the mon-ticket handshake happens there)
    and replaced when they die; a dead daemon surfaces as the
    factory's connect error on the caller."""

    def __init__(self, factory, size: int = 4,
                 mode: str = MODE_CRC, window: int = 16,
                 name: str = "", shm_dir: Optional[str] = None,
                 shm_bytes: int = 0):
        from ..common.lockdep import LockdepLock
        self._factory = factory
        self.size = max(1, int(size))
        self.mode = mode
        self.window = max(1, int(window))
        self.name = name
        self._lock = LockdepLock("wire.streampool", recursive=False)
        self._streams = []
        # same-host shared-memory lane (msg/shm_ring.py): ONE ring
        # per (client, daemon) pair shared by every stream of this
        # pool — a resubmit on a fresh stream must still find the
        # payload at the extents baked into the doorbell meta.  Built
        # lazily with the first stream; any daemon refusal disables
        # the lane for good (pure-socket fallback, no renegotiation
        # churn).
        self._shm_dir = shm_dir
        self._shm_bytes = int(shm_bytes)
        self._ring_obj = None
        self._ring_dead = shm_bytes <= 0 or shm_dir is None
        # True only after a stream's MSG_SHM_ATTACH was ACCEPTED: a
        # doorbell baked into a frame before the verdict is known
        # would turn an attach refusal into a hard op failure (the
        # daemon cannot resolve it), so payloads ride the socket
        # until the lane is proven up
        self._ring_attached = False

    def _ring(self):
        with self._lock:
            if self._ring_dead:
                return None
            if self._ring_obj is None:
                try:
                    from .shm_ring import ShmRing
                    self._ring_obj = ShmRing.create(
                        self._shm_dir, self.name, self._shm_bytes)
                except OSError:
                    self._ring_dead = True
                    return None
            return self._ring_obj

    def _ensure_attach(self) -> None:
        """Resolve the attach verdict BEFORE any doorbell is staged:
        grow the first stream (whose construction runs the
        MSG_SHM_ATTACH handshake synchronously) when none is live
        yet.  Streams that already exist carry a verdict — attach
        happens inside Stream.__init__, so 'live stream + not
        attached' can only mean the daemon refused (lane dead)."""
        with self._lock:
            if self._ring_dead or self._ring_attached:
                return
            have = any(not s.dead for s in self._streams)
        if not have:
            try:
                self._grow()
            except (OSError, IOError):
                pass          # daemon unreachable: submit will retry

    def ring_put(self, data, csums=None):
        """Stage one payload in the shared-memory ring; returns the
        doorbell token (meta extent + crc) or None when the lane is
        unavailable/full — the caller falls back to the socket
        scatter-gather tail transparently.  Never stages before some
        stream's attach handshake has been ACCEPTED: a doorbell baked
        into a frame before the verdict would turn a refusal into a
        hard op failure (the daemon cannot resolve it)."""
        self._ensure_attach()
        with self._lock:
            if not self._ring_attached or self._ring_dead:
                return None
        ring = self._ring()
        if ring is None:
            return None
        combined = csums.combined if (
            csums is not None and csums.length == len(data)) else None
        return ring.put(data, combined)

    def ring_free(self, tok) -> None:
        with self._lock:
            ring = self._ring_obj
        if ring is not None:
            ring.free(tok)

    def ring_live(self) -> bool:
        with self._lock:
            return self._ring_obj is not None and not self._ring_dead

    def _live(self) -> list:
        with self._lock:
            self._streams = [s for s in self._streams if not s.dead]
            return list(self._streams)

    def _grow(self) -> Stream:
        # build outside the pool lock: the factory does wire RTTs
        st = Stream(self._factory(), mode=self.mode,
                    window=self.window, ring=self._ring())
        if self._ring() is not None:
            with self._lock:
                if st.ring_ok:
                    self._ring_attached = True
                else:
                    # the daemon refused the mapping: disable the
                    # lane (every stream of a pool must agree — a
                    # doorbell routed to a ring-less connection
                    # would error)
                    self._ring_dead = True
        with self._lock:
            self._streams.append(st)
        return st

    def submit(self, req_meta: bytes, data=None, cb=None,
               csums=None) -> None:
        """Fill-first with spill-on-backpressure: the frame goes to
        the FIRST live stream whose send window has room — frames
        concentrate on few streams (deep sender batches, few hot
        threads), and a new stream spins up only when every live
        sender is saturated (its crypto+socket lane is the
        bottleneck), up to ``size``.  Hosts with spare cores spread
        to real parallel lanes; small hosts self-limit instead of
        thrashing.  Raises the connect/submit error when no stream
        can take the frame — the caller's retry-once contract
        handles it like any dropped connection."""
        last: Optional[Exception] = None
        for _ in range(2):
            live = self._live()
            try:
                taken = False
                for st in live:
                    if st.try_submit(req_meta, data=data, cb=cb,
                                     csums=csums):
                        taken = True
                        break
                if taken:
                    return
                if len(live) < self.size and \
                        not any(st.sending for st in live):
                    # every window full with senders CPU-bound in
                    # crypto/assembly: a new lane adds throughput.
                    # (A sender blocked INSIDE sendmsg means the
                    # peer is saturated — more connections to the
                    # same daemon add contention, not capacity.)
                    self._grow().submit(req_meta, data=data, cb=cb,
                                        csums=csums)
                else:
                    # every window full at the cap: block on the
                    # least-loaded sender until it drains
                    min(live,
                        key=lambda s: s.inflight()).submit(
                            req_meta, data=data, cb=cb, csums=csums)
                return
            except (OSError, IOError) as e:
                last = e
        raise last if last is not None else WireClosed("pool closed")

    def streams_live(self) -> int:
        with self._lock:
            return len([s for s in self._streams if not s.dead])

    def close(self) -> None:
        with self._lock:
            streams, self._streams = self._streams, []
            ring, self._ring_obj = self._ring_obj, None
            self._ring_dead = True
        for s in streams:
            s.close()
        if ring is not None:
            ring.close(unlink=True)
