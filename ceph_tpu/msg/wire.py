"""Socket wire protocol — typed envelopes between daemon processes.

The process-boundary transport of the messenger (the AsyncMessenger /
Protocol V2 role, src/msg/async/ProtocolV2.cc): length-prefixed,
CRC-protected frames carrying the same typed envelopes the in-process
queues move, over unix-domain or TCP sockets.  Kept deliberately small:
banner exchange, an authentication frame (ceph_tpu.common.auth — the
cephx handshake role), then framed request/reply.

Frame:  u32 magic | u32 type | u64 id | i32 shard | u32 len |
        u32 crc(wire_payload) | wire_payload
Secure mode (every frame after the auth handshake, Protocol V2's
crypto_onwire role, src/msg/async/crypto_onwire.cc): the payload is a
SEALED BOX under the session key (PRF-CTR encryption, encrypt-then-MAC
— common/auth.seal), so traffic is unreadable on the socket, plus a
32-byte HMAC-SHA256 trailer over header+ciphertext so the plaintext
header cannot be tampered with either.  Pre-auth frames (banner,
nonce, auth blobs) are plaintext by necessity; secrets inside them are
themselves sealed under entity keys.
"""
from __future__ import annotations

import hmac
import socket
import struct
import zlib
from typing import Optional

from ..common import faults
from .queue import Envelope

# messenger-frame faultpoints (the qa msgr-failures suite axes): armed
# by the thrasher / fault_injection admin command, never in production
faults.declare("wire.drop_frame",
               "drop an outbound frame before any byte hits the "
               "socket (connection torn down, peer sees a clean "
               "close) — the ms_inject_socket_failures send half")
faults.declare("wire.truncate_frame",
               "send only the first half of a frame, then tear the "
               "connection down — the peer's length-prefixed read "
               "unblocks with WireClosed when the socket dies")
faults.declare("wire.flip_bit",
               "flip one bit in the last byte of the assembled frame "
               "(payload crc in plaintext mode, MAC trailer in secure "
               "mode) — the receiver must REJECT the frame, never "
               "deliver corrupt bytes")

MAGIC = 0x43455054        # "CEPT"
BANNER = b"ceph-tpu v1\n"
_FHDR = struct.Struct("<IIQiII")
_MAC_LEN = 32
# unauthenticated peers control the length field: cap it so a forged
# header cannot make _recv_exact buffer gigabytes pre-auth (the
# Throttle/ms_max_message_size role)
MAX_FRAME = 256 << 20


class WireError(IOError):
    pass


class WireClosed(WireError):
    pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise WireClosed("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, env: Envelope,
               session_key: Optional[bytes] = None,
               src: Optional[str] = None,
               dst: Optional[str] = None) -> None:
    """``src``/``dst`` are the sending/receiving entity names, passed
    by callers that know them (WireClient requests, WireServer
    replies): an armed ``net.partition`` that severs src -> dst drops
    the frame before any byte hits the socket — per-direction, so a
    oneway cut can deliver the request yet drop the reply (the
    half-open-link shape the session-replay machinery must absorb)."""
    if src is not None and dst is not None and \
            faults.partitioned(src, dst):
        raise WireClosed(f"fault injected: {src} -> {dst} partitioned")
    payload = env.payload or b""
    if session_key is not None:
        from ..common.auth import seal
        payload = seal(session_key, payload)    # secure mode
    hdr = _FHDR.pack(MAGIC, env.type, env.id, env.shard, len(payload),
                     zlib.crc32(payload))
    mac = b""
    if session_key is not None:
        mac = hmac.new(session_key, hdr + payload, "sha256").digest()
    blob = hdr + payload + mac
    if faults.fire("wire.drop_frame", type=env.type) is not None:
        raise WireClosed("fault injected: frame dropped before send")
    if faults.fire("wire.truncate_frame", type=env.type) is not None:
        sock.sendall(blob[:max(1, len(blob) // 2)])
        raise WireClosed("fault injected: frame truncated mid-send")
    if faults.fire("wire.flip_bit", type=env.type) is not None:
        # last byte = MAC trailer (secure) or the crc-covered payload
        # tail / header crc field (plaintext): rejection either way
        blob = blob[:-1] + bytes([blob[-1] ^ 0x01])
    sock.sendall(blob)


def recv_frame(sock: socket.socket,
               session_key: Optional[bytes] = None) -> Envelope:
    hdr = _recv_exact(sock, _FHDR.size)
    magic, typ, mid, shard, ln, crc = _FHDR.unpack(hdr)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic:#x}")
    if ln > MAX_FRAME:
        raise WireError(f"frame length {ln} exceeds cap {MAX_FRAME}")
    payload = _recv_exact(sock, ln) if ln else b""
    if zlib.crc32(payload) != crc:
        raise WireError("payload crc mismatch")
    if session_key is not None:
        mac = _recv_exact(sock, _MAC_LEN)
        want = hmac.new(session_key, hdr + payload, "sha256").digest()
        if not hmac.compare_digest(mac, want):
            raise WireError("frame MAC rejected")
        from ..common.auth import AuthError, unseal
        try:
            payload = unseal(session_key, payload)
        except AuthError as e:
            raise WireError(f"secure payload rejected: {e}")
    return Envelope(typ, mid, shard, payload)


def exchange_banners(sock: socket.socket) -> None:
    sock.sendall(BANNER)
    got = _recv_exact(sock, len(BANNER))
    if got != BANNER:
        raise WireError(f"bad banner {got!r}")
