"""Socket wire protocol — typed envelopes between daemon processes.

The process-boundary transport of the messenger (the AsyncMessenger /
Protocol V2 role, src/msg/async/ProtocolV2.cc): length-prefixed,
CRC-protected frames carrying the same typed envelopes the in-process
queues move, over unix-domain or TCP sockets.  Kept deliberately small:
banner exchange, an authentication frame (ceph_tpu.common.auth — the
cephx handshake role), then framed request/reply.

Frame:  u32 magic | u32 type | u64 id | i32 shard | u32 len |
        u32 crc(wire_payload) | wire_payload
Secure mode (every frame after the auth handshake, Protocol V2's
crypto_onwire role, src/msg/async/crypto_onwire.cc): the payload is a
SEALED BOX under the session key (PRF-CTR encryption, encrypt-then-MAC
— common/auth.seal), so traffic is unreadable on the socket, plus a
32-byte HMAC-SHA256 trailer over header+ciphertext so the plaintext
header cannot be tampered with either.  Pre-auth frames (banner,
nonce, auth blobs) are plaintext by necessity; secrets inside them are
themselves sealed under entity keys.
"""
from __future__ import annotations

import hmac
import os
import socket
import struct
import threading
import time
import zlib
from typing import Optional

from ..common import crcutil, faults
from .queue import Envelope

# messenger-frame faultpoints (the qa msgr-failures suite axes): armed
# by the thrasher / fault_injection admin command, never in production
faults.declare("wire.drop_frame",
               "drop an outbound frame before any byte hits the "
               "socket (connection torn down, peer sees a clean "
               "close) — the ms_inject_socket_failures send half")
faults.declare("wire.truncate_frame",
               "send only the first half of a frame, then tear the "
               "connection down — the peer's length-prefixed read "
               "unblocks with WireClosed when the socket dies")
faults.declare("wire.flip_bit",
               "flip one bit in the last byte of the assembled frame "
               "(payload crc in plaintext mode, MAC trailer in secure "
               "mode) — the receiver must REJECT the frame, never "
               "deliver corrupt bytes")

MAGIC = 0x43455054        # "CEPT"
BANNER = b"ceph-tpu v1\n"
_FHDR = struct.Struct("<IIQiII")
_U32 = struct.Struct("<I")
_MAC_LEN = 32
# unauthenticated peers control the length field: cap it so a forged
# header cannot make _recv_exact buffer gigabytes pre-auth (the
# Throttle/ms_max_message_size role)
MAX_FRAME = 256 << 20

# message types (the protocol's canonical home; cluster/daemon.py
# aliases these for its handshake/dispatch code)
MSG_AUTH_NONCE = 0x01
MSG_AUTH_SECRET = 0x02       # secret-mode proof
MSG_AUTH_TICKET = 0x03       # ticket-mode (ticket + authorizer)
MSG_AUTH_OK = 0x04
MSG_AUTH_FAIL = 0x05
MSG_REQ = 0x10               # typed-encoded {"cmd": ..., ...}
MSG_REPLY = 0x11
MSG_ERR = 0x12
MSG_REQ_SG = 0x13            # scatter-gather request: u32 metalen |
#                              encoded meta dict | raw data payload —
#                              bulk bytes never pass through the typed
#                              encoder (zero intermediate copies)
MSG_SET_MODE = 0x14          # authenticated per-connection downgrade
#                              to "crc" data mode (the reference's
#                              ms_mode crc vs secure negotiation)
MSG_SHM_ATTACH = 0x15        # same-host shared-memory ring handoff:
#                              the client asks the daemon to map its
#                              ring file; subsequent requests may then
#                              carry payloads out-of-band with only a
#                              doorbell (meta + ring extent + crc)
#                              crossing the socket (msg/shm_ring.py)
MSG_REPLY_SG = 0x16          # scatter-gather REPLY: u32 metalen |
#                              meta | raw bulk bytes — the reply value
#                              IS the data segment, and the daemon
#                              folds store-trusted blob csums into the
#                              frame crc (crc32_combine) so the reply
#                              leaves with ZERO send scans
MSG_SHM_FREE = 0x17          # reply-ring reclaim doorbell (client ->
#                              daemon, rid 0, no reply): the client
#                              consumed the reply records named in the
#                              payload, the daemon may reuse their
#                              extents.  Ordering: the client
#                              materializes the payload BEFORE sending
#                              this, so the extent is never read after
#                              it is freed.

# per-connection data modes after the auth handshake (the reference's
# ms_cluster_mode / ms_client_mode values, src/msg/msg_types.h):
#   secure — payload sealed (PRF-CTR + MAC): confidentiality + integrity
#   crc    — payload plaintext but hdr+payload HMAC'd under the session
#            key: integrity/authenticity only, the reference's DEFAULT
#            for intra-cluster traffic (and ~10x cheaper per byte on
#            stdlib-crypto hosts, which is what lets the multi-stream
#            data path reach device-adjacent rates)
MODE_SECURE = "secure"
MODE_CRC = "crc"


class WireError(IOError):
    pass


class WireClosed(WireError):
    pass


# cached ZeroWire config flags (common/crcutil.flag, observer-refreshed
# — the hot path must not pay a layered-options lookup per frame):
# wire_one_pass gates the sub-crc/combine integrity scan, wire_zero_copy
# the buffer-view spine (both default True; the bench's "before" phases
# flip them to price the legacy 3-pass/copying path against the same
# daemons)
_opt = crcutil.flag

# observer-cached wire_device_crc MODE (a string enum, not a bool, so
# crcutil.flag cannot carry it): auto / on / off, refreshed on config
# set like the hot bool flags
_dev_crc: dict = {}


def _device_crc_mode() -> str:
    v = _dev_crc.get("mode")
    if v is None:
        from ..common.options import config
        cfg = config()

        def _refresh(_n, val):
            _dev_crc["mode"] = str(val)

        cfg.observe("wire_device_crc", _refresh)
        v = _dev_crc["mode"] = str(cfg.get("wire_device_crc"))
    return v


def _device_worthwhile() -> bool:
    # backend probe cached for the process: "auto" consults it once
    v = _dev_crc.get("worthwhile")
    if v is None:
        try:
            from ..ops import crc32_gf2
            v = bool(crc32_gf2.device_worthwhile())
        except Exception:
            v = False
        _dev_crc["worthwhile"] = v
    return v


def receive_csums(buf, site: str = "verify") -> crcutil.Csums:
    """THE receive-verify scanner — every inbound bulk payload
    (socket SG frames, request-ring doorbells, reply-ring records)
    funnels through here.  With ``wire_device_crc`` active the scan
    is the batched ``[N,8B]@[8B,32]`` GF(2) matmul on the accelerator
    slice (ops/crc32_gf2.csums_for: full 4-KiB blocks in ONE device
    dispatch, the sub-block tail host-scanned and counted at
    ``device_tail``) — ZERO host passes over the full blocks, with
    device dispatches counted separately so the zero is falsifiable.
    Off / auto-on-cpu / device failure: one counted host pass,
    bit-identical verdict either way — a flipped bit fails the
    combine on both paths."""
    mode = _device_crc_mode()
    if mode == "on" or (mode == "auto" and _device_worthwhile()):
        try:
            from ..ops import crc32_gf2
            return crc32_gf2.csums_for(crcutil.as_u8(buf))
        except Exception:
            crcutil._counters().inc("device_crc_fallbacks")
    # noqa: CTL131 — receive-direction counted host fallback of the
    # device verify, not a reply send (flagged only because the serve
    # loop hands this scanner to the ring readers)
    return crcutil.Csums.scan(buf, block=crcutil.CSUM_BLOCK,  # noqa: CTL131
                              site=site)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    # recv_into a preallocated buffer: bulk payloads land in place
    # (one allocation, no per-chunk copies) — on the multi-stream
    # data path this is a per-byte cost, not a nicety
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise WireClosed("peer closed")
        got += r
    return bytes(buf)  # noqa: CTL130 — pre-auth handshake frames
    # only (banner/nonce/auth blobs): small and off the data path


_IOV_MAX = 1024      # POSIX sysconf(_SC_IOV_MAX) floor; sendmsg with
                     # more iovecs fails EMSGSIZE, and a greedy batch
                     # drain of a deep window can exceed it


def _sendmsg_all(sock: socket.socket, parts) -> None:
    """sendall over a scatter-gather buffer list: one syscall per
    window, partial sends resumed without re-joining the parts."""
    bufs = [memoryview(p) for p in parts if len(p)]
    while bufs:
        sent = sock.sendmsg(bufs[:_IOV_MAX])
        while bufs and sent >= len(bufs[0]):
            sent -= len(bufs[0])
            bufs.pop(0)
        if sent and bufs:
            bufs[0] = bufs[0][sent:]


def _frame_parts(env_type: int, env_id: int, shard: int, parts,
                 session_key: Optional[bytes],
                 mode: str, data_csums=None) -> list:
    """Assemble one frame as a buffer list: header | payload [| mac].
    Per-byte integrity is mode-priced the way the reference prices
    ms_mode: secure seals and MACs every payload byte; crc mode runs
    one crc32 pass (C speed) and binds the digest into the header,
    whose HMAC is then constant-cost — the payload never feeds SHA256,
    which is the difference between ~150 MiB/s and line rate on a
    syscall-priced host.  Plaintext (no session key) is crc-only.

    ``data_csums`` (a crcutil.Csums for the LAST part — the bulk data
    segment) is the one-pass handoff: its combined sub-crcs are FOLDED
    into the frame crc via crc32_combine, so a payload whose csums are
    already known (device crc kernel, staging digest, content cache)
    crosses the sender with ZERO crc scans.  The wire value is
    bit-identical to a whole-payload zlib.crc32 — receivers cannot
    tell the difference."""
    crc = 0
    if session_key is not None and mode == MODE_SECURE:
        from ..common.auth import seal_parts
        parts = seal_parts(session_key, parts)
    elif data_csums is not None and parts and \
            data_csums.length == len(parts[-1]) and _opt("wire_one_pass"):
        for p in parts[:-1]:
            crc = zlib.crc32(p, crc)
            crcutil.note_scan(len(p), "send")
        crc = crcutil.crc32_combine(crc, data_csums.combined,
                                    data_csums.length)
    else:
        for p in parts:
            crc = zlib.crc32(p, crc)
            crcutil.note_scan(len(p), "send")
    total = sum(len(p) for p in parts)
    hdr = _FHDR.pack(MAGIC, env_type, env_id, shard, total, crc)
    if session_key is None:
        return [hdr] + list(parts)
    mac = hmac.new(session_key, hdr, "sha256")
    if mode == MODE_SECURE:
        for p in parts:
            mac.update(p)
    return [hdr] + list(parts) + [mac.digest()]


def prepare_frame(sock: socket.socket, env_type: int, env_id: int,
                  shard: int, parts,
                  session_key: Optional[bytes], mode: str,
                  src: Optional[str], dst: Optional[str],
                  data_csums=None) -> list:
    """Per-frame assembly with every wire faultpoint applied; returns
    the frame's buffer list WITHOUT sending it, so callers (the
    stream sender, the server's reply batching) can coalesce many
    frames into one sendmsg.  A fired drop/truncate raises exactly as
    the unbatched path did (truncate pushes its half-frame first)."""
    if src is not None and dst is not None and \
            faults.partitioned(src, dst):
        raise WireClosed(f"fault injected: {src} -> {dst} partitioned")
    blobs = _frame_parts(env_type, env_id, shard, parts,
                         session_key, mode, data_csums=data_csums)
    if faults.fire("wire.drop_frame", type=env_type) is not None:
        raise WireClosed("fault injected: frame dropped before send")
    if faults.fire("wire.truncate_frame", type=env_type) is not None:
        whole = b"".join(bytes(p) for p in blobs)  # noqa: CTL130 —
        # fault path only: the half-frame join never runs in production
        sock.sendall(whole[:max(1, len(whole) // 2)])
        raise WireClosed("fault injected: frame truncated mid-send")
    if faults.fire("wire.flip_bit", type=env_type) is not None:
        # last non-empty blob: MAC trailer (MAC'd frames), crc-covered
        # payload tail (plaintext), or the header itself when the
        # plaintext payload is empty — rejection every way
        for bi in range(len(blobs) - 1, -1, -1):
            tail = bytes(blobs[bi])
            if tail:
                blobs[bi] = tail[:-1] + bytes([tail[-1] ^ 0x01])
                break
    return blobs


def _send_parts(sock: socket.socket, env_type: int, env_id: int,
                shard: int, parts,
                session_key: Optional[bytes],
                mode: str,
                src: Optional[str], dst: Optional[str],
                data_csums=None) -> None:
    _sendmsg_all(sock, prepare_frame(sock, env_type, env_id, shard,
                                     parts, session_key, mode,
                                     src, dst, data_csums=data_csums))


def send_frame(sock: socket.socket, env: Envelope,
               session_key: Optional[bytes] = None,
               src: Optional[str] = None,
               dst: Optional[str] = None,
               mode: str = MODE_SECURE) -> None:
    """``src``/``dst`` are the sending/receiving entity names, passed
    by callers that know them (WireClient requests, WireServer
    replies): an armed ``net.partition`` that severs src -> dst drops
    the frame before any byte hits the socket — per-direction, so a
    oneway cut can deliver the request yet drop the reply (the
    half-open-link shape the session-replay machinery must absorb).
    ``mode`` applies only when a session key is present: "secure"
    seals the payload, "crc" sends it plaintext with a crc32 bound
    into the HMAC-authenticated header (constant-cost MAC)."""
    _send_parts(sock, env.type, env.id, env.shard,
                [env.payload or b""], session_key, mode, src, dst)


def send_frame_sg(sock: socket.socket, env_type: int, env_id: int,
                  meta: bytes, data,
                  session_key: Optional[bytes] = None,
                  src: Optional[str] = None,
                  dst: Optional[str] = None,
                  mode: str = MODE_SECURE,
                  data_csums=None) -> None:
    """Scatter-gather frame: typed-encoded ``meta`` plus a raw bulk
    ``data`` buffer shipped as separate segments of ONE frame
    (u32 metalen | meta | data), so multi-MB shard payloads go from
    their staging buffers to the socket without passing through the
    typed encoder or any intermediate join (crc mode: zero copies;
    secure mode: single cipher+MAC pass via auth.seal_parts).
    ``data_csums`` (crcutil.Csums of ``data``) folds precomputed
    sub-crcs into the frame crc instead of re-scanning."""
    _send_parts(sock, env_type, env_id, -1,
                [_U32.pack(len(meta)), meta, data],
                session_key, mode, src, dst, data_csums=data_csums)


def split_sg(payload):
    """Inverse of the SG payload layout: -> (meta_bytes, data).

    ``data`` is a zero-copy memoryview over the received frame buffer
    (the buffer stays alive as long as the view does — Python buffer
    semantics carry the lifetime); the meta prefix is materialized
    because the typed decoder wants bytes and it is ~100 bytes.  With
    ``wire_zero_copy`` off the legacy whole-payload copy runs and is
    COUNTED (copies/MiB in the bench decomposition)."""
    mv = crcutil.as_u8(payload)
    if len(mv) < 4:
        raise WireError("SG frame truncated")
    (mlen,) = _U32.unpack_from(mv, 0)
    if 4 + mlen > len(mv):
        raise WireError("SG meta length exceeds frame")
    data = mv[4 + mlen:]
    if not _opt("wire_zero_copy"):
        crcutil.note_copy(len(data), "split_sg")
        data = bytes(data)  # noqa: CTL130 — the counted legacy path
    return bytes(mv[4:4 + mlen]), data


# bulk payloads at/above this ride a scatter-gather frame: below it
# the typed encoder re-buffers anyway and the SG framing overhead
# dominates.  ONE constant shared by both senders (the async
# objecter's client streams and the daemon's peer client) — the
# zero-copy view contract relies on every sender agreeing on it.
SG_MIN = 1024


def extract_bulk(req, site: str):
    """Split a bulk ``data`` payload (and its precomputed ``_csums``)
    out of a request dict for the scatter-gather frame tail; returns
    (req, data|None, csums|None).  Zero-copy: the payload buffer
    (bytes, bytearray or memoryview — staged numpy shards arrive as
    views) goes to the frame assembly UNTOUCHED; with
    ``wire_zero_copy`` off the legacy materialization runs and is
    COUNTED at ``site``.  Sub-SG_MIN payloads ride the typed encoder
    (memoryviews materialized — tiny by definition) and drop their
    ``_csums`` (not wire-encodable, and the scan saved is tiny)."""
    payload = req.get("data") if isinstance(req, dict) else None
    if isinstance(payload, (bytes, bytearray, memoryview)) and \
            len(payload) >= SG_MIN:
        req = dict(req)
        data = req.pop("data")
        csums = req.pop("_csums", None)
        if not _opt("wire_zero_copy") and not isinstance(data, bytes):
            crcutil.note_copy(len(data), site)
            data = bytes(data)  # noqa: CTL130 — counted legacy path
        return req, data, csums
    if isinstance(req, dict) and ("_csums" in req or
                                  isinstance(payload, memoryview)):
        req = dict(req)
        req.pop("_csums", None)
        if isinstance(payload, memoryview):
            req["data"] = bytes(payload)  # noqa: CTL130 — sub-SG_MIN
            # payloads ride the typed encoder, which re-buffers
            # anyway (tiny by definition)
    return req, None, None


class BulkReply:
    """Handler-arm carrier for a bulk reply: the payload plus the
    Csums the STORE already trusts for it (BlueStore blob csums via
    read_with_csums, or a receive-verify product).  The serve loop's
    reply chokepoint turns it into a reply-ring record (same-host:
    zero copies, zero scans) or a MSG_REPLY_SG socket frame whose
    crc the trusted csums FOLD into (crc32_combine — zero send
    scans); in-process dispatch unwraps it to the raw value.  csums
    None means no trusted digest exists (compressed blob, csums off)
    — the send side scans once and COUNTS it, same as today."""

    __slots__ = ("data", "csums")

    def __init__(self, data, csums=None):
        self.data = data
        self.csums = csums

    def to_bytes(self) -> bytes:
        d = self.data
        return d if isinstance(d, bytes) else bytes(d)


def unwrap_bulk(val):
    """Collapse BulkReply carriers to their raw values — the
    in-process dispatch path (local OSD calls, tests poking
    _handle_inner) sees exactly what the wire client would."""
    if isinstance(val, BulkReply):
        return val.to_bytes()
    if isinstance(val, dict) and \
            any(isinstance(v, BulkReply) for v in val.values()):
        return {k: (v.to_bytes() if isinstance(v, BulkReply) else v)
                for k, v in val.items()}
    return val


def _parse_frame(hdr: bytes, payload, mac: Optional[bytes],
                 session_key: Optional[bytes],
                 mode: str) -> Envelope:
    """Verify one received frame (crc / MAC / unseal) — shared by the
    raw-socket recv_frame and the buffered SockReader.

    One-pass integrity (ZeroWire): for a scatter-gather frame (either
    direction — MSG_REQ_SG requests, MSG_REPLY_SG replies) the verify
    scan runs per 4-KiB sub-block of the data segment and the
    sub-crcs are COMBINED (crc32_combine) against the header crc —
    same accept/reject verdict as a whole-payload crc32, but the
    sub-crcs survive the verify as TRUSTED values on the returned
    envelope, which the daemon hands to BlueStore as ready-made blob
    csums: the store never scans payload bytes again.  The scan
    itself is ``receive_csums``: with ``wire_device_crc`` active it
    is the GF(2) matmul on the accelerator slice and the host never
    touches the full blocks at all."""
    magic, typ, mid, shard, ln, crc = _FHDR.unpack(hdr)
    csums = None
    if crc and typ in (MSG_REQ_SG, MSG_REPLY_SG) and \
            _opt("wire_one_pass"):
        mv = crcutil.as_u8(payload)
        if len(mv) < 4:
            raise WireError("payload crc mismatch")
        (mlen,) = _U32.unpack_from(mv, 0)
        dstart = 4 + mlen
        if dstart > len(mv):
            raise WireError("payload crc mismatch")
        head_crc = zlib.crc32(mv[:dstart])
        crcutil.note_scan(dstart, "verify")
        csums = receive_csums(mv[dstart:], site="verify")
        got = crcutil.crc32_combine(head_crc, csums.combined,
                                    csums.length)
        if got != crc:
            raise WireError("payload crc mismatch")
    elif crc:
        if zlib.crc32(payload) != crc:
            raise WireError("payload crc mismatch")
        crcutil.note_scan(len(payload), "verify")
    if session_key is not None:
        # the MAC covers the header always (which binds the crc field,
        # hence the payload, in crc mode) and the payload bytes only
        # in secure mode — mirror of _frame_parts' pricing
        want = hmac.new(session_key, hdr, "sha256")
        if mode == MODE_SECURE:
            want.update(payload)
        if mac is None or not hmac.compare_digest(mac, want.digest()):
            raise WireError("frame MAC rejected")
        if mode == MODE_SECURE:
            from ..common.auth import AuthError, unseal
            try:
                payload = unseal(session_key, bytes(payload))  # noqa: CTL130
                # — secure mode decrypts into fresh bytes by nature;
                # zero-copy applies to the crc data mode
            except AuthError as e:
                raise WireError(f"secure payload rejected: {e}")
    return Envelope(typ, mid, shard, payload, csums)


def _check_hdr(hdr: bytes) -> int:
    magic, typ, mid, shard, ln, crc = _FHDR.unpack(hdr)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic:#x}")
    if ln > MAX_FRAME:
        raise WireError(f"frame length {ln} exceeds cap {MAX_FRAME}")
    return ln


def recv_frame(sock: socket.socket,
               session_key: Optional[bytes] = None,
               mode: str = MODE_SECURE) -> Envelope:
    hdr = _recv_exact(sock, _FHDR.size)
    ln = _check_hdr(hdr)
    payload = _recv_exact(sock, ln) if ln else b""
    mac = _recv_exact(sock, _MAC_LEN) if session_key is not None \
        else None
    return _parse_frame(hdr, payload, mac, session_key, mode)


class SockReader:
    """Buffered frame reader over one socket.

    On hosts where every syscall is expensive (sandboxed kernels —
    exactly where this repo's daemons run in CI), reading one frame
    as hdr/payload/mac recv calls costs three syscalls per frame;
    under a pipelined stream most of those frames are ALREADY in the
    kernel buffer.  This reader pulls large chunks and parses frames
    out of its own buffer: one recv can yield a whole window of
    pipelined frames (and ``try_frame`` drains them with no syscall
    at all, which is what lets a server batch its replies).

    A socket timeout mid-frame leaves the partial bytes buffered;
    the next read resumes where it stopped (the raw ``_recv_exact``
    path would have dropped them)."""

    # one recv per window, not per frame: sized to the 2 MiB kernel
    # buffers the streams set, so a full bulk frame (or several) lands
    # in ONE syscall — at ~1 ms/syscall a 256 KiB chunk made every
    # 1 MiB frame cost four recvs before any byte was parsed
    CHUNK = 1 << 21

    # payloads at/above this size take the DIRECT path: recv_into a
    # dedicated exact-size buffer handed out as a zero-copy memoryview
    # (no scratch->buf append, no _take materialization — the two
    # avoidable copies the legacy reader charged every bulk byte)
    BIG = 1 << 16

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._buf = bytearray()
        self._pos = 0
        # persistent recv_into target: recv(CHUNK) would allocate (and
        # mmap) CHUNK bytes per call even for a 100-byte reply frame.
        # Starts small so the many control connections don't each pin
        # 2 MiB; the first bulk frame upgrades it to CHUNK for good.
        self._scratch = bytearray(1 << 16)
        # a direct big-frame read interrupted by a socket timeout
        # parks here and resumes on the next read_frame call (the
        # buffered path gets the same resume property from _buf)
        self._partial: Optional[tuple] = None

    def _avail(self) -> int:
        return len(self._buf) - self._pos

    def _fill(self, want: int) -> None:
        """Grow the buffer to at least ``want`` available bytes."""
        while self._avail() < want:
            if self._pos and self._pos >= (1 << 20):
                del self._buf[:self._pos]
                self._pos = 0
            if want - self._avail() > len(self._scratch):
                self._scratch = bytearray(self.CHUNK)
            r = self.sock.recv_into(self._scratch)
            if not r:
                raise WireClosed("peer closed")
            self._buf += memoryview(self._scratch)[:r]

    def _take(self, n: int) -> bytes:
        out = bytes(self._buf[self._pos:self._pos + n])
        self._pos += n
        if self._pos == len(self._buf):
            self._buf.clear()
            self._pos = 0
        return out

    def _take_view(self, n: int):
        """Zero-copy take: hand out a memoryview over the CURRENT
        buffer and retire it (a bytearray with an exported buffer can
        never be resized, so the reader starts a fresh one seeded
        with the few bytes that followed this frame — those would
        have been copied by their own _take anyway)."""
        old = self._buf
        view = memoryview(old)[self._pos:self._pos + n]
        self._buf = bytearray(memoryview(old)[self._pos + n:])
        self._pos = 0
        return view

    def _frame_len(self, with_mac: bool) -> Optional[int]:
        """Total length of the next frame if its header is buffered
        (validates it), else None."""
        if self._avail() < _FHDR.size:
            return None
        hdr = bytes(self._buf[self._pos:self._pos + _FHDR.size])
        ln = _check_hdr(hdr)
        return _FHDR.size + ln + (_MAC_LEN if with_mac else 0)

    def try_frame(self, session_key: Optional[bytes] = None,
                  mode: str = MODE_SECURE) -> Optional[Envelope]:
        """Parse one frame ENTIRELY from the buffer; None when the
        next frame is absent or incomplete (never a syscall)."""
        total = self._frame_len(session_key is not None)
        if total is None or self._avail() < total:
            return None
        return self._consume(session_key, mode)

    def read_frame(self, session_key: Optional[bytes] = None,
                   mode: str = MODE_SECURE) -> Envelope:
        """Blocking read of one frame (buffered; bulk payloads land
        DIRECTLY in a dedicated buffer — one recv-side copy total,
        handed out as a zero-copy view)."""
        if self._partial is not None:
            hdr, buf, got = self._partial
            return self._finish_big(hdr, buf, got, session_key, mode)
        self._fill(_FHDR.size)
        total = self._frame_len(session_key is not None)
        ln = total - _FHDR.size - \
            (_MAC_LEN if session_key is not None else 0)
        if ln >= self.BIG and _opt("wire_zero_copy"):
            hdr = self._take(_FHDR.size)
            buf = bytearray(ln)
            mv = memoryview(buf)
            have = min(self._avail(), ln)
            if have:
                mv[:have] = memoryview(self._buf)[
                    self._pos:self._pos + have]
                self._pos += have
                if self._pos == len(self._buf):
                    self._buf.clear()
                    self._pos = 0
            return self._finish_big(hdr, buf, have, session_key, mode)
        self._fill(total)
        return self._consume(session_key, mode)

    def _finish_big(self, hdr: bytes, buf: bytearray, got: int,
                    session_key: Optional[bytes],
                    mode: str) -> Envelope:
        """Drain the rest of a direct big-frame read; a socket timeout
        parks the partial state for the next call (the stream reader's
        idle/stall loop relies on resumability)."""
        mv = memoryview(buf)
        try:
            while got < len(buf):
                r = self.sock.recv_into(mv[got:])
                if not r:
                    raise WireClosed("peer closed")
                got += r
            mac = None
            if session_key is not None:
                self._fill(_MAC_LEN)
        except socket.timeout:
            self._partial = (hdr, buf, got)
            raise
        self._partial = None
        if session_key is not None:
            mac = self._take(_MAC_LEN)
        return _parse_frame(hdr, mv, mac, session_key, mode)

    def _consume(self, session_key: Optional[bytes],
                 mode: str) -> Envelope:
        hdr = self._take(_FHDR.size)
        ln = _FHDR.unpack(hdr)[4]
        if ln >= self.BIG and _opt("wire_zero_copy"):
            # whole frame already buffered (pipelined window): hand
            # out a view instead of materializing the payload
            payload = self._take_view(ln)
        elif ln:
            payload = self._take(ln)
            if ln >= self.BIG:
                crcutil.note_copy(ln, "reader")
        else:
            payload = b""
        mac = self._take(_MAC_LEN) if session_key is not None \
            else None
        return _parse_frame(hdr, payload, mac, session_key, mode)


def exchange_banners(sock: socket.socket) -> None:
    sock.sendall(BANNER)
    got = _recv_exact(sock, len(BANNER))
    if got != BANNER:
        raise WireError(f"bad banner {got!r}")


def raise_reply_error(payload: bytes) -> None:
    """Re-raise a MSG_ERR payload as the matching client-side
    exception (shared by the blocking WireClient and the async
    streams, so both paths surface identical error types)."""
    from . import encoding
    from ..common import auth as _cx
    name, msg = encoding.loads(payload)
    exc = {"IOError": IOError, "OSError": IOError,
           "KeyError": KeyError,
           "AuthError": _cx.AuthError,
           "PermissionError": PermissionError,
           "ClsError": IOError,
           "ObjectStoreError": IOError}.get(name, RuntimeError)
    raise exc(f"{name}: {msg}")


# ------------------------------------------------------------- streams ---

class Stream:
    """One PIPELINED framed connection — the async half of the
    messenger (AsyncConnection role): a bounded send window feeding a
    sender thread (frame assembly + crypto runs there, so N streams
    give N concurrent crypto lanes off the submitter's thread) and a
    reader thread matching replies to pending completions by frame id.
    Submissions never wait for replies; completions are delivered as
    ``cb(result, exc)`` callbacks from the reader thread.

    Built OVER an authenticated connection (a WireClient that finished
    its handshake): per-stream framing, faultpoints and the
    net.partition src/dst checks are exactly the blocking path's.  If
    ``mode`` is "crc" the stream performs the authenticated
    MSG_SET_MODE downgrade before pipelining begins.
    """

    def __init__(self, conn, mode: str = MODE_SECURE,
                 window: int = 16, ring=None,
                 want_reply: bool = False, resolver=None):
        import queue as _queue
        from ..common.lockdep import LockdepLock
        self._conn = conn                  # owns the socket lifetime
        self.sock = conn.sock
        self.key = conn.key
        self.entity = conn.entity
        self.peer = getattr(conn, "peer", None)
        self.mode = MODE_SECURE
        self.ring_ok = False
        # daemon→client reply ring (RingReply): ``want_reply`` asks
        # for one in the MSG_SHM_ATTACH handshake; the daemon's ack
        # names its ring file in ``reply_info`` = (path, size).  The
        # ``resolver`` (StreamPool.resolve_reply) turns reply-ring
        # doorbells arriving on this stream back into bytes.
        self._want_reply = bool(want_reply)
        self._resolver = resolver
        self.reply_info = None
        # MSG_SHM_FREE doorbells that hit a full send window park
        # here and ride the front of the next free (order preserved;
        # frees are idempotent daemon-side so a lost one only delays
        # extent reuse until conn close)
        self._free_backlog: list = []
        self.dead = False
        # True while the sender thread is inside sendmsg: a full
        # window + a socket-blocked sender means the PEER is the
        # bottleneck (the pool must not spill to more streams); a
        # full window with the sender in crypto/assembly means this
        # lane's CPU is, and a second lane genuinely helps
        self.sending = False
        self._id = 0
        self._lock = LockdepLock("wire.stream", recursive=False)
        self._pending = {}                 # id -> (cb, t_submit)
        self._sendq = _queue.Queue(maxsize=max(1, window))
        self._stall_s = (self.sock.gettimeout() or 30.0) * 2.0
        # deep kernel buffers: a pipelined stream must absorb a full
        # window of bulk frames without blocking the sender mid-batch
        for opt in (socket.SO_SNDBUF, socket.SO_RCVBUF):
            try:
                self.sock.setsockopt(socket.SOL_SOCKET, opt, 1 << 21)
            except OSError:
                pass
        if mode == MODE_CRC:
            self._negotiate_crc()
        if ring is not None:
            self._attach_ring(ring)
        self._sender = threading.Thread(
            target=self._sender_loop, daemon=True,
            name=f"stream-send-{self.peer}")
        self._reader = threading.Thread(
            target=self._reader_loop, daemon=True,
            name=f"stream-recv-{self.peer}")
        self._sender.start()
        self._reader.start()

    # ------------------------------------------------------ handshake --
    def _negotiate_crc(self) -> None:
        """Authenticated downgrade to crc data mode: the request and
        its ack travel sealed+MAC'd, so a middle box cannot forge the
        downgrade; only then do frames switch to crc'd plaintext
        under header-only HMAC.  ``reply_sg`` advertises that this
        reader understands MSG_REPLY_SG frames — the daemon sends
        bulk replies scatter-gather (trusted csums folded, zero send
        scans) only to connections that said so; legacy blocking
        clients keep getting typed replies."""
        from . import encoding
        send_frame(self.sock, Envelope(
            MSG_SET_MODE, 0, -1,
            encoding.dumps({"mode": MODE_CRC, "reply_sg": True})),
            session_key=self.key, src=self.entity, dst=self.peer)
        env = recv_frame(self.sock, session_key=self.key)
        if env.type != MSG_REPLY:
            raise WireError("mode negotiation rejected")
        self.mode = MODE_CRC

    def _attach_ring(self, ring) -> None:
        """Shared-memory lane negotiation (the session_hello-time
        handoff): ask the daemon to map this client's ring file.  The
        request and ack ride the authenticated connection, so only
        the cephx-verified peer learns the path.  A daemon that
        refuses (shm disabled, foreign path) leaves the stream on the
        pure socket lane — fallback is per-stream and silent.  With
        ``want_reply`` the request also asks for the daemon→client
        REPLY ring; an accepting daemon's ack carries its ring file
        as ``reply_path``/``reply_size`` (one reply ring per client
        request ring, shared by every conn of the pool)."""
        from . import encoding
        send_frame(self.sock, Envelope(
            MSG_SHM_ATTACH, 0, -1,
            encoding.dumps({"path": ring.path, "size": ring.size,
                            "reply": self._want_reply})),
            session_key=self.key, src=self.entity, dst=self.peer,
            mode=self.mode)
        env = recv_frame(self.sock, session_key=self.key,
                         mode=self.mode)
        ack = encoding.loads(bytes(env.payload)) \
            if env.type == MSG_REPLY else {}
        self.ring_ok = bool(isinstance(ack, dict) and ack.get("ok"))
        if self.ring_ok and self._want_reply and ack.get("reply_path"):
            self.reply_info = (str(ack["reply_path"]),
                               int(ack.get("reply_size") or 0))

    # --------------------------------------------------------- submit --
    def inflight(self) -> int:
        with self._lock:
            return len(self._pending)

    def submit(self, req_meta: bytes, data=None, cb=None,
               csums=None) -> None:
        """Queue one request frame (blocks only on the send window).
        ``req_meta`` is the typed-encoded request dict; ``data``, when
        given, rides the scatter-gather tail (MSG_REQ_SG) straight
        from its buffer; ``csums`` (crcutil.Csums of ``data``) lets
        the sender fold precomputed sub-crcs into the frame crc
        instead of re-scanning.  ``cb(result, exc)`` fires from the
        reader thread on reply, or with the error that killed the
        stream."""
        with self._lock:
            if self.dead:
                raise WireClosed(f"stream to {self.peer} is dead")
            self._id += 1
            rid = self._id
            self._pending[rid] = (cb, time.monotonic())
        # bounded-wait put: a stream that dies with a FULL window has
        # no sender draining it — the pending entry registered above
        # already got its failure callback from _fail_all, but this
        # producer must not block forever on the dead queue
        import queue as _q
        while True:
            try:
                self._sendq.put((rid, req_meta, data, csums),
                                timeout=0.2)
                return
            except _q.Full:
                with self._lock:
                    if self.dead:
                        raise WireClosed(
                            f"stream to {self.peer} died mid-submit")

    def try_submit(self, req_meta: bytes, data=None, cb=None,
                   csums=None) -> bool:
        """Non-blocking submit: False when the send window is full
        (the pool's spill signal — this sender is saturated)."""
        import queue as _q
        with self._lock:
            if self.dead:
                return False
            self._id += 1
            rid = self._id
            self._pending[rid] = (cb, time.monotonic())
        try:
            self._sendq.put_nowait((rid, req_meta, data, csums))
            return True
        except _q.Full:
            with self._lock:
                self._pending.pop(rid, None)
            return False

    def queue_free(self, payload: bytes) -> None:
        """Queue one MSG_SHM_FREE reclaim doorbell (rid 0 — no
        pending entry, the daemon never replies).  Non-blocking from
        the reader thread: a full send window parks the doorbell on
        the backlog, flushed by the next call; a dead stream drops
        it (the daemon's conn-close cleanup frees the extents)."""
        import queue as _q
        with self._lock:
            if self.dead:
                return
            items, self._free_backlog = \
                self._free_backlog + [payload], []
        for i, p in enumerate(items):
            try:
                self._sendq.put_nowait((0, p, None, None))
            except _q.Full:
                with self._lock:
                    self._free_backlog = \
                        items[i:] + self._free_backlog
                return

    # -------------------------------------------------------- threads --
    def _sender_loop(self) -> None:
        import queue as _q
        while True:
            item = self._sendq.get()
            if item is None:
                return
            # greedy drain: every frame already queued rides ONE
            # sendmsg — per-frame thread wakeups and syscalls are
            # what caps small-op throughput on a busy host, and the
            # coalesced write is how "batch i+1 encodes while batch
            # i is on the wire" survives the GIL.  Fault checks
            # (partition, drop/truncate/flip) stay per-frame.
            batch = [item]
            try:
                while True:
                    nxt = self._sendq.get_nowait()
                    if nxt is None:
                        self._sendq.put(None)   # close() sentinel
                        break
                    batch.append(nxt)
            except _q.Empty:
                pass
            try:
                blobs: list = []
                for rid, meta, data, csums in batch:
                    if rid == 0:
                        # reply-ring reclaim doorbell (queue_free):
                        # a control frame riding the same coalesced
                        # sendmsg as the data frames around it
                        typ, parts = MSG_SHM_FREE, [meta]
                    elif data is None:
                        typ, parts = MSG_REQ, [meta]
                    else:
                        typ = MSG_REQ_SG
                        parts = [_U32.pack(len(meta)), meta, data]
                    blobs.extend(prepare_frame(
                        self.sock, typ, rid, -1, parts, self.key,
                        self.mode, self.entity, self.peer,
                        data_csums=csums))
                self.sending = True
                try:
                    _sendmsg_all(self.sock, blobs)
                finally:
                    self.sending = False
            except (OSError, IOError) as e:
                self._fail_all(e)
                return

    def _reader_loop(self) -> None:
        rd = SockReader(self.sock)
        while True:
            try:
                env = rd.read_frame(session_key=self.key,
                                    mode=self.mode)
            except socket.timeout:
                # idle is fine; a pending op older than the stall
                # bound means the peer wedged mid-reply — fail the
                # stream so callers retry elsewhere (the blocking
                # client's per-call socket timeout, stream-shaped)
                with self._lock:
                    oldest = min((t for _, t in
                                  self._pending.values()),
                                 default=None)
                if oldest is not None and \
                        time.monotonic() - oldest > self._stall_s:
                    self._fail_all(IOError(
                        f"stream to {self.peer}: reply stalled "
                        f"past {self._stall_s:.0f}s"))
                    return
                continue
            except (OSError, IOError) as e:
                self._fail_all(e)
                return
            with self._lock:
                ent = self._pending.pop(env.id, None)
            if ent is None:
                continue                   # unsolicited/duplicate id
            cb = ent[0]
            if cb is None:
                continue
            result, exc, poison = None, None, None
            if env.type == MSG_ERR:
                try:
                    raise_reply_error(env.payload)
                except Exception as e:
                    exc = e
            elif env.type == MSG_REPLY_SG:
                # bulk reply: the data segment IS the reply value,
                # already one-pass verified by _parse_frame (device
                # crc when armed).  Materialized once here — the
                # ownership copy out of the reader's frame buffer,
                # same convention as the typed decoder's output —
                # then the buffer retires.
                try:
                    _meta, data = split_sg(env.payload)
                    result = bytes(data)  # noqa: CTL130 — ownership copy out of the retiring frame buffer, not an avoidable dup
                except Exception as e:
                    exc = e
            else:
                from . import encoding
                try:
                    result = encoding.loads(env.payload)
                except Exception as e:
                    exc = e
                if exc is None and self._resolver is not None and \
                        isinstance(result, dict) and \
                        len(result) == 1 and \
                        ("_shm_reply" in result or
                         "_shm_objs" in result):
                    # reply-ring doorbell: resolve the ring extents
                    # to bytes (verify scan via receive_csums) and
                    # queue the reclaim doorbell.  A poisoned record
                    # gets connection-drop parity with a flipped
                    # socket frame: deliver the error, then kill the
                    # stream so the caller's retry machinery re-asks.
                    try:
                        result = self._resolver(result, self)
                    except WireError as e:
                        result, poison = None, e
                    except Exception as e:
                        exc = e
            try:
                cb(result, exc if poison is None else poison)
            except Exception:
                pass                       # callbacks must not kill IO
            if poison is not None:
                self._fail_all(poison)
                return

    def _fail_all(self, exc: Exception) -> None:
        with self._lock:
            if self.dead:
                pending, self._pending = self._pending, {}
            else:
                self.dead = True
                pending, self._pending = self._pending, {}
            # parked reclaim doorbells die with the conn — the
            # daemon's conn-close cleanup frees the extents
            self._free_backlog = []
        try:
            self.sock.close()
        except OSError:
            pass
        # drain unsent frames so no submitter blocks on a dead window
        try:
            while True:
                self._sendq.get_nowait()
        except Exception:
            pass
        for cb, _t in pending.values():
            if cb is None:
                continue
            try:
                cb(None, exc)
            except Exception:
                pass

    def close(self) -> None:
        self._fail_all(WireClosed("stream closed"))
        try:
            self._sendq.put_nowait(None)
        except Exception:
            pass


class StreamPool:
    """N parallel pipelined streams to ONE daemon: a logical op's
    shard fan-out (and whole batches of ops) stripe across the
    streams, so frame crypto and socket writes run concurrently while
    the daemon's per-connection threads handle them in parallel.
    Streams are built lazily from ``factory`` (an authenticated
    connection constructor — the mon-ticket handshake happens there)
    and replaced when they die; a dead daemon surfaces as the
    factory's connect error on the caller."""

    def __init__(self, factory, size: int = 4,
                 mode: str = MODE_CRC, window: int = 16,
                 name: str = "", shm_dir: Optional[str] = None,
                 shm_bytes: int = 0):
        from ..common.lockdep import LockdepLock
        self._factory = factory
        self.size = max(1, int(size))
        self.mode = mode
        self.window = max(1, int(window))
        self.name = name
        self._lock = LockdepLock("wire.streampool", recursive=False)
        self._streams = []
        # same-host shared-memory lane (msg/shm_ring.py): ONE ring
        # per (client, daemon) pair shared by every stream of this
        # pool — a resubmit on a fresh stream must still find the
        # payload at the extents baked into the doorbell meta.  Built
        # lazily with the first stream; any daemon refusal disables
        # the lane for good (pure-socket fallback, no renegotiation
        # churn).
        self._shm_dir = shm_dir
        self._shm_bytes = int(shm_bytes)
        self._ring_obj = None
        self._ring_dead = shm_bytes <= 0 or shm_dir is None
        # daemon→client reply ring (RingReply): the daemon creates
        # and bump-allocates it, this pool only MAPS it (RingReader)
        # and reclaims consumed records via MSG_SHM_FREE doorbells.
        # One reply ring per client request ring — a reply doorbell
        # resolved on any stream of the pool finds the same extents.
        self._reply_reader = None
        self._want_reply = not self._ring_dead and \
            crcutil.flag("wire_reply_ring")
        # True only after a stream's MSG_SHM_ATTACH was ACCEPTED: a
        # doorbell baked into a frame before the verdict is known
        # would turn an attach refusal into a hard op failure (the
        # daemon cannot resolve it), so payloads ride the socket
        # until the lane is proven up
        self._ring_attached = False

    def _ring(self):
        with self._lock:
            if self._ring_dead:
                return None
            if self._ring_obj is None:
                try:
                    from .shm_ring import ShmRing
                    self._ring_obj = ShmRing.create(
                        self._shm_dir, self.name, self._shm_bytes)
                except OSError:
                    self._ring_dead = True
                    return None
            return self._ring_obj

    def _ensure_attach(self) -> None:
        """Resolve the attach verdict BEFORE any doorbell is staged:
        grow the first stream (whose construction runs the
        MSG_SHM_ATTACH handshake synchronously) when none is live
        yet.  Streams that already exist carry a verdict — attach
        happens inside Stream.__init__, so 'live stream + not
        attached' can only mean the daemon refused (lane dead)."""
        with self._lock:
            if self._ring_dead or self._ring_attached:
                return
            have = any(not s.dead for s in self._streams)
        if not have:
            try:
                self._grow()
            except (OSError, IOError):
                pass          # daemon unreachable: submit will retry

    def ring_put(self, data, csums=None):
        """Stage one payload in the shared-memory ring; returns the
        doorbell token (meta extent + crc) or None when the lane is
        unavailable/full — the caller falls back to the socket
        scatter-gather tail transparently.  Never stages before some
        stream's attach handshake has been ACCEPTED: a doorbell baked
        into a frame before the verdict would turn a refusal into a
        hard op failure (the daemon cannot resolve it)."""
        self._ensure_attach()
        with self._lock:
            if not self._ring_attached or self._ring_dead:
                return None
        ring = self._ring()
        if ring is None:
            return None
        combined = csums.combined if (
            csums is not None and csums.length == len(data)) else None
        return ring.put(data, combined)

    def ring_free(self, tok) -> None:
        with self._lock:
            ring = self._ring_obj
        if ring is not None:
            ring.free(tok)

    def ring_live(self) -> bool:
        with self._lock:
            return self._ring_obj is not None and not self._ring_dead

    def _live(self) -> list:
        with self._lock:
            self._streams = [s for s in self._streams if not s.dead]
            return list(self._streams)

    def _grow(self) -> Stream:
        # client-side orphan sweep on every (re)connect: a kill9'd
        # daemon can never unlink the reply rings IT created, and
        # the daemon that replaces it makes fresh ones — same
        # creator-pid liveness rule as the daemon's zwring sweep at
        # bind, mirrored (the satellite-4 ownership bugfix)
        if self._shm_dir is not None and not self._ring_dead:
            try:
                from .shm_ring import sweep_stale
                sweep_stale(self._shm_dir, prefix="zwreply")
            except OSError:
                pass
        # build outside the pool lock: the factory does wire RTTs
        st = Stream(self._factory(), mode=self.mode,
                    window=self.window, ring=self._ring(),
                    want_reply=self._want_reply,
                    resolver=self.resolve_reply)
        if self._ring() is not None:
            with self._lock:
                if st.ring_ok:
                    self._ring_attached = True
                else:
                    # the daemon refused the mapping: disable the
                    # lane (every stream of a pool must agree — a
                    # doorbell routed to a ring-less connection
                    # would error)
                    self._ring_dead = True
        if st.reply_info is not None:
            self._open_reply_reader(*st.reply_info)
        with self._lock:
            self._streams.append(st)
        return st

    def _open_reply_reader(self, path: str, size: int) -> None:
        """Map the daemon's reply ring named in an accepted attach
        ack.  Mirrors the daemon's own path check: the ring file must
        live in this pool's shm dir (next to the daemon socket) — an
        ack naming a foreign path leaves the reply lane off.  The
        ring PATH keys the daemon generation (creator pid + random
        token in the filename): an ack naming a different path means
        the daemon restarted and made a fresh ring, so the stale
        mapping is replaced — resolving a new doorbell against the
        dead generation's mmap would fail every retry forever."""
        with self._lock:
            cur = self._reply_reader
            if self._ring_dead or \
                    (cur is not None and cur.path == path):
                return
        if self._shm_dir is None or os.path.dirname(
                os.path.realpath(path)) != os.path.realpath(
                    self._shm_dir):
            return
        try:
            from .shm_ring import RingReader
            rd = RingReader(path, size)
        except (OSError, IOError):  # noqa: CTL603 — the reply ring
            # is an OPTIMIZATION lane: a map failure here must not
            # poison the pool (the daemon falls back to MSG_REPLY_SG
            # socket frames for every reply it cannot ring), so
            # "absent reader" is the correct, fully-served state.
            return
        stale = None
        with self._lock:
            cur = self._reply_reader
            if cur is not None and cur.path == path:
                rd.close()            # raced with another _grow
                return
            stale, self._reply_reader = cur, rd
        if stale is not None:
            stale.close()

    def resolve_reply(self, result: dict, stream: Stream):
        """Resolve a reply-ring doorbell (called from a stream reader
        thread): read each named extent through ``receive_csums``
        (device crc when armed — zero host passes), materialize the
        bytes, THEN queue the MSG_SHM_FREE reclaim doorbell — the
        daemon never reuses an extent before its free arrives, so the
        read is race-free by construction.  ``_shm_reply`` marks a
        whole-reply bulk value; ``_shm_objs`` a recovery-pull dict
        whose values may each be a ring extent.  WireError (torn or
        poisoned record) propagates — the caller kills the stream,
        connection-drop parity with a flipped socket frame."""
        rd = self._reply_reader
        if rd is None:
            raise WireError("reply doorbell without a mapped "
                            "reply ring")
        pc = crcutil._counters()
        frees: list = []
        try:
            if "_shm_reply" in result:
                meta = result["_shm_reply"]
                view, _cs = rd.read(meta, scanner=receive_csums)
                out = bytes(view)
                frees.append([int(meta[0]), int(meta[2])])
                pc.inc("shm_reply_frames_served")
                pc.inc("shm_reply_bytes_served", len(out))
                return out
            objs = result["_shm_objs"]
            out_d: dict = {}
            for oid, m in objs.items():
                if isinstance(m, (list, tuple)):
                    view, _cs = rd.read(m, scanner=receive_csums)
                    out_d[oid] = bytes(view)
                    frees.append([int(m[0]), int(m[2])])
                    pc.inc("shm_reply_frames_served")
                    pc.inc("shm_reply_bytes_served", len(out_d[oid]))
                else:
                    out_d[oid] = m    # inline bytes / None
            return out_d
        finally:
            if frees:
                from . import encoding
                stream.queue_free(encoding.dumps(frees))

    def submit(self, req_meta: bytes, data=None, cb=None,
               csums=None) -> None:
        """Fill-first with spill-on-backpressure: the frame goes to
        the FIRST live stream whose send window has room — frames
        concentrate on few streams (deep sender batches, few hot
        threads), and a new stream spins up only when every live
        sender is saturated (its crypto+socket lane is the
        bottleneck), up to ``size``.  Hosts with spare cores spread
        to real parallel lanes; small hosts self-limit instead of
        thrashing.  Raises the connect/submit error when no stream
        can take the frame — the caller's retry-once contract
        handles it like any dropped connection."""
        last: Optional[Exception] = None
        for _ in range(2):
            live = self._live()
            try:
                taken = False
                for st in live:
                    if st.try_submit(req_meta, data=data, cb=cb,
                                     csums=csums):
                        taken = True
                        break
                if taken:
                    return
                if len(live) < self.size and \
                        not any(st.sending for st in live):
                    # every window full with senders CPU-bound in
                    # crypto/assembly: a new lane adds throughput.
                    # (A sender blocked INSIDE sendmsg means the
                    # peer is saturated — more connections to the
                    # same daemon add contention, not capacity.)
                    self._grow().submit(req_meta, data=data, cb=cb,
                                        csums=csums)
                else:
                    # every window full at the cap: block on the
                    # least-loaded sender until it drains
                    min(live,
                        key=lambda s: s.inflight()).submit(
                            req_meta, data=data, cb=cb, csums=csums)
                return
            except (OSError, IOError) as e:
                last = e
        raise last if last is not None else WireClosed("pool closed")

    def streams_live(self) -> int:
        with self._lock:
            return len([s for s in self._streams if not s.dead])

    def close(self) -> None:
        with self._lock:
            streams, self._streams = self._streams, []
            ring, self._ring_obj = self._ring_obj, None
            reply_rd, self._reply_reader = self._reply_reader, None
            self._ring_dead = True
        for s in streams:
            s.close()
        if ring is not None:
            ring.close(unlink=True)
        if reply_rd is not None:
            reply_rd.close()          # the DAEMON owns the unlink
