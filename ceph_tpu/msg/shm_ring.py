"""Shared-memory ring lane for same-host client↔daemon payloads.

The vstart topology colocates every daemon with its clients, yet each
bulk payload still paid sendmsg+recv syscalls and a trip through two
kernel socket buffers — on this repo's syscall-priced sandboxes that
tax capped the whole wire tier (BENCH r05/PR 7 decomposition).  This
module moves the BYTES out of band: the client appends each payload
to a file-backed ring both processes mmap, and only a doorbell — the
typed request meta plus ``[offset, length, gen, crc]`` — crosses the
socket (the reference's rdma/dpdk "posted buffer + completion"
shape, src/msg/async/rdma, grafted onto the unix-socket messenger).

Safety model:

  * ORDERING — the socket doorbell is the happens-before edge: the
    client publishes the record (payload, then seqlock header) before
    sending the doorbell, and the daemon only dereferences an extent
    named by a received doorbell.  No cross-process atomics needed.
  * INTEGRITY — the doorbell carries the payload's combined crc32
    inside the crc/MAC-protected socket frame; the daemon's ONE
    verify scan over the ring bytes (per-4KiB sub-crcs, combined)
    must reproduce it.  A torn/overwritten/bit-flipped ring record is
    REJECTED exactly like a corrupt socket frame: the connection
    drops and the client's resend machinery takes over
    (``wire.flip_bit`` has a fire site on the ring write path so the
    thrasher can prove it).
  * SEQLOCK — each record starts with (magic, gen, len); the daemon
    checks it before AND after the scan, so a client reusing the
    extent mid-read surfaces as a gen mismatch, not silent garbage.
  * RECLAIM — extents free when the op completes (reply or terminal
    failure); a resubmit-after-stream-death reuses the SAME extent,
    which is why the ring belongs to the (client, daemon) pool, not
    to one connection.  Ring full / lane refused / daemon restarted
    without the file ⇒ transparent fallback to the socket
    scatter-gather tail (no acked-write loss — proven by the kill9
    chaos test).
"""
from __future__ import annotations

import mmap
import os
import secrets
import struct
import zlib
from collections import deque
from typing import List, Optional, Tuple

from ..common import crcutil, faults
from ..common.lockdep import LockdepLock

_HDR = struct.Struct("<III")        # file header: magic, version, rsvd
_REC = struct.Struct("<IIQ")        # record: magic, gen, payload len
MAGIC = 0x5A57524E                  # "ZWRN"
REC_MAGIC = 0x5A57524B              # "ZWRK"
HDR_SPACE = 4096                    # header page; data area follows
_ALIGN = 64


class ShmRingError(IOError):
    pass


def sweep_stale(dir_path: str, prefix: str = "zwring") -> int:
    """Unlink ring files whose creator process is gone.  The filename
    embeds the creating pid (``<prefix>.<name>.<pid>.<hex>``) and the
    lane is same-host BY DESIGN, so pid liveness is an authoritative
    orphan test.  Ownership decides who sweeps what: daemons sweep
    CLIENT-created request rings (``zwring``) when they bind their
    socket — a kill9'd client can never reclaim its ring, and nothing
    else will; clients sweep DAEMON-created reply rings (``zwreply``)
    when they (re)connect — a kill9'd daemon orphans its reply rings
    the same way, and the daemon that replaces it creates fresh ones.
    Live rings (creator running) and rings a serving connection
    already mapped (mmap survives the unlink) are safe either way."""
    n = 0
    want = prefix.rstrip(".") + "."
    try:
        names = os.listdir(dir_path)
    except OSError:  # noqa: CTL603 — best-effort housekeeping: an
        # unreadable dir means nothing to sweep, not lost state
        return 0
    for fn in names:
        if not fn.startswith(want):
            continue
        try:
            pid = int(fn.split(".")[-2])
        except (ValueError, IndexError):
            continue
        try:
            os.kill(pid, 0)
            continue                  # creator alive: ring is live
        except ProcessLookupError:
            pass                      # creator gone: orphan
        except OSError:
            continue                  # EPERM etc — assume alive
        try:
            os.unlink(os.path.join(dir_path, fn))
            n += 1
        except OSError:
            pass
    return n


class ShmRing:
    """Client-side ring: single-owner allocator + record writer.

    Allocation is a bump cursor with wraparound over the data area;
    extents retire in completion order behind a deque of live records
    (out-of-order completions delay reuse, never corrupt it).  ``put``
    returns None when the contiguous space is exhausted — the caller
    falls back to the socket for that frame."""

    def __init__(self, path: str, size: int, create: bool):
        self.path = path
        self.size = int(size)
        if create:
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL,
                         0o600)
            try:
                os.ftruncate(fd, HDR_SPACE + self.size)
                self.mm = mmap.mmap(fd, HDR_SPACE + self.size)
            finally:
                os.close(fd)
            _HDR.pack_into(self.mm, 0, MAGIC, 1, 0)
        else:
            raise ShmRingError("use ShmRing.create")
        self._lock = LockdepLock("wire.shmring", recursive=False)
        self._head = 0                  # next alloc offset (data area)
        self._gen = 0
        # (off, total_len, gen, freed) in allocation order
        self._live: deque = deque()
        self._used = 0
        self.closed = False

    @classmethod
    def create(cls, shm_dir: str, name: str, size: int,
               prefix: str = "zwring") -> "ShmRing":
        """Ring file next to the daemon's socket (both processes can
        reach it there); unique per creator process + pool.  The
        ``prefix`` names the OWNER: ``zwring`` = client-created
        request ring (daemon sweeps orphans at bind), ``zwreply`` =
        daemon-created reply ring (client sweeps orphans on
        reconnect) — the embedded pid is the creator's either way."""
        fname = (f"{prefix}.{name or 'pool'}.{os.getpid()}."
                 f"{secrets.token_hex(4)}")
        return cls(os.path.join(shm_dir, fname), size, create=True)

    # ---------------------------------------------------------- alloc --
    def _fit(self, need: int) -> Optional[int]:
        """Contiguous offset for ``need`` bytes, or None.  Live
        extents occupy [tail_off, head) in ring order."""
        if need > self.size:
            return None
        if not self._live:
            self._head = 0
            return 0
        tail = self._live[0][0]
        head = self._head
        if head == tail:
            # live extents cover the whole ring ([tail, head) wrapped
            # all the way around): FULL, not empty — allocating here
            # would overwrite the oldest in-flight record's seqlock
            # header and poison its doorbell
            return None
        if head > tail:
            if self.size - head >= need:
                return head
            if tail >= need:          # wrap: skip the ragged end
                return 0
            return None
        return head if tail - head >= need else None

    def put(self, data, combined: Optional[int] = None):
        """Write one payload record; returns the doorbell token or
        None (ring full / closed).  ``combined`` is the payload's
        crc32 when the caller already knows it (precomputed Csums —
        zero client scans); otherwise ONE scan here is the client's
        single integrity pass for this payload."""
        mv = crcutil.as_u8(data)
        ln = len(mv)
        need = _REC.size + ln
        need += (-need) % _ALIGN
        with self._lock:
            if self.closed:
                return None
            off = self._fit(need)
            if off is None:
                crcutil._counters().inc("shm_full")
                return None
            self._gen += 1
            gen = self._gen
            self._live.append([off, need, gen, False])
            self._head = (off + need) % self.size
            self._used += need
            base = HDR_SPACE + off
            self.mm[base + _REC.size:base + _REC.size + ln] = mv
            _REC.pack_into(self.mm, base, REC_MAGIC, gen, ln)
        if combined is None:
            combined = zlib.crc32(mv)
            crcutil.note_scan(ln, "shm_send")
        inj = faults.fire("wire.flip_bit", site="shm_ring")
        if inj is not None and ln:
            # corrupt ONE ring byte after the crc was taken: the
            # daemon's verify scan must reject the record and drop
            # the connection, exactly like the socket-frame flip
            pos = HDR_SPACE + off + _REC.size + (ln - 1)
            self.mm[pos] ^= 0x01
        pc = crcutil._counters()
        pc.inc("shm_frames")
        pc.inc("shm_bytes", ln)
        return ShmToken(off, ln, gen, combined & 0xFFFFFFFF)

    def free(self, tok: "ShmToken") -> None:
        with self._lock:
            for rec in self._live:
                if rec[0] == tok.off and rec[2] == tok.gen:
                    rec[3] = True
                    break
            while self._live and self._live[0][3]:
                _off, need, _gen, _ = self._live.popleft()
                self._used -= need

    def close(self, unlink: bool = False) -> None:
        with self._lock:
            if self.closed:
                return
            self.closed = True
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass                      # exported views keep it alive
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


class ShmToken:
    """Doorbell payload: where the bytes live + what they must hash
    to.  ``meta`` is the wire-encodable form carried on the request
    dict's ``_shm`` key."""

    __slots__ = ("off", "ln", "gen", "crc")

    def __init__(self, off: int, ln: int, gen: int, crc: int):
        self.off, self.ln, self.gen, self.crc = off, ln, gen, crc

    @property
    def meta(self) -> List[int]:
        return [self.off, self.ln, self.gen, self.crc]


class RingReader:
    """Daemon-side view of a client's ring (read-only mmap).  One per
    authenticated connection; ``read`` resolves a doorbell into a
    zero-copy memoryview plus the TRUSTED sub-crcs its verify scan
    produced (the same one-pass handoff the socket SG path does)."""

    def __init__(self, path: str, size: int):
        st = os.stat(path)
        if st.st_size < HDR_SPACE + size:
            raise ShmRingError(f"ring file shorter than advertised "
                               f"({st.st_size} < {HDR_SPACE + size})")
        fd = os.open(path, os.O_RDONLY)
        try:
            self.mm = mmap.mmap(fd, HDR_SPACE + size,
                                prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        magic, version, _ = _HDR.unpack_from(self.mm, 0)
        if magic != MAGIC:
            self.close()
            raise ShmRingError(f"bad ring magic {magic:#x}")
        self.size = int(size)
        self.path = path

    def _rec_hdr(self, off: int) -> Tuple[int, int, int]:
        return _REC.unpack_from(self.mm, HDR_SPACE + off)

    def read(self, meta, scanner=None
             ) -> Tuple[memoryview, crcutil.Csums]:
        """Resolve one doorbell: seqlock-check the record header,
        ONE verify scan (sub-crcs + combine) against the doorbell's
        crc, re-check the header.  Any mismatch raises WireError —
        the serve loop drops the connection like a poisoned socket
        frame.  ``scanner`` (a ``view -> Csums`` callable, e.g.
        ``wire.receive_csums``) replaces the host verify scan — the
        device-crc path: same combine verdict, zero host passes over
        the full blocks; a flipped ring byte still fails the combine
        and kills the connection exactly like the host path."""
        from .wire import WireError
        try:
            off, ln, gen, want = (int(meta[0]), int(meta[1]),
                                  int(meta[2]), int(meta[3]))
        except (TypeError, ValueError, IndexError):
            raise WireError("malformed shm doorbell")
        if off < 0 or ln < 0 or off + _REC.size + ln > self.size:
            raise WireError("shm doorbell extent out of bounds")
        magic, g, l = self._rec_hdr(off)
        if magic != REC_MAGIC or g != gen or l != ln:
            raise WireError(
                f"shm record header mismatch at {off} "
                f"(gen {g} != {gen} or len {l} != {ln})")
        view = memoryview(self.mm)[HDR_SPACE + off + _REC.size:
                                   HDR_SPACE + off + _REC.size + ln]
        if scanner is not None:
            csums = scanner(view)
            ok = csums.combined == (want & 0xFFFFFFFF)
        else:
            ok, csums = crcutil.verify_blocks(
                view, crcutil.CSUM_BLOCK, want, site="verify")
        if not ok:
            raise WireError("shm payload crc mismatch")
        magic, g, l = self._rec_hdr(off)      # seqlock re-check
        if magic != REC_MAGIC or g != gen:
            raise WireError("shm record overwritten mid-read")
        pc = crcutil._counters()
        pc.inc("shm_frames_served")
        pc.inc("shm_bytes_served", ln)
        return view, csums

    def close(self) -> None:
        try:
            self.mm.close()
        except (BufferError, ValueError):
            pass                      # exported views keep it alive
