from .queue import (Envelope, MessageQueue, QueueClosed, QueueFull,
                    MSG_OSD_OP, MSG_OSD_OP_REPLY, MSG_EC_SUB_WRITE,
                    MSG_EC_SUB_WRITE_REPLY, MSG_EC_SUB_READ,
                    MSG_EC_SUB_READ_REPLY, MSG_PING)
from .dispatcher import BatchingDispatcher, ShardFanout

__all__ = ["Envelope", "MessageQueue", "QueueClosed", "QueueFull",
           "BatchingDispatcher", "ShardFanout",
           "MSG_OSD_OP", "MSG_OSD_OP_REPLY", "MSG_EC_SUB_WRITE",
           "MSG_EC_SUB_WRITE_REPLY", "MSG_EC_SUB_READ",
           "MSG_EC_SUB_READ_REPLY", "MSG_PING"]
