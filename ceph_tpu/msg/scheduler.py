"""Op scheduler — dmClock-style QoS queues.

Role of the OSD's OpScheduler (src/osd/scheduler/OpScheduler.{h,cc},
mClockScheduler.cc over the dmclock library): classify incoming ops
(client / background-recovery / background-best-effort, the reference's
op_scheduler_class) and dequeue by mClock tags so every class gets its
RESERVATION (minimum rate), shares leftover capacity by WEIGHT, and
never exceeds its LIMIT.

Compact single-server dmClock: per class (r, w, l) in ops/sec; each op
gets reservation/proportion/limit tags from the class's previous tags;
dequeue picks (1) the earliest eligible reservation tag, else (2) the
smallest proportion tag among classes under their limit.  Virtual time
is a monotonic counter advanced per dequeue, so the scheduler is
deterministic under test while preserving the dmClock invariants.

Per-TENANT client classes (the dmclock multi-client role the
reference drives through osd_mclock_scheduler_client_* per client
profile): class names of the form ``client.<tenant>`` auto-register
on first enqueue with the tenant defaults (or an explicit
``set_qos`` entry), so a gateway's tenant identity — propagated from
S3 auth through the objecter into op dispatch — lands each tenant in
its OWN dmClock class.  Because virtual time advances one unit per
dequeue, a reservation r is a guaranteed FRACTION of dispatch slots
under backlog: a noisy tenant with a huge weight cannot push a
reserved tenant below its r floor (the invariant the serving
harness asserts).
"""
from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

CLASS_CLIENT = "client"
CLASS_RECOVERY = "background_recovery"
CLASS_BEST_EFFORT = "background_best_effort"

TENANT_PREFIX = "client."


def tenant_class(tenant: str) -> str:
    """The scheduler class a tenant's client ops dispatch under."""
    return TENANT_PREFIX + str(tenant)


@dataclass(frozen=True)
class QoS:
    """Per-class service parameters (osd_mclock_scheduler_*_res/wgt/lim)."""
    reservation: float           # guaranteed ops per unit time (0 = none)
    weight: float                # share of leftover capacity
    limit: float = float("inf")  # hard cap, ops per unit time


DEFAULT_QOS: Dict[str, QoS] = {
    CLASS_CLIENT: QoS(reservation=1.0, weight=2.0),
    CLASS_RECOVERY: QoS(reservation=0.25, weight=1.0, limit=2.0),
    CLASS_BEST_EFFORT: QoS(reservation=0.0, weight=0.5, limit=1.0),
}


@dataclass
class _Tagged:
    seq: int
    op: Any
    r_tag: float
    p_tag: float
    l_tag: float


class MClockScheduler:
    """enqueue(op, class) / dequeue() with dmClock tag selection.

    ``client.<tenant>`` classes auto-register on first enqueue (the
    dynamic per-tenant client profiles); every other unknown class
    still raises — a typo'd background class is a bug, not a tenant.
    """

    def __init__(self, qos: Optional[Dict[str, QoS]] = None,
                 tenant_default: Optional[QoS] = None):
        self.qos = dict(DEFAULT_QOS)
        if qos:
            self.qos.update(qos)
        # QoS for tenant classes that were never explicitly
        # configured (osd_mclock_scheduler_client_* defaults)
        self.tenant_default = tenant_default or \
            self.qos[CLASS_CLIENT]
        self._queues: Dict[str, List[_Tagged]] = {
            c: [] for c in self.qos}
        self._last: Dict[str, _Tagged] = {}
        self._seq = itertools.count()
        self._vt = 0.0                    # virtual time
        self.stats = {c: 0 for c in self.qos}

    def set_qos(self, klass: str, qos: QoS) -> None:
        """Register or retune one class's (r, w, l) at runtime — the
        `osd_mclock_scheduler_client_*` per-tenant knobs.  Existing
        queue entries keep their tags; new enqueues tag under the
        new parameters."""
        self.qos[klass] = qos
        self._queues.setdefault(klass, [])
        self.stats.setdefault(klass, 0)

    # dynamic tenant classes are bounded: the tenant tag is a
    # caller-supplied label on an authenticated session, and an
    # adversarial client cycling unique tags must not grow the
    # scheduler state without limit — past the cap, unconfigured
    # tenants fold into the plain client class (explicitly
    # set_qos'd tenants never fold; they were configured by the
    # operator)
    MAX_DYNAMIC_TENANTS = 64

    def ensure_class(self, klass: str) -> str:
        """Find-or-register ``klass``; returns the class the op will
        actually dispatch under (tenant classes vivify with the
        tenant default up to MAX_DYNAMIC_TENANTS, then fold to the
        plain client class; any other unknown class raises)."""
        if klass in self.qos:
            return klass
        if not klass.startswith(TENANT_PREFIX):
            raise KeyError(f"unknown scheduler class {klass!r}")
        n_tenants = sum(1 for k in self.qos
                        if k.startswith(TENANT_PREFIX))
        if n_tenants >= self.MAX_DYNAMIC_TENANTS:
            return CLASS_CLIENT
        self.set_qos(klass, self.tenant_default)
        return klass

    def enqueue(self, op: Any, klass: str = CLASS_CLIENT) -> None:
        klass = self.ensure_class(klass)
        q = self.qos[klass]
        prev = self._last.get(klass)
        now = self._vt
        r_tag = now if q.reservation <= 0 else max(
            now, (prev.r_tag + 1.0 / q.reservation) if prev else now)
        # weight 0 is a legal "starved" profile (tenant QoS specs):
        # tags space by a huge-but-finite stride instead of dividing
        # by zero, so the class drains work-conservingly, last
        wgt = max(q.weight, 1e-9)
        p_tag = max(now, (prev.p_tag + 1.0 / wgt) if prev else now)
        l_tag = now if q.limit == float("inf") else max(
            now, (prev.l_tag + 1.0 / q.limit) if prev else now)
        t = _Tagged(next(self._seq), op, r_tag, p_tag, l_tag)
        self._last[klass] = t
        self._queues[klass].append(t)

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def dequeue(self) -> Optional[Tuple[str, Any]]:
        """One op by dmClock selection; None when idle."""
        if not len(self):
            return None
        self._vt += 1.0
        now = self._vt
        # phase 1: earliest ELIGIBLE reservation tag (tag <= now)
        best = None
        for klass, q in self._queues.items():
            if not q or self.qos[klass].reservation <= 0:
                continue
            head = q[0]
            if head.r_tag <= now and (
                    best is None or head.r_tag < best[1].r_tag):
                best = (klass, head)
        if best is None:
            # phase 2: smallest proportion tag among under-limit classes
            for klass, q in self._queues.items():
                if not q:
                    continue
                head = q[0]
                if head.l_tag > now:
                    continue             # over limit
                if best is None or head.p_tag < best[1].p_tag:
                    best = (klass, head)
        if best is None:
            # everything over limit: take the earliest limit tag so the
            # queue still drains (work-conserving fallback)
            for klass, q in self._queues.items():
                if not q:
                    continue
                head = q[0]
                if best is None or head.l_tag < best[1].l_tag:
                    best = (klass, head)
        klass, head = best
        self._queues[klass].pop(0)
        self.stats[klass] += 1
        return klass, head.op
