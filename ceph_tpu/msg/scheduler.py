"""Op scheduler — dmClock-style QoS queues.

Role of the OSD's OpScheduler (src/osd/scheduler/OpScheduler.{h,cc},
mClockScheduler.cc over the dmclock library): classify incoming ops
(client / background-recovery / background-best-effort, the reference's
op_scheduler_class) and dequeue by mClock tags so every class gets its
RESERVATION (minimum rate), shares leftover capacity by WEIGHT, and
never exceeds its LIMIT.

Compact single-server dmClock: per class (r, w, l) in ops/sec; each op
gets reservation/proportion/limit tags from the class's previous tags;
dequeue picks (1) the earliest eligible reservation tag, else (2) the
smallest proportion tag among classes under their limit.  Virtual time
is a monotonic counter advanced per dequeue, so the scheduler is
deterministic under test while preserving the dmClock invariants.
"""
from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

CLASS_CLIENT = "client"
CLASS_RECOVERY = "background_recovery"
CLASS_BEST_EFFORT = "background_best_effort"


@dataclass(frozen=True)
class QoS:
    """Per-class service parameters (osd_mclock_scheduler_*_res/wgt/lim)."""
    reservation: float           # guaranteed ops per unit time (0 = none)
    weight: float                # share of leftover capacity
    limit: float = float("inf")  # hard cap, ops per unit time


DEFAULT_QOS: Dict[str, QoS] = {
    CLASS_CLIENT: QoS(reservation=1.0, weight=2.0),
    CLASS_RECOVERY: QoS(reservation=0.25, weight=1.0, limit=2.0),
    CLASS_BEST_EFFORT: QoS(reservation=0.0, weight=0.5, limit=1.0),
}


@dataclass
class _Tagged:
    seq: int
    op: Any
    r_tag: float
    p_tag: float
    l_tag: float


class MClockScheduler:
    """enqueue(op, class) / dequeue() with dmClock tag selection."""

    def __init__(self, qos: Optional[Dict[str, QoS]] = None):
        self.qos = dict(DEFAULT_QOS)
        if qos:
            self.qos.update(qos)
        self._queues: Dict[str, List[_Tagged]] = {
            c: [] for c in self.qos}
        self._last: Dict[str, _Tagged] = {}
        self._seq = itertools.count()
        self._vt = 0.0                    # virtual time
        self.stats = {c: 0 for c in self.qos}

    def enqueue(self, op: Any, klass: str = CLASS_CLIENT) -> None:
        q = self.qos.get(klass)
        if q is None:
            raise KeyError(f"unknown scheduler class {klass!r}")
        prev = self._last.get(klass)
        now = self._vt
        r_tag = now if q.reservation <= 0 else max(
            now, (prev.r_tag + 1.0 / q.reservation) if prev else now)
        p_tag = max(now, (prev.p_tag + 1.0 / q.weight) if prev else now)
        l_tag = now if q.limit == float("inf") else max(
            now, (prev.l_tag + 1.0 / q.limit) if prev else now)
        t = _Tagged(next(self._seq), op, r_tag, p_tag, l_tag)
        self._last[klass] = t
        self._queues[klass].append(t)

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def dequeue(self) -> Optional[Tuple[str, Any]]:
        """One op by dmClock selection; None when idle."""
        if not len(self):
            return None
        self._vt += 1.0
        now = self._vt
        # phase 1: earliest ELIGIBLE reservation tag (tag <= now)
        best = None
        for klass, q in self._queues.items():
            if not q or self.qos[klass].reservation <= 0:
                continue
            head = q[0]
            if head.r_tag <= now and (
                    best is None or head.r_tag < best[1].r_tag):
                best = (klass, head)
        if best is None:
            # phase 2: smallest proportion tag among under-limit classes
            for klass, q in self._queues.items():
                if not q:
                    continue
                head = q[0]
                if head.l_tag > now:
                    continue             # over limit
                if best is None or head.p_tag < best[1].p_tag:
                    best = (klass, head)
        if best is None:
            # everything over limit: take the earliest limit tag so the
            # queue still drains (work-conserving fallback)
            for klass, q in self._queues.items():
                if not q:
                    continue
                head = q[0]
                if best is None or head.l_tag < best[1].l_tag:
                    best = (klass, head)
        klass, head = best
        self._queues[klass].pop(0)
        self.stats[klass] += 1
        return klass, head.op
