"""Typed message queues over the native C++ runtime — messenger-analog.

The reference's Messenger stack (src/msg/Messenger.cc:15-42 transport
selection, AsyncMessenger worker loops, DispatchQueue, per-peer Throttle
policies, and the 170 typed classes in src/messages/) exists to move
typed, flow-controlled messages between daemons.  On the TPU runtime
the hop that matters is host producers → batched device dispatch; what
this layer preserves (SURVEY.md §2.4) is:

  * typed request/reply envelopes (the src/messages/ role — a compact
    type tag instead of 170 subclasses),
  * backpressure: bounded item+byte throttles with blocking producers
    (src/common/Throttle.h role),
  * batch forming: the consumer drains up to N envelopes or lingers
    T µs so device dispatches stay large (DispatchQueue role).

The queue core is C++ (native/msgqueue.cpp) behind ctypes, matching
the reference's native messenger; this module is the typed veneer.
"""
from __future__ import annotations

import ctypes
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .. import native_bridge

# message types (role of src/messages/M*.h — the subset the framework
# speaks; values are arbitrary but stable)
MSG_PING = 1                  # MOSDPing
MSG_OSD_OP = 10               # MOSDOp
MSG_OSD_OP_REPLY = 11         # MOSDOpReply
MSG_EC_SUB_WRITE = 20         # MOSDECSubOpWrite
MSG_EC_SUB_WRITE_REPLY = 21   # MOSDECSubOpWriteReply
MSG_EC_SUB_READ = 22          # MOSDECSubOpRead
MSG_EC_SUB_READ_REPLY = 23    # MOSDECSubOpReadReply


class QueueFull(RuntimeError):
    """Throttle exhausted and the push deadline passed."""


class QueueClosed(RuntimeError):
    pass


@dataclass(frozen=True)
class Envelope:
    type: int
    id: int
    shard: int
    payload: bytes          # bytes, or a zero-copy memoryview over
    #                         the receive buffer (wire.SockReader)
    # trusted per-block sub-crcs from the wire's one-pass verify scan
    # (common/crcutil.Csums) — present only on scatter-gather request
    # frames received in crc mode; the store consumes them as blob
    # csums without re-scanning the payload
    csums: Optional[object] = None


_U8P = ctypes.POINTER(ctypes.c_uint8)
_configured = False


def _lib() -> ctypes.CDLL:
    global _configured
    lib = native_bridge.lib()
    if not _configured:
        lib.ceph_tpu_mq_create.restype = ctypes.c_void_p
        lib.ceph_tpu_mq_create.argtypes = [ctypes.c_uint64,
                                           ctypes.c_uint64]
        lib.ceph_tpu_mq_destroy.argtypes = [ctypes.c_void_p]
        lib.ceph_tpu_mq_close.argtypes = [ctypes.c_void_p]
        lib.ceph_tpu_mq_push.restype = ctypes.c_int
        lib.ceph_tpu_mq_push.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_int32, _U8P, ctypes.c_uint64, ctypes.c_int64]
        lib.ceph_tpu_mq_pop_batch.restype = ctypes.c_int64
        lib.ceph_tpu_mq_pop_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(_U8P),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.ceph_tpu_mq_free_payload.argtypes = [_U8P]
        lib.ceph_tpu_mq_stats.argtypes = [ctypes.c_void_p] + \
            [ctypes.POINTER(ctypes.c_uint64)] * 5
        _configured = True
    return lib


class MessageQueue:
    """Bounded typed queue with byte+item throttles (native-backed)."""

    def __init__(self, capacity_items: int = 4096,
                 capacity_bytes: int = 1 << 30):
        self._lib = _lib()
        self._q = self._lib.ceph_tpu_mq_create(capacity_items,
                                               capacity_bytes)
        if not self._q:
            raise MemoryError("mq_create failed")

    def push(self, env: Envelope, timeout: Optional[float] = None) -> None:
        """Blocks while the throttle is exhausted; QueueFull on
        deadline, QueueClosed after close()."""
        payload = env.payload or b""
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload) \
            if payload else None
        t_us = -1 if timeout is None else int(timeout * 1e6)
        rc = self._lib.ceph_tpu_mq_push(
            self._q, env.type, env.id, env.shard,
            ctypes.cast(buf, _U8P) if buf else None,
            len(payload), t_us)
        if rc == -1:
            raise QueueFull(f"push timed out after {timeout}s")
        if rc == -2:
            raise QueueClosed("queue closed")
        if rc == -3:
            raise ValueError("payload exceeds queue byte capacity")
        if rc == -4:
            raise MemoryError("envelope payload allocation failed")

    def pop_batch(self, max_items: int = 256,
                  max_bytes: int = 1 << 30,
                  wait_first: Optional[float] = 1.0,
                  linger: float = 0.0) -> List[Envelope]:
        """Blocks up to ``wait_first`` for one envelope, then drains up
        to the caps, lingering ``linger`` seconds for stragglers (the
        batch-forming window).  Empty list on timeout/close."""
        n = max_items
        types = (ctypes.c_uint32 * n)()
        ids = (ctypes.c_uint64 * n)()
        shards = (ctypes.c_int32 * n)()
        payloads = (_U8P * n)()
        lens = (ctypes.c_uint64 * n)()
        w_us = -1 if wait_first is None else int(wait_first * 1e6)
        got = self._lib.ceph_tpu_mq_pop_batch(
            self._q, n, max_bytes, w_us, int(linger * 1e6),
            types, ids, shards, payloads, lens)
        out: List[Envelope] = []
        for i in range(got):
            ln = lens[i]
            data = ctypes.string_at(payloads[i], ln) if ln else b""
            if payloads[i]:
                self._lib.ceph_tpu_mq_free_payload(payloads[i])
            out.append(Envelope(types[i], ids[i], shards[i], data))
        return out

    def stats(self) -> dict:
        vals = [ctypes.c_uint64() for _ in range(5)]
        self._lib.ceph_tpu_mq_stats(self._q, *[ctypes.byref(v)
                                               for v in vals])
        keys = ("depth", "bytes", "pushed", "popped", "throttle_waits")
        return dict(zip(keys, (v.value for v in vals)))

    def close(self) -> None:
        if self._q:
            self._lib.ceph_tpu_mq_close(self._q)

    def destroy(self) -> None:
        """Free the native queue.  The native side closes the queue,
        wakes all waiters, and defers the delete until every REGISTERED
        in-flight push/pop_batch/stats call has drained (Queue::inflight
        covers the call from its first instruction), so destroying with
        parked waiter threads is safe.  A thread that has called into an
        entry point but not yet executed its first instruction is
        indistinguishable from a new call — callers must ensure no calls
        can START once destroy begins (stop producers/consumers first;
        threads already blocked inside the queue need no joining)."""
        if self._q:
            self._lib.ceph_tpu_mq_destroy(self._q)
            self._q = None

    def __del__(self):
        # close (wakes waiters) but deliberately LEAK the native queue:
        # a racing push/pop entered AFTER interpreter teardown began
        # could still touch a freed Queue header; callers with
        # known-quiesced queues use destroy() explicitly
        try:
            self.close()
        except Exception:
            pass
