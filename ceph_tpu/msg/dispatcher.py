"""Batching dispatcher + shard fan-out/gather over MessageQueues.

Two messenger roles on top of the native queues:

  * BatchingDispatcher — the consumer loop in front of a jitted kernel:
    a worker thread drains envelope batches and hands them to a
    handler whose replies (if any) are routed to a reply queue.  This
    is the OSD-side pattern `ms_fast_dispatch -> sharded OpScheduler ->
    dequeue` (src/osd/OSD.cc:7114,9745) collapsed to one stage whose
    queue IS the batch former.
  * ShardFanout — the ECBackend primary pattern: send one sub-op per
    shard queue, gather k+m acks before completing the op
    (src/osd/ECBackend.cc: per-shard MOSDECSubOpWrite fan-out,
    handle_sub_write_reply gathering).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

from ..common import faults
from ..common import tracer as _trace
from ..common.lockdep import LockdepLock
from ..common.perf_counters import perf as _perf
from .queue import Envelope, MessageQueue

Handler = Callable[[List[Envelope]], Optional[List[Envelope]]]


class BatchingDispatcher:
    """Worker thread: pop_batch(in_q) -> handler -> push(reply_q)."""

    def __init__(self, in_q: MessageQueue, handler: Handler,
                 reply_q: Optional[MessageQueue] = None,
                 max_items: int = 256, linger: float = 0.0005,
                 name: str = "dispatcher"):
        self.in_q = in_q
        self.reply_q = reply_q
        self.handler = handler
        self.max_items = max_items
        self.linger = linger
        self.last_error: Optional[Exception] = None
        self._pc = _perf(f"msg.{name}")
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=name)

    def start(self) -> "BatchingDispatcher":
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self.in_q.pop_batch(max_items=self.max_items,
                                        wait_first=0.05,
                                        linger=self.linger)
            if not batch:
                continue
            self._pc.inc("batches")
            self._pc.inc("envelopes", len(batch))
            self._pc.inc("bytes", sum(len(e.payload) for e in batch))
            try:
                with self._pc.time("handle_s"):
                    replies = self.handler(batch)
                if replies and self.reply_q is not None:
                    for r in replies:
                        self.reply_q.push(r)
            except Exception as e:           # the loop must survive: a
                # dead worker silently deadlocks every producer on the
                # bounded queue
                self._pc.inc("handler_errors")
                self.last_error = e

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(timeout)


class ShardFanout:
    """Primary-side fan-out/gather: one envelope per shard queue, op
    completes when every shard acked (or fails on nack)."""

    def __init__(self, shard_queues: Sequence[MessageQueue],
                 ack_q: MessageQueue, entity: str = "client",
                 shard_entities: Optional[Sequence[str]] = None):
        """``entity``/``shard_entities`` name this primary and its
        shard servers for the ``net.partition`` faultpoint: a severed
        sub-op is never enqueued (the peer's frame vanished), so the
        gather sees a missing ack — exactly a netsplit's face."""
        self.shard_queues = list(shard_queues)
        self.ack_q = ack_q
        self.entity = entity
        self.shard_entities = list(shard_entities) if shard_entities \
            else [f"shard.{i}" for i in range(len(self.shard_queues))]
        self._lock = LockdepLock("msg.fanout", recursive=False)
        self._pending: Dict[int, Dict] = {}
        self._pc = _perf("msg.fanout")

    def submit(self, op_id: int, msg_type: int,
               shard_payloads: Sequence[bytes],
               tctx: Optional[Sequence[int]] = None) -> None:
        """``tctx`` links this fan-out under an active trace: the
        sub-op scatter is a stage of the op that triggered it (the
        CTL701 propagation contract for dispatch fan-out sites).
        Callers without an explicit context inherit the submitting
        thread's active span."""
        if len(shard_payloads) != len(self.shard_queues):
            raise ValueError("one payload per shard queue")
        with self._lock:
            self._pending[op_id] = {
                "want": len(shard_payloads), "got": 0, "failed": False,
                "event": threading.Event()}
        self._pc.inc("ops_submitted")
        # service = the fanning-out entity (this primary), not the
        # process-wide default — sim-tier spans must name who ran them
        with _trace.linked_span("msg.fanout", tctx,
                                service=self.entity,
                                shards=len(shard_payloads)):
            for shard, (q, payload) in enumerate(
                    zip(self.shard_queues, shard_payloads)):
                if faults.partitioned(self.entity,
                                      self.shard_entities[shard]):
                    # the frame is lost on the cut link: no push, no
                    # ack — the waiter's timeout is the failure
                    # signal, as on a real netsplit (a nack would be
                    # a delivered frame)
                    self._pc.inc("subops_partitioned")
                    continue
                q.push(Envelope(msg_type, op_id, shard, payload))

    def ack(self, op_id: int, shard: int, ok: bool = True) -> None:
        """Called by shard servers (normally via the ack queue)."""
        with self._lock:
            st = self._pending.get(op_id)
            if st is None:
                return
            if not ok:
                st["failed"] = True
            st["got"] += 1
            if st["got"] >= st["want"]:
                st["event"].set()

    def pump_acks(self, wait_first: float = 0.05) -> int:
        """Drain the ack queue into pending-op state; returns count."""
        batch = self.ack_q.pop_batch(wait_first=wait_first, linger=0.0)
        for e in batch:
            self.ack(e.id, e.shard, ok=(not e.payload or
                                        e.payload[0] == 0))
        return len(batch)

    def wait(self, op_id: int, timeout: float = 10.0) -> bool:
        """True when all shards acked ok; raises on failed sub-op."""
        with self._lock:
            st = self._pending.get(op_id)
        if st is None:
            raise KeyError(f"unknown op {op_id}")
        import time
        t_end = time.monotonic() + timeout
        while not st["event"].is_set():
            if time.monotonic() > t_end:
                return False
            self.pump_acks(wait_first=0.02)
        with self._lock:
            self._pending.pop(op_id, None)
        if st["failed"]:
            raise IOError(f"op {op_id}: sub-op failed")
        self._pc.inc("ops_completed")
        return True
