"""Typed wire encoding — the src/messages/ encode/decode role.

Every daemon payload is a tree of {None, bool, int, float, str, bytes,
list/tuple, dict}; this module serializes exactly that set with
tag-length-value framing and NOTHING else.  Replaces pickle on all
network input (VERDICT r3 missing #6: unauthenticated pickle is
RCE-adjacent; the reference encodes typed message structs, it never
deserializes arbitrary objects — src/include/encoding.h).

Wire grammar (all integers little-endian):
    N                         None
    T / F                     True / False
    i <i64>                   int (fits 64-bit signed)
    I <u32 len> <bytes>       big int (signed, two's complement)
    d <f64>                   float
    s <u32 len> <utf8>        str
    b <u32 len> <bytes>       bytes
    l <u32 count> item*       list (tuples encode as lists)
    m <u32 count> (key value)*  dict
Decoding enforces a depth limit and rejects unknown tags.
"""
from __future__ import annotations

import struct
from typing import Any, Tuple

_I64 = struct.Struct("<q")
_U32 = struct.Struct("<I")
_F64 = struct.Struct("<d")

MAX_DEPTH = 32


class EncodingError(ValueError):
    pass


def _hashable(k):
    """Decoded dict keys: lists (wire form of tuples) convert back to
    tuples RECURSIVELY so nested-tuple keys round-trip."""
    if isinstance(k, list):
        return tuple(_hashable(x) for x in k)
    return k


def _enc(obj: Any, out: bytearray, depth: int) -> None:
    if depth > MAX_DEPTH:
        raise EncodingError("structure too deep")
    if obj is None:
        out.append(ord("N"))
    elif obj is True:
        out.append(ord("T"))
    elif obj is False:
        out.append(ord("F"))
    elif isinstance(obj, int):
        if -(1 << 63) <= obj < (1 << 63):
            out.append(ord("i"))
            out.extend(_I64.pack(obj))
        else:
            raw = obj.to_bytes((obj.bit_length() + 8) // 8,
                               "little", signed=True)
            out.append(ord("I"))
            out.extend(_U32.pack(len(raw)))
            out.extend(raw)
    elif isinstance(obj, float):
        out.append(ord("d"))
        out.extend(_F64.pack(obj))
    elif isinstance(obj, str):
        raw = obj.encode()
        out.append(ord("s"))
        out.extend(_U32.pack(len(raw)))
        out.extend(raw)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        raw = bytes(obj)
        out.append(ord("b"))
        out.extend(_U32.pack(len(raw)))
        out.extend(raw)
    elif isinstance(obj, (list, tuple)):
        out.append(ord("l"))
        out.extend(_U32.pack(len(obj)))
        for item in obj:
            _enc(item, out, depth + 1)
    elif isinstance(obj, dict):
        out.append(ord("m"))
        out.extend(_U32.pack(len(obj)))
        for k, v in obj.items():
            _enc(k, out, depth + 1)
            _enc(v, out, depth + 1)
    else:
        raise EncodingError(
            f"type {type(obj).__name__} is not wire-encodable")


def dumps(obj: Any) -> bytes:
    out = bytearray()
    _enc(obj, out, 0)
    return bytes(out)


def _dec(buf: bytes, pos: int, depth: int) -> Tuple[Any, int]:
    if depth > MAX_DEPTH:
        raise EncodingError("structure too deep")
    if pos >= len(buf):
        raise EncodingError("truncated")
    tag = buf[pos]
    pos += 1
    if tag == ord("N"):
        return None, pos
    if tag == ord("T"):
        return True, pos
    if tag == ord("F"):
        return False, pos
    if tag == ord("i"):
        if pos + 8 > len(buf):
            raise EncodingError("truncated i64")
        return _I64.unpack_from(buf, pos)[0], pos + 8
    if tag == ord("d"):
        if pos + 8 > len(buf):
            raise EncodingError("truncated f64")
        return _F64.unpack_from(buf, pos)[0], pos + 8
    if tag in (ord("I"), ord("s"), ord("b")):
        if pos + 4 > len(buf):
            raise EncodingError("truncated length")
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        raw = buf[pos:pos + n]
        if len(raw) != n:
            raise EncodingError("truncated payload")
        pos += n
        if tag == ord("I"):
            return int.from_bytes(raw, "little", signed=True), pos
        if tag == ord("s"):
            return raw.decode(), pos
        return raw, pos
    if tag == ord("l"):
        if pos + 4 > len(buf):
            raise EncodingError("truncated count")
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _dec(buf, pos, depth + 1)
            items.append(item)
        return items, pos
    if tag == ord("m"):
        if pos + 4 > len(buf):
            raise EncodingError("truncated count")
        (n,) = _U32.unpack_from(buf, pos)
        pos += 4
        d = {}
        for _ in range(n):
            k, pos = _dec(buf, pos, depth + 1)
            v, pos = _dec(buf, pos, depth + 1)
            d[_hashable(k)] = v
        return d, pos
    raise EncodingError(f"unknown tag {tag:#x}")


def loads(buf: bytes) -> Any:
    obj, pos = _dec(bytes(buf), 0, 0)  # noqa: CTL130 — typed metas
    # are ~100 bytes; bulk payloads never pass through this decoder
    # (they ride the scatter-gather frame tail / shm ring)
    if pos != len(buf):
        raise EncodingError(f"{len(buf) - pos} trailing bytes")
    return obj
