"""RBD-style block images — striped virtual block devices over objects.

Role of src/librbd/ (block images striped across RADOS objects: image
metadata in a header object, data in `<prefix>.<objectno>` objects,
random-offset read/write, resize) built on the striper math
(FileLayout/file_to_extents — the same layout librbd's default
striping v1 uses: stripe_unit == object_size, stripe_count == 1,
order=22 -> 4 MiB objects) and the IoCtx client surface.

Kept behaviors: create/open/remove/list, size/resize (shrink discards
whole objects past the boundary), offset read/write crossing object
boundaries, sparse reads of never-written ranges as zeros.
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import List, Optional

from ..cluster.striper import FileLayout, file_to_extents
from .rados import IoCtx, ObjectNotFound

_DIR_OID = "rbd_directory"


class ImageExists(ValueError):
    pass


class ImageNotFound(KeyError):
    pass


@dataclass
class ImageInfo:
    name: str
    size: int
    order: int                   # object size = 1 << order
    object_prefix: str

    @property
    def layout(self) -> FileLayout:
        osize = 1 << self.order
        return FileLayout(stripe_unit=osize, stripe_count=1,
                          object_size=osize)


class RBD:
    """Image directory ops (librbd `RBD` class)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    def _dir(self) -> dict:
        try:
            return json.loads(self.ioctx.read(_DIR_OID).decode())
        except ObjectNotFound:
            return {}

    def _write_dir(self, d: dict) -> None:
        self.ioctx.write_full(_DIR_OID, json.dumps(d).encode())

    def create(self, name: str, size: int, order: int = 22) -> None:
        d = self._dir()
        if name in d:
            raise ImageExists(name)
        info = {"size": size, "order": order,
                "object_prefix": f"rbd_data.{name}"}
        d[name] = info
        self.ioctx.write_full(f"rbd_header.{name}",
                              json.dumps(info).encode())
        self._write_dir(d)

    def list(self) -> List[str]:
        return sorted(self._dir())

    def remove(self, name: str) -> None:
        d = self._dir()
        if name not in d:
            raise ImageNotFound(name)
        img = Image(self.ioctx, name)
        for objno in img._written_objects():
            try:
                self.ioctx.remove(img._oid(objno))
            except ObjectNotFound:
                pass
        self.ioctx.remove(f"rbd_header.{name}")
        del d[name]
        self._write_dir(d)


class Image:
    """One open image (librbd `Image`)."""

    def __init__(self, ioctx: IoCtx, name: str):
        self.ioctx = ioctx
        self.name = name
        try:
            raw = ioctx.read(f"rbd_header.{name}")
        except ObjectNotFound:
            raise ImageNotFound(name) from None
        meta = json.loads(raw.decode())
        self.info = ImageInfo(name=name, size=meta["size"],
                              order=meta["order"],
                              object_prefix=meta["object_prefix"])

    # ------------------------------------------------------------ layout --
    def _oid(self, objno: int) -> str:
        return f"{self.info.object_prefix}.{objno:016x}"

    def _written_objects(self) -> List[int]:
        prefix = self.info.object_prefix + "."
        out = []
        for oid in self.ioctx.list_objects():
            if not oid.startswith(prefix):
                continue
            suffix = oid[len(prefix):]
            # another image's name may extend this prefix ('a' vs
            # 'a.b'): only exact 16-hex-digit suffixes are ours
            if len(suffix) == 16:
                try:
                    out.append(int(suffix, 16))
                except ValueError:
                    pass
        return sorted(out)

    def size(self) -> int:
        return self.info.size

    def _save_header(self) -> None:
        self.ioctx.write_full(
            f"rbd_header.{self.name}",
            json.dumps({"size": self.info.size,
                        "order": self.info.order,
                        "object_prefix": self.info.object_prefix})
            .encode())

    # --------------------------------------------------------------- i/o --
    def write(self, offset: int, data: bytes) -> int:
        if offset + len(data) > self.info.size:
            raise ValueError("write past image size")
        pos = 0
        for objno, ooff, olen in file_to_extents(
                self.info.layout, offset, len(data)):
            self.ioctx.write(self._oid(objno), data[pos:pos + olen],
                             offset=ooff)
            pos += olen
        return len(data)

    def read(self, offset: int, length: int) -> bytes:
        if offset + length > self.info.size:
            length = max(0, self.info.size - offset)
        out = bytearray(length)
        pos = 0
        for objno, ooff, olen in file_to_extents(
                self.info.layout, offset, length):
            try:
                piece = self.ioctx.read(self._oid(objno), length=olen,
                                        offset=ooff)
            except ObjectNotFound:
                piece = b""                 # sparse: zeros
            out[pos:pos + len(piece)] = piece
            pos += olen
        return bytes(out)

    def resize(self, new_size: int) -> None:
        """Grow is metadata-only; shrink discards objects wholly past
        the boundary AND zero-truncates the boundary object (librbd
        trim semantics — stale bytes must not reappear after a later
        grow)."""
        if new_size < self.info.size:
            osize = 1 << self.info.order
            first_dead = -(-new_size // osize)
            for objno in self._written_objects():
                if objno >= first_dead:
                    try:
                        self.ioctx.remove(self._oid(objno))
                    except ObjectNotFound:
                        pass
            cut = new_size % osize
            if cut:
                bno = new_size // osize
                try:
                    cur = self.ioctx.read(self._oid(bno))
                except ObjectNotFound:
                    cur = b""
                if len(cur) > cut:
                    self.ioctx.write_full(self._oid(bno), cur[:cut])
        self.info.size = new_size
        self._save_header()
