"""RBD-style block images — striped virtual block devices over objects.

Role of src/librbd/ (block images striped across RADOS objects: image
metadata in a header object, data in `<prefix>.<objectno>` objects,
random-offset read/write, resize) built on the striper math
(FileLayout/file_to_extents — the same layout librbd's default
striping v1 uses: stripe_unit == object_size, stripe_count == 1,
order=22 -> 4 MiB objects) and the IoCtx client surface.

Kept behaviors: create/open/remove/list, size/resize (shrink discards
whole objects past the boundary), offset read/write crossing object
boundaries, sparse reads of never-written ranges as zeros.
"""
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import List, Optional

from ..cluster.striper import FileLayout, file_to_extents
from .rados import IoCtx, ObjectNotFound

_DIR_OID = "rbd_directory"


class ImageExists(ValueError):
    pass


class ImageNotFound(KeyError):
    pass


@dataclass
class ImageInfo:
    name: str
    size: int
    order: int                   # object size = 1 << order
    object_prefix: str

    @property
    def layout(self) -> FileLayout:
        osize = 1 << self.order
        return FileLayout(stripe_unit=osize, stripe_count=1,
                          object_size=osize)


class RBD:
    """Image directory ops (librbd `RBD` class)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    def _dir(self) -> dict:
        try:
            return json.loads(self.ioctx.read(_DIR_OID).decode())
        except ObjectNotFound:
            return {}

    def _write_dir(self, d: dict) -> None:
        self.ioctx.write_full(_DIR_OID, json.dumps(d).encode())

    def create(self, name: str, size: int, order: int = 22) -> None:
        d = self._dir()
        if name in d:
            raise ImageExists(name)
        info = {"size": size, "order": order,
                "object_prefix": f"rbd_data.{name}"}
        d[name] = info
        self.ioctx.write_full(f"rbd_header.{name}",
                              json.dumps(info).encode())
        self._write_dir(d)

    def list(self) -> List[str]:
        return sorted(self._dir())

    def remove(self, name: str) -> None:
        d = self._dir()
        if name not in d:
            raise ImageNotFound(name)
        img = Image(self.ioctx, name)
        if img.children():
            raise ValueError(f"image {name} has clone children")
        if img.parent is not None:
            # detach from the parent snap's children list so the
            # parent can later be unprotected/removed
            try:
                parent = Image(self.ioctx, img.parent["image"])
                rec = parent.snaps.get(img.parent["snap"])
                if rec and name in rec.get("children", []):
                    rec["children"].remove(name)
                    parent._save_header()
            except ImageNotFound:
                pass
        for objno in img._written_objects():
            try:
                self.ioctx.remove(img._oid(objno))
            except ObjectNotFound:
                pass
        self.ioctx.remove(f"rbd_header.{name}")
        del d[name]
        self._write_dir(d)

    def clone(self, parent_name: str, parent_snap: str,
              child_name: str) -> None:
        """Layering (librbd clone): the child starts as a sparse image
        whose reads fall through to the parent's PROTECTED snapshot;
        writes copy-up the touched object first (librbd
        CopyupRequest role)."""
        parent = Image(self.ioctx, parent_name)
        if parent.parent is not None:
            raise ValueError(
                f"{parent_name} is itself an unflattened clone — "
                "flatten it before cloning from it (chains unsupported)")
        rec = parent.snaps.get(parent_snap)
        if rec is None:
            raise KeyError(f"{parent_name} has no snap {parent_snap!r}")
        if not rec.get("protected"):
            raise ValueError(
                f"snap {parent_snap!r} is not protected (librbd "
                "requires protect before clone)")
        d = self._dir()
        if child_name in d:
            raise ImageExists(child_name)
        info = {"size": rec["size"], "order": parent.info.order,
                "object_prefix": f"rbd_data.{child_name}",
                # parent spec carries everything reads need (librbd
                # parent_spec): no per-read parent header fetches, and
                # overlap shrinks with child resizes
                "parent": {"image": parent_name, "snap": parent_snap,
                           "snap_id": rec["id"], "size": rec["size"],
                           "object_prefix": parent.info.object_prefix,
                           "overlap": rec["size"]}}
        d[child_name] = {"size": rec["size"],
                         "order": parent.info.order,
                         "object_prefix": info["object_prefix"]}
        self.ioctx.write_full(f"rbd_header.{child_name}",
                              json.dumps(info).encode())
        self._write_dir(d)
        parent.snaps[parent_snap].setdefault("children", []).append(
            child_name)
        parent._save_header()


class Image:
    """One open image (librbd `Image`); ``snapshot`` opens it read-only
    at a named snap (librbd open-at-snap)."""

    def __init__(self, ioctx: IoCtx, name: str,
                 snapshot: Optional[str] = None):
        self.ioctx = ioctx
        self.name = name
        try:
            raw = ioctx.read(f"rbd_header.{name}")
        except ObjectNotFound:
            raise ImageNotFound(name) from None
        meta = json.loads(raw.decode())
        self.info = ImageInfo(name=name, size=meta["size"],
                              order=meta["order"],
                              object_prefix=meta["object_prefix"])
        self.snaps: dict = meta.get("snaps", {})
        self.parent: Optional[dict] = meta.get("parent")
        self.snap_id: Optional[int] = None
        if snapshot is not None:
            if snapshot not in self.snaps:
                raise KeyError(f"image {name} has no snap {snapshot!r}")
            self.snap_id = self.snaps[snapshot]["id"]
            self.info.size = self.snaps[snapshot]["size"]

    # ------------------------------------------------------------ layout --
    def _oid(self, objno: int) -> str:
        return f"{self.info.object_prefix}.{objno:016x}"

    def _written_objects(self) -> List[int]:
        prefix = self.info.object_prefix + "."
        out = []
        for oid in self.ioctx.list_objects():
            if not oid.startswith(prefix):
                continue
            suffix = oid[len(prefix):]
            # another image's name may extend this prefix ('a' vs
            # 'a.b'): only exact 16-hex-digit suffixes are ours
            if len(suffix) == 16:
                try:
                    out.append(int(suffix, 16))
                except ValueError:
                    pass
        return sorted(out)

    def size(self) -> int:
        return self.info.size

    def _save_header(self) -> None:
        blob = {"size": self.info.size,
                "order": self.info.order,
                "object_prefix": self.info.object_prefix,
                "snaps": self.snaps}
        if self.parent is not None:
            blob["parent"] = self.parent
        self.ioctx.write_full(f"rbd_header.{self.name}",
                              json.dumps(blob).encode())
        # header watchers learn about metadata changes (librbd's
        # ImageWatcher header_update notifications)
        self.ioctx.notify(f"rbd_header.{self.name}", b"header_update")

    # ---------------------------------------------------------- snapshots --
    def snap_create(self, snap_name: str) -> int:
        """Image snapshot: a pool snap + a header record, so data
        objects COW lazily on the next write (librbd snap_create).

        Header mutators refresh first: another handle may have added
        clone linkage (children/protected) since this one opened, and
        a blind save would lose it (librbd serializes this through the
        exclusive lock + watch/notify; refresh-before-mutate is the
        single-writer equivalent)."""
        if self.snap_id is not None:
            raise IOError("image opened at a snapshot is read-only")
        self.refresh()
        if snap_name in self.snaps:
            raise ValueError(f"snap {snap_name!r} exists")
        sid = self.ioctx.snap_create(
            f"rbd.{self.name}@{snap_name}")
        self.snaps[snap_name] = {"id": sid, "size": self.info.size}
        self._save_header()
        return sid

    def snap_list(self) -> List[str]:
        return sorted(self.snaps)

    def snap_rollback(self, snap_name: str) -> None:
        """Roll every data object in the SNAPPED extent range back to
        the snap state and restore the snapped size (librbd
        snap_rollback) — including objects deleted since the snap
        (e.g. by a shrink), whose clones the cluster still holds."""
        if self.snap_id is not None:
            raise IOError("image opened at a snapshot is read-only")
        self.refresh()
        if snap_name not in self.snaps:
            raise KeyError(snap_name)
        rec = self.snaps[snap_name]
        sid = rec["id"]
        osize = 1 << self.info.order
        snap_objs = -(-rec["size"] // osize)
        covered = set(range(snap_objs)) | set(self._written_objects())
        for objno in sorted(covered):
            oid = self._oid(objno)
            try:
                self.ioctx.snap_rollback_id(oid, sid)
            except KeyError:
                # no state at the snap: rolls back to absent
                try:
                    self.ioctx.remove(oid)
                except ObjectNotFound:
                    pass
        self.info.size = rec["size"]
        self._save_header()

    def snap_remove(self, snap_name: str) -> None:
        if self.snap_id is not None:
            raise IOError("image opened at a snapshot is read-only")
        self.refresh()
        if snap_name not in self.snaps:
            raise KeyError(snap_name)
        rec = self.snaps[snap_name]
        if rec.get("protected"):
            raise ValueError(
                f"snap {snap_name!r} is protected (unprotect first)")
        if rec.get("children"):
            raise ValueError(
                f"snap {snap_name!r} has clone children")
        rec = self.snaps.pop(snap_name)
        self.ioctx._rados._sim.snap_remove(self.ioctx.pool_id,
                                           rec["id"])
        self._save_header()

    # -------------------------------------------------------------- watch --
    def watch_header(self, callback) -> int:
        """Watch the header object (ImageWatcher role): fires on
        resize/snap operations from ANY handle of this image."""
        return self.ioctx.watch(f"rbd_header.{self.name}", callback)

    def unwatch_header(self, watch_id: int) -> None:
        self.ioctx.unwatch(f"rbd_header.{self.name}", watch_id)

    def refresh(self) -> None:
        """Re-read the header (what a watcher callback triggers)."""
        meta = json.loads(
            self.ioctx.read(f"rbd_header.{self.name}").decode())
        self.info.size = meta["size"]
        self.snaps = meta.get("snaps", {})
        self.parent = meta.get("parent")

    # ---------------------------------------------------------- layering --
    def _parent_object(self, objno: int) -> Optional[bytes]:
        """The parent snapshot's bytes for one of OUR objects, clipped
        to the parent OVERLAP (shrunk by child resizes, so regrown
        ranges read zeros, not resurrected parent data)."""
        if self.parent is None:
            return None
        overlap = self.parent.get("overlap", self.parent["size"])
        osize = 1 << self.info.order
        start = objno * osize
        if start >= overlap:
            return None
        prefix = self.parent.get(
            "object_prefix", f"rbd_data.{self.parent['image']}")
        oid = f"{prefix}.{objno:016x}"
        try:
            data = self.ioctx.read(oid, snap=self.parent["snap_id"])
        except ObjectNotFound:
            return None
        return data[:max(0, overlap - start)]

    def _copy_up(self, objno: int) -> None:
        """Before a partial write to an object the child doesn't have,
        materialize the parent's bytes (CopyupRequest role)."""
        oid = self._oid(objno)
        try:
            self.ioctx.read(oid, length=0)
            return                       # child already has the object
        except ObjectNotFound:
            pass
        pdata = self._parent_object(objno)
        if pdata:
            self.ioctx.write_full(oid, pdata)

    def children(self) -> List[str]:
        out = []
        for rec in self.snaps.values():
            out.extend(rec.get("children", []))
        return sorted(out)

    def protect_snap(self, snap_name: str) -> None:
        if self.snap_id is not None:
            raise IOError("image opened at a snapshot is read-only")
        self.refresh()
        self.snaps[snap_name]["protected"] = True
        self._save_header()

    def unprotect_snap(self, snap_name: str) -> None:
        if self.snap_id is not None:
            raise IOError("image opened at a snapshot is read-only")
        self.refresh()
        rec = self.snaps[snap_name]
        if rec.get("children"):
            raise ValueError(
                f"snap {snap_name!r} has clone children")
        rec["protected"] = False
        self._save_header()

    def flatten(self) -> None:
        """Copy every parent-backed object into the child and detach
        (librbd flatten): the parent can then be unprotected.  Refused
        while the clone has snapshots of its own — those snaps were
        taken over parent-backed objects and would read zeros once the
        parent detaches (librbd keeps the parent linked per-snap; this
        slice requires snapshot-free flatten instead)."""
        if self.snap_id is not None:
            raise IOError("image opened at a snapshot is read-only")
        self.refresh()
        if self.parent is None:
            return
        if self.snaps:
            raise ValueError(
                "flatten with clone snapshots is unsupported: remove "
                f"snaps {sorted(self.snaps)} first")
        osize = 1 << self.info.order
        for objno in range(-(-self.parent["size"] // osize)):
            self._copy_up(objno)
        parent = Image(self.ioctx, self.parent["image"])
        rec = parent.snaps.get(self.parent["snap"])
        if rec and self.name in rec.get("children", []):
            rec["children"].remove(self.name)
            parent._save_header()
        self.parent = None
        self._save_header()

    # --------------------------------------------------------------- i/o --
    def write(self, offset: int, data: bytes) -> int:
        if self.snap_id is not None:
            raise IOError("image opened at a snapshot is read-only")
        if offset + len(data) > self.info.size:
            raise ValueError("write past image size")
        pos = 0
        osize = 1 << self.info.order
        for objno, ooff, olen in file_to_extents(
                self.info.layout, offset, len(data)):
            # full-object writes need no copy-up (librbd skips copyup
            # when the write covers the whole object)
            if self.parent is not None and not (ooff == 0 and
                                                olen >= osize):
                self._copy_up(objno)
            self.ioctx.write(self._oid(objno), data[pos:pos + olen],
                             offset=ooff)
            pos += olen
        return len(data)

    def read(self, offset: int, length: int) -> bytes:
        if offset + length > self.info.size:
            length = max(0, self.info.size - offset)
        out = bytearray(length)
        pos = 0
        for objno, ooff, olen in file_to_extents(
                self.info.layout, offset, length):
            try:
                piece = self.ioctx.read(self._oid(objno), length=olen,
                                        offset=ooff, snap=self.snap_id)
            except ObjectNotFound:
                # clones fall through to the parent snapshot; plain
                # images read sparse zeros
                pdata = self._parent_object(objno)
                piece = pdata[ooff:ooff + olen] if pdata else b""
            out[pos:pos + len(piece)] = piece
            pos += olen
        return bytes(out)

    def resize(self, new_size: int) -> None:
        """Grow is metadata-only; shrink discards objects wholly past
        the boundary AND zero-truncates the boundary object (librbd
        trim semantics — stale bytes must not reappear after a later
        grow).  For clones the parent overlap shrinks with the image,
        so regrown ranges never resurrect parent bytes."""
        if self.snap_id is not None:
            raise IOError("image opened at a snapshot is read-only")
        self.refresh()
        if new_size < self.info.size and self.parent is not None:
            self.parent["overlap"] = min(
                self.parent.get("overlap", self.parent["size"]),
                new_size)
        if new_size < self.info.size:
            osize = 1 << self.info.order
            first_dead = -(-new_size // osize)
            for objno in self._written_objects():
                if objno >= first_dead:
                    try:
                        self.ioctx.remove(self._oid(objno))
                    except ObjectNotFound:
                        pass
            cut = new_size % osize
            if cut:
                bno = new_size // osize
                try:
                    cur = self.ioctx.read(self._oid(bno))
                except ObjectNotFound:
                    cur = b""
                if len(cur) > cut:
                    self.ioctx.write_full(self._oid(bno), cur[:cut])
        self.info.size = new_size
        self._save_header()
