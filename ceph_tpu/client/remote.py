"""RemoteCluster — client for the process cluster (librados-over-wire).

Connects to the mon with the client keyring (cephx secret mode), pulls
the cluster map (crush text recompiled through the CrushCompiler — the
same map the daemons trust), computes placement locally with the real
CRUSH pipeline, obtains per-OSD tickets, and performs object I/O
against the OSD daemons:

  * replicated pools: PUT goes to the PRIMARY, which persists locally
    and fans out to its replicas daemon-to-daemon (the
    ReplicatedBackend shape); GET walks the up set.
  * EC pools: the client is the TPU-attached primary — stripes are
    encoded on device, shards written per OSD; reads gather
    minimum_to_decode shards and decode on device
    (the ECBackend primary role).

Map refreshes on epoch bump; op failures trigger a refresh + retry
(the Objecter resend-on-map-change contract).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..common import auth as cx
from ..common import tracer as _trace
from ..common.backoff import ExpBackoff
from ..common.op_tracker import tracker as _op_tracker
from ..cluster.daemon import WireClient
from ..cluster.osdmap import OSDMap, PGPool, POOL_ERASURE
from ..ec import instance as ec_registry
from ..ec.interface import ErasureCodeError
from ..ops import hashing
from ..placement.compiler import compile_crushmap
from ..placement.crush_map import ITEM_NONE


class RemoteObjectMissing(IOError):
    """Every reachable target answered and none holds the object — a
    definitive ENOENT, distinct from connectivity trouble so existence
    probes skip the retry sweep (rados ENOENT vs EIO distinction)."""


def _as_buf(arr) -> memoryview:
    """A numpy array's bytes as a flat uint8 memoryview — the
    zero-copy handoff to the scatter-gather wire path (tobytes()
    duplicated every shard before it ever reached the socket)."""
    from ..common import crcutil
    return crcutil.as_u8(np.ascontiguousarray(arr))


def _staged_csums(arrs):
    """Per-shard Csums for a flush batch, computed ONCE per byte:
    on device via the GF(2) crc matmul (ops/crc32_gf2) when the
    backend makes it worthwhile — the shards were just staged in
    HBM — else a single host scan each.  ``wire_device_crc``:
    auto/on/off."""
    from ..common import crcutil
    from ..common.options import config
    mode = str(config().get("wire_device_crc"))
    if mode == "on" or (mode == "auto" and _device_crc_ok()):
        from ..ops import crc32_gf2
        return crc32_gf2.csums_many([_as_buf(a) for a in arrs])
    return [crcutil.Csums.scan(_as_buf(a), site="client")
            for a in arrs]


def _device_crc_ok() -> bool:
    from ..ops import crc32_gf2
    return crc32_gf2.device_worthwhile()


class RemoteCluster:
    def __init__(self, cluster_dir: str, entity: str = "client.admin",
                 ec_profiles: Optional[Dict[str, Dict[str, str]]] = None):
        self.dir = cluster_dir
        self.entity = entity
        ring = cx.Keyring.load(os.path.join(cluster_dir,
                                            "keyring.client"))
        self.secret = ring.secret(entity)
        self.mon: Optional[WireClient] = None
        # mon failover ROTATES: a reconnect after a failure must not
        # land on the same (possibly minority-partitioned, lease-
        # stalled) mon forever — start each connect sweep at the rank
        # after the one that just failed
        self._mon_rot = 0
        self._connect_mon()
        # per-OSD messenger sessions: a session id survives RECONNECTS
        # (that is its whole point), and each mutating op draws one seq
        # from it — resends reuse the (sid, seq), the daemon dedups
        self._sessions: Dict[int, Dict] = {}
        self.session_resets = 0          # stale-session resets seen
        # hooks: each called with the osd id when a session RESET is
        # detected on reconnect (daemon lost our state — session-
        # scoped registrations like watches must be re-established).
        # A LIST with explicit unregistration: many ioctxs share one
        # cluster handle, and a closed ioctx must not stay reachable
        # through a permanently-chained closure
        self._session_reset_cbs: List = []
        # socket timeout of the SHARED per-OSD clients: anything that
        # blocks a daemon handler longer (notify_wait) must ride a
        # dedicated connection with a DERIVED timeout, or the timed-out
        # read kills the shared connection under every other caller
        self._osd_timeout = 10.0
        self._osd_clients: Dict[int, WireClient] = {}
        self._aio = None            # lazy AsyncObjecter (wire core)
        self.ec_profiles = ec_profiles or {}
        self._codecs: Dict[int, object] = {}
        self._backends: Dict[int, object] = {}
        self._dev = None            # lazy DeviceShardCache
        self._staged_attrs: Dict = {}
        self._tier_reads: Dict = {}   # client-local warmth counters
        self._admin = None          # opt-in objecter.asok (serve_admin)
        self._admin_path: Optional[str] = None
        import threading
        self._client_lock = threading.Lock()
        # tenant identity for per-tenant QoS (S3 auth -> objecter ->
        # op dispatch): a handle-wide default (one gateway client per
        # tenant, the serving harness shape) plus a thread-local
        # override (one frontend serving many tenants on request
        # threads).  Stamped onto client-class data-path requests by
        # the async objecter; daemons dispatch them under the
        # tenant's own dmClock class.
        self._tenant_default: Optional[str] = None
        self._tenant_tls = threading.local()
        # every retry sweep in this client paces itself here:
        # exponential with deterministic per-entity jitter, so N
        # clients hammering a recovering daemon decorrelate instead
        # of stampeding in lockstep (and seeded runs reproduce)
        import zlib as _zlib
        self._backoff = ExpBackoff(
            base=0.05, cap=1.0, seed=_zlib.crc32(entity.encode()))
        self.refresh_map()

    def serve_admin(self, name: str = "objecter") -> str:
        """Opt-in client admin socket (`<dir>/<name>.asok`): a
        long-running client process (the TPU host) exposes its own
        tracked-op and perf-dump surfaces so `ceph daemon objecter
        dump_historic_ops | perf dump` works, matching the reference's
        client asok workflow.  Idempotent for the same name; a second
        call with a different name raises rather than returning a path
        that was never served."""
        from ..common.admin import AdminServer
        path = os.path.join(self.dir, f"{name}.asok")
        if self._admin is not None:
            if path != self._admin_path:
                raise RuntimeError(
                    f"already serving {self._admin_path}")
            return self._admin_path
        srv = AdminServer()
        srv.serve(path)          # a failed bind leaves us retryable
        self._admin = srv
        self._admin_path = path
        return path

    def _tracked(self, optype: str, pool_id: int, name: str, fn):
        """Wrap one top-level client op with an OpTracker record.
        Nested calls (tier routing recursion) ride the parent's
        record instead of opening their own."""
        tr = _op_tracker()
        if tr.current() is not None:
            return fn()
        top = tr.create(optype, service="objecter", pool=pool_id,
                        obj=name)
        error = None
        try:
            # client ROOT span: every wire_submit below nests under
            # it, and the op-id -> trace-id mapping on the tracked op
            # is what `ceph trace <op>` resolves through (slow ops
            # auto-pin this trace via op_tracker.finish).  The
            # tracker's active-op registration stays — sub-op sites
            # (call_async's dispatched_wire mark, nested tier
            # routing) find the op through tr.current()
            with _trace.start_span(f"client.{optype}", pool=pool_id,
                                   obj=name) as span:
                if span.trace_id and top.tracked:
                    top.tags["trace_id"] = span.trace_id
                with tr.track(top):
                    return fn()
        except BaseException as e:
            error = type(e).__name__
            raise
        finally:
            tr.finish(top, error=error)

    # ---------------------------------------------------------------- mon --
    def _mon_socks(self) -> List[str]:
        from ..cluster.daemon import mon_sockets
        return mon_sockets(self.dir)

    def _connect_mon(self) -> None:
        """Any quorum member serves reads and forwards mutations to
        the leader; fail over across the configured mons, starting at
        the rotation point (the mon AFTER the last failure) so a
        stalled minority mon cannot capture every reconnect."""
        last: Optional[Exception] = None
        socks = self._mon_socks()
        for i in range(len(socks)):
            sock = socks[(self._mon_rot + i) % len(socks)]
            mon_ent = os.path.basename(sock)[:-len(".sock")]
            try:
                self.mon = WireClient(sock, self.entity,
                                      secret=self.secret,
                                      peer=mon_ent)
                self._mon_rot = (self._mon_rot + i) % len(socks)
                return
            except (OSError, IOError, cx.AuthError) as e:
                last = e
        raise IOError(f"no mon reachable: {last}")

    def mon_call(self, req: Dict) -> Dict:
        """Bounded mon sweep: a failing/stalled mon (connection error
        OR a retryable IOError reply such as a minority-side lease
        stall) rotates the client to the next quorum member — the
        'bounded stall or redirect, never a stale map' contract."""
        last: Optional[Exception] = None
        for attempt in range(3):
            # snapshot the shared client: a CONCURRENT mon_call that
            # hit its own failure may null/replace self.mon between
            # our check and use (seen as AttributeError under the
            # socket-failure soak)
            mon = self.mon
            if mon is None:
                try:
                    self._connect_mon()
                except (OSError, IOError) as e:
                    last = e
                    self._backoff.sleep(attempt)
                    continue
                mon = self.mon
                if mon is None:
                    continue
            try:
                return mon.call(req)
            except (OSError, IOError) as e:
                last = e
                try:
                    mon.close()
                except OSError:
                    pass
                if self.mon is mon:
                    self.mon = None
                self._mon_rot += 1       # next reconnect: next mon
                if attempt < 2:
                    self._backoff.sleep(attempt)
        raise IOError(f"mon unreachable ({last})")

    def report_plane_perf(self) -> None:
        """Ship this client's process perf dump (the data-plane chip
        counters live HERE — the plane runs client-side) to the mon's
        ClusterStats, tagged with the multihost host label, so
        `ceph -s` / `cluster_stats["mesh"]` show the plane against
        live daemons.  Under the multi-process plane each rank's
        client reports under its own host label and the mgr's
        mesh_rollup sums the (host, chip) cells; single-process it is
        one reporter owning every cell.  Attribution stays the
        AUTHENTICATED wire entity — the label only tags the row."""
        import time as _time
        from ..common.perf_counters import perf as _perf
        from ..parallel.multihost import host_label
        self.mon_call({"cmd": "report_perf",
                       "report": {"perf": _perf().dump_typed(),
                                  "ts": _time.time(),
                                  "host": host_label()}})

    # ---------------------------------------------------------------- map --
    def refresh_map(self) -> None:
        blob = self.mon_call({"cmd": "get_map"})
        cmap = compile_crushmap(blob["crush_text"])
        m = OSDMap(cmap, epoch=blob["epoch"])
        m.mark_all_in_up()
        for i, up in enumerate(blob["osd_up"]):
            m.osd_up[i] = up
        for i, w in enumerate(blob["osd_weight"]):
            m.osd_weight[i] = w
        for p in blob["pools"]:
            m.add_pool(PGPool(**p))
        m.flags = set(blob.get("flags", []))
        self.osdmap = m
        self._up_cache: Dict = {}
        self.addrs = {int(k): v for k, v in blob["addrs"].items()}
        self.pool_snaps = {int(k): v for k, v in
                           blob.get("pool_snaps", {}).items()}

    def _session(self, osd: int) -> Dict:
        """This client's messenger session with one OSD — created
        once, kept across reconnects (caller holds _client_lock or is
        single-threaded through osd_call's seq draw)."""
        st = self._sessions.get(osd)
        if st is None:
            import secrets as _secrets
            st = self._sessions[osd] = {"sid": _secrets.token_hex(8),
                                        "seq": 0}
        return st

    def osd_client(self, osd: int) -> WireClient:
        c = self._osd_clients.get(osd)
        if c is not None:
            return c
        # serialized: concurrent fan-out threads must not race two
        # connects (and the mon ticket round) for the same OSD
        with self._client_lock:
            c = self._osd_clients.get(osd)
            if c is not None:
                return c
            grant = self.mon_call({"cmd": "get_ticket",
                                   "service": f"osd.{osd}"})
            key = cx.open_key_box(self.secret, grant["key_box"])
            c = WireClient(self.addrs[osd], self.entity,
                           ticket=grant["ticket"], session_key=key,
                           timeout=self._osd_timeout,
                           peer=f"osd.{osd}")
            self._osd_clients[osd] = c
        self._hello(osd, c)
        return c

    def _hello(self, osd: int, c: WireClient) -> None:
        """Session resume on a fresh connection, OUTSIDE the client
        lock (it is a wire call): announce (sid, highest seq used);
        the daemon answers whether it still holds our session — a
        resume against an unknown sid is a detected STALE SESSION
        (daemon restarted/evicted): both sides reset, and session-
        scoped state (watches) must be re-established by the owner."""
        with self._client_lock:
            st = self._session(osd)
        try:
            hello = c.call({"cmd": "session_hello",
                            "session": st["sid"], "seq": st["seq"]})
            if not hello.get("known") and st["seq"] > 0:
                self.session_resets += 1
                for cb in list(self._session_reset_cbs):
                    try:
                        cb(osd)
                    except Exception:
                        pass
        except (OSError, IOError):
            pass          # hello is advisory; ops re-hello via retry

    def _stream_conn(self, osd: int) -> WireClient:
        """Authenticated connection factory for the async objecter's
        stream pool: a dedicated connection per stream, with the same
        session-hello reset detection the shared clients perform (a
        stream rebuilt against a restarted daemon must still trigger
        watch re-establishment)."""
        c = self.new_osd_client(osd)
        self._hello(osd, c)
        return c

    @property
    def aio(self):
        """The asynchronous objecter core (cluster/async_objecter.py):
        per-OSD stream pools + completion engine.  Built lazily — a
        client that never touches OSD data paths starts no threads."""
        if self._aio is None:
            with self._client_lock:
                if self._aio is None:
                    from ..cluster.async_objecter import AsyncObjecter
                    self._aio = AsyncObjecter(self)
        return self._aio

    def _next_stamp(self, osd: int) -> Dict:
        """Draw one (session, seq) replay stamp for a logical
        mutating op against ``osd`` — the single place the stamping
        contract (lock discipline, sid scope) lives."""
        with self._client_lock:
            st = self._session(osd)
            st["seq"] += 1
            return {"session": st["sid"], "seq": st["seq"]}

    # ------------------------------------------------------------ tenant --
    def set_tenant(self, tenant: Optional[str],
                   thread_only: bool = False) -> None:
        """Bind a tenant identity (an S3-auth-verified uid) to this
        handle's data-path ops.  ``thread_only`` scopes the binding
        to the calling thread — the S3 frontend sets it per request
        after SigV4 verification, so one shared cluster handle serves
        many tenants without cross-talk."""
        if thread_only:
            self._tenant_tls.tenant = tenant
        else:
            self._tenant_default = tenant

    @property
    def tenant(self) -> Optional[str]:
        t = getattr(self._tenant_tls, "tenant", None)
        return t if t is not None else self._tenant_default

    def add_session_reset_cb(self, cb) -> None:
        self._session_reset_cbs.append(cb)

    def remove_session_reset_cb(self, cb) -> None:
        try:
            self._session_reset_cbs.remove(cb)
        except ValueError:
            pass

    def new_osd_client(self, osd: int,
                       timeout: Optional[float] = None) -> WireClient:
        """A DEDICATED (unshared) authenticated connection to one OSD.
        Long-blocking calls (notify_wait) hold a connection's lock for
        their whole wait, so background pollers must not ride the
        shared per-OSD clients — the ack they need to deliver would
        serialize behind the very wait it unblocks.  ``timeout`` lets
        a caller that KNOWS its server-side wait derive a socket
        timeout that outlives it."""
        grant = self.mon_call({"cmd": "get_ticket",
                               "service": f"osd.{osd}"})
        key = cx.open_key_box(self.secret, grant["key_box"])
        return WireClient(self.addrs[osd], self.entity,
                          ticket=grant["ticket"], session_key=key,
                          timeout=timeout if timeout is not None
                          else self._osd_timeout,
                          peer=f"osd.{osd}")

    def _evict_staging(self, pool_id: int, pg: int, name: str) -> None:
        """Invalidate this client's staged shards + attrs for one
        object (called on every overwrite/delete: a dirty staged
        entry is served unconditionally and flushed later, so leaving
        one behind would resurrect dead data)."""
        if self._dev is not None:
            self._dev.evict_object(pool_id, pg, name)
        for k in [k for k in self._staged_attrs
                  if k[0] == pool_id and k[1] == pg and k[2] == name]:
            self._staged_attrs.pop(k, None)

    def drop_osd_client(self, osd: int) -> None:
        c = self._osd_clients.pop(osd, None)
        if c:
            c.close()

    # mutations that ride the (session, seq) replay contract: the
    # daemon applies each at most once, so the reconnect-retry below
    # (and any caller resending the SAME dict) is a safe replay.
    # Mirrors OSDDaemon._REPLAY_CMDS — the bulk frames joined in
    # CTLint v2
    _REPLAY_CMDS = frozenset((
        "put_shard", "put_object", "delete_shard", "delete_object",
        "setattr_shard", "copy_from", "exec_cls",
        "put_objects", "delete_objects", "delete_shards"))

    def osd_call(self, osd: int, req: Dict):
        """One OSD request — a THIN BLOCKING SHIM over the async
        objecter core (cluster/async_objecter.py), which owns the
        whole contract this call used to implement inline: a single
        same-target retry on a FRESH stream when the connection died
        under the op, and (session, seq) stamping drawn ONCE per
        mutating request so the retry is a REPLAY the daemon applies
        at most once (returning the recorded completion).  Sync and
        async submissions share that one code path; the results are
        byte-identical."""
        return self.aio.call(osd, req)

    # --------------------------------------------------- async client --
    def aio_osd_call(self, osd: int, req: Dict):
        """Async form of osd_call: returns the AioCompletion."""
        return self.aio.call_async(osd, req)

    def aio_put(self, pool_id: int, name: str, data: bytes,
                csums=None):
        """Asynchronous put (librados aio_write_full): the op runs
        its submit -> encode -> fan-out -> gather-commits machine on
        the completion engine; same-object ops execute in submission
        order (the librados write-ordering contract).  ``csums`` as
        in :meth:`put` — precomputed trusted csums keep the client's
        send path scan-free."""
        return self.aio.engine.submit(
            lambda: self.put(pool_id, name, data, csums=csums),
            key=("obj", pool_id, name))

    def aio_get(self, pool_id: int, name: str):
        return self.aio.engine.submit(
            lambda: self.get(pool_id, name),
            key=("obj", pool_id, name))

    def aio_delete(self, pool_id: int, name: str):
        return self.aio.engine.submit(
            lambda: self.delete(pool_id, name),
            key=("obj", pool_id, name))

    # ---------------------------------------------------------- placement --
    def _pg_for(self, pool: PGPool, name: str) -> int:
        """object -> pg (the ceph_stable_mod hash pipeline, same as the
        in-process simulator so placements agree)."""
        ps = hashing.str_hash_rjenkins(name.encode())
        return pool.raw_pg_to_pg(ps)

    def _up(self, pool: PGPool, pg: int) -> List[int]:
        """Memoized per (pool, pg) against the current map epoch —
        the Objecter's cached-target role: batched surfaces hit the
        same PGs every round and must not recompute the scalar CRUSH
        descent each time (refresh_map drops the cache)."""
        key = (pool.id, pg)
        hit = self._up_cache.get(key)
        if hit is not None:
            return hit
        up, _, acting, _ = self.osdmap.pg_to_up_acting_osds(pool.id, pg)
        r = acting or up
        self._up_cache[key] = r
        return r

    def codec_for(self, pool: PGPool):
        codec = self._codecs.get(pool.id)
        if codec is None:
            prof = dict(self.ec_profiles.get(
                pool.erasure_code_profile,
                {"plugin": "jax", "k": "4", "m": "2"}))
            plugin = prof.get("plugin", "jax")
            if plugin == "jax" and "layout" not in prof:
                # cluster default (erasure_code_default_layout):
                # bitsliced — shard bytes at rest ARE the plane words
                # the masked-XOR kernel consumes, on daemons too
                from ..common.options import config
                prof["layout"] = config().get(
                    "erasure_code_default_layout")
            codec = ec_registry().factory(plugin, prof)
            self._codecs[pool.id] = codec
        return codec

    # ------------------------------------------------- EC backend seam --
    def ec_backend(self, pool_id: int):
        """The shared ECBackend engine (cluster/ec_backend.py) over
        this client's wire transport — the same backend class the
        in-process simulator uses (PGBackend seam,
        src/osd/PGBackend.cc:571)."""
        be = self._backends.get(pool_id)
        if be is None:
            from ..cluster.ec_backend import ECBackend
            pool = self.osdmap.pools[pool_id]
            be = ECBackend(self.codec_for(pool),
                           WireShardIO(self, pool_id))
            self._backends[pool_id] = be
        return be

    @property
    def dev(self):
        """Client-side HBM staging of shard plane words (the client is
        the TPU-attached EC primary; shards it wrote or read stay
        device-resident and serve zero-copy)."""
        if self._dev is None:
            from ..cluster.device_store import DeviceShardCache
            self._dev = DeviceShardCache()
        return self._dev

    # ----------------------------------------------------------- snapshots --
    def snap_create(self, pool_id: int, name: str) -> int:
        """Pool snapshot: committed mon state (quorum decree); clones
        appear lazily on the next write per object (pool snap_seq +
        COW, the OSDMonitor prepare_pool_op / make_writeable shape)."""
        r = self.mon_call({"cmd": "pool_snap_create", "pool": pool_id,
                           "name": name})
        self.refresh_map()
        return int(r["snap_seq"])

    def snap_remove(self, pool_id: int, name: str) -> Dict:
        """Remove a pool snapshot by name (rados rmsnap): committed
        mon state like creation; clones already materialized by COW
        stay readable through their object snapsets until trimmed."""
        r = self.mon_call({"cmd": "pool_snap_remove",
                           "pool": pool_id, "name": name})
        self.refresh_map()
        return r

    def snap_ls(self, pool_id: int) -> Dict:
        """List a pool's snapshots (rados lssnap): the mon's
        committed {"seq": int, "snaps": {id: name}} state, read from
        the quorum rather than this client's possibly-stale map."""
        return self.mon_call({"cmd": "pool_snap_ls",
                              "pool": pool_id})

    def snap_lookup(self, pool_id: int, name: str) -> int:
        snaps = self.pool_snaps.get(pool_id, {}).get("snaps", {})
        for sid, nm in snaps.items():
            if nm == name:
                return int(sid)
        raise KeyError(f"no snapshot {name!r} in pool {pool_id}")

    def _snapset_of(self, pool: PGPool, pg: int,
                    name: str) -> Optional[Dict]:
        """The snapset attr from ANY member holding it (replicated:
        every replica stores it; a member without the attr — e.g. one
        restored by data-only recovery — must not mask the others)."""
        coll = [pool.id, pg]
        up = self._up(pool, pg)
        answered = False
        for o in [x for x in up if x != ITEM_NONE]:
            try:
                raw = self.osd_client(o).call(_trace.stamp({
                    "cmd": "getattr_shard", "coll": coll,
                    "oid": f"0:{name}", "key": "snapset"}))
            except (OSError, IOError):
                self.drop_osd_client(o)
                continue
            answered = True
            if raw is not None:
                return json.loads(bytes(raw).decode())
        if not answered:
            raise IOError(f"{name}: no member reachable for snapset")
        return None

    def _store_snapset(self, pool: PGPool, pg: int, name: str,
                       snapset: Dict) -> None:
        """Persist the snapset on EVERY mapped member (replicated:
        all replicas; EC: every shard).  Zero acks is a hard error —
        a silently-lost snapset corrupts later COW rounds."""
        coll = [pool.id, pg]
        up = self._up(pool, pg)
        blob = json.dumps(snapset).encode()
        n_shards = self.codec_for(pool).get_chunk_count() \
            if pool.type == POOL_ERASURE else len(
                [x for x in up if x != ITEM_NONE])
        fan = []
        for shard in range(n_shards):
            if pool.type == POOL_ERASURE:
                tgt = up[shard] if shard < len(up) else ITEM_NONE
                oid = f"{shard}:{name}"
            else:
                tgt = [x for x in up if x != ITEM_NONE][shard]
                oid = f"0:{name}"
            if tgt == ITEM_NONE:
                continue
            # the async chokepoint stamps BOTH the trace context and
            # the (session, seq) replay id (setattr_shard is a
            # mutating cmd: a reconnect retry must not double-apply
            # around a concurrent snapset update), and the fan-out
            # pipelines instead of paying one RTT per shard
            fan.append(self.aio.call_async(tgt, {
                "cmd": "setattr_shard", "coll": coll,
                "oid": oid, "attrs": {"snapset": blob}}))
        acks = 0
        for comp in fan:
            try:
                comp.get_return_value()
                acks += 1
            except (OSError, IOError):
                pass
        if acks == 0:
            raise IOError(f"{name}: snapset not persisted anywhere")

    def _maybe_cow(self, pool: PGPool, pg: int,
                   name: str) -> Optional[Dict]:
        """Copy-on-write before the first overwrite after a snapshot
        (PrimaryLogPG make_writeable role, driven by the TPU-attached
        client as primary): preserve the head as a clone object.
        Returns the snapset to store after the head write."""
        info = self.pool_snaps.get(pool.id) or {"seq": 0, "snaps": {}}
        seq = int(info["seq"])
        if seq == 0:
            return None       # never-snapped pool: zero write overhead
        ss = self._snapset_of(pool, pg, name)
        if ss is None:
            # no snapset attr: distinguish a brand-new object (born
            # at the current seq) from one written before snapshots
            # existed (implicit write_seq 0 -> COW applies)
            exists = False
            for o in [x for x in self._up(pool, pg)
                      if x != ITEM_NONE]:
                try:
                    exists = self.osd_client(o).call(_trace.stamp({
                        "cmd": "digest_shard", "coll": [pool.id, pg],
                        "oid": f"0:{name}"})) is not None
                    break
                except (OSError, IOError):
                    self.drop_osd_client(o)
            if not exists:
                # a RECREATED object resumes its sidecar snapset (the
                # delete path parked it there): the old clones must
                # ride back onto the new head's attr, or the history
                # orphans.  The object was ABSENT for snaps since the
                # deletion, so no clone is minted for them — absent is
                # exactly what write_seq >= snap reports.
                try:
                    side = json.loads(
                        self.get(pool.id, f"{name}@snapset"))
                    side["write_seq"] = seq
                    return side
                except (RemoteObjectMissing, IOError, ValueError):
                    pass
                return {"write_seq": seq, "clones": []} if seq \
                    else None
            ss = {"write_seq": 0, "clones": []}
        if int(ss.get("write_seq", 0)) >= seq:
            return ss
        covered = [int(s) for s in info["snaps"]
                   if int(ss.get("write_seq", 0)) < int(s) <= seq]
        if covered:
            # idempotency: if a previous COW round already preserved
            # this clone (but the snapset update was lost), do NOT
            # overwrite it with the newer head
            clone = f"{name}@{seq}"
            cpg = self._pg_for(pool, clone)
            exists = False
            for o in [x for x in self._up(pool, cpg)
                      if x != ITEM_NONE]:
                try:
                    exists = self.osd_client(o).call(_trace.stamp({
                        "cmd": "digest_shard",
                        "coll": [pool.id, cpg],
                        "oid": f"0:{clone}"})) is not None
                    break
                except (OSError, IOError):
                    self.drop_osd_client(o)
            if not exists:
                data = self.get(pool.id, name)
                self.put(pool.id, clone, data)
            ss.setdefault("clones", []).append(
                {"id": seq, "snaps": covered})
        ss["write_seq"] = seq
        return ss

    def get_snap(self, pool_id: int, name: str, snap_id: int) -> bytes:
        """Read an object AT a snapshot: clone covering it, else the
        unchanged head (SnapSet resolution).  KeyError when the object
        DID NOT EXIST at that snapshot — a head written at/after the
        snap with no covering clone means the object was born later,
        and serving the head would invent post-snap data."""
        pool = self.osdmap.pools[pool_id]
        pg = self._pg_for(pool, name)
        ss = self._snapset_of(pool, pg, name)
        if ss is None:
            # deleted head: its snapset survives in the sidecar object
            try:
                ss = json.loads(self.get(pool_id, f"{name}@snapset"))
            except (RemoteObjectMissing, IOError, ValueError):
                ss = None
        if ss:
            for c in ss.get("clones", []):
                if snap_id in c["snaps"]:
                    return self.get(pool_id, f"{name}@{c['id']}")
            if int(ss.get("write_seq", 0)) >= snap_id:
                raise KeyError(f"{name}: no state at snap {snap_id}")
        return self.get(pool_id, name)

    # ------------------------------------------------- cache-tier ops --
    def tier_add(self, base_id: int, cache_id: int,
                 mode: str = "writeback") -> None:
        """Wire a cache pool over a base pool: committed MAP state
        (quorum incremental — OSDMonitor 'osd tier add')."""
        self.mon_call({"cmd": "pool_tier_add", "base": base_id,
                       "cache": cache_id, "mode": mode})
        self.refresh_map()

    def tier_remove(self, base_id: int, cache_id: int,
                    force: bool = False) -> None:
        """Refused until the cache pool is drained (flush + evict) —
        unwiring with data in the cache strands acknowledged writes
        out of the read path (the reference's 'osd tier remove'
        refuses the same way).  The drain check runs SERVER-side at
        the mon — the commit point — so a write racing this call
        cannot slip through a client-only check (the old TOCTOU);
        ``force`` is forwarded for operators who accept stranding."""
        self.mon_call({"cmd": "pool_tier_remove", "base": base_id,
                       "cache": cache_id, "force": force})
        self.refresh_map()

    def copy_from(self, dst_pool: int, dst_name: str,
                  src_pool: int, src_name: str) -> int:
        """COPY_FROM between pools as an OP: the DESTINATION primary
        daemon pulls the source object server-side (possibly from
        another OSD) and commits it as a logged replicated write —
        the client never carries the payload
        (src/osd/PrimaryLogPG.cc:5886 do_copy_from; daemon handler
        cluster/daemon.py 'copy_from')."""
        dpool = self.osdmap.pools[dst_pool]
        spool = self.osdmap.pools[src_pool]
        dpg = self._pg_for(dpool, dst_name)
        spg = self._pg_for(spool, src_name)
        dst_members = [o for o in self._up(dpool, dpg)
                       if o != ITEM_NONE]
        src_members = [o for o in self._up(spool, spg)
                       if o != ITEM_NONE]
        if not dst_members or not src_members:
            raise IOError("copy_from: no primary")
        r = self.osd_call(dst_members[0], {
            "cmd": "copy_from", "coll": [dst_pool, dpg],
            "oid": f"0:{dst_name}",
            "src_coll": [src_pool, spg], "src_oid": f"0:{src_name}",
            "src_osd": src_members[0], "replicas": dst_members})
        return int(r["acks"])

    def _tier_mark(self, cache_id: int, name: str,
                   dirty: bool) -> None:
        pool = self.osdmap.pools[cache_id]
        pg = self._pg_for(pool, name)
        blob = b"1" if dirty else b"0"
        for o in [x for x in self._up(pool, pg) if x != ITEM_NONE]:
            try:
                self.osd_call(o, {"cmd": "setattr_shard",
                                  "coll": [cache_id, pg],
                                  "oid": f"0:{name}",
                                  "attrs": {"tier_dirty": blob}})
            except (OSError, IOError):
                pass

    def tier_dirty(self, base_id: int, name: str) -> bool:
        pool = self.osdmap.pools[base_id]
        cache = self.osdmap.pools[pool.read_tier]
        pg = self._pg_for(cache, name)
        for o in [x for x in self._up(cache, pg) if x != ITEM_NONE]:
            try:
                raw = self.osd_call(o, {"cmd": "getattr_shard",
                                        "coll": [cache.id, pg],
                                        "oid": f"0:{name}",
                                        "key": "tier_dirty"})
            except (OSError, IOError):
                continue
            return raw == b"1"
        return False

    def tier_flush(self, base_id: int, name: str) -> int:
        """Writeback flush: demote a dirty cache object to the base
        tier as a COPY_FROM op, then mark it clean.

        Concurrency caveat (same single-writer assumption as
        RemoteIoCtx.write's RMW): a put racing between the copy and
        the clean-mark can be marked clean unflushed — callers that
        run multiple agents/writers against one tiered pool must
        serialize flushes per object."""
        pool = self.osdmap.pools[base_id]
        acks = self.copy_from(base_id, name, pool.write_tier, name)
        self._tier_mark(pool.write_tier, name, False)
        return acks

    def tier_evict(self, base_id: int, name: str) -> int:
        """Evict a CLEAN cache object (dirty must flush first)."""
        pool = self.osdmap.pools[base_id]
        if self.tier_dirty(base_id, name):
            raise IOError(f"{name}: dirty, flush before evict")
        return self.delete(pool.read_tier, name)

    def tier_agent_work(self, base_id: int,
                        target_objects: int = 0) -> Dict[str, int]:
        """One agent pass over the cache pool: flush every dirty
        object; evict the COLDEST clean ones down to target_objects
        (warmth = this client's read counters — the agent that runs
        the workload holds the hit history, the sim tier's
        HitSetHistory role)."""
        pool = self.osdmap.pools[base_id]
        cache_id = pool.read_tier
        stats = {"flushed": 0, "evicted": 0}
        cached = self.list_objects(cache_id)
        for nm in cached:
            if self.tier_dirty(base_id, nm):
                self.tier_flush(base_id, nm)
                stats["flushed"] += 1
        if target_objects and len(cached) > target_objects:
            cold = sorted(cached, key=lambda nm: self._tier_reads.get(
                (base_id, nm), 0))
            for nm in cold[:len(cached) - target_objects]:
                self.tier_evict(base_id, nm)
                stats["evicted"] += 1
        return stats

    # ----------------------------------------------------------------- IO --
    def put(self, pool_id: int, name: str, data: bytes,
            csums=None) -> int:
        """Returns the number of shard/replica writes acknowledged.

        ``csums`` — optional precomputed :class:`crcutil.Csums` for
        ``data`` (the staged-in-HBM shape: ``crc32_gf2.csums_for``
        computes them on-device).  With them the client never
        host-scans the payload — the wire layer folds the combined
        crc into the frame/doorbell and the daemon's single verify
        re-derives the trusted blob csums it stores and forwards to
        replicas.  Replicated pools only; EC encode re-chunks the
        bytes, so per-chunk csums come from the encode path instead."""
        return self._tracked("put", pool_id, name,
                             lambda: self._put_routed(pool_id, name,
                                                      data, csums))

    def _put_routed(self, pool_id: int, name: str, data: bytes,
                    csums=None) -> int:
        pool = self.osdmap.pools[pool_id]
        if pool.write_tier >= 0 and "@" not in name:
            # writeback cache routing (the Objecter consults the
            # pool's write_tier): the write lands in the cache pool
            # marked dirty; the agent/flush demotes it later.  Writes
            # count as warmth like the sim's HitSet record, or the
            # agent would evict just-written objects first
            self._tier_reads[(pool_id, name)] = \
                self._tier_reads.get((pool_id, name), 0) + 1
            return self._put_inner(pool.write_tier, name, data,
                                   extra_attrs={"tier_dirty": b"1"},
                                   csums=csums)
        return self._put_inner(pool_id, name, data, csums=csums)

    def _put_inner(self, pool_id: int, name: str, data: bytes,
                   extra_attrs: Optional[Dict[str, bytes]] = None,
                   csums=None) -> int:
        pool = self.osdmap.pools[pool_id]
        pg = self._pg_for(pool, name)
        up = self._up(pool, pg)
        coll = [pool_id, pg]
        snapset = self._maybe_cow(pool, pg, name) \
            if "@" not in name else None
        if pool.type != POOL_ERASURE:
            # bounded retry with a map refresh between attempts: a
            # dropped connection (daemon restart, injected socket
            # failure) is transient, and the full-object write +
            # fresh version make the resend idempotent.  8 attempts
            # with capped backoff out-wait a kill9'd primary's reboot
            # window instead of racing it (the pre-ISSUE-9 5-attempt
            # budget exhausted under CPU contention).
            last: Optional[Exception] = None
            # (session, seq) stamps are PER PRIMARY: a resend to the
            # SAME primary replays one stamp (its dup table applies
            # the write at most once), while a re-homed primary gets
            # its own fresh stamp — sessions are per-OSD state, and
            # replaying osd.A's stamp at osd.B would smuggle seqs
            # into an unrelated dedup stream
            stamps: Dict[int, Dict] = {}
            attempts = 8
            for attempt in range(attempts):
                replicas = [o for o in up if o != ITEM_NONE]
                if not replicas:
                    # booting cluster / transient all-down map: retry
                    # against a refreshed map like any other failure
                    last = IOError(f"{name}: no live replica target")
                    self._backoff.sleep(attempt)
                    try:
                        self.refresh_map()
                    except (OSError, IOError):
                        pass
                    up = self._up(pool, pg)
                    continue
                primary = replicas[0]
                stamp = stamps.get(primary)
                if stamp is None:
                    stamp = stamps[primary] = self._next_stamp(primary)
                try:
                    req = {"cmd": "put_object", "coll": coll,
                           "oid": f"0:{name}", "data": data,
                           "attrs": extra_attrs,
                           "replicas": replicas, **stamp}
                    if csums is not None and \
                            csums.length == len(data):
                        # trusted client csums: the wire layer folds
                        # the combined crc instead of re-scanning
                        req["_csums"] = csums
                    r = self.osd_call(primary, req)
                except (OSError, IOError) as e:
                    last = e
                    if attempt < attempts - 1:   # no backoff on the
                        # last throw
                        self._backoff.sleep(attempt)
                        try:
                            self.refresh_map()
                        except (OSError, IOError):
                            pass
                        up = self._up(pool, pg)
                    continue
                # snapset persistence is OUTSIDE the retry: the object
                # write committed, so its failure must surface as its
                # own error, not masquerade as a dead primary
                if snapset is not None:
                    self._store_snapset(pool, pg, name, snapset)
                return int(r["acks"])
            raise IOError(f"{name}: put failed after retries ({last})")
        codec = self.codec_for(pool)
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        self._evict_staging(pool_id, pg, name)
        chunks = codec.encode(set(range(n)), data)
        # geometry attrs are REWRITTEN on every put: an overwrite of a
        # stripewise (batched-put) object must not leave stale S/U
        # behind, or readers would reassemble the new single-stripe
        # chunks with the old stripe interleave
        chunk_len = int(np.asarray(chunks[0]).size)
        obj_attrs = {"size": str(len(data)).encode(),
                     "S": b"1", "U": str(chunk_len).encode()}
        if extra_attrs:
            obj_attrs.update(extra_attrs)
        # EC write contract (VERDICT r3 weak #2): the primary gathers
        # ALL shard commits before acknowledging
        # (src/osd/ECBackend.cc:1150) — transient failures retry
        # against a refreshed map, and success requires every MAPPED
        # shard committed (plus >= k overall: a write that cannot
        # tolerate the advertised failures must not ack)
        # acked maps shard -> the OSD that committed it; a shard only
        # counts when its ack matches its CURRENT mapped home, so a
        # mid-write re-homing (map refresh between attempts) resends
        # rather than silently counting a write to the old home
        acked: Dict[int, int] = {}
        attempts = 3
        for attempt in range(attempts):
            # shard fan-out rides the async core: every sub-write is
            # submitted to its target's stream pool (payload on the
            # scatter-gather frame tail), then the GATHER-COMMITS
            # step collects per-shard verdicts — the k+m frames
            # encode/transmit concurrently across streams instead of
            # one blocking RTT per shard
            fan: List[Tuple[int, int, object]] = []
            # submission order: on a multi-host plane the sub-writes
            # interleave round-robin across the targets' hosts so
            # every host's dispatch queue fills from the first
            # submit; single-host it is the identity order (today's
            # fan-out, byte for byte)
            targets = [up[s] if s < len(up) else ITEM_NONE
                       for s in range(n)]
            from ..parallel.multihost import stripe_order
            for shard in stripe_order(targets):
                tgt = targets[shard]
                if tgt == ITEM_NONE or acked.get(shard) == tgt:
                    continue
                fan.append((shard, tgt, self.aio.call_async(tgt, {
                    "cmd": "put_shard", "coll": coll,
                    "oid": f"{shard}:{name}",
                    # zero-copy: the encoded shard's buffer view goes
                    # straight to the SG frame / shm ring — tobytes()
                    # re-copied every shard byte client-side
                    "data": _as_buf(chunks[shard]),
                    # logical object size travels as shard metadata
                    # so ANY client can unpad reads (object_info_t)
                    "attrs": obj_attrs})))
            fatal: Optional[BaseException] = None
            for (shard, tgt, comp), (_r, err) in zip(
                    fan, self.aio.gather([c for _, _, c in fan])):
                if err is None:
                    acked[shard] = tgt
                elif not isinstance(err, OSError):
                    # only connection-class failures are transient
                    # resend material; a daemon REJECTION (caps,
                    # registry, cls errors surfaced as non-IO types)
                    # must not be laundered into 'EC write incomplete'
                    # by the retry loop — same taxonomy the blocking
                    # osd_call path applied
                    fatal = err
            if fatal is not None:
                raise fatal
            mapped = [s for s in range(n)
                      if s < len(up) and up[s] != ITEM_NONE]
            # an UNMAPPED slot is not "done" either: a stale client
            # map (fetched before a booting OSD's epoch landed) maps
            # the slot ITEM_NONE while every sub-write succeeds — the
            # refresh below fills the hole and the next round writes
            # the missing shard instead of acking a degraded-at-birth
            # object; a slot that stays unmapped after the retries is
            # a genuinely down OSD and the >= k verdict applies
            done = len(mapped) == n and \
                all(acked.get(s) == up[s] for s in mapped)
            if done or attempt == attempts - 1:
                break
            # transient shard failure: re-pull the map (the target may
            # have been marked down/re-homed) and resend the misses
            self._backoff.sleep(attempt)
            try:
                self.refresh_map()
            except (OSError, IOError):
                pass
            up = self._up(pool, pg)
        # verdict against the map the final sends targeted
        mapped = [s for s in range(n)
                  if s < len(up) and up[s] != ITEM_NONE]
        missing = [s for s in mapped if acked.get(s) != up[s]]
        acks = sum(1 for s in mapped if acked.get(s) == up[s])
        if missing or acks < k:
            raise IOError(
                f"{name}: EC write incomplete — {acks}/{n} shards "
                f"committed, unacked mapped shards {missing} "
                f"(gather-all-commits contract)")
        if snapset is not None:
            self._store_snapset(pool, pg, name, snapset)
        return acks

    def get(self, pool_id: int, name: str,
            size: Optional[int] = None) -> bytes:
        """Read with bounded whole-read retries: one round can lose to
        transient connection drops on every holder (socket-failure
        injection, daemons restarting); the retry refreshes the map
        and sweeps again before reporting the object unreadable.

        Tiered pools (read_tier set): the read serves from the cache
        pool; a cache MISS promotes the object through the op engine
        (COPY_FROM base -> cache, executed by the cache primary
        daemon — PrimaryLogPG::promote_object, :3932) and then serves
        the promoted copy."""
        return self._tracked("get", pool_id, name,
                             lambda: self._get_routed(pool_id, name,
                                                      size))

    def _get_routed(self, pool_id: int, name: str,
                    size: Optional[int] = None) -> bytes:
        pool = self.osdmap.pools[pool_id]
        if pool.read_tier >= 0 and "@" not in name:
            try:
                data = self.get(pool.read_tier, name, size)
                self._tier_reads[(pool_id, name)] = \
                    self._tier_reads.get((pool_id, name), 0) + 1
                return data
            except RemoteObjectMissing:
                pass
            try:
                self.copy_from(pool.read_tier, name, pool_id, name)
            except (OSError, IOError):
                # promote failed — could be a TRANSIENT daemon issue,
                # not absence: fall back to a PROXY READ of the base
                # tier (Ceph's proxy-read mode); only a definitive
                # base miss propagates as missing
                return self._get_base_direct(pool_id, name, size)
            self._tier_reads[(pool_id, name)] = \
                self._tier_reads.get((pool_id, name), 0) + 1
            return self.get(pool.read_tier, name, size)
        return self._get_base_direct(pool_id, name, size)

    def _get_base_direct(self, pool_id: int, name: str,
                         size: Optional[int] = None) -> bytes:
        """The retrying read against ONE pool, no tier routing.  Six
        attempts with capped backoff + map refresh: a degraded sweep
        can lose one round to EVERY holder transiently (kill9'd
        daemons whose sockets refuse, starved survivors, injected
        drops) — the budget must out-wait a markdown/reboot window
        rather than race it (the same ISSUE-9 contention fix as the
        put path)."""
        last: Optional[Exception] = None
        attempts = 6
        for attempt in range(attempts):
            try:
                return self._get_once(pool_id, name, size)
            except RemoteObjectMissing:
                raise        # definitive miss (targets answered): no retry
            except (OSError, IOError) as e:
                last = e
                if attempt < attempts - 1:   # no backoff on last throw
                    self._backoff.sleep(attempt)
                    try:
                        self.refresh_map()
                    except (OSError, IOError):
                        pass
        raise IOError(f"{name}: unreadable after retries ({last})")

    def _get_once(self, pool_id: int, name: str,
                  size: Optional[int] = None) -> bytes:
        pool = self.osdmap.pools[pool_id]
        pg = self._pg_for(pool, name)
        up = self._up(pool, pg)
        coll = [pool_id, pg]
        if pool.type != POOL_ERASURE:
            last_err = None
            conn_errors = 0
            for o in [x for x in up if x != ITEM_NONE] + \
                    [x for x in self.addrs if x not in up]:
                try:
                    data = self.osd_call(o, {
                        "cmd": "get_shard", "coll": coll,
                        "oid": f"0:{name}"})
                except (OSError, IOError) as e:
                    last_err = e
                    conn_errors += 1
                    continue
                if data is not None:
                    return data
            if conn_errors == 0:
                # every target ANSWERED and none has it: a definitive
                # miss, not a connectivity problem — callers probing
                # existence must not pay the retry sweep
                raise RemoteObjectMissing(f"{name}: no such object")
            raise IOError(f"{name}: no replica served ({last_err})")
        codec = self.codec_for(pool)
        k, n = codec.get_data_chunk_count(), codec.get_chunk_count()
        shards: Dict[int, bytes] = {}
        obj_size: Optional[int] = None
        geom_s: Optional[int] = None
        geom_u: Optional[int] = None
        geom_resolved = False
        conn_errors = 0
        for shard in range(n):
            # client HBM staging first: a shard this client wrote or
            # read serves from device words (dirty entries are
            # authoritative; clean ones validate against the daemon's
            # stored checksum, one digest RTT, no payload transfer)
            key = (pool_id, pg, name, shard)
            staged = self.dev.dirty_get(key)
            attrs_src = None
            if staged is None and self.dev.has(key):
                io = self.ec_backend(pool_id).io
                try:
                    dg = io._digest(pg, shard, name)
                except (OSError, IOError):
                    dg = None      # unreachable: fall to wire fetch
                if dg is not None:
                    staged = self.dev.get(key, dg)
            if staged is not None:
                shards[shard] = np.asarray(staged).tobytes()
                a = self._staged_attrs.get(key)
                if a:
                    attrs_src = lambda kk, a=a: a.get(kk)
                else:
                    io = self.ec_backend(pool_id).io

                    def attrs_src(kk, shard=shard):
                        return io.getattr(pg, name, shard, kk)
            else:
                srcs = [up[shard]] if shard < len(up) and \
                    up[shard] != ITEM_NONE else []
                srcs += [o for o in self.addrs if o not in srcs]
                for o in srcs:
                    try:
                        d = self.osd_call(o, {
                            "cmd": "get_shard", "coll": coll,
                            "oid": f"{shard}:{name}"})
                    except (OSError, IOError):
                        conn_errors += 1
                        continue
                    if d is not None:
                        shards[shard] = d

                        def attrs_src(kk, o=o, shard=shard):
                            # propagate wire errors: "attr absent"
                            # and "holder unreachable" must not be
                            # conflated (geometry decides assembly)
                            return self.osd_call(o, {
                                "cmd": "getattr_shard",
                                "coll": coll,
                                "oid": f"{shard}:{name}",
                                "key": kk})
                        break
            if attrs_src is not None and not geom_resolved:
                try:
                    sz = attrs_src("size")
                    s_raw, u_raw = attrs_src("S"), attrs_src("U")
                except (OSError, IOError):
                    continue      # try the next shard's holder
                if sz is not None:
                    obj_size = int(sz)
                # a DEFINITIVE answer: attrs answered (None = a
                # legacy single-stripe object, values = stripewise)
                geom_resolved = True
                if s_raw is not None and u_raw is not None:
                    geom_s, geom_u = int(s_raw), int(u_raw)
        if len(shards) < k:
            if not shards and conn_errors == 0:
                raise RemoteObjectMissing(f"{name}: no such object")
            raise IOError(f"{name}: only {len(shards)} shards (< k)")
        if not geom_resolved and obj_size is None and shards:
            # shards readable but NO holder answered the attr probes:
            # assembling with guessed geometry could silently scramble
            # a stripewise object — error out and let the caller's
            # retry loop re-sweep
            raise IOError(f"{name}: shard attrs unreadable "
                          f"(geometry unknown)")
        be = self.ec_backend(pool_id)
        plan, missing = be.plan(list(shards))
        if geom_s is not None and geom_u:
            # stripewise object (batched put): shard files are S
            # chunks of U bytes; degraded decode runs per-stripe
            # geometry — on device in the word domain when the codec
            # supports it
            S, U = geom_s, geom_u
            dec8 = None
            if missing:
                if be.words_supported():
                    import jax.numpy as jnp
                    stack = np.stack(
                        [np.frombuffer(shards[c], dtype="<i4")
                         .reshape(S, U // 4) for c in plan], axis=1)
                    job = (plan, jnp.asarray(stack), missing)
                    dec = be.decode_signature_groups([job])[0]
                    dec8 = np.asarray(dec).view(np.uint8).reshape(
                        S, len(missing), U)
                else:
                    stack = np.stack(
                        [np.frombuffer(shards[c], dtype=np.uint8)
                         .reshape(S, U) for c in plan], axis=1)
                    dec8 = np.asarray(codec.decode_chunks_batch(
                        plan, stack, missing))
            cols = []
            for c in range(k):
                if c in shards:
                    cols.append(np.frombuffer(shards[c],
                                              dtype=np.uint8)
                                .reshape(S, U))
                else:
                    cols.append(dec8[:, missing.index(c)])
            buf = np.stack(cols, axis=1).reshape(-1).tobytes()
        else:
            # legacy single-stripe object: whole shard = one chunk
            stack = np.stack([np.frombuffer(shards[c], dtype=np.uint8)
                              for c in plan])
            if missing:
                dec = np.asarray(codec.decode_chunks(plan, stack,
                                                     missing))
            data_chunks = []
            for c in range(k):
                if c in shards:
                    data_chunks.append(np.frombuffer(shards[c],
                                                     dtype=np.uint8))
                else:
                    data_chunks.append(dec[missing.index(c)])
            buf = np.concatenate(data_chunks).tobytes()
        if size is None:
            size = obj_size if obj_size is not None else len(buf)
        return buf[:size]

    def delete(self, pool_id: int, name: str) -> int:
        """Delete an object.  Replicated pools go through the
        primary's LOGGED delete (delete_object: version + OP_DELETE
        entry + fan-out — src/osd/PrimaryLogPG.cc delete shape), so a
        down replica cannot resurrect the object on log-driven
        recovery.  EC pools delete per shard, mirroring this client's
        shard-direct write path.

        In a snapped pool the head is COW-preserved first and its
        snapset moves to a sidecar object (the head's xattr dies with
        it) — deleting an object must not delete its history
        (make_writeable-on-delete; the sim keeps this in SnapMapper).

        Tiered base pools delete BOTH copies (cache first), or the
        next read would promote the object back to life."""
        pool = self.osdmap.pools[pool_id]
        if pool.write_tier >= 0 and "@" not in name:
            # delete the cache copy FIRST — a real failure here must
            # surface (a surviving cache copy would keep serving, and
            # a later flush would resurrect the object in the base);
            # then fall through to the base delete, which is
            # idempotent on absence
            try:
                self.delete(pool.write_tier, name)
            except RemoteObjectMissing:
                pass              # not (or no longer) cached
            self._tier_reads.pop((pool_id, name), None)
        pg = self._pg_for(pool, name)
        if "@" not in name:
            ss = self._maybe_cow(pool, pg, name)
            if ss is not None and (ss.get("clones") or
                                   ss.get("write_seq")):
                self.put(pool_id, f"{name}@snapset",
                         json.dumps(ss).encode())
        self._evict_staging(pool_id, pg, name)
        up = self._up(pool, pg)
        coll = [pool_id, pg]
        if pool.type != POOL_ERASURE:
            last: Optional[Exception] = None
            for attempt in range(3):
                replicas = [o for o in up if o != ITEM_NONE]
                if not replicas:
                    raise IOError(f"{name}: no live replica target")
                try:
                    r = self.osd_call(replicas[0], {
                        "cmd": "delete_object", "coll": coll,
                        "oid": f"0:{name}", "replicas": replicas})
                    return int(r["acks"])
                except (OSError, IOError) as e:
                    last = e
                    if attempt < 2:
                        self._backoff.sleep(attempt)
                        try:
                            self.refresh_map()
                        except (OSError, IOError):
                            pass
                        up = self._up(pool, pg)
            raise IOError(f"{name}: delete failed after retries "
                          f"({last})")
        acks = 0
        codec = self.codec_for(pool)
        for shard in range(codec.get_chunk_count()):
            tgt = up[shard] if shard < len(up) else ITEM_NONE
            if tgt == ITEM_NONE:
                continue
            try:
                # osd_call: session-stamped (replay-safe) + one
                # reconnect retry per target
                self.osd_call(tgt, {
                    "cmd": "delete_shard", "coll": coll,
                    "oid": f"{shard}:{name}"})
                acks += 1
            except (OSError, IOError):
                pass
        return acks

    def list_objects(self, pool_id: int) -> List[str]:
        """Logical object names in a pool: PG-walk each primary's
        listing, collapsing shard prefixes and snapshot clones (the
        `rados ls` shape; also the admin CLIs' shared listing)."""
        pool = self.osdmap.pools[pool_id]
        names = set()
        for pg in range(pool.pg_num):
            ups = self._up(pool, pg)
            members = [o for o in ups if o != ITEM_NONE]
            if not members:
                continue
            # the PRIMARY is the one member guaranteed current (it
            # applies every write locally before fanning out), so ask
            # it first; if it is truly unreachable, fall back to the
            # surviving member with the HIGHEST pg-log head — a plain
            # union would transiently resurrect objects a stale
            # replica missed the logged delete for, and a stale
            # replica alone could hide a degraded write; the log head
            # identifies the most-current survivor
            listed: Optional[List[str]] = None
            for attempt in range(3):
                try:
                    listed = self.osd_call(
                        members[0],
                        {"cmd": "list_pg", "coll": [pool_id, pg]})
                    break
                except (OSError, IOError):
                    self._backoff.sleep(attempt)
            if listed is None:
                # cheap pg_info probe first, then list only the
                # best-head member; a member whose probe failed is
                # still tried last so one blip cannot turn a listable
                # PG into an error
                heads = []
                for tgt in members[1:]:
                    try:
                        info = self.osd_call(
                            tgt,
                            {"cmd": "pg_info", "coll": [pool_id, pg]})
                        heads.append((tuple(info["head"]), tgt))
                    except (OSError, IOError):
                        heads.append(((-1, -1), tgt))
                heads.sort(key=lambda h: h[0], reverse=True)
                for _, tgt in heads:
                    try:
                        listed = self.osd_call(
                            tgt,
                            {"cmd": "list_pg", "coll": [pool_id, pg]})
                        break
                    except (OSError, IOError):
                        continue
                if listed is None:
                    raise IOError(
                        f"pg {pool_id}.{pg}: no member listable")
            for n in listed:
                # PG-internal rows ("meta:pglog") carry no shard
                # prefix; data objects are "<shard>:<name>"
                if n.startswith("meta:") or ":" not in n:
                    continue
                head = n.split(":", 1)[1]
                if head.startswith("meta:") or "@" in head:
                    continue
                names.add(head)
        return sorted(names)

    # ------------------------------------------------------------ recovery --
    def recover_pool(self, pool_id: int) -> Dict:
        """Replicated pools: primary-driven PEERING recovery per PG
        (GetInfo/GetLog/GetMissing on the primary daemon; members
        catch up by log delta when the log covers their gap, else
        backfill — src/osd/PeeringState.h:561, PGLog.h).

        PGs recover CONCURRENTLY under the daemons' recovery
        reservations (osd_max_backfills): each primary takes a local
        slot plus remote slots on its members before moving a byte; a
        denied PG comes back ``deferred`` and requeues.  When a whole
        round defers (every slot held elsewhere), one PG runs solo so
        the loop always advances."""
        pool = self.osdmap.pools[pool_id]
        totals = {"copied": 0, "delta_objects": 0,
                  "backfill_objects": 0, "deletes_applied": 0,
                  "modes": {"delta": 0, "backfill": 0, "clean": 0}}
        work = []
        for pg in range(pool.pg_num):
            up = self._up(pool, pg)
            members = [o for o in up if o != ITEM_NONE]
            if not members:
                continue
            # every non-member OSD is a potential STRAY log/data
            # source (the past-interval role): a map flap can have
            # landed acked writes on a substitute member that has
            # since dropped out of the set — the primary must be
            # able to find that log or the objects are unreachable
            # to recovery forever
            strays = [int(o) for o in self.addrs
                      if int(o) not in members]
            work.append((pg, members, strays))

        def run_pg(item):
            pg, members, strays = item
            for attempt in range(3):  # a skipped PG stays unrepaired
                try:
                    return self.osd_call(members[0], {
                        "cmd": "recover_pg", "coll": [pool_id, pg],
                        "members": members, "strays": strays})
                except (OSError, IOError):
                    self._backoff.sleep(attempt)
            return None

        def merge(r) -> None:
            for key in ("copied", "delta_objects",
                        "backfill_objects", "deletes_applied"):
                totals[key] += r.get(key, 0)
            for mode in r.get("mode", {}).values():
                totals["modes"][mode] = \
                    totals["modes"].get(mode, 0) + 1

        def run(item):
            r = run_pg(item)
            if r is None:
                return {}         # unreachable primary: next pass
            return None if r.get("deferred") else r

        left = self._drain_pg_queue(list(work), run, merge)
        if left:
            totals["deferred_pgs"] = left
        return totals

    def _drain_pg_queue(self, queue: List, run, merge,
                        max_workers: int = 8) -> int:
        """Concurrent requeue loop shared by the reservation-gated
        recovery sweeps: ``run(item)`` returns a stats dict (merged)
        or None for a DEFERRED item (requeued).  When a whole round
        defers, one item runs SOLO so the loop always advances; a
        bounded stall (a foreign client holding every slot) gives up
        and returns how many items stayed deferred."""
        import concurrent.futures as cf
        stalled = 0
        with cf.ThreadPoolExecutor(
                max_workers=min(max_workers,
                                max(1, len(queue) or 1))) as ex:
            while queue:
                deferred = []
                for item, r in zip(queue, ex.map(run, queue)):
                    if r is None:
                        deferred.append(item)
                    else:
                        merge(r)
                if len(deferred) == len(queue):
                    r = run(deferred[0])
                    if r is not None:
                        merge(r)
                        deferred.pop(0)
                        stalled = 0   # solo progress IS progress
                    else:
                        stalled += 1
                        if stalled > 10:
                            return len(deferred)
                        self._backoff.sleep(stalled)
                else:
                    stalled = 0
                queue = deferred
        return 0

    def scrub_pool(self, pool_id: int,
                   repair: bool = False) -> Dict:
        """Cross-replica scrub over the wire, per PG on the primary
        (pg_scrubber role): digests compared across members,
        inconsistencies listed, optionally repaired from the
        majority."""
        pool = self.osdmap.pools[pool_id]
        if pool.type == POOL_ERASURE:
            raise IOError(
                "scrub_pool compares replica digests; EC pools "
                "scrub by parity re-encode (ClusterSim.scrub / "
                "recover_ec_pool)")
        totals = {"objects": 0, "inconsistent": [], "repaired": 0}
        for pg in range(pool.pg_num):
            up = self._up(pool, pg)
            members = [o for o in up if o != ITEM_NONE]
            if not members:
                continue
            r = None
            for attempt in range(3):  # a skipped PG goes unscrubbed
                try:
                    r = self.osd_call(members[0], {
                        "cmd": "scrub_pg", "coll": [pool_id, pg],
                        "members": members, "repair": repair})
                    break
                except (OSError, IOError):
                    self._backoff.sleep(attempt)
            if r is None:
                continue
            totals["objects"] += r["objects"]
            totals["inconsistent"].extend(
                dict(i, pg=pg) for i in r["inconsistent"])
            totals["repaired"] += r["repaired"]
        return totals

    def _reserve_pg_members(self, members: List[int]
                            ) -> Optional[List[int]]:
        """Client-side reservation acquisition for CLIENT-driven EC
        recovery (this client is the TPU-attached primary): one
        REMOTE slot per member, all-or-nothing with rollback — an
        explicit denial defers the PG to the caller's requeue loop
        (returns None), never waits while holding.  Returns the list
        of members actually holding a slot (the ONLY ones the caller
        may release — releasing an unreserved member would decrement
        a concurrent PG's slot)."""
        got: List[int] = []
        for m in members:
            try:
                r = self.osd_call(m, {"cmd": "reserve_recovery",
                                      "role": "remote"})
            except (OSError, IOError):
                # UNREACHABLE member: nothing to reserve — proceed
                # without its slot (its pushes will fail and the
                # object stays visibly missing for the next pass);
                # deferring on a dead-but-in-map member would block
                # every reachable member's repair forever
                continue
            if not (r or {}).get("granted"):
                self._release_pg_members(got)
                return None
            got.append(m)
        return got

    def _release_pg_members(self, members: List[int]) -> None:
        for m in members:
            try:
                self.osd_call(m, {"cmd": "release_recovery",
                                  "role": "remote"})
            except (OSError, IOError):
                pass

    def _gather_shard_fetches(self, coll, wants: Dict) -> Dict:
        """Submit-all-then-gather shard reads for one PG's repair
        set: every (object, shard) fetch pipelines onto the
        AsyncObjecter's multi-stream pools as one round per holder
        rank — the per-shard blocking round trips this replaces were
        the wire tier's recovery floor.  ``wants`` maps (name, shard)
        to (ordered holder list, byte ranges|None); a failed holder
        fails over to the next on the following round."""
        out: Dict = {}
        pending = {wk: (list(hs), rg)
                   for wk, (hs, rg) in wants.items()}
        while pending:
            fan = []
            for wk, (hs, rg) in list(pending.items()):
                if not hs:
                    del pending[wk]
                    continue
                o = hs.pop(0)
                name, shard = wk
                req = {"cmd": "get_shard", "coll": coll,
                       "oid": f"{shard}:{name}",
                       "klass": "background_recovery"}
                if rg:
                    req["ranges"] = [list(r) for r in rg]
                fan.append((wk, o, self.aio.call_async(o, req)))
            if not fan:
                break
            for (wk, o, _c), (d, err) in zip(
                    fan, self.aio.gather([c for _, _, c in fan])):
                if err is None and d is not None:
                    out[wk] = (d, o)
                    pending.pop(wk, None)
        return out

    def _gather_attrs(self, coll, cands: Dict) -> Dict:
        """One ``getattrs_shard`` round trip per object (size/S/U in
        a single frame), submit-all-then-gather; ``cands`` maps name
        to its ordered (holder, shard) candidates — each candidate is
        asked about the shard IT served, and one holder supplies ALL
        attrs (mixing two holders' geometries is how stale attrs
        corrupt a rebuild)."""
        out: Dict = {}
        pending = {nm: list(cs) for nm, cs in cands.items()}
        while pending:
            fan = []
            for nm, cs in list(pending.items()):
                if not cs:
                    del pending[nm]
                    continue
                o, shard = cs.pop(0)
                fan.append((nm, self.aio.call_async(o, {
                    "cmd": "getattrs_shard", "coll": coll,
                    "oid": f"{shard}:{nm}",
                    "keys": ["size", "S", "U"],
                    "klass": "background_recovery"})))
            if not fan:
                break
            for (nm, _c), (d, err) in zip(
                    fan, self.aio.gather([c for _, c in fan])):
                if err is None and d:
                    cand = {ak: bytes(av) for ak, av in d.items()
                            if av is not None}
                    if cand:
                        out[nm] = cand
                        pending.pop(nm, None)
        return out

    def recover_ec_pool(self, pool_id: int) -> Dict[str, int]:
        """Client-driven EC recovery (the client is the TPU-attached
        primary), reservation-gated and CONCURRENT across PGs, each
        PG in three passes: (1) union every daemon's shard listing
        and fetch only the shards the codec's MINIMAL repair plan
        requires (``minimum_to_decode`` — LRC repairs inside the
        covering local group, Clay single losses fetch d helpers'
        repair SUB-CHUNK ranges and regenerate via ``codec.repair``);
        (2) decode the PG's lost shards in signature-GROUPED device
        dispatches; (3) push surviving copies and rebuilt shards to
        their up targets.  Every fetch and push is submit-all-then-
        gather on the AsyncObjecter's pipelined streams; pushes carry
        (session, seq) stamps so a stream-death replay applies at
        most once.  PG-scoped batching keeps client memory bounded by
        one PG's repair set."""
        pool = self.osdmap.pools[pool_id]
        be = self.ec_backend(pool_id)
        stats: Dict[str, int] = {"objects": 0, "shards_copied": 0,
                                 "shards_rebuilt": 0}
        live = [o for o in self.addrs
                if self.osdmap.osd_up[o]]

        def sweep(pg: int) -> Optional[Dict[str, int]]:
            return self._recover_ec_pg(pool, be, pg, live)

        def merge(r) -> None:
            for kk, v in r.items():
                stats[kk] = stats.get(kk, 0) + v

        left = self._drain_pg_queue(list(range(pool.pg_num)), sweep,
                                    merge)
        if left:
            stats["deferred_pgs"] = left
        return stats

    def _recover_ec_pg(self, pool: PGPool, be, pg: int,
                       live: List[int]) -> Optional[Dict[str, int]]:
        """One PG's repair sweep; None = reservation denied (the
        caller requeues).  The reservation is taken only once the
        plan pass proves there is work to move — a clean PG costs
        its listings, never a reservation round."""
        codec, k, n = be.codec, be.k, be.n
        stats = {"objects": 0, "shards_copied": 0, "shards_rebuilt": 0}
        coll = [pool.id, pg]
        # -- listings: one async gather across every live daemon
        fan = [(o, self.aio.call_async(o, {"cmd": "list_pg",
                                           "coll": coll}))
               for o in live]
        holdings: Dict[int, set] = {}
        for (o, _c), (r, err) in zip(
                fan, self.aio.gather([c for _, c in fan])):
            if err is None and r is not None:
                holdings[o] = set(r)
        names = set()
        for objs in holdings.values():
            for oid in objs:
                shard_s, nm = oid.split(":", 1)
                names.add(nm)
        up = self._up(pool, pg)

        def holders_of(name, shard):
            oid = f"{shard}:{name}"
            return [x for x, objs in holdings.items() if oid in objs]

        # -- plan pass: decide, per object, the minimal fetch set
        plans = {}
        for name in sorted(names):
            stats["objects"] += 1
            # cheap membership pass first: skip healthy objects
            # without moving a byte (holdings already lists every
            # daemon's oids)
            have_somewhere = {s for s in range(n)
                              if any(f"{s}:{name}" in objs
                                     for objs in holdings.values())}
            need = [s for s in range(n)
                    if s < len(up) and up[s] != ITEM_NONE and
                    f"{s}:{name}" not in holdings.get(up[s], set())]
            if not need:
                continue
            lost = [s for s in need if s not in have_somewhere]
            # fetch only what the repair requires: the sources of
            # displaced shards, plus the codec's MINIMAL decode set
            # (not every survivor) when shards must be rebuilt
            fetch = set(need) & have_somewhere
            sub_plan = None
            if lost:
                try:
                    sub_plan = codec.minimum_to_decode(
                        set(lost), set(have_somewhere))
                except ErasureCodeError:
                    sub_plan = None
                if sub_plan is None:
                    fetch |= set(sorted(have_somewhere)[:n])
                else:
                    fetch |= set(sub_plan)
            plans[name] = (sorted(fetch), lost, have_somewhere,
                           sub_plan)
        if not plans:
            return stats      # clean PG: listings only, no reservation
        # there IS work to move: take the recovery reservations
        # (one REMOTE slot per member, all-or-nothing) before the
        # first payload byte; an explicit denial defers the whole PG
        members = [o for o in up if o != ITEM_NONE]
        reserved = self._reserve_pg_members(members)
        if reserved is None:
            return None
        try:
            return self._recover_ec_pg_move(
                pool, be, pg, coll, up, plans, holdings, holders_of,
                stats)
        finally:
            self._release_pg_members(reserved)

    def _recover_ec_pg_move(self, pool: PGPool, be, pg: int, coll,
                            up: List[int], plans: Dict,
                            holdings: Dict[int, set], holders_of,
                            stats: Dict[str, int]) -> Dict[str, int]:
        codec, k, n = be.codec, be.k, be.n
        sub_chunks = codec.get_sub_chunk_count()
        records: List[Dict] = []
        # -- ranged (regenerating-code) single-loss repair CANDIDATES
        # — the partial-plan shape is decidable from the SubChunkPlan
        # alone; only these need geometry attrs BEFORE their byte
        # fetch (byte ranges derive from U), so only they pay a
        # pre-fetch attr round against listing-derived holders
        maybe_ranged = {
            name for name, (fetch, lost, _h, sub_plan)
            in plans.items()
            if sub_plan is not None and len(lost) == 1 and
            not (set(fetch) - set(sub_plan)) and
            any(sum(c for _o, c in rg) < sub_chunks
                for rg in sub_plan.values())}
        attrs_by_name = self._gather_attrs(coll, {
            name: [(h, s) for s in plans[name][0]
                   for h in holders_of(name, s)]
            for name in sorted(maybe_ranged)})
        ranged = {name: plans[name][3] for name in maybe_ranged
                  if "U" in attrs_by_name.get(name, {})}
        wants: Dict = {}
        for name, (fetch, lost, have, sub_plan) in plans.items():
            if name in ranged:
                continue
            for shard in fetch:
                wants[(name, shard)] = (holders_of(name, shard), None)
        fetched = self._gather_shard_fetches(coll, wants)
        # -- attrs for the decode/push path come from the holders
        # that actually SERVED each object's bytes (one holder, all
        # attrs — a holder serving stale bytes with fresh attrs, or
        # vice versa, must not mix geometries; stripewise objects
        # must decode with per-stripe plane geometry, and the attrs
        # ride along to re-homed copies so geometry never strands)
        attrs_by_name.update(self._gather_attrs(coll, {
            name: [(src, shard)
                   for shard in fetch
                   if (name, shard) in fetched
                   for src in [fetched[(name, shard)][1]]]
            for name, (fetch, _l, _h, _p) in plans.items()
            if name not in ranged and fetch}))
        pushes: List[Tuple] = []
        for name, sub_plan in ranged.items():
            st = self._repair_ranged_wire(pool, be, pg, name, up,
                                          plans[name],
                                          attrs_by_name.get(name, {}),
                                          holders_of, pushes)
            for kk, v in st.items():
                stats[kk] = stats.get(kk, 0) + v
        # gather the rebuilt-shard pushes submitted above: one
        # blocking put_shard RTT per repaired object was the ranged
        # loop's wire floor (CTL120) — the pushes pipeline on the
        # async objecter and complete here in one gather
        for comp, tgt, oid, nbytes_fetched in pushes:
            try:
                comp.get_return_value()
            except (OSError, IOError):
                # not a swallowed loss: the shard stays missing in
                # the next sweep's listings; this pass reports it
                stats["unrecoverable"] = \
                    stats.get("unrecoverable", 0) + 1
                continue
            holdings.setdefault(tgt, set()).add(oid)
            for kk, v in (("shards_rebuilt", 1),
                          ("ranged_repairs", 1),
                          ("repair_bytes_fetched", nbytes_fetched)):
                stats[kk] = stats.get(kk, 0) + v
        # top-up round: ONLY a name whose minimal-plan fetch actually
        # FAILED a shard widens to the survivors the plan skipped
        # (the old fetch-everything slack, paid strictly on failure —
        # a successful LRC local-group plan is SMALLER than k by
        # design and must not trigger a fetch of every survivor)
        topup: Dict = {}
        for name, (fetch, lost, have, sub_plan) in plans.items():
            if name in ranged or not lost:
                continue
            if any((name, s) not in fetched for s in fetch):
                for s in sorted(have - set(fetch)):
                    topup[(name, s)] = (holders_of(name, s), None)
        if topup:
            fetched.update(self._gather_shard_fetches(coll, topup))
            # a top-up source may be the only holder that answered
            # at all: its attrs must be fetchable too (an object
            # decoded without its S would scramble stripewise plane
            # boundaries past the geometry gate)
            attrs_by_name.update(self._gather_attrs(coll, {
                name: [(src, shard)
                       for (nm, shard), (_d, src) in sorted(
                           fetched.items(),
                           key=lambda it: it[0][1])
                       if nm == name]
                for name in {nm for nm, _s in topup}
                if name not in attrs_by_name}))
        for name, (fetch, lost, have, sub_plan) in plans.items():
            if name in ranged:
                continue
            shards: Dict[int, bytes] = {}
            shard_src: Dict[int, int] = {}
            for shard in set(fetch) | (set(have) if lost else set()):
                hit = fetched.get((name, shard))
                if hit is not None:
                    shards[shard], shard_src[shard] = hit
            missing = [s for s in lost if s not in shards]
            if missing:
                # decodability gate: can the FETCHED set regenerate
                # the losses?  (Not `len(shards) < k` — an LRC
                # local-group plan is SMALLER than k by design and
                # still decodes; only the codec can answer.)  A 'no'
                # is an UNFOUND object callers must see — a
                # clean-looking stats dict would hide data loss
                try:
                    codec.minimum_to_decode(set(missing), set(shards))
                except ErasureCodeError:
                    stats["unrecoverable"] = \
                        stats.get("unrecoverable", 0) + 1
                    continue
            obj_attrs = attrs_by_name.get(name, {})
            S_obj = int(obj_attrs["S"]) if "S" in obj_attrs else 1
            # geometry gate: every fetched shard must be ONE
            # consistent length L with L == S_obj * U (attrs) —
            # a mismatched holder (truncated shard, stale attrs)
            # counts the object unrecoverable/skipped instead of
            # an uncaught reshape ValueError killing the whole
            # pool sweep
            lengths = {len(d) for d in shards.values()}
            L = lengths.pop() if len(lengths) == 1 else None
            bad = shards and (
                L is None or (S_obj > 1 and L % S_obj != 0))
            if not bad and shards and "U" in obj_attrs:
                bad = L != S_obj * int(obj_attrs["U"])
            if bad:
                stats["unrecoverable"] = \
                    stats.get("unrecoverable", 0) + 1
                stats["geometry_skipped"] = \
                    stats.get("geometry_skipped", 0) + 1
                continue
            records.append({"pg": pg, "coll": coll, "name": name,
                            "up": up, "holdings": holdings,
                            "shards": shards, "missing": missing,
                            "S": S_obj, "attrs": obj_attrs,
                            "rebuilt": set()})
        # -- signature-grouped decode of this PG's rebuilds
        jobs, job_recs = [], []
        for rec in records:
            missing, shards = rec["missing"], rec["shards"]
            if not missing:
                continue
            plan = sorted(codec.minimum_to_decode(set(missing),
                                                  set(shards)))
            # decode-fetch payload only (same semantics as the sim
            # tier's counter: displaced-copy traffic is re-placement,
            # not repair bandwidth)
            stats["repair_bytes_fetched"] = \
                stats.get("repair_bytes_fetched", 0) + \
                sum(len(shards[c]) for c in plan)
            L = len(rec["shards"][plan[0]])
            S_obj = rec["S"]
            if be.words_supported() and L % 4 == 0 and \
                    L % max(S_obj, 1) == 0:
                import jax.numpy as jnp
                # [S, n_avail, W]: per-stripe plane geometry
                stack = np.stack(
                    [np.frombuffer(shards[c], dtype="<i4")
                     .reshape(S_obj, -1) for c in plan], axis=1)
                jobs.append((plan, jnp.asarray(stack), missing))
                job_recs.append(rec)
            else:
                stackb = np.stack(
                    [np.frombuffer(shards[c], dtype=np.uint8)
                     .reshape(S_obj, -1) for c in plan], axis=1)
                dec = np.asarray(codec.decode_chunks_batch(
                    plan, stackb, missing))
                for i, s in enumerate(missing):
                    shards[s] = np.ascontiguousarray(
                        dec[:, i]).tobytes()
                    rec["rebuilt"].add(s)
                    stats["shards_rebuilt"] += 1
        if jobs:
            decs = be.decode_signature_groups(jobs)
            for rec, dec in zip(job_recs, decs):
                out = np.asarray(dec)          # [S, n_erased, W]
                for i, s in enumerate(rec["missing"]):
                    rec["shards"][s] = np.ascontiguousarray(
                        out[:, i]).tobytes()
                    rec["rebuilt"].add(s)
                    stats["shards_rebuilt"] += 1
        # -- push surviving copies + rebuilt shards to up targets:
        # submit-all-then-gather on the async streams; put_shard is a
        # replay-stamped mutation, so the one fresh-stream resubmit
        # after a stream death applies at most once
        pending_push = []
        for rec in records:
            up_r, holdings_r = rec["up"], rec["holdings"]
            for shard, data in rec["shards"].items():
                if shard >= len(up_r) or up_r[shard] == ITEM_NONE:
                    continue
                tgt = up_r[shard]
                oid = f"{shard}:{rec['name']}"
                if oid in holdings_r.get(tgt, set()):
                    continue
                pending_push.append((rec, shard, tgt, oid, data))
        # multi-host plane: interleave push submission across target
        # hosts (identity order on a single host — see stripe_order)
        from ..parallel.multihost import stripe_order
        push_fan = []
        for i in stripe_order([p[2] for p in pending_push]):
            rec, shard, tgt, oid, data = pending_push[i]
            push_fan.append(
                (rec, shard, tgt, oid,
                 self.aio.call_async(tgt, {
                     "cmd": "put_shard", "coll": rec["coll"],
                     "oid": oid, "data": data,
                     "attrs": rec["attrs"],
                     "klass": "background_recovery"})))
        for (rec, shard, tgt, oid, _c), (_r, err) in zip(
                push_fan,
                self.aio.gather([c for *_ign, c in push_fan])):
            if err is not None:
                continue          # dropped push: next pass
            rec["holdings"].setdefault(tgt, set()).add(oid)
            if shard not in rec["rebuilt"]:
                stats["shards_copied"] += 1
        return stats

    def _repair_ranged_wire(self, pool: PGPool, be, pg: int,
                            name: str, up: List[int], plan_item,
                            obj_attrs: Dict[str, bytes], holders_of,
                            pushes: List[Tuple]
                            ) -> Dict[str, int]:
        """Minimum-bandwidth single-loss repair over the wire: each
        helper in the codec's SubChunkPlan ships ONLY its repair
        sub-chunk byte ranges (ranged get_shard), ``codec.repair``
        regenerates the lost chunk client-side, and the rebuilt
        shard's push is SUBMITTED async onto ``pushes`` — the caller
        gathers all pushes after its ranged loop (submit-all-then-
        gather) and accounts ``shards_rebuilt``/``ranged_repairs``/
        ``repair_bytes_fetched`` per landed push, so benches/tests
        can assert the byte saving vs k full-chunk reads."""
        codec = be.codec
        _fetch, lost, _have, sub_plan = plan_item
        (lost_shard,) = lost
        coll = [pool.id, pg]
        if "U" not in obj_attrs:
            return {"unrecoverable": 1}
        U = int(obj_attrs["U"])
        S = int(obj_attrs["S"]) if "S" in obj_attrs else 1
        sc = U // codec.get_sub_chunk_count()
        # per-stripe ranges: a striped object's shard file is S
        # independent U-byte codeword chunks back to back
        wants = {(name, c): (holders_of(name, c),
                             [(s * U + off * sc, cnt * sc)
                              for s in range(S) for off, cnt in rg])
                 for c, rg in sorted(sub_plan.items())}
        got = self._gather_shard_fetches(coll, wants)
        if len(got) < len(wants):
            return {"unrecoverable": 1}   # helper lost: next pass
        helpers = {c: np.frombuffer(got[(name, c)][0], dtype=np.uint8)
                   for c, _rg in sub_plan.items()}
        fetched = sum(h.size for h in helpers.values())
        per_stripe = {c: h.size // S for c, h in helpers.items()}
        try:
            rebuilt = np.concatenate([codec.repair(
                lost_shard,
                {c: h[s * per_stripe[c]:(s + 1) * per_stripe[c]]
                 for c, h in helpers.items()}, U)
                for s in range(S)])
        except ErasureCodeError:
            return {"unrecoverable": 1}
        tgt = up[lost_shard] if lost_shard < len(up) else ITEM_NONE
        if tgt == ITEM_NONE:
            return {}
        oid = f"{lost_shard}:{name}"
        pushes.append((self.aio.call_async(tgt, {
            "cmd": "put_shard", "coll": coll, "oid": oid,
            "data": np.ascontiguousarray(rebuilt).tobytes(),
            "attrs": obj_attrs,
            "klass": "background_recovery"}), tgt, oid, fetched))
        return {}

    # ------------------------------------------ batched EC device plane --
    def put_many(self, pool_id: int, names: List[str],
                 datas: List[bytes]) -> Dict[str, int]:
        """Batched EC put: ONE device encode dispatch for all N
        objects (through the shared ECBackend engine), shard bytes
        committed to daemons with the gather-all-commits contract,
        shard plane words staged client-side for zero-copy reads.
        Falls back to per-object put() for non-EC pools / non-device
        codecs.  Returns {name: acked shard count}."""
        pool = self.osdmap.pools[pool_id]
        be = self.ec_backend(pool_id) \
            if pool.type == POOL_ERASURE else None
        if be is None or not be.words_supported():
            return {n: self.put(pool_id, n, d)
                    for n, d in zip(names, datas)}
        snapsets = {}
        if int(self.pool_snaps.get(pool_id, {}).get("seq", 0) or 0):
            for name in names:
                if "@" in name:
                    continue
                pg = self._pg_for(pool, name)
                ss = self._maybe_cow(pool, pg, name)
                if ss is not None:
                    snapsets[name] = (pg, ss)
        from ..cluster.ec_backend import ObjectGeom
        # group by stripe-count class: one encode dispatch per class.
        # Padding EVERY object to the largest object's stripe count
        # would write-amplify a mixed batch (a 100-byte object shipped
        # at a 256 MiB object's geometry); same-S objects share one
        # dispatch with zero amplification beyond their own padding
        by_class: Dict[int, List[int]] = {}
        for i, d in enumerate(datas):
            Si, U = be.batch_geometry([len(d)], pool.stripe_unit)
            by_class.setdefault(Si, []).append(i)
        acked_all: Dict[str, int] = {}
        for S, idxs in by_class.items():
            gnames = [names[i] for i in idxs]
            gdatas = [datas[i] for i in idxs]
            _, U = be.batch_geometry([len(d) for d in gdatas],
                                     pool.stripe_unit)
            stripe = be.k * U
            payload = np.zeros(len(gnames) * S * stripe,
                               dtype=np.uint8)
            for j, d in enumerate(gdatas):
                payload[j * S * stripe:j * S * stripe + len(d)] = \
                    np.frombuffer(d, dtype=np.uint8)
            geom = ObjectGeom(S * stripe, S, U)
            pg_of = {n: self._pg_for(pool, n) for n in gnames}
            sizes = {n: len(d) for n, d in zip(gnames, gdatas)}
            last: Optional[Exception] = None
            for attempt in range(3):
                writes = be.encode_to_writes(pg_of, gnames, payload,
                                             geom, durable=True,
                                             sizes=sizes)
                try:
                    acked = be.submit(writes)
                    break
                except IOError as e:
                    last = e
                    if attempt == 2:
                        raise
                    self._backoff.sleep(attempt)
                    try:
                        self.refresh_map()
                    except (OSError, IOError):
                        pass
            acked_all.update({n: len(t) for n, t in acked.items()})
        for name, (pg, ss) in snapsets.items():
            self._store_snapset(pool, pg, name, ss)
        return acked_all

    def put_many_from_device(self, pool_id: int, names: List[str],
                             payload,
                             durable: bool = False
                             ) -> Dict[str, Dict[int, int]]:
        """Batched EC ingest of an on-device payload ([N*S, k, W]
        int32 plane words — a TPU producer's output), encoded in ONE
        dispatch.  ``durable=False`` is staged/WAL mode: the ack means
        the client's HBM holds the authoritative shards and
        flush_staged() defers the daemon commit — the BlueStore
        deferred-write contract at client scope (a client crash before
        flush loses the staged writes, exactly like an un-flushed
        writeback cache; use durable=True for commit-on-ack)."""
        pool = self.osdmap.pools[pool_id]
        if pool.type != POOL_ERASURE:
            raise IOError("put_many_from_device requires an EC pool")
        be = self.ec_backend(pool_id)
        if not be.words_supported():
            raise IOError("device put requires the bitsliced jax codec")
        snapsets = {}
        if int(self.pool_snaps.get(pool_id, {}).get("seq", 0) or 0):
            # snapped pool: COW each overwritten head first, exactly
            # like put_many / the sim's put_many_from_device
            for name in names:
                if "@" in name:
                    continue
                pg = self._pg_for(pool, name)
                ss = self._maybe_cow(pool, pg, name)
                if ss is not None:
                    snapsets[name] = (pg, ss)
        from ..cluster.ec_backend import ObjectGeom
        S_total = int(payload.shape[0])
        if S_total % len(names):
            raise IOError("payload stripes not divisible by names")
        S = S_total // len(names)
        W = int(payload.shape[-1])
        geom = ObjectGeom(S * be.k * W * 4, S, W * 4)
        pg_of = {n: self._pg_for(pool, n) for n in names}
        writes = be.encode_to_writes(pg_of, names, payload, geom,
                                     durable=durable)
        acked = be.submit(writes)
        for name, (pg, ss) in snapsets.items():
            self._store_snapset(pool, pg, name, ss)
        return acked

    def flush_staged(self, pool_id: int) -> int:
        """Write every dirty client-staged shard through to its
        daemon (the WAL flush half of put_many_from_device).  A shard
        whose target is unreachable or homeless STAYS dirty — the
        device copy remains authoritative and a later flush (after
        the map re-homes it) retries; returns the count flushed.

        The drain is ONE bulk device->host readback per DISTINCT
        staged buffer (shards are columns of shared encode/stripe
        buffers — materialize_bulk slices them host-side) followed by
        an async scatter-gather sweep: every put_shard frame
        pipelines onto its daemon's stream pool round-robin, ONE
        gather for the whole drain instead of a blocking readback +
        RTT per shard.

        ZeroWire: each flushed shard's per-4KiB sub-crcs are computed
        ONCE — on device (ops/crc32_gf2's GF(2) matmul, when the
        backend makes it worthwhile) or by a single host scan — and
        that one Csums feeds the frame crc, the daemon's trusted blob
        csums AND the staging digest; the shard bytes themselves ride
        as memoryviews (no tobytes() materialization)."""
        from ..common import crcutil
        from ..cluster.device_store import materialize_bulk
        pool = self.osdmap.pools[pool_id]
        by_tgt: Dict[int, List] = {}
        for key, ref in self.dev.dirty_items():
            pid, pg, name, shard = key
            if pid != pool_id:
                continue
            up = self._up(pool, pg)
            tgt = up[shard] if shard < len(up) else ITEM_NONE
            if tgt == ITEM_NONE:
                continue
            by_tgt.setdefault(tgt, []).append((key, ref, pg, name,
                                               shard))
        if not by_tgt:
            return 0
        # bulk readback first: one transfer per distinct buffer
        flat = [it for items in by_tgt.values() for it in items]
        hosts = materialize_bulk([ref for _k, ref, *_r in flat])
        host_of = {}
        csums_of = {}
        i = 0
        for items in by_tgt.values():
            for it in items:
                host_of[it[0]] = hosts[i]
                i += 1
        for key, cs in zip(host_of,
                           _staged_csums(list(host_of.values()))):
            csums_of[key] = cs
        fan: List[Tuple[Any, int, object]] = []
        # round-robin across daemons so every stream pool fills while
        # the others' frames are still queueing
        queues = {t: list(items) for t, items in by_tgt.items()}
        while queues:
            for tgt in list(queues):
                items = queues[tgt]
                if not items:
                    del queues[tgt]
                    continue
                key, ref, pg, name, shard = items.pop(0)
                cs = csums_of[key]
                fan.append((key, cs.combined,
                            self.aio.call_async(tgt, {
                                "cmd": "put_shard",
                                "coll": [pool_id, pg],
                                "oid": f"{shard}:{name}",
                                "data": _as_buf(host_of[key]),
                                "_csums": cs,
                                "attrs": self._staged_attrs.get(
                                    key, {})})))
        flushed = 0
        fatal: Optional[BaseException] = None
        for (key, crc, comp), (_r, err) in zip(
                fan, self.aio.gather([c for _, _, c in fan])):
            if err is not None:
                # not a fabricated default: the entry STAYS DIRTY in
                # the staging tier and the next flush pass retries it
                # — but only connection-class failures are retryable;
                # a daemon rejection surfaces after the sweep settles
                if not isinstance(err, OSError):
                    fatal = err
                continue
            self.dev.mark_clean(key, crc)
            flushed += 1
        if fatal is not None:
            raise fatal
        return flushed

    def get_many_to_device(self, pool_id: int, names: List[str]):
        """Batched EC read returning each object's [S, k, W] device
        words (client staging hits serve zero-copy; misses upload from
        daemon bytes; degraded objects decode through the
        signature-grouped device path).  Healthy same-geometry objects
        assemble in ONE device dispatch (assemble_many)."""
        be = self.ec_backend(pool_id)
        pool = self.osdmap.pools[pool_id]
        if not be.words_supported():
            raise IOError("device get requires the bitsliced jax codec")
        out: List[Optional[object]] = [None] * len(names)
        items, item_idx = [], []
        for idx, name in enumerate(names):
            pg = self._pg_for(pool, name)
            geom = be.read_geom(pg, name)
            if geom is None:
                raise RemoteObjectMissing(f"{name}: no such object")
            if geom.U == 0:          # legacy single-stripe object
                raw = self.get(pool_id, name)
                raw += b"\0" * ((-len(raw)) % (be.k * 4))
                out[idx] = be.to_words(raw, 1, len(raw) // be.k)
                continue
            items.append((pg, name, geom))
            item_idx.append(idx)
        if items:
            for idx, words in zip(item_idx,
                                  be.read_many_words(items)):
                out[idx] = words
        return out

    # ------------------------------------------------------ cls / watch --
    def exec_cls(self, pool_id: int, name: str, cls: str, method: str,
                 inp: bytes = b"") -> bytes:
        """Object-class call ON THE PRIMARY DAEMON (the wire
        CEPH_OSD_OP_CALL): the method executes inside the OSD process
        through the same ClassHandler the sim uses, and replicates to
        the peer replicas (deterministic re-execution)."""
        pool = self.osdmap.pools[pool_id]
        if pool.type == POOL_ERASURE:
            raise IOError("object classes require a replicated pool")
        pg = self._pg_for(pool, name)
        members = [o for o in self._up(pool, pg) if o != ITEM_NONE]
        if not members:
            raise IOError(f"{name}: no primary for cls call")
        return self.osd_call(members[0], {
            "cmd": "exec_cls", "coll": [pool_id, pg],
            "oid": f"0:{name}", "cls": cls, "method": method,
            "payload": inp, "replicas": members})

    def _watch_primary(self, pool_id: int, name: str):
        pool = self.osdmap.pools[pool_id]
        pg = self._pg_for(pool, name)
        members = [o for o in self._up(pool, pg) if o != ITEM_NONE]
        if not members:
            raise IOError(f"{name}: no primary for watch")
        return members[0], pg

    def watch_register(self, pool_id: int, name: str):
        prim, pg = self._watch_primary(pool_id, name)
        r = self.osd_call(prim, {"cmd": "watch_register",
                                 "coll": [pool_id, pg],
                                 "oid": f"0:{name}"})
        return prim, pg, int(r["cookie"])

    def notify(self, pool_id: int, name: str, payload: bytes = b"",
               timeout: float = 3.0) -> Dict:
        """Notify the object's watchers via its primary daemon and
        gather their acks (Watch/Notify over the wire,
        src/osd/Watch.cc): watchers that do not ack within the
        timeout report as None.

        The server-side wait must never outlive the transporting
        socket's timeout: a notify_wait riding the SHARED per-OSD
        client with ``timeout >= socket timeout`` used to time the
        socket out mid-wait — dropping the shared connection under
        every other caller and surfacing an IOError instead of the
        pending-watcher result.  Waits that fit comfortably inside
        the shared timeout use it; longer waits ride a DEDICATED
        connection whose socket timeout is derived from the wait."""
        prim, pg = self._watch_primary(pool_id, name)
        r = self.osd_call(prim, {"cmd": "notify",
                                 "coll": [pool_id, pg],
                                 "oid": f"0:{name}",
                                 "payload": payload})
        if not r["watchers"]:
            return {"notify_id": r["notify_id"], "acks": {}}
        req = {"cmd": "notify_wait", "notify_id": r["notify_id"],
               "timeout": timeout}
        if timeout < self._osd_timeout - 2.0:
            w = self.osd_call(prim, req)
        else:
            dc = self.new_osd_client(prim, timeout=timeout + 5.0)
            try:
                w = dc.call(req)
            finally:
                dc.close()
        acks = {int(c): a for c, a in w["acks"].items()}
        for c in w.get("pending", []):
            acks[int(c)] = None
        return {"notify_id": r["notify_id"], "acks": acks}

    # ---------------------------------------------------------- status --
    def status(self) -> Dict:
        return self.mon_call({"cmd": "status"})

    def mon_status(self) -> Dict:
        return self.mon_call({"cmd": "mon_status"})

    def osd_fsck(self, osd: int) -> List:
        """On-demand store consistency walk on one live OSD over the
        wire (the asok ``store_fsck`` twin for wire-only callers):
        returns the store's error list — [] is clean."""
        return self.osd_call(osd, {"cmd": "fsck"})

    def close(self) -> None:
        if self._aio is not None:
            self._aio.close()       # stream pools + engine workers
            self._aio = None
        for c in self._osd_clients.values():
            c.close()
        if self.mon is not None:
            self.mon.close()
        if self._admin is not None:
            self._admin.close()
            self._admin = None
            self._admin_path = None


class WireShardIO:
    """ShardIO transport over authenticated daemon sockets — the wire
    half of the PGBackend seam (cluster/ec_backend.py).  Sub-writes
    fan out concurrently across OSD connections (each WireClient
    serializes its own socket; distinct targets run in parallel), and
    every shard this client writes or reads is STAGED in its HBM cache
    as plane words, validated against the daemon's stored checksum on
    reuse — the TPU-attached client is the EC primary and serves its
    own data zero-copy (ARCHITECTURE.md §4; the at-rest-layout
    property of src/osd/ECBackend.cc:934,1015)."""

    def __init__(self, rc: "RemoteCluster", pool_id: int):
        self.rc = rc
        self.pool_id = pool_id
        # (pg, shard, name) -> target of this client's last committed
        # sub-write: the stray-supersession sweep only needs to run
        # when the shard's home CHANGED (or on first contact, where a
        # stray from before this client's lifetime could exist) — a
        # repeat commit to the same home overwrote the only copy our
        # previous sweep left, so the O(daemons) purge is skipped on
        # the steady-state write path
        self._committed_to: Dict[Tuple[int, int, str], int] = {}

    def _pool(self) -> PGPool:
        return self.rc.osdmap.pools[self.pool_id]

    def up_set(self, pg: int) -> List[int]:
        return self.rc._up(self._pool(), pg)

    # ---------------------------------------------------------- writes --
    def fanout(self, writes):
        """Sub-write fan-out on the ASYNC core: each durable shard is
        submitted to its target's stream pool as soon as its bytes
        materialize, so the device->host readback of write i+1
        overlaps the wire transmission of write i (the pipelined
        double-buffering the flush path needed), and the gather step
        collects every commit before the verdict."""
        rc = self.rc
        from ..common import crcutil

        sweep: List = []
        results: List = []
        fan: List[Tuple[Any, object, object]] = []
        for w in writes:
            key = (self.pool_id, w.pg, w.name, w.shard)
            data = w.bytes_fn()
            if data is None:
                # staged/WAL mode: the client HBM ref is the
                # authoritative copy until flush_staged() (the
                # BlueStore deferred-write shape; durability contract
                # documented on put_many_from_device)
                rc.dev.put(key, w.ref, None)
                rc._staged_attrs[key] = w.attrs
                results.append(w)
                continue
            # ONE client-side scan per sub-write: the same sub-crcs
            # feed the frame crc (combine, no re-scan in the sender),
            # the daemon's trusted blob csums, and the staging digest
            # below — this fan-out used to scan every byte twice
            # (frame crc + zlib.crc32 digest)
            cs = crcutil.Csums.scan(data, site="client")
            fan.append((w, cs, rc.aio.call_async(w.target, {
                "cmd": "put_shard",
                "coll": [self.pool_id, w.pg],
                "oid": f"{w.shard}:{w.name}",
                "data": data, "_csums": cs, "attrs": w.attrs})))
        fatal: Optional[BaseException] = None
        for (w, cs, comp), (_r, err) in zip(
                fan, rc.aio.gather([c for _, _, c in fan])):
            key = (self.pool_id, w.pg, w.name, w.shard)
            if err is not None:
                if not isinstance(err, OSError):
                    # daemon rejection, not a dead connection: the
                    # caller's resend loop cannot fix it — surface it
                    # after every gathered commit is recorded
                    fatal = err
                # a pre-existing staged entry for this shard is now
                # stale relative to the sibling shards that DID land:
                # drop it, or later reads would mix shard versions
                rc.dev.evict(key)
                rc._staged_attrs.pop(key, None)
                # ...and the same hazard exists SERVER-side: any
                # daemon still holding a previous version of this
                # shard would serve it to the any-holder read
                # fallback, mixing versions into a decode.  Purge,
                # mirroring SimShardIO's "no older shard version is
                # ever servable" invariant (failure path only, so
                # the sweep cost lands on the rare case).
                self.purge_shard(w.pg, w.shard, w.name, None)
                self._committed_to.pop((w.pg, w.shard, w.name), None)
                continue
            rc.dev.put(key, w.ref, cs.combined)
            # success supersedes strays: a RE-HOMED shard's previous
            # copy on its old home must not outlive this commit (the
            # peering-time supersession SimShardIO.fanout applies) —
            # without this, killing the new home resurrects the old
            # version through the any-holder fallback and the reader
            # decodes MIXED shard versions to garbage.  The sweep is
            # DEFERRED and batched below: one bulk delete_shards call
            # per daemon per fanout, and only for shards whose memoed
            # home moved (or first contact) — a repeat commit to the
            # memoized home overwrote the only copy the previous
            # sweep left (steady-state writes skip it entirely).
            if self._committed_to.get(
                    (w.pg, w.shard, w.name)) != w.target:
                sweep.append(w)
            rc._staged_attrs[key] = w.attrs
            results.append(w)
        if sweep:
            self._bulk_supersede(sweep)
        if fatal is not None:
            raise fatal
        return results

    def _bulk_supersede(self, sweep) -> None:
        """Batched stray purge for committed sub-writes: ONE
        delete_shards wire call per up daemon, covering every swept
        shard that daemon could hold — so a put_many batch of N new
        objects pays D daemon RTTs total (in parallel), not N*(k+m)*D.
        First-contact writes DO sweep: the client cannot distinguish
        a genuinely-new object from one re-homed before it connected,
        and put_shard's "existed on target" would be exactly the
        wrong signal (a re-homed shard's new target also reports
        not-existed while the stray sits on the old home) — a
        per-shard version attr is the eventual cheap evidence.
        Only a COMPLETE sweep is memoized per shard — a daemon down
        (or erroring) may still hold a stale copy, so that shard's
        next commit sweeps again.  (The memo is per-client
        best-effort — cross-client races remain the domain of
        recovery/scrub, as before.)"""
        import concurrent.futures as cf
        rc = self.rc
        daemons = list(rc.addrs)

        def purge_on(o):
            items = [[[self.pool_id, w.pg], f"{w.shard}:{w.name}"]
                     for w in sweep if w.target != o]
            if not items:
                return True
            if not rc.osdmap.osd_up[o]:
                return False            # unreachable possible holder
            try:
                rc.osd_call(o, {"cmd": "delete_shards",
                                "items": items})
                return True
            except (OSError, IOError):   # noqa: CTL603 — False =
                # "daemon unreached": the sweep is NOT memoized and
                # re-runs on the next commit (deferred retry, not a
                # fabricated result)
                return False
        if len(daemons) <= 1:
            reached = {o: purge_on(o) for o in daemons}
        else:
            with cf.ThreadPoolExecutor(
                    max_workers=min(8, len(daemons))) as ex:
                reached = dict(zip(daemons,
                                   ex.map(purge_on, daemons)))
        for w in sweep:
            memo_key = (w.pg, w.shard, w.name)
            if all(ok for o, ok in reached.items()
                   if o != w.target):
                self._committed_to[memo_key] = w.target
            else:
                self._committed_to.pop(memo_key, None)
        # unbounded-growth backstop: the memo is an optimization, so
        # wholesale reset just costs extra sweeps, never correctness
        if len(self._committed_to) > (1 << 20):
            self._committed_to.clear()

    def purge_shard(self, pg: int, shard: int, name: str,
                    keep_target) -> None:
        self.rc.dev.evict((self.pool_id, pg, name, shard))
        self._purge_daemons(pg, shard, name, keep_target)

    def _purge_daemons(self, pg: int, shard: int, name: str,
                       keep_target) -> bool:
        """Delete the shard from every daemon except ``keep_target``
        (client staging untouched).  Returns True only when every
        other daemon was REACHED — a daemon that is down or errored
        may still hold a stale copy, and callers memoizing "this
        shard is stray-free" must not record an incomplete sweep
        (the revived daemon would serve its old version forever)."""
        rc = self.rc
        complete = True
        for o in list(rc.addrs):
            if o == keep_target:
                continue
            if not rc.osdmap.osd_up[o]:
                complete = False      # unreachable possible holder
                continue
            try:
                rc.osd_call(o, {"cmd": "delete_shard",
                                "coll": [self.pool_id, pg],
                                "oid": f"{shard}:{name}"})
            except (OSError, IOError):
                complete = False
        return complete

    # ----------------------------------------------------------- reads --
    def _digest(self, pg: int, shard: int, name: str) -> Optional[int]:
        """Stored checksum from any holder; None = every reachable
        daemon ANSWERED and none holds the shard (definitive absence).
        Raises IOError when nobody answered — 'unreachable' must not
        read as 'absent' (a transient outage would otherwise evict
        valid client staging)."""
        up = self.up_set(pg)
        srcs = [up[shard]] if shard < len(up) and \
            up[shard] != ITEM_NONE else []
        srcs += [o for o in self.rc.addrs if o not in srcs]
        unreached = 0
        for o in srcs:
            try:
                d = self.rc.osd_call(o, {
                    "cmd": "digest_shard",
                    "coll": [self.pool_id, pg],
                    "oid": f"{shard}:{name}"})
            except (OSError, IOError):
                unreached += 1
                continue
            if d is not None:
                return int(d)
        if unreached:
            # ANY unreachable daemon could be the sole holder: only a
            # full sweep of answers makes absence definitive (a
            # non-holder's None must not evict a valid staged copy)
            raise IOError(f"{name} shard {shard}: {unreached} "
                          f"daemons unreachable for digest")
        return None

    def get_shard_ref(self, pg: int, shard: int, name: str):
        rc = self.rc
        key = (self.pool_id, pg, name, shard)
        dirty = rc.dev.dirty_get(key)
        if dirty is not None:
            return dirty
        if rc.dev.has(key):
            # the digest RTT only VALIDATES an existing staged entry;
            # an absent key goes straight to the byte fetch
            try:
                digest = self._digest(pg, shard, name)
            except (OSError, IOError):
                digest = False    # unreachable: keep the entry
            if digest is not None and digest is not False:
                arr = rc.dev.get(key, digest)
                if arr is not None:
                    return arr
            elif digest is None:
                # definitive absence on the daemons: the staged copy
                # is an orphan of a deleted/rewritten object
                rc.dev.evict(key)
        data = self.get_shard_bytes(pg, shard, name)
        if data is None or len(data) % 4:
            return None
        import zlib
        import jax.numpy as jnp
        from ..cluster.device_store import as_ref
        ref = as_ref(jnp.asarray(np.frombuffer(data, dtype="<i4")))
        rc.dev.put(key, ref, zlib.crc32(data))
        return ref

    def get_shard_bytes(self, pg: int, shard: int,
                        name: str) -> Optional[bytes]:
        rc = self.rc
        dirty = rc.dev.dirty_get((self.pool_id, pg, name, shard))
        if dirty is not None:
            return np.asarray(dirty).tobytes()
        up = self.up_set(pg)
        srcs = [up[shard]] if shard < len(up) and \
            up[shard] != ITEM_NONE else []
        srcs += [o for o in rc.addrs if o not in srcs]
        for o in srcs:
            try:
                d = rc.osd_call(o, {"cmd": "get_shard",
                                    "coll": [self.pool_id, pg],
                                    "oid": f"{shard}:{name}"})
            except (OSError, IOError):
                continue
            if d is not None:
                return d
        return None

    def getattr(self, pg: int, name: str, shard: int,
                key: str) -> Optional[bytes]:
        rc = self.rc
        akey = (self.pool_id, pg, name, shard)
        if rc.dev.dirty_get(akey) is not None:
            raw = rc._staged_attrs.get(akey, {}).get(key)
            if raw is not None:
                return raw
        up = self.up_set(pg)
        srcs = [up[shard]] if shard < len(up) and \
            up[shard] != ITEM_NONE else []
        srcs += [o for o in rc.addrs if o not in srcs]
        for o in srcs:
            try:
                d = rc.osd_call(o, {"cmd": "getattr_shard",
                                    "coll": [self.pool_id, pg],
                                    "oid": f"{shard}:{name}",
                                    "key": key})
            except (OSError, IOError):
                continue
            if d is not None:
                return d
        return None
