"""RemoteCluster — client for the process cluster (librados-over-wire).

Connects to the mon with the client keyring (cephx secret mode), pulls
the cluster map (crush text recompiled through the CrushCompiler — the
same map the daemons trust), computes placement locally with the real
CRUSH pipeline, obtains per-OSD tickets, and performs object I/O
against the OSD daemons:

  * replicated pools: PUT goes to the PRIMARY, which persists locally
    and fans out to its replicas daemon-to-daemon (the
    ReplicatedBackend shape); GET walks the up set.
  * EC pools: the client is the TPU-attached primary — stripes are
    encoded on device, shards written per OSD; reads gather
    minimum_to_decode shards and decode on device
    (the ECBackend primary role).

Map refreshes on epoch bump; op failures trigger a refresh + retry
(the Objecter resend-on-map-change contract).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from ..common import auth as cx
from ..cluster.daemon import WireClient
from ..cluster.osdmap import OSDMap, PGPool, POOL_ERASURE
from ..ec import instance as ec_registry
from ..ops import hashing
from ..placement.compiler import compile_crushmap
from ..placement.crush_map import ITEM_NONE


class RemoteCluster:
    def __init__(self, cluster_dir: str, entity: str = "client.admin",
                 ec_profiles: Optional[Dict[str, Dict[str, str]]] = None):
        self.dir = cluster_dir
        self.entity = entity
        ring = cx.Keyring.load(os.path.join(cluster_dir,
                                            "keyring.client"))
        self.secret = ring.secret(entity)
        self.mon = WireClient(os.path.join(cluster_dir, "mon.sock"),
                              entity, secret=self.secret)
        self._osd_clients: Dict[int, WireClient] = {}
        self.ec_profiles = ec_profiles or {}
        self._codecs: Dict[int, object] = {}
        self.refresh_map()

    # ---------------------------------------------------------------- map --
    def refresh_map(self) -> None:
        blob = self.mon.call({"cmd": "get_map"})
        cmap = compile_crushmap(blob["crush_text"])
        m = OSDMap(cmap, epoch=blob["epoch"])
        m.mark_all_in_up()
        for i, up in enumerate(blob["osd_up"]):
            m.osd_up[i] = up
        for i, w in enumerate(blob["osd_weight"]):
            m.osd_weight[i] = w
        for p in blob["pools"]:
            m.add_pool(PGPool(**p))
        self.osdmap = m
        self.addrs = {int(k): v for k, v in blob["addrs"].items()}

    def osd_client(self, osd: int) -> WireClient:
        c = self._osd_clients.get(osd)
        if c is not None:
            return c
        grant = self.mon.call({"cmd": "get_ticket",
                               "service": f"osd.{osd}"})
        key = cx.open_key_box(self.secret, grant["key_box"])
        c = WireClient(self.addrs[osd], self.entity,
                       ticket=grant["ticket"], session_key=key,
                       timeout=10.0)
        self._osd_clients[osd] = c
        return c

    def drop_osd_client(self, osd: int) -> None:
        c = self._osd_clients.pop(osd, None)
        if c:
            c.close()

    # ---------------------------------------------------------- placement --
    def _pg_for(self, pool: PGPool, name: str) -> int:
        """object -> pg (the ceph_stable_mod hash pipeline, same as the
        in-process simulator so placements agree)."""
        ps = hashing.str_hash_rjenkins(name.encode())
        return pool.raw_pg_to_pg(ps)

    def _up(self, pool: PGPool, pg: int) -> List[int]:
        up, _, acting, _ = self.osdmap.pg_to_up_acting_osds(pool.id, pg)
        return acting or up

    def codec_for(self, pool: PGPool):
        codec = self._codecs.get(pool.id)
        if codec is None:
            prof = self.ec_profiles.get(pool.erasure_code_profile,
                                        {"plugin": "jax", "k": "4",
                                         "m": "2"})
            plugin = prof.get("plugin", "jax")
            codec = ec_registry().factory(plugin, dict(prof))
            self._codecs[pool.id] = codec
        return codec

    # ----------------------------------------------------------------- IO --
    def put(self, pool_id: int, name: str, data: bytes) -> int:
        """Returns the number of shard/replica writes acknowledged."""
        pool = self.osdmap.pools[pool_id]
        pg = self._pg_for(pool, name)
        up = self._up(pool, pg)
        coll = [pool_id, pg]
        if pool.type != POOL_ERASURE:
            replicas = [o for o in up if o != ITEM_NONE]
            if not replicas:
                raise IOError(f"{name}: no live replica target")
            primary = replicas[0]
            try:
                r = self.osd_client(primary).call({
                    "cmd": "put_object", "coll": coll,
                    "oid": f"0:{name}", "data": data,
                    "replicas": replicas})
                return int(r["acks"])
            except (OSError, IOError):
                self.drop_osd_client(primary)
                raise
        codec = self.codec_for(pool)
        k = codec.get_data_chunk_count()
        n = codec.get_chunk_count()
        chunks = codec.encode(set(range(n)), data)
        acks = 0
        for shard in range(n):
            tgt = up[shard] if shard < len(up) else ITEM_NONE
            if tgt == ITEM_NONE:
                continue
            try:
                self.osd_client(tgt).call({
                    "cmd": "put_shard", "coll": coll,
                    "oid": f"{shard}:{name}",
                    "data": np.asarray(chunks[shard]).tobytes(),
                    # logical object size travels as shard metadata so
                    # ANY client can unpad reads (object_info_t role)
                    "attrs": {"size": str(len(data)).encode()}})
                acks += 1
            except (OSError, IOError):
                self.drop_osd_client(tgt)
        if acks < k:
            raise IOError(f"{name}: only {acks} shards stored (< k={k})")
        return acks

    def get(self, pool_id: int, name: str,
            size: Optional[int] = None) -> bytes:
        pool = self.osdmap.pools[pool_id]
        pg = self._pg_for(pool, name)
        up = self._up(pool, pg)
        coll = [pool_id, pg]
        if pool.type != POOL_ERASURE:
            last_err = None
            for o in [x for x in up if x != ITEM_NONE] + \
                    [x for x in self.addrs if x not in up]:
                try:
                    data = self.osd_client(o).call({
                        "cmd": "get_shard", "coll": coll,
                        "oid": f"0:{name}"})
                except (OSError, IOError) as e:
                    self.drop_osd_client(o)
                    last_err = e
                    continue
                if data is not None:
                    return data
            raise IOError(f"{name}: no replica served ({last_err})")
        codec = self.codec_for(pool)
        k, n = codec.get_data_chunk_count(), codec.get_chunk_count()
        shards: Dict[int, bytes] = {}
        obj_size: Optional[int] = None
        for shard in range(n):
            srcs = [up[shard]] if shard < len(up) and \
                up[shard] != ITEM_NONE else []
            srcs += [o for o in self.addrs if o not in srcs]
            for o in srcs:
                try:
                    d = self.osd_client(o).call({
                        "cmd": "get_shard", "coll": coll,
                        "oid": f"{shard}:{name}"})
                except (OSError, IOError):
                    self.drop_osd_client(o)
                    continue
                if d is not None:
                    shards[shard] = d
                    if obj_size is None:
                        try:
                            sz = self.osd_client(o).call({
                                "cmd": "getattr_shard", "coll": coll,
                                "oid": f"{shard}:{name}",
                                "key": "size"})
                            if sz is not None:
                                obj_size = int(sz)
                        except (OSError, IOError):
                            pass
                    break
        if len(shards) < k:
            raise IOError(f"{name}: only {len(shards)} shards (< k)")
        want = set(range(k))
        plan = sorted(codec.minimum_to_decode(want, set(shards)))
        stack = np.stack([np.frombuffer(shards[c], dtype=np.uint8)
                          for c in plan])
        missing = sorted(want - set(shards))
        if missing:
            dec = np.asarray(codec.decode_chunks(plan, stack, missing))
        data_chunks = []
        for c in range(k):
            if c in shards:
                data_chunks.append(np.frombuffer(shards[c],
                                                 dtype=np.uint8))
            else:
                data_chunks.append(dec[missing.index(c)])
        buf = np.concatenate(data_chunks).tobytes()
        if size is None:
            size = obj_size if obj_size is not None else len(buf)
        return buf[:size]

    # ------------------------------------------------------------ recovery --
    def recover_pool(self, pool_id: int) -> Dict[str, int]:
        """Replicated pools: primary-driven list/pull/push per PG."""
        pool = self.osdmap.pools[pool_id]
        totals = {"objects": 0, "copied": 0}
        for pg in range(pool.pg_num):
            up = self._up(pool, pg)
            members = [o for o in up if o != ITEM_NONE]
            if not members:
                continue
            try:
                r = self.osd_client(members[0]).call({
                    "cmd": "recover_pg", "coll": [pool_id, pg],
                    "members": members})
            except (OSError, IOError):
                self.drop_osd_client(members[0])
                continue
            totals["objects"] += r["objects"]
            totals["copied"] += r["copied"]
        return totals

    def status(self) -> Dict:
        return self.mon.call({"cmd": "status"})

    def close(self) -> None:
        for c in self._osd_clients.values():
            c.close()
        self.mon.close()
