"""RemoteIoCtx — the librados IoCtx surface over the wire client.

The convergence piece the feature tiers needed: RGW, CephFS/MDS, the
Journaler, RadosStriper and librbd all program against the IoCtx
contract (client/rados.py), which previously only the in-process
simulator provided.  This adapter serves the same contract from a
REAL daemon cluster through RemoteCluster's authenticated wire ops —
so the S3 gateway, the filesystem and block images run against OSD
processes with no code changes in those layers (the reference's
gateways link the same librados the external clients use).

Mapping:
  read/write_full/remove/stat/list_objects  → get/put/delete/list
  write(offset)                             → client-side read-modify-
                                              write (full-object ops
                                              are the wire contract,
                                              like the EC client path)
  snap_create/lookup + read(snap=)          → the mon-committed pool
                                              snapshots + COW reads
  watch/notify                              → OVER THE WIRE: the
                                              object's primary daemon
                                              keeps the watcher
                                              registry, watchers poll
                                              + ack on a background
                                              thread — notifies reach
                                              watchers in OTHER
                                              processes too
  exec (object classes)                     → runs inside the primary
                                              daemon via exec_cls
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .rados import ObjectNotFound, ObjectStat
from .remote import RemoteCluster, RemoteObjectMissing


class RemoteIoCtx:
    """IoCtx over one pool of a process cluster.

    Concurrency caveat: `write(offset=...)` (and RadosStriper.write on
    top of it) is a CLIENT-side read-modify-write — full get, splice,
    full put — unlike the sim-tier IoCtx, where the OSD applies the
    offset write server-side.  Two concurrent writers to the same
    object from different processes can lose updates; callers that
    share objects across gateways must serialize per object (the
    module docstring's watch/notify gap makes the same process-local
    assumption).
    """

    def __init__(self, rc: RemoteCluster, pool_name: str):
        self._rc = rc
        pid = next((p.id for p in rc.osdmap.pools.values()
                    if p.name == pool_name or str(p.id) == pool_name),
                   None)
        if pid is None:
            raise KeyError(f"no pool {pool_name!r}")
        self.pool_id = pid
        self._watch_lock = threading.Lock()
        self._watches: Dict[Tuple[str, int], Tuple] = {}
        self._watch_thread: Optional[threading.Thread] = None
        self._watch_stop = threading.Event()
        self._poll_clients: Dict[int, object] = {}
        # watches marked for proactive re-registration: a messenger
        # session RESET (daemon restarted / evicted our session) means
        # session-scoped daemon state is gone — re-register instead of
        # waiting for a poll to come back "gone" after a notify was
        # already missed.  Registered on the shared rc's callback
        # LIST (several ioctxs share one cluster handle) and removed
        # again in close().
        self._rewatch: set = set()

        def _on_reset(osd: int) -> None:
            with self._watch_lock:
                for (oid, cookie), (prim, pg, _cb) in \
                        self._watches.items():
                    if prim == osd:
                        self._rewatch.add((oid, cookie))

        self._on_reset_cb = _on_reset
        rc.add_session_reset_cb(_on_reset)

    # ------------------------------------------------------------- data --
    def write_full(self, oid: str, data: bytes) -> None:
        # no snapshot: put() gathers every sub-write commit before
        # returning, so the caller's buffer is done being read when
        # control comes back (the zero-copy spine carries it as a
        # view all the way to the frames)
        self._rc.put(self.pool_id, oid, data)

    def write(self, oid: str, data: bytes, offset: int = 0) -> None:
        try:
            cur = bytearray(self._rc.get(self.pool_id, oid))
        except RemoteObjectMissing:
            cur = bytearray()
        # any OTHER IOError propagates: treating a transient read
        # failure as "absent" would splice into zeros and ack a write
        # that silently destroyed the rest of the object
        if len(cur) < offset + len(data):
            cur.extend(b"\0" * (offset + len(data) - len(cur)))
        cur[offset:offset + len(data)] = data
        self._rc.put(self.pool_id, oid, bytes(cur))

    def read(self, oid: str, length: Optional[int] = None,
             offset: int = 0, snap: Optional[int] = None) -> bytes:
        try:
            if snap is not None:
                data = self._rc.get_snap(self.pool_id, oid, snap)
            else:
                data = self._rc.get(self.pool_id, oid)
        except RemoteObjectMissing:
            raise ObjectNotFound(oid) from None
        except KeyError:
            raise ObjectNotFound(f"{oid}@{snap}") from None
        if length is None:
            return data[offset:]
        return data[offset:offset + length]

    # -------------------------------------------------------------- aio --
    # Real async submission (librados aio_*): ops ride the cluster
    # handle's async-objecter completion engine under a per-object
    # key, so overlapping ops on one object execute in submission
    # order while distinct objects run concurrently — same engine,
    # same key-space as RemoteCluster.aio_put, so mixing the two
    # surfaces on one object still serializes correctly.
    def _aio_key(self, oid: str):
        return ("obj", self.pool_id, oid)

    def _bind_tenant(self, fn):
        """Capture the SUBMITTING thread's tenant identity into the
        closure: aio ops execute on engine worker threads, where the
        request thread's thread-local tenant binding (set by the S3
        frontend after SigV4 verification) would otherwise be lost."""
        tenant = self._rc.tenant
        if tenant is None:
            return fn

        def run():
            self._rc.set_tenant(tenant, thread_only=True)
            try:
                return fn()
            finally:
                self._rc.set_tenant(None, thread_only=True)
        return run

    def aio_write_full(self, oid: str, data: bytes):
        buf = bytes(data)  # noqa: CTL130 — deliberate snapshot: the
        # op outlives this call and the caller may reuse its buffer
        # (librados aio semantics made safe instead of documented-UB)
        return self._rc.aio.engine.submit(
            self._bind_tenant(lambda: self.write_full(oid, buf)),
            key=self._aio_key(oid))

    def aio_read(self, oid: str, length: Optional[int] = None,
                 offset: int = 0, snap: Optional[int] = None):
        return self._rc.aio.engine.submit(
            self._bind_tenant(
                lambda: self.read(oid, length, offset, snap)),
            key=self._aio_key(oid))

    def aio_remove(self, oid: str):
        return self._rc.aio.engine.submit(
            self._bind_tenant(lambda: self.remove(oid)),
            key=self._aio_key(oid))

    def _shard0_probe(self, oid: str, cmd: str):
        """No-payload probe against the acting set (authoritative
        after peering); non-members are swept only when the acting set
        is degraded or unreachable — a routine ENOENT must not cost
        O(cluster) wire calls."""
        rc = self._rc
        pool = rc.osdmap.pools[self.pool_id]
        pg = rc._pg_for(pool, oid)
        ups = rc._up(pool, pg)
        members = [x for x in ups if x >= 0]
        req = {"cmd": cmd, "coll": [self.pool_id, pg],
               "oid": f"0:{oid}"}
        errors = 0
        answers = 0
        for o in members:
            try:
                r = rc.osd_call(o, req)
            except (OSError, IOError):
                errors += 1
                continue
            answers += 1
            if r is not None:
                return r
        if errors or len(members) < len(ups):
            for o in [x for x in rc.addrs if x not in members]:
                try:
                    r = rc.osd_call(o, req)
                except (OSError, IOError):
                    continue
                answers += 1
                if r is not None:
                    return r
        if answers == 0:
            # nobody ANSWERED: connectivity trouble, not absence —
            # reporting ObjectNotFound here would make layered tiers
            # (bucket index, inodes) treat live data as deleted
            raise IOError(f"{oid}: no OSD reachable for probe")
        return None

    def _exists(self, oid: str) -> bool:
        return self._shard0_probe(oid, "digest_shard") is not None

    def remove(self, oid: str) -> None:
        # the logical namespace is what callers reason about; probe it
        # first so removing a missing object raises like librados
        if not self._exists(oid):
            raise ObjectNotFound(oid)
        self._rc.delete(self.pool_id, oid)

    def stat(self, oid: str) -> ObjectStat:
        pool = self._rc.osdmap.pools[self.pool_id]
        from ..cluster.osdmap import POOL_ERASURE
        if pool.type != POOL_ERASURE:
            # replicated: shard 0 IS the object — size without payload
            st = self._shard0_probe(oid, "stat_shard")
            if st is not None:
                return ObjectStat(size=int(st["size"]), n_stripes=1)
            raise ObjectNotFound(oid)
        # EC: logical size travels as shard metadata (object_info_t) —
        # one no-payload attr probe, never a full decode
        rc = self._rc
        pg = rc._pg_for(pool, oid)
        ups = rc._up(pool, pg)
        answers = 0
        members = [x for x in ups if x >= 0]
        for shard, o in enumerate(ups):
            if o < 0:
                continue
            try:
                sz = rc.osd_call(o, {"cmd": "getattr_shard",
                                     "coll": [self.pool_id, pg],
                                     "oid": f"{shard}:{oid}",
                                     "key": "size"})
            except (OSError, IOError):
                continue
            answers += 1
            if sz is not None:
                # n_stripes matches the write path (full-object = 1),
                # NOT the live shard count — stat must not vary with
                # cluster health
                return ObjectStat(size=int(sz), n_stripes=1)
        if answers == 0:
            raise IOError(f"{oid}: no OSD reachable for stat")
        raise ObjectNotFound(oid)

    def list_objects(self) -> List[str]:
        return self._rc.list_objects(self.pool_id)

    # -------------------------------------------------------- snapshots --
    def snap_create(self, snap_name: str) -> int:
        return self._rc.snap_create(self.pool_id, snap_name)

    def snap_lookup(self, snap_name: str) -> int:
        return self._rc.snap_lookup(self.pool_id, snap_name)

    def snap_rollback_id(self, oid: str, snap_id: int) -> None:
        """Rollback by snap id: restore the object's bytes AT the
        snapshot (client-driven: COW snap read + full-object write);
        KeyError when the object has no state at that snap — matching
        the sim IoCtx contract rbd's roll-back-to-absent path catches."""
        try:
            data = self._rc.get_snap(self.pool_id, oid, snap_id)
        except RemoteObjectMissing:
            raise KeyError(f"{oid}: no state at snap {snap_id}") \
                from None
        self._rc.put(self.pool_id, oid, data)

    # ----------------------------------------------------- watch/notify --
    # Watch/notify rides the WIRE (VERDICT r4 weak #7: no longer a
    # process-local registry): the object's primary DAEMON keeps the
    # watcher registry; this client polls its pending-notification
    # queue on a background thread, invokes callbacks, and acks.
    # Watchers in OTHER processes (a second gateway) see the same
    # notifies — the src/osd/Watch.cc shape on a poll transport.

    def watch(self, oid: str, callback) -> int:
        prim, pg, cookie = self._rc.watch_register(self.pool_id, oid)
        with self._watch_lock:
            self._watches[(oid, cookie)] = (prim, pg, callback)
            stopping = self._watch_stop.is_set()
            t = self._watch_thread
        if stopping and t is not None:
            # an unwatch-of-last just told the old poller to exit; it
            # may not have noticed yet.  Join it OUTSIDE the lock (it
            # takes the lock each loop) before re-arming, or the new
            # watch could be left with a stop-flagged poller that
            # exits immediately — silently unpolled
            t.join(timeout=10)
        with self._watch_lock:
            if self._watch_thread is None or \
                    not self._watch_thread.is_alive():
                self._watch_stop.clear()
                self._watch_thread = threading.Thread(
                    target=self._watch_poller, daemon=True,
                    name="ioctx-watch-poll")
                self._watch_thread.start()
        return cookie

    def unwatch(self, oid: str, watch_id: int) -> None:
        with self._watch_lock:
            ent = self._watches.pop((oid, watch_id), None)
            if not self._watches:
                # last watch gone: the poller exits instead of
                # spinning (and RE-arms on the next watch())
                self._watch_stop.set()
        if ent is not None:
            prim, pg, _ = ent
            try:
                self._rc.osd_call(prim, {
                    "cmd": "watch_unregister",
                    "coll": [self.pool_id, pg], "oid": f"0:{oid}",
                    "cookie": watch_id})
            except (OSError, IOError):
                pass          # daemon gone: the watch died with it

    def close(self) -> None:
        """Stop the watch poller and release its connections (the
        ioctx destructor role).  The wire unregisters run OUTSIDE
        _watch_lock: osd_call can reconnect and run the session-reset
        hooks, and this ioctx's own hook takes _watch_lock — holding
        it across the call would self-deadlock."""
        with self._watch_lock:
            watches = dict(self._watches)
            self._watches.clear()
            self._watch_stop.set()
        self._rc.remove_session_reset_cb(self._on_reset_cb)
        for (oid, cookie), (prim, pg, _) in watches.items():
            try:
                self._rc.osd_call(prim, {
                    "cmd": "watch_unregister",
                    "coll": [self.pool_id, pg],
                    "oid": f"0:{oid}", "cookie": cookie})
            except (OSError, IOError):
                pass

    def _poll_call(self, prim: int, req: dict):
        """Poller-owned wire call on a DEDICATED connection: the main
        thread's notify_wait holds the shared per-OSD connection lock
        for its whole wait, so acks must travel on their own socket."""
        c = self._poll_clients.get(prim)
        if c is None:
            c = self._poll_clients[prim] = \
                self._rc.new_osd_client(prim)
        try:
            return c.call(req)
        except (OSError, IOError):
            self._poll_clients.pop(prim, None)
            try:
                c.close()
            except OSError:
                pass
            raise

    def _reregister(self, oid: str, cookie: int, cb) -> None:
        """Re-establish one watch under a fresh cookie, refreshing the
        map first if placement moved (after a restart/heal the object
        may have a NEW primary — re-registering on the cached one
        would silently watch nothing)."""
        for attempt in range(2):
            try:
                np_, npg = self._rc._watch_primary(self.pool_id, oid)
                nc = int(self._poll_call(np_, {
                    "cmd": "watch_register",
                    "coll": [self.pool_id, npg],
                    "oid": f"0:{oid}"})["cookie"])
            except (OSError, IOError):
                if attempt:
                    return            # next poll tick retries
                try:
                    self._rc.refresh_map()
                except (OSError, IOError):  # noqa: CTL603 — the
                    # poller tick IS the retry loop: giving up here
                    # re-enters on the next poll interval
                    return
                continue
            with self._watch_lock:
                if (oid, cookie) in self._watches:
                    del self._watches[(oid, cookie)]
                    self._watches[(oid, nc)] = (np_, npg, cb)
                    return
            # the watch was unwatched/closed while we re-registered:
            # release the fresh cookie, or the daemon holds a watcher
            # nobody polls and every notify blocks to its timeout
            try:
                self._poll_call(np_, {
                    "cmd": "watch_unregister",
                    "coll": [self.pool_id, npg],
                    "oid": f"0:{oid}", "cookie": nc})
            except (OSError, IOError):
                pass
            return

    def _watch_poller(self, interval: float = 0.05) -> None:
        while not self._watch_stop.is_set():
            with self._watch_lock:
                watches = dict(self._watches)
            if not watches:
                time.sleep(interval)
                continue
            for (oid, cookie), (prim, pg, cb) in watches.items():
                if (oid, cookie) in self._rewatch:
                    # session reset detected on reconnect: daemon-side
                    # watch state is session-scoped and gone — do not
                    # wait for a missed notify to find out
                    self._rewatch.discard((oid, cookie))
                    self._reregister(oid, cookie, cb)
                    continue
                try:
                    r = self._poll_call(prim, {
                        "cmd": "watch_poll",
                        "coll": [self.pool_id, pg],
                        "oid": f"0:{oid}", "cookie": cookie})
                except (OSError, IOError):
                    continue          # primary down: retry next tick
                if r.get("gone"):
                    # daemon restarted and lost the registry:
                    # re-register under a fresh cookie (on the
                    # poller's own connection)
                    self._reregister(oid, cookie, cb)
                    continue
                for nid, payload in r.get("events", []):
                    try:
                        ack = cb(nid, bytes(payload))
                    except Exception:
                        continue      # no ack: notifier times out
                    try:
                        self._poll_call(prim, {
                            "cmd": "notify_ack", "notify_id": nid,
                            "cookie": cookie, "ack": ack})
                    except (OSError, IOError):
                        pass
            time.sleep(interval)
        for c in self._poll_clients.values():
            try:
                c.close()
            except OSError:
                pass
        self._poll_clients.clear()

    def notify(self, oid: str, payload: bytes = b"",
               timeout: float = 3.0) -> dict:
        r = self._rc.notify(self.pool_id, oid, payload,
                            timeout=timeout)
        return {"notify_id": r["notify_id"], "acks": r["acks"]}

    # --------------------------------------------------------- cls exec --
    def exec(self, oid: str, cls: str, method: str,
             inp: bytes = b"") -> bytes:
        """librados exec: run an object-class method inside the
        object's primary OSD daemon."""
        return self._rc.exec_cls(self.pool_id, oid, cls, method, inp)


def open_remote_ioctx(cluster_dir: str, pool_name: str,
                      rc: Optional[RemoteCluster] = None
                      ) -> RemoteIoCtx:
    """Convenience: connect (or reuse) a RemoteCluster and open one
    pool's IoCtx — the Rados.open_ioctx shape for the process tier."""
    return RemoteIoCtx(rc or RemoteCluster(cluster_dir), pool_name)
