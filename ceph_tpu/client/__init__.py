from .rados import IoCtx, Rados, ObjectNotFound
from .remote import RemoteCluster, RemoteObjectMissing
from .remote_ioctx import RemoteIoCtx, open_remote_ioctx
from .striper import RadosStriper

__all__ = ["IoCtx", "Rados", "ObjectNotFound", "RemoteCluster",
           "RemoteObjectMissing", "RemoteIoCtx", "open_remote_ioctx",
           "RadosStriper"]
