from .rados import IoCtx, Rados, ObjectNotFound

__all__ = ["IoCtx", "Rados", "ObjectNotFound"]
