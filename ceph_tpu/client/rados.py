"""librados-style client API — the L9 surface.

Role of src/librados/ (the `rados_*` C API / C++ `Rados`/`IoCtx`
classes every client program uses) and the async AIO surface: a
cluster handle that connects to the mon, per-pool I/O contexts doing
object read/write/remove/stat/list, and futures-based AIO, all routed
through the Objecter (cached map + resend) so clients behave correctly
across map changes.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..cluster.monitor import Monitor
from ..cluster.objecter import Objecter
from ..cluster.simulator import ClusterSim


class ObjectNotFound(KeyError):
    pass


@dataclass
class ObjectStat:
    size: int
    n_stripes: int


class Rados:
    """Cluster handle (librados `rados_t`): connect() attaches to the
    mon + cluster, then open_ioctx() per pool.

    AIO rides the async objecter's completion engine
    (cluster/async_objecter.py AioEngine), not a flat thread pool:
    ops to the SAME object execute strictly in submission order (the
    librados per-object write-ordering contract two overlapping
    ``aio_write_full`` calls rely on) while distinct objects run
    concurrently, and every verb returns an ``AioCompletion`` wearing
    the librados waiting verbs (is_complete / wait_for_complete /
    get_return_value / set_complete_callback)."""

    def __init__(self, sim: ClusterSim, mon: Monitor):
        self._sim = sim
        self._mon = mon
        self._objecter: Optional[Objecter] = None
        self._aio = None                  # lazy AioEngine
        self._aio_lock = threading.Lock()

    def connect(self) -> "Rados":
        self._objecter = Objecter(self._sim, self._mon)
        return self

    @property
    def connected(self) -> bool:
        return self._objecter is not None

    def open_ioctx(self, pool_name: str) -> "IoCtx":
        if not self.connected:
            raise RuntimeError("connect() first")
        for pid, pool in self._sim.osdmap.pools.items():
            if pool.name == pool_name or str(pid) == pool_name:
                return IoCtx(self, pid)
        raise KeyError(f"no pool {pool_name!r}")

    def pool_list(self) -> List[str]:
        return [p.name or str(pid)
                for pid, p in sorted(self._sim.osdmap.pools.items())]

    def cluster_stat(self) -> Dict[str, int]:
        objs = len(self._sim.objects)
        bytes_ = sum(i.size for i in self._sim.objects.values())
        return {"num_objects": objs, "kb": bytes_ // 1024,
                "num_osds": self._sim.osdmap.max_osd,
                "epoch": self._sim.osdmap.epoch}

    def health(self) -> str:
        return self._mon.health_status(self._sim)

    @property
    def aio_engine(self):
        """The completion engine behind the aio verbs — built lazily
        so a handle that never submits async work starts no threads."""
        if self._aio is None:
            with self._aio_lock:
                if self._aio is None:
                    from ..cluster.async_objecter import AioEngine
                    self._aio = AioEngine(workers=4, name="rados-aio")
        return self._aio

    def shutdown(self) -> None:
        if self._aio is not None:
            self._aio.close()
            self._aio = None
        self._objecter = None


class IoCtx:
    """Per-pool I/O context (librados `rados_ioctx_t`)."""

    def __init__(self, rados: Rados, pool_id: int):
        self._rados = rados
        self.pool_id = pool_id

    # ------------------------------------------------------------- sync --
    def write_full(self, oid: str, data: bytes) -> None:
        self._rados._objecter.put(self.pool_id, oid, data)

    def write(self, oid: str, data: bytes, offset: int = 0) -> None:
        self._rados._objecter.write(self.pool_id, oid, offset, data)

    def read(self, oid: str, length: Optional[int] = None,
             offset: int = 0, snap: Optional[int] = None) -> bytes:
        """``snap``: read the object's state AT that pool snapshot
        (librados snap_read context role)."""
        sim = self._rados._sim
        if snap is not None:
            try:
                data = sim.get_snap(self.pool_id, oid, snap)
            except KeyError:
                raise ObjectNotFound(f"{oid}@{snap}") from None
        else:
            if (self.pool_id, oid) not in sim.objects:
                raise ObjectNotFound(oid)
            data = self._rados._objecter.get(self.pool_id, oid)
        if length is None:
            return data[offset:]
        return data[offset:offset + length]

    # ------------------------------------------------------- snapshots --
    def snap_create(self, snap_name: str) -> int:
        return self._rados._sim.snap_create(self.pool_id, snap_name)

    def snap_lookup(self, snap_name: str) -> int:
        return self._rados._sim.snap_lookup(self.pool_id, snap_name)

    def snap_remove(self, snap_name: str) -> int:
        sid = self.snap_lookup(snap_name)
        return self._rados._sim.snap_remove(self.pool_id, sid)

    def snap_rollback(self, oid: str, snap_name: str) -> None:
        sid = self.snap_lookup(snap_name)
        self._rados._sim.snap_rollback(self.pool_id, oid, sid)

    def snap_rollback_id(self, oid: str, snap_id: int) -> None:
        """Rollback by snap ID (selfmanaged-snap rollback role —
        librbd tracks ids, not pool snap names); KeyError when the
        object has no state at that snap."""
        self._rados._sim.snap_rollback(self.pool_id, oid, snap_id)

    # ------------------------------------------------------------ exec --
    def exec(self, oid: str, cls: str, method: str,
             data: bytes = b"") -> bytes:
        """Run an in-OSD object-class method (rados_exec role)."""
        return self._rados._sim.exec_cls(self.pool_id, oid, cls,
                                         method, data)

    # ----------------------------------------------------- watch/notify --
    def watch(self, oid: str, callback) -> int:
        return self._rados._sim.watch(self.pool_id, oid, callback)

    def unwatch(self, oid: str, watch_id: int) -> None:
        self._rados._sim.unwatch(self.pool_id, oid, watch_id)

    def notify(self, oid: str, payload: bytes = b"") -> dict:
        return self._rados._sim.notify(self.pool_id, oid, payload)

    def remove(self, oid: str) -> None:
        sim = self._rados._sim
        if (self.pool_id, oid) not in sim.objects:
            raise ObjectNotFound(oid)
        sim.delete(self.pool_id, oid)

    def stat(self, oid: str) -> ObjectStat:
        info = self._rados._sim.objects.get((self.pool_id, oid))
        if info is None:
            raise ObjectNotFound(oid)
        return ObjectStat(size=info.size, n_stripes=info.n_stripes)

    def list_objects(self) -> List[str]:
        return sorted(name for (pid, name) in self._rados._sim.objects
                      if pid == self.pool_id)

    # -------------------------------------------------------------- aio --
    # Async submission through the completion engine: same-object ops
    # serialize in submission order (overlapping aio_write_full to one
    # object commit in order; a read submitted after a write observes
    # it), distinct objects run concurrently across the workers.
    def _aio_key(self, oid: str):
        return ("obj", self.pool_id, oid)

    def aio_write_full(self, oid: str, data: bytes):
        return self._rados.aio_engine.submit(
            lambda: self.write_full(oid, data),
            key=self._aio_key(oid))

    def aio_read(self, oid: str, length: Optional[int] = None,
                 offset: int = 0, snap: Optional[int] = None):
        return self._rados.aio_engine.submit(
            lambda: self.read(oid, length, offset, snap),
            key=self._aio_key(oid))

    def aio_remove(self, oid: str):
        return self._rados.aio_engine.submit(
            lambda: self.remove(oid), key=self._aio_key(oid))
