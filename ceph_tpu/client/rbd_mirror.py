"""rbd-mirror slice — journal-based image replication.

The src/journal/ consumer role (rbd-mirror daemon + librbd journaling
feature): a PRIMARY image with journaling enabled records every
mutation into an image journal BEFORE applying it; a replayer on the
peer side consumes the journal from its committed position and applies
the entries to the secondary image, which converges to a
point-in-time-consistent copy.  Positions are tracked per peer (the
journal client registration role), so replay is incremental and
restart-safe.

    prim = JournaledImage(ioctx_a, "vol")      # journaling feature on
    prim.write(0, b"...")                      # journal-first
    rep = MirrorReplayer(ioctx_a, ioctx_b, "vol", peer="site-b")
    rep.replay()                               # secondary catches up

Entries are JSON (data base64) in ceph_tpu.fs.Journaler objects named
``rbd_journal.<image>`` in the PRIMARY's pool.
"""
from __future__ import annotations

import base64
import json
from typing import Optional

from ..fs.journaler import Journaler
from .rbd import RBD, Image, ImageNotFound


class JournaledImage(Image):
    """Image with the journaling feature: mutations are recorded to
    the image journal before they land (librbd journal-first order,
    the basis of crash-consistent mirroring)."""

    def __init__(self, ioctx, name: str):
        super().__init__(ioctx, name)
        self.journal = Journaler(ioctx, f"rbd_journal.{name}")

    def write(self, offset: int, data: bytes) -> int:
        self.journal.append(json.dumps({
            "op": "write", "offset": offset,
            "data": base64.b64encode(data).decode()}).encode())
        return super().write(offset, data)

    def resize(self, new_size: int) -> None:
        self.journal.append(json.dumps({
            "op": "resize", "size": new_size}).encode())
        super().resize(new_size)

    def snap_create(self, snap_name: str) -> int:
        sid = super().snap_create(snap_name)
        self.journal.append(json.dumps({
            "op": "snap_create", "name": snap_name}).encode())
        return sid


class MirrorReplayer:
    """Peer-side journal replayer (rbd-mirror ImageReplayer role)."""

    def __init__(self, src_ioctx, dst_ioctx, image: str,
                 peer: str = "peer"):
        self.src = src_ioctx
        self.dst = dst_ioctx
        self.image = image
        self.peer = peer
        self.journal = Journaler(src_ioctx, f"rbd_journal.{image}")

    # ------------------------------------------------------- positions --
    def _pos_oid(self) -> str:
        return f"rbd_mirror.{self.image}.{self.peer}"

    def committed_position(self) -> int:
        try:
            return int(self.src.read(self._pos_oid()).decode())
        except (KeyError, ValueError):
            # genuinely absent (fresh peer) or corrupt marker: replay
            # from the start.  A TRANSIENT read error now propagates —
            # treating it as "no position" forced a full re-sync and
            # re-applied every logged delete (the _read_index bug
            # class, CTL603)
            return -1

    def _commit(self, seq: int) -> None:
        self.src.write_full(self._pos_oid(), str(seq).encode())

    # ----------------------------------------------------------- replay --
    def _open_or_create_secondary(self) -> Image:
        try:
            return Image(self.dst, self.image)
        except ImageNotFound:
            src_img = Image(self.src, self.image)
            RBD(self.dst).create(self.image, size=src_img.size(),
                                 order=src_img.info.order)
            return Image(self.dst, self.image)

    def replay(self) -> int:
        """Apply journal entries past the committed position to the
        secondary; returns entries applied.  Idempotent/incremental."""
        img = self._open_or_create_secondary()
        pos = self.committed_position()
        applied = 0
        for seq, payload in self.journal.replay():
            if seq <= pos:
                continue
            ent = json.loads(payload.decode())
            op = ent["op"]
            if op == "write":
                data = base64.b64decode(ent["data"])
                end = ent["offset"] + len(data)
                if end > img.size():
                    img.resize(end)
                img.write(ent["offset"], data)
            elif op == "resize":
                img.resize(ent["size"])
            elif op == "snap_create":
                if ent["name"] not in img.snaps:
                    img.snap_create(ent["name"])
            self._commit(seq)
            pos = seq
            applied += 1
        return applied

    def trim_committed(self) -> int:
        """Expire journal objects every peer has consumed (journal
        trim-to-minimum-commit role; single-peer form)."""
        pos = self.committed_position()
        return self.journal.trim_to(pos + 1) if pos >= 0 else 0
