"""RadosStriper — striped single-object API over librados.

The libradosstriper role (src/libradosstriper/RadosStriperImpl.cc): a
logical "striped object" whose bytes are spread round-robin across
many RADOS objects by the file layout, presented through a plain
write/read/stat/truncate/remove surface.  The reference stores the
striper geometry and logical size as xattrs of the first stripe
object (striper.layout.*, striper.size) so any client can reopen the
striped object without out-of-band metadata; this librados slice
exposes object data (not raw xattrs), so the same role is played by a
sidecar metadata object ("<soid>.striper") holding size + layout.

Re-uses cluster/striper.py's extent math (the Striper::file_to_extents
role shared with RBD and the MDS file layout).
"""
from __future__ import annotations

import json
from typing import Optional, Set

from ..cluster.striper import FileLayout, file_to_extents


class StripedObjectError(IOError):
    pass


class RadosStriper:
    """Striped-object facade over one IoCtx."""

    def __init__(self, ioctx, layout: Optional[FileLayout] = None):
        self.ioctx = ioctx
        self.layout = layout or FileLayout(
            stripe_unit=1 << 16, stripe_count=4, object_size=1 << 18)

    # ----------------------------------------------------------- layout --
    def _oid(self, soid: str, objno: int) -> str:
        return f"{soid}.{objno:016x}"

    def _meta_oid(self, soid: str) -> str:
        return f"{soid}.striper"

    def _meta(self, soid: str) -> dict:
        # Only absence (ObjectNotFound is a KeyError) means "no striped
        # object"; a transient IOError must propagate, or exists() would
        # answer False and write() would silently reinitialize an
        # existing object's geometry.
        try:
            raw = bytes(self.ioctx.read(self._meta_oid(soid)))
        except KeyError:
            raise StripedObjectError(
                f"no striped object {soid!r}") from None
        return json.loads(raw.decode())

    def _read_size(self, soid: str) -> int:
        return self._meta(soid)["size"]

    def _write_meta(self, soid: str, size: int) -> None:
        lay = self.layout
        self.ioctx.write_full(self._meta_oid(soid), json.dumps(
            {"size": size, "stripe_unit": lay.stripe_unit,
             "stripe_count": lay.stripe_count,
             "object_size": lay.object_size}).encode())

    def open_layout(self, soid: str) -> FileLayout:
        """Recover the geometry a striped object was written with."""
        m = self._meta(soid)
        return FileLayout(m["stripe_unit"], m["stripe_count"],
                          m["object_size"])

    # -------------------------------------------------------------- api --
    def exists(self, soid: str) -> bool:
        try:
            self._read_size(soid)
            return True
        except StripedObjectError:
            return False

    def write(self, soid: str, data: bytes, offset: int = 0) -> int:
        if self.exists(soid):
            self.layout = self.open_layout(soid)
            size = self._read_size(soid)
        else:
            size = 0
        for objno, ooff, olen, pos in self._extents(offset, len(data)):
            oid = self._oid(soid, objno)
            try:
                cur = bytearray(self.ioctx.read(oid))
            except KeyError:        # absent stripe object: fresh write
                cur = bytearray()
            if len(cur) < ooff + olen:
                cur.extend(b"\0" * (ooff + olen - len(cur)))
            cur[ooff:ooff + olen] = data[pos:pos + olen]
            self.ioctx.write_full(oid, bytes(cur))
        self._write_meta(soid, max(size, offset + len(data)))
        return len(data)

    def _extents(self, offset: int, length: int):
        pos = 0
        for objno, ooff, olen in file_to_extents(self.layout, offset,
                                                 length):
            yield objno, ooff, olen, pos
            pos += olen

    def read(self, soid: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        size = self._read_size(soid)
        self.layout = self.open_layout(soid)
        if length is None:
            length = max(0, size - offset)
        length = min(length, max(0, size - offset))
        out = bytearray(length)
        for objno, ooff, olen, pos in self._extents(offset, length):
            try:
                piece = self.ioctx.read(self._oid(soid, objno))
            except KeyError:
                piece = b""       # absent object = sparse hole; an
            #                       IOError propagates (not zeros)
            chunk = bytes(piece)[ooff:ooff + olen]
            out[pos:pos + len(chunk)] = chunk
        return bytes(out)

    def stat(self, soid: str) -> dict:
        size = self._read_size(soid)
        lay = self.open_layout(soid)
        return {"size": size, "stripe_unit": lay.stripe_unit,
                "stripe_count": lay.stripe_count,
                "object_size": lay.object_size}

    def _objnos(self, size: int) -> Set[int]:
        """Stripe objects a `size`-byte object can touch.  NOT simply
        ceil(size/object_size): round-robin striping spreads early
        bytes across a whole object SET, so small sizes still touch
        stripe_count objects."""
        return {objno for objno, _, _ in
                file_to_extents(self.layout, 0, size)}

    def _obj_valid_len(self, size: int, objno: int) -> int:
        """Bytes of stripe object `objno` that lie below `size`."""
        valid = 0
        for off_objno, ooff, olen in file_to_extents(
                self.layout, 0, size):
            if off_objno == objno:
                valid = max(valid, ooff + olen)
        return min(valid, self.layout.object_size)

    def truncate(self, soid: str, size: int) -> None:
        cur = self._read_size(soid)
        self.layout = self.open_layout(soid)
        if size < cur:
            keep = self._objnos(size)
            for objno in self._objnos(cur) - keep:
                try:
                    self.ioctx.remove(self._oid(soid, objno))
                except KeyError:
                    pass
            # clip every surviving object so a regrow reads zeros
            for objno in keep:
                blen = self._obj_valid_len(size, objno)
                oid = self._oid(soid, objno)
                try:
                    data = bytes(self.ioctx.read(oid))
                except KeyError:
                    continue
                if len(data) > blen:
                    self.ioctx.write_full(oid, data[:blen])
        self._write_meta(soid, size)

    def remove(self, soid: str) -> None:
        size = self._read_size(soid)
        self.layout = self.open_layout(soid)
        for objno in self._objnos(size):
            try:
                self.ioctx.remove(self._oid(soid, objno))
            except KeyError:
                pass
        self.ioctx.remove(self._meta_oid(soid))
