"""neorados — the asio-native async RADOS client surface.

The reference rewrote librados around asio completions (src/neorados/:
`RADOS::execute` returning awaitable operations instead of blocking
calls).  The Python-native analog is asyncio: every I/O verb returns
an awaitable and fan-out happens with `asyncio.gather`.  Data verbs
ride REAL async submission — the underlying ioctx's ``aio_*``
completions (the async objecter's engine, per-object ordered) wrapped
via ``asyncio.wrap_future`` — so an `await io.write_full(...)` is the
same submit→complete machinery the wire core runs, not a thread
parked on a blocking call.  Verbs with no aio counterpart (snap DDL,
listings) fall back to a small executor.

    async with AsyncRados(rados) as ar:
        io = await ar.open_ioctx("rep")
        await io.write_full("a", b"1")
        datas = await asyncio.gather(*[io.read(f"o{i}")
                                       for i in range(32)])

Works over BOTH tiers: an in-process `Rados` ioctx or a process
cluster's `RemoteIoCtx` (pass the opened ioctx to ``AsyncIoCtx``
directly).
"""
from __future__ import annotations

import asyncio
from concurrent.futures import ThreadPoolExecutor
from typing import Optional


class AsyncIoCtx:
    """Awaitable facade over any object implementing the IoCtx
    contract (client/rados.py IoCtx or client/remote_ioctx.py
    RemoteIoCtx)."""

    def __init__(self, ioctx, executor: Optional[ThreadPoolExecutor] = None):
        self._io = ioctx
        # only a pool we CREATED may be shut down by close(): a shared
        # executor (AsyncRados hands out its own) outlives any one ioctx
        self._own_pool = executor is None
        self._pool = executor or ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="neorados")

    def _run(self, fn, *args, **kw):
        loop = asyncio.get_running_loop()
        return loop.run_in_executor(self._pool,
                                    lambda: fn(*args, **kw))

    def _aio(self, verb: str, fallback, *args):
        """Prefer the ioctx's real async submission (an AioCompletion
        IS a concurrent.futures.Future, so wrap_future turns it into
        an awaitable with no thread parked on it); executor fallback
        keeps foreign IoCtx implementations working."""
        fn = getattr(self._io, verb, None)
        if fn is not None:
            return asyncio.wrap_future(fn(*args))
        return self._run(fallback, *args)

    # ------------------------------------------------------------- verbs --
    def write_full(self, oid: str, data: bytes):
        return self._aio("aio_write_full", self._io.write_full,
                         oid, data)

    def write(self, oid: str, data: bytes, offset: int = 0):
        return self._run(self._io.write, oid, data, offset)

    def read(self, oid: str, length: Optional[int] = None,
             offset: int = 0, snap: Optional[int] = None):
        return self._aio("aio_read", self._io.read,
                         oid, length, offset, snap)

    def remove(self, oid: str):
        return self._aio("aio_remove", self._io.remove, oid)

    def stat(self, oid: str):
        return self._run(self._io.stat, oid)

    def list_objects(self):
        return self._run(self._io.list_objects)

    def snap_create(self, snap_name: str):
        return self._run(self._io.snap_create, snap_name)

    def close(self) -> None:
        if self._own_pool:
            self._pool.shutdown(wait=False)


class AsyncRados:
    """Async cluster handle (neorados::RADOS role) over a connected
    sync Rados or RemoteCluster."""

    def __init__(self, rados):
        self._rados = rados
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix="neorados")

    async def open_ioctx(self, pool_name: str) -> AsyncIoCtx:
        loop = asyncio.get_running_loop()
        if hasattr(self._rados, "open_ioctx"):
            io = await loop.run_in_executor(
                self._pool, self._rados.open_ioctx, pool_name)
        else:
            # RemoteCluster: wrap the wire tier's IoCtx adapter
            from .remote_ioctx import RemoteIoCtx
            io = await loop.run_in_executor(
                self._pool, RemoteIoCtx, self._rados, pool_name)
        return AsyncIoCtx(io, executor=self._pool)

    async def __aenter__(self) -> "AsyncRados":
        return self

    async def __aexit__(self, *exc) -> None:
        self._pool.shutdown(wait=False)
