"""File-system layer: journaler + metadata server slice (src/journal/
+ src/mds/ roles)."""
from .journaler import Journaler  # noqa: F401
from .mds import MDS, CephFSClient, FSError  # noqa: F401
