"""File-system layer: journaler + metadata server slice (src/journal/
+ src/mds/ roles)."""
from .journaler import Journaler  # noqa: F401
from .mds import MDS, CephFSClient, ForwardError, FSError  # noqa: F401
from .mdsmap import MDSMap  # noqa: F401
from .multimds import MDBalancer, MDSCluster  # noqa: F401
