"""MDS slice — journaled POSIX-ish metadata over RADOS objects.

The thin metadata-service slice VERDICT r2 asked for (missing #8): the
src/mds/ roles reduced to their core shape rather than the 89k-LoC
cache machinery:

  * directory tree as dirfrag objects in a metadata pool — one object
    per directory inode holding its dentries (the CDir/dirfrag store,
    src/mds/CDir.cc commit format's role);
  * EVERY metadata mutation journaled through the Journaler BEFORE the
    dirfrag objects update (the MDLog write-ahead contract,
    src/mds/MDLog.cc): an MDS that crashes mid-operation replays the
    journal on startup and converges to the journaled state;
  * inode numbers from a journal-recovered allocator (InoTable role);
  * file DATA striped into a data pool via the file layout
    (src/osdc/Striper + fs_types file_layout_t), like CephFS clients
    write directly to RADOS.

``CephFSClient`` is the path-based facade (libcephfs surface subset:
mkdir/create/write/read/unlink/rmdir/rename/listdir/stat).
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Tuple

from ..cluster.striper import FileLayout, file_to_extents
from .journaler import Journaler

ROOT_INO = 1


class FSError(IOError):
    pass


class ForwardError(FSError):
    """This MDS is not authoritative for the path — retry against
    `rank` (the reference forwards the request between MDSs instead,
    MDSRank::forward; here the client/facade re-routes)."""

    def __init__(self, rank: int, path: str):
        super().__init__(f"not authoritative for {path}: rank {rank}")
        self.rank = rank
        self.path = path


class MDS:
    """Metadata server over (metadata ioctx, data ioctx).

    Single-MDS by default.  In a multi-rank cluster (fs/multimds.py)
    each rank gets its own journal ("mdlog.<rank>") and an MDSMap;
    mutations, capabilities and locks are only served for paths whose
    subtree this rank owns — others raise ForwardError (the request-
    forwarding role).  Plain reads (stat/listdir/read_file) are served
    by any rank: dirfrags are shared RADOS objects, and coherence is
    enforced where it matters, at the cap/lock/mutation layer.
    """

    def __init__(self, meta_ioctx, data_ioctx,
                 layout: Optional[FileLayout] = None,
                 rank: Optional[int] = None, mdsmap=None):
        self.meta = meta_ioctx
        self.data = data_ioctx
        self.rank = rank
        self.mdsmap = mdsmap
        self.layout = layout or FileLayout(
            stripe_unit=1 << 16, stripe_count=1, object_size=1 << 16)
        jname = "mdlog" if rank is None else f"mdlog.{rank}"
        self.journal = Journaler(meta_ioctx, jname)
        # ino allocator recovers from the durable InoTable object, not
        # only the (possibly trimmed) journal window (InoTable role)
        self._next_ino = ROOT_INO + 1
        try:
            self._next_ino = max(
                self._next_ino,
                int(self.meta.read("mds_inotable").decode()))
        except Exception:
            pass
        # advisory file locks (Locker role) — MDS session state
        self._locks: Dict[int, Dict[str, bool]] = {}
        # client sessions + per-inode capability grants (Capability.h /
        # SessionMap roles) — session state, rebuilt on reconnect like
        # the reference's client-reconnect phase
        self._sessions: Dict[str, dict] = {}
        self._caps: Dict[int, Dict[str, str]] = {}
        # root must exist before replay: journaled ops re-apply into it
        if not self._dir_exists(ROOT_INO):
            self._write_dir(ROOT_INO, {})
        self._replay()

    # ---------------------------------------------------------- dirfrags --
    def _dir_oid(self, ino: int) -> str:
        return f"dirfrag.{ino:016x}"

    def _dir_exists(self, ino: int) -> bool:
        try:
            self.meta.read(self._dir_oid(ino))
            return True
        except Exception:
            return False

    def _read_dir(self, ino: int) -> Dict[str, dict]:
        try:
            return json.loads(self.meta.read(self._dir_oid(ino)).decode())
        except Exception:
            raise FSError(f"no such directory inode {ino}") from None

    def _write_dir(self, ino: int, entries: Dict[str, dict]) -> None:
        self.meta.write_full(self._dir_oid(ino),
                             json.dumps(entries).encode())

    # ------------------------------------------------------------ journal --
    # applied ops older than this many entries are expired from the
    # journal (MDLog segment expiry role): dirfrags are the durable
    # state once written, so replay only needs the unexpired window
    JOURNAL_KEEP = 256

    def _journal_and_apply(self, op: dict) -> None:
        """MDLog contract: journal first, then apply to dirfrags."""
        op["ts"] = op.get("ts", time.time())
        seq = self.journal.append(json.dumps(op).encode())
        self._apply(op)
        if seq and seq % self.JOURNAL_KEEP == 0:
            self.journal.trim_to(seq - self.JOURNAL_KEEP + 1)

    def _apply(self, op: dict) -> None:
        kind = op["op"]
        if kind == "mkdir":
            d = self._read_dir(op["parent"])
            d[op["name"]] = {"ino": op["ino"], "type": "dir"}
            if not self._dir_exists(op["ino"]):
                # replay over surviving dirfrags must not wipe them
                self._write_dir(op["ino"], {})
            self._write_dir(op["parent"], d)
        elif kind == "create":
            d = self._read_dir(op["parent"])
            d[op["name"]] = {"ino": op["ino"], "type": "file", "size": 0}
            self._write_dir(op["parent"], d)
        elif kind == "setsize":
            d = self._read_dir(op["parent"])
            if op["name"] in d:
                d[op["name"]]["size"] = op["size"]
                self._write_dir(op["parent"], d)
        elif kind == "unlink":
            d = self._read_dir(op["parent"])
            d.pop(op["name"], None)
            self._write_dir(op["parent"], d)
        elif kind == "rmdir":
            d = self._read_dir(op["parent"])
            d.pop(op["name"], None)
            self._write_dir(op["parent"], d)
            try:
                self.meta.remove(self._dir_oid(op["ino"]))
            except Exception:
                pass
        elif kind == "link_dentry":
            # destination half of a cross-rank rename (multimds.py):
            # link an existing inode's dentry into this rank's subtree
            d = self._read_dir(op["parent"])
            d[op["name"]] = dict(op["ent"])
            self._write_dir(op["parent"], d)
        elif kind == "rename":
            src = self._read_dir(op["src_parent"])
            ent = src.pop(op["src_name"], None)
            if ent is None:
                return          # idempotent replay over applied state
            self._write_dir(op["src_parent"], src)
            dst = self._read_dir(op["dst_parent"])
            dst[op["dst_name"]] = ent
            self._write_dir(op["dst_parent"], dst)
        if "ino" in op:
            if op["ino"] + 1 > self._next_ino:
                self._next_ino = op["ino"] + 1
                self.meta.write_full("mds_inotable",
                                     str(self._next_ino).encode())

    def _replay(self) -> None:
        """Startup recovery: re-apply the whole journal (idempotent
        ops), recovering the ino allocator along the way."""
        for _seq, payload in self.journal.replay():
            try:
                self._apply(json.loads(payload.decode()))
            except FSError:
                pass           # partially-applied op against lost frag

    # ---------------------------------------------------------- authority --
    def _check_auth(self, path: str) -> None:
        """Raise ForwardError when another rank owns this subtree."""
        if self.rank is None or self.mdsmap is None:
            return
        owner = self.mdsmap.auth_rank(path)
        if owner != self.rank:
            raise ForwardError(owner, path)

    def subtree_inos(self, path: str) -> List[int]:
        """Every inode under (and including) the directory at `path` —
        the set whose session state must move on subtree export."""
        ent = self._lookup(path)
        inos = [ent["ino"]]
        if ent["type"] != "dir":
            return inos
        stack = [ent["ino"]]
        while stack:
            ino = stack.pop()
            for child in self._read_dir(ino).values():
                inos.append(child["ino"])
                if child["type"] == "dir":
                    stack.append(child["ino"])
        return inos

    def export_subtree(self, path: str, to_rank: int) -> List[int]:
        """Source half of a subtree migration (the Migrator export
        role, with cap/lock state flushed-and-dropped rather than
        migrated — clients reacquire against the new rank, the
        client-reconnect shape): journal an EExport marker, flush
        every cap under the subtree (buffered writers write back),
        drop the subtree's locks, return the inode list."""
        self._check_auth(path)
        inos = self.subtree_inos(path)
        self._journal_and_apply({"op": "export", "path": path,
                                 "to": to_rank})
        for ino in inos:
            self._flush_and_drop_caps(ino)
            self._locks.pop(ino, None)
        return inos

    def import_subtree(self, path: str, from_rank: int) -> None:
        """Destination half: journal the EImport marker.  Dirfrags are
        shared RADOS objects, so authority (not data) is what moves."""
        self._journal_and_apply({"op": "import", "path": path,
                                 "from": from_rank})

    # -------------------------------------------------------- path logic --
    def _resolve(self, path: str) -> Tuple[int, str]:
        """-> (parent dir ino, leaf name); '' leaf means the root."""
        parts = [p for p in path.split("/") if p]
        if not parts:
            return ROOT_INO, ""
        ino = ROOT_INO
        for p in parts[:-1]:
            d = self._read_dir(ino)
            ent = d.get(p)
            if ent is None or ent["type"] != "dir":
                raise FSError(f"no such directory: {p}")
            ino = ent["ino"]
        return ino, parts[-1]

    def _lookup(self, path: str) -> dict:
        parent, name = self._resolve(path)
        if not name:
            return {"ino": ROOT_INO, "type": "dir"}
        ent = self._read_dir(parent).get(name)
        if ent is None:
            raise FSError(f"no such entry: {path}")
        return ent

    # ----------------------------------------------------------- osd data --
    def _data_oid(self, ino: int, objno: int) -> str:
        return f"{ino:016x}.{objno:08x}"

    def write_file(self, path: str, data: bytes, offset: int = 0) -> int:
        self._check_auth(path)
        parent, name = self._resolve(path)
        ent = self._read_dir(parent).get(name)
        if ent is None or ent["type"] != "file":
            raise FSError(f"no such file: {path}")
        pos = 0
        for objno, ooff, olen in file_to_extents(self.layout, offset,
                                                 len(data)):
            oid = self._data_oid(ent["ino"], objno)
            try:
                cur = bytearray(self.data.read(oid))
            except Exception:
                cur = bytearray()
            if len(cur) < ooff + olen:
                cur.extend(b"\0" * (ooff + olen - len(cur)))
            cur[ooff:ooff + olen] = data[pos:pos + olen]
            self.data.write_full(oid, bytes(cur))
            pos += olen
        new_size = max(ent.get("size", 0), offset + len(data))
        self._journal_and_apply({"op": "setsize", "parent": parent,
                                 "name": name, "size": new_size})
        return len(data)

    def read_file(self, path: str, offset: int = 0,
                  length: Optional[int] = None) -> bytes:
        ent = self._lookup(path)
        if ent["type"] != "file":
            raise FSError(f"not a file: {path}")
        return self.read_ino(ent, offset, length)

    def read_ino(self, ent: dict, offset: int = 0,
                 length: Optional[int] = None) -> bytes:
        """Read file content from an INODE record alone — the half a
        replica-holding non-auth rank can serve without any path
        authority (data objects live in the shared data pool)."""
        size = ent.get("size", 0)
        if length is None:
            length = max(0, size - offset)
        length = min(length, max(0, size - offset))
        out = bytearray(length)
        pos = 0
        for objno, ooff, olen in file_to_extents(self.layout, offset,
                                                 length):
            try:
                piece = self.data.read(self._data_oid(ent["ino"],
                                                      objno))
            except Exception:
                piece = b""
            chunk = piece[ooff:ooff + olen]
            out[pos:pos + len(chunk)] = chunk
            pos += olen
        return bytes(out)

    # ------------------------------------------------------------- locker --
    # Advisory file locks (the src/mds/Locker.cc setfilelock/flock
    # role, reduced to its semantics): shared locks coexist, exclusive
    # locks exclude everything, per-owner release.  Lock state is MDS
    # session state (the reference's locks live in the MDS's in-memory
    # lock machine, not the journal) — a failed-over MDS starts with
    # clean locks, like real clients re-acquiring after reconnect.

    # ------------------------------------------------------ capabilities --
    # The client-coherence protocol (src/mds/Capability.h + Locker.cc
    # filelock states, collapsed to the decisive shape):
    #   "r"  may read            "w"  may write
    #   "c"  may CACHE/BUFFER    (the Fc/Fb file-cap role: only ever
    #        granted to a single client per inode — the loner)
    # Grant rules: a lone client gets rwc; concurrent readers share r;
    # any reader/writer mix forces SYNC I/O (rw, no c).  Conflicting
    # grants REVOKE the current holders first — a revoked client's
    # flush callback writes its dirty data back before the new grant
    # is issued, which is what makes two clients coherent.
    # Every session's caps sit under a LEASE; a client that stops
    # renewing is evicted and its caps/locks drop (session timeout,
    # src/mds/Sessionmap.h + Locker revoke-on-eviction).

    LEASE_TTL = 30.0

    def open_session(self, client: str, flush_cb=None,
                     now: Optional[float] = None) -> None:
        """flush_cb(ino, why) is called when a cap this client holds
        is being revoked; it must write back dirty state."""
        self._sessions[client] = {
            "flush_cb": flush_cb,
            "renewed": time.time() if now is None else now}

    def renew_session(self, client: str,
                      now: Optional[float] = None) -> None:
        s = self._sessions.get(client)
        if s is None:
            raise FSError(f"ESTALE: no session for {client}")
        s["renewed"] = time.time() if now is None else now

    def _session_live(self, client: str, now: float) -> bool:
        s = self._sessions.get(client)
        return s is not None and now - s["renewed"] < self.LEASE_TTL

    def _revoke(self, ino: int, client: str, caps_lost: str) -> None:
        held = self._caps.get(ino, {})
        cur = held.get(client, "")
        rest = "".join(c for c in cur if c not in caps_lost)
        s = self._sessions.get(client)
        if s and s["flush_cb"] and ("c" in cur or "w" in cur):
            s["flush_cb"](ino, caps_lost)
        if rest:
            held[client] = rest
        else:
            held.pop(client, None)

    def acquire_caps(self, client: str, path: str, want: str,
                     now: Optional[float] = None) -> str:
        """Grant capabilities on the inode at ``path`` (revoking
        conflicting holders first).  Returns the granted cap string.
        ``want``: subset of "rwc" ("c" upgrades to exclusive when this
        client is alone)."""
        self._check_auth(path)
        now = time.time() if now is None else now
        if not self._session_live(client, now):
            raise FSError(f"ESTALE: session for {client} expired")
        self.evict_expired(now)
        ino = self._lookup(path)["ino"]
        held = self._caps.setdefault(ino, {})
        others = {c: v for c, v in held.items() if c != client}
        if others:
            # ANY second client breaks the loner: every other holder's
            # cache cap is revoked first — a buffered writer flushes
            # before even a plain reader proceeds (reader/writer mix
            # forces sync I/O)
            for o, v in list(others.items()):
                if "c" in v:
                    self._revoke(ino, o, "c")
            others = {c: v for c, v in held.items() if c != client}
        grant = "".join(c for c in want if c in "rw")
        if "c" in want and not others:
            grant += "c"                 # loner: exclusive/caching
        if others and "c" in held.get(client, ""):
            self._revoke(ino, client, "c")
        held[client] = "".join(sorted(set(held.get(client, "")) |
                                      set(grant)))
        return held[client]

    def acquire_caps_path(self, path: str, client: str, want: str,
                          now: Optional[float] = None) -> str:
        """Path-first adapter for the multi-MDS router (ForwardError
        carries the path, so the router dispatches path-first)."""
        return self.acquire_caps(client, path, want, now)

    def release_caps_path(self, path: str, client: str) -> None:
        return self.release_caps(client, path)

    def release_caps(self, client: str, path: str) -> None:
        """Voluntary cap return: routed through the revoke path so a
        buffered/caching client flushes AND drops its local cache —
        otherwise a later lone re-grant would serve stale bytes."""
        self._check_auth(path)
        ino = self._lookup(path)["ino"]
        held = self._caps.get(ino)
        if held and client in held:
            self._revoke(ino, client, "rwc")
            held.pop(client, None)
            if not held:
                del self._caps[ino]

    def caps_of(self, path: str) -> Dict[str, str]:
        ino = self._lookup(path)["ino"]
        return dict(self._caps.get(ino, {}))

    def evict_expired(self, now: Optional[float] = None) -> List[str]:
        """Drop lapsed sessions: their caps and locks vanish (the
        session-timeout eviction path)."""
        now = time.time() if now is None else now
        evicted = []
        for client in list(self._sessions):
            if not self._session_live(client, now):
                for ino in list(self._caps):
                    self._caps[ino].pop(client, None)
                    if not self._caps[ino]:
                        del self._caps[ino]
                self.release_owner(client)
                del self._sessions[client]
                evicted.append(client)
        return evicted

    def setlk(self, path: str, owner: str,
              exclusive: bool = True) -> bool:
        """Try-lock; False on conflict (the F_SETLK no-wait shape)."""
        self._check_auth(path)
        ent = self._lookup(path)
        ino = ent["ino"]
        holders = self._locks.setdefault(ino, {})
        cur = holders.get(owner)
        if cur is not None and cur == exclusive:
            return True                      # re-grant, idempotent
        others = {o: x for o, x in holders.items() if o != owner}
        if exclusive and others:
            return False
        if not exclusive and any(others.values()):
            return False
        holders[owner] = exclusive
        return True

    def getlk(self, path: str) -> Dict[str, bool]:
        """Current holders: {owner: exclusive} (F_GETLK role)."""
        ent = self._lookup(path)
        return dict(self._locks.get(ent["ino"], {}))

    def unlock(self, path: str, owner: str) -> None:
        ent = self._lookup(path)
        holders = self._locks.get(ent["ino"])
        if holders is not None:
            holders.pop(owner, None)
            if not holders:
                del self._locks[ent["ino"]]

    def release_owner(self, owner: str) -> int:
        """Drop every lock a (dead) client held — the session-close
        cleanup the reference's Locker does on client eviction."""
        n = 0
        for ino in list(self._locks):
            holders = self._locks[ino]
            if holders.pop(owner, None) is not None:
                n += 1
            if not holders:
                del self._locks[ino]
        return n

    # ------------------------------------------------------------ the API --
    def mkdir(self, path: str) -> int:
        self._check_auth(path)
        parent, name = self._resolve(path)
        if not name:
            raise FSError("root exists")
        if name in self._read_dir(parent):
            raise FSError(f"exists: {path}")
        ino = self._next_ino
        self._journal_and_apply({"op": "mkdir", "parent": parent,
                                 "name": name, "ino": ino})
        return ino

    def create(self, path: str) -> int:
        self._check_auth(path)
        parent, name = self._resolve(path)
        if name in self._read_dir(parent):
            raise FSError(f"exists: {path}")
        ino = self._next_ino
        self._journal_and_apply({"op": "create", "parent": parent,
                                 "name": name, "ino": ino})
        return ino

    def _flush_and_drop_caps(self, ino: int) -> None:
        """Before a namespace op kills/moves an inode: revoke every
        holder's caps (buffered writers flush via their callbacks
        while the path still resolves), then drop the cap state —
        caps die with the inode like locks do."""
        for client in list(self._caps.get(ino, {})):
            self._revoke(ino, client, "rwc")
        self._caps.pop(ino, None)

    def unlink(self, path: str) -> None:
        self._check_auth(path)
        parent, name = self._resolve(path)
        ent = self._read_dir(parent).get(name)
        if ent is None or ent["type"] != "file":
            raise FSError(f"no such file: {path}")
        self._flush_and_drop_caps(ent["ino"])
        # purge every data object the file's size can cover; sparse
        # holes (missing objnos) are skipped, not treated as the end
        n_objs = -(-ent.get("size", 0) // self.layout.object_size)
        for objno in range(n_objs):
            try:
                self.data.remove(self._data_oid(ent["ino"], objno))
            except Exception:
                pass
        self._journal_and_apply({"op": "unlink", "parent": parent,
                                 "name": name})
        # locks die with the inode — only AFTER the unlink committed
        # (a failed unlink must not release other clients' locks)
        self._locks.pop(ent["ino"], None)

    def rmdir(self, path: str) -> None:
        self._check_auth(path)
        parent, name = self._resolve(path)
        ent = self._read_dir(parent).get(name)
        if ent is None or ent["type"] != "dir":
            raise FSError(f"no such directory: {path}")
        if self._read_dir(ent["ino"]):
            raise FSError(f"directory not empty: {path}")
        self._journal_and_apply({"op": "rmdir", "parent": parent,
                                 "name": name, "ino": ent["ino"]})
        self._locks.pop(ent["ino"], None)   # after the commit, as above

    def rename(self, src: str, dst: str) -> None:
        self._check_auth(src)
        self._check_auth(dst)
        sp, sn = self._resolve(src)
        dp, dn = self._resolve(dst)
        ent = self._read_dir(sp).get(sn)
        if ent is None:
            raise FSError(f"no such entry: {src}")
        if dn in self._read_dir(dp):
            raise FSError(f"exists: {dst}")
        # buffered holders flush while the SOURCE path still resolves;
        # their path-keyed client caches cannot follow the move
        self._flush_and_drop_caps(ent["ino"])
        self._journal_and_apply({"op": "rename", "src_parent": sp,
                                 "src_name": sn, "dst_parent": dp,
                                 "dst_name": dn})

    def listdir(self, path: str) -> List[str]:
        ent = self._lookup(path)
        if ent["type"] != "dir":
            raise FSError(f"not a directory: {path}")
        return sorted(self._read_dir(ent["ino"]))

    def stat(self, path: str) -> dict:
        ent = self._lookup(path)
        return dict(ent)


class CephFSClient:
    """Path-based facade (libcephfs surface subset) with a
    capability-coherent client cache: exclusive ("c") caps buffer
    writes and serve cached reads; a revoke from the MDS (another
    client opened the file) writes dirty data back and drops the
    cache — the reference's Fb/Fc client cap behavior
    (src/client/Client.cc + mds/Locker.cc)."""

    def __init__(self, mds: MDS, client_id: Optional[str] = None):
        self.mds = mds
        self.client = client_id or f"client.{id(self):x}"
        self._cache: Dict[str, bytes] = {}
        self._dirty: set = set()
        self._ino_path: Dict[int, str] = {}
        mds.open_session(self.client, flush_cb=self._on_revoke)

    # ------------------------------------------------------- cap plumbing --
    def _on_revoke(self, ino: int, caps_lost: str) -> None:
        path = self._ino_path.get(ino)
        if path is None:
            return
        if path in self._dirty:
            self.mds.write_file(path, self._cache[path], 0)
            self._dirty.discard(path)
        self._cache.pop(path, None)

    def _caps_for(self, path: str, want: str) -> str:
        try:
            caps = self.mds.acquire_caps(self.client, path, want)
        except FSError as e:
            if "ESTALE" not in str(e):
                raise
            # lease lapsed: the MDS evicted us.  Reconnect with a COLD
            # cache — buffered-but-unflushed data from the dead session
            # is LOST (exactly the reference's eviction semantics) and
            # cached reads may be stale against post-eviction writers.
            self._cache.clear()
            self._dirty.clear()
            self.mds.open_session(self.client,
                                  flush_cb=self._on_revoke)
            caps = self.mds.acquire_caps(self.client, path, want)
        self._ino_path[self.mds.stat(path)["ino"]] = path
        return caps

    def renew(self) -> None:
        self.mds.renew_session(self.client)

    def flush(self) -> None:
        """Write back every buffered file (client cap flush)."""
        for path in list(self._dirty):
            self.mds.write_file(path, self._cache[path], 0)
            self._dirty.discard(path)

    def mkdir(self, path: str) -> None:
        self.mds.mkdir(path)

    def listdir(self, path: str = "/") -> List[str]:
        return self.mds.listdir(path)

    def write(self, path: str, data: bytes, offset: int = 0) -> int:
        try:
            self.mds.stat(path)
        except FSError:
            self.mds.create(path)
        caps = self._caps_for(path, "rwc")
        if "c" not in caps:
            # shared file: sync write-through (no buffering cap)
            return self.mds.write_file(path, data, offset)
        base = self._cache.get(path)
        if base is None:
            base = self.mds.read_file(path)
        buf = bytearray(base)
        if len(buf) < offset + len(data):
            buf.extend(b"\0" * (offset + len(data) - len(buf)))
        buf[offset:offset + len(data)] = data
        self._cache[path] = bytes(buf)
        self._dirty.add(path)
        return len(data)

    def read(self, path: str, offset: int = 0,
             length: Optional[int] = None) -> bytes:
        caps = self._caps_for(path, "rc")
        if "c" not in caps:
            # sync mode: read exactly the requested extent through
            return self.mds.read_file(path, offset, length)
        if path in self._cache:
            data = self._cache[path]
        else:
            data = self.mds.read_file(path)
            self._cache[path] = data
        end = len(data) if length is None else offset + length
        return data[offset:end]

    def unlink(self, path: str) -> None:
        self._cache.pop(path, None)
        self._dirty.discard(path)
        self.mds.unlink(path)

    def rmdir(self, path: str) -> None:
        self.mds.rmdir(path)

    def rename(self, src: str, dst: str) -> None:
        # namespace ops flush buffered data first (the reference
        # journals rename only after cap flush)
        if src in self._dirty:
            self.mds.write_file(src, self._cache[src], 0)
            self._dirty.discard(src)
        self._cache.pop(src, None)
        self._cache.pop(dst, None)
        self._dirty.discard(dst)
        self.mds.rename(src, dst)

    def stat(self, path: str) -> dict:
        st = self.mds.stat(path)
        if path in self._dirty:
            # buffered writer provides the authoritative size (the
            # client-caps size-projection the reference does for Fw
            # holders)
            st = dict(st, size=len(self._cache[path]))
        return st
