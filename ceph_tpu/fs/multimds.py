"""Multi-MDS: active ranks, subtree migration, balancer.

The reference runs max_mds active metadata servers, each authoritative
for a set of subtrees; the Migrator moves a subtree's authority
between ranks (src/mds/Migrator.cc export/import state machines,
journaled as EExport/EImport), and the MDBalancer picks what to move
from load measurements (src/mds/MDBalancer.cc).  Clients whose request
lands on the wrong rank are forwarded (MDSRank::forward).

This module re-derives that shape on the repo's seams:

  * every rank is an ``MDS`` with its OWN journal (mdlog.<rank>) over
    the SHARED metadata/data pools — dirfrags are RADOS objects, so a
    migration transfers *authority* (and flushes the subtree's
    cap/lock session state), never dirfrag bytes;
  * the durable subtree-authority table is the MDSMap
    (fs/mdsmap.py); migration = EExport marker on the source journal,
    EImport marker on the destination journal, then the map epoch
    bump — the map write is the commit point, and a crash between the
    markers and the map write leaves authority unchanged (markers are
    diagnostic, ops replay idempotently);
  * ``MDSCluster`` is also the request router: ops go to the subtree
    owner, ForwardError re-routes (bounded retries), cross-rank rename
    is decomposed into an import-then-export dentry pair on the two
    owners (the master/slave rename collapsed to its effect);
  * ``MDBalancer`` counts requests per top-level subtree and migrates
    the hottest subtree off the busiest rank (req-count heuristic —
    the reference balances on a load vector);
  * CROSS-RANK READ CACHING (VERDICT r4 next #8): non-auth ranks hold
    read-only dentry/inode REPLICAS obtained by DISCOVER from the
    auth rank, held under a time-bounded LEASE; the auth rank tracks
    replica holders and revokes (EXPIRE) them on every mutation of
    the entry — src/mds/MDCache.h:624,794 (replica_map / discover),
    the dentry lease shape of Locker.  A read entering a NON-auth
    rank serves from its replica with no forward; file reads need
    only the inode (data objects live in the shared data pool), so a
    replica-holding rank serves whole file reads locally.
"""
from __future__ import annotations

import time as _time
from typing import Dict, List, Optional, Tuple

from ..cluster.striper import FileLayout
from .mds import MDS, ForwardError, FSError
from .mdsmap import MDSMap, normalize

_MAX_FORWARDS = 4


class MDSCluster:
    """N active MDS ranks over shared pools + the request router."""

    MUTATING_OPS = frozenset((
        "mkdir", "create", "write_file", "unlink", "rmdir",
        "setlk", "acquire_caps_path"))

    def __init__(self, meta_ioctx, data_ioctx, n_ranks: int = 2,
                 layout: Optional[FileLayout] = None,
                 lease_s: float = 30.0):
        self.mdsmap = MDSMap(meta_ioctx, n_ranks=n_ranks)
        self.ranks: List[MDS] = [
            MDS(meta_ioctx, data_ioctx, layout=layout, rank=r,
                mdsmap=self.mdsmap)
            for r in range(self.mdsmap.n_ranks)]
        # per-top-level-subtree request counts, by rank (balancer input)
        self.load: Dict[str, int] = {}
        # -------- cross-rank read replicas (MDCache replica_map) --------
        self.lease_s = lease_s
        # per-rank: path -> (stat ent, lease expiry)
        self._replicas: List[Dict[str, Tuple[dict, float]]] = [
            {} for _ in range(self.mdsmap.n_ranks)]
        # auth side: path -> set of replica-holder ranks
        self._replica_holders: Dict[str, set] = {}
        self.replica_stats = {"hits": 0, "discovers": 0,
                              "expires": 0, "invalidations": 0}

    # ------------------------------------------- replica cache (reads) --
    def _replica_get(self, rank: int, path: str,
                     now: Optional[float] = None) -> Optional[dict]:
        """A live replica of ``path`` on ``rank``, or None.  Expired
        leases drop (the holder must re-discover — the lease-renewal
        half of the dentry lease protocol)."""
        p = normalize(path)
        hit = self._replicas[rank].get(p)
        if hit is None:
            return None
        ent, expires = hit
        if (now if now is not None else _time.monotonic()) >= expires:
            self._replicas[rank].pop(p, None)
            self._replica_holders.get(p, set()).discard(rank)
            self.replica_stats["expires"] += 1
            return None
        self.replica_stats["hits"] += 1
        return ent

    def _discover(self, rank: int, path: str,
                  now: Optional[float] = None) -> dict:
        """DISCOVER: the non-auth rank asks the subtree owner for a
        read-only replica of the entry; the owner registers the
        holder so mutations can revoke (MDCache.h:624 discover /
        :794 replica tracking)."""
        p = normalize(path)
        ent = self.mds_for(p).stat(p)
        t = now if now is not None else _time.monotonic()
        self._replicas[rank][p] = (ent, t + self.lease_s)
        self._replica_holders.setdefault(p, set()).add(rank)
        self.replica_stats["discovers"] += 1
        return ent

    def invalidate_replicas(self, path: str) -> None:
        """EXPIRE: revoke every rank's replica of the entry (sent by
        the auth rank on mutation, before the client sees the new
        state — here the cluster object IS the mon-grade messenger)."""
        p = normalize(path)
        for holder in self._replica_holders.pop(p, set()):
            if self._replicas[holder].pop(p, None) is not None:
                self.replica_stats["invalidations"] += 1

    def invalidate_replica_subtree(self, path: str) -> None:
        """Revoke replicas of an entry AND everything under it —
        namespace ops on a directory (rename) orphan every child
        path, and a path-keyed revoke of just the directory would
        leave children serving from a tree that no longer exists."""
        p = normalize(path)
        prefix = p if p.endswith("/") else p + "/"
        doomed = [q for q in self._replica_holders
                  if q == p or q.startswith(prefix)]
        for q in doomed:
            self.invalidate_replicas(q)

    def stat_via(self, rank: int, path: str,
                 now: Optional[float] = None) -> dict:
        """stat entering at an arbitrary rank: the auth rank serves
        its own; a non-auth rank serves its REPLICA with no forward,
        discovering one on first touch."""
        p = normalize(path)
        self._count(p)
        if self.mdsmap.auth_rank(p) == rank:
            return self.ranks[rank].stat(p)
        ent = self._replica_get(rank, p, now)
        if ent is None:
            ent = self._discover(rank, p, now)
        return ent

    def read_file_via(self, rank: int, path: str, offset: int = 0,
                      length: Optional[int] = None,
                      now: Optional[float] = None) -> bytes:
        """File read entering at an arbitrary rank: the inode replica
        is all the metadata a read needs (file bytes live in the
        SHARED data pool), so a replica-holding non-auth rank serves
        the whole read locally — zero forwards."""
        p = normalize(path)
        self._count(p)
        if self.mdsmap.auth_rank(p) == rank:
            return self.ranks[rank].read_file(p, offset, length)
        ent = self._replica_get(rank, p, now)
        if ent is None:
            ent = self._discover(rank, p, now)
        if ent.get("type") == "dir":
            raise FSError(f"is a directory: {path}")
        return self.ranks[rank].read_ino(ent, offset, length)

    # ------------------------------------------------------------ routing --
    def mds_for(self, path: str) -> MDS:
        return self.ranks[self.mdsmap.auth_rank(path)]

    def _routed(self, op: str, path: str, *args, **kw):
        """Dispatch op to the subtree owner, following forwards.
        Mutations REVOKE every outstanding read replica of the entry
        (and its parent: namespace ops change the parent's state) —
        the lease-expire half of the replica protocol."""
        self._count(path)
        if op in self.MUTATING_OPS:
            self.invalidate_replicas(path)
            parent = normalize(path).rsplit("/", 1)[0] or "/"
            self.invalidate_replicas(parent)
        rank = self.mdsmap.auth_rank(path)
        for _ in range(_MAX_FORWARDS):
            try:
                return getattr(self.ranks[rank], op)(path, *args, **kw)
            except ForwardError as f:
                rank = f.rank
        raise FSError(f"{op} {path}: forward loop (map churn?)")

    def _count(self, path: str) -> None:
        parts = [p for p in path.split("/") if p]
        key = "/" + parts[0] if parts else "/"
        self.load[key] = self.load.get(key, 0) + 1

    # ---------------------------------------------------------- the API --
    def mkdir(self, path: str) -> int:
        return self._routed("mkdir", path)

    def create(self, path: str) -> int:
        return self._routed("create", path)

    def write_file(self, path: str, data: bytes, offset: int = 0) -> int:
        return self._routed("write_file", path, data, offset)

    def read_file(self, path: str, offset: int = 0,
                  length: Optional[int] = None) -> bytes:
        self._count(path)
        return self.mds_for(path).read_file(path, offset, length)

    def unlink(self, path: str) -> None:
        return self._routed("unlink", path)

    def rmdir(self, path: str) -> None:
        return self._routed("rmdir", path)

    def listdir(self, path: str) -> List[str]:
        self._count(path)
        return self.mds_for(path).listdir(path)

    def stat(self, path: str) -> dict:
        return self.mds_for(path).stat(path)

    def rename(self, src: str, dst: str) -> None:
        s_rank = self.mdsmap.auth_rank(src)
        d_rank = self.mdsmap.auth_rank(dst)
        self._count(src)
        for p in (src, dst):
            self.invalidate_replica_subtree(p)
            self.invalidate_replicas(
                normalize(p).rsplit("/", 1)[0] or "/")
        if s_rank == d_rank:
            return self.ranks[s_rank].rename(src, dst)
        # cross-rank rename (the master/slave rename collapsed): the
        # destination owner imports the dentry first (visible-twice
        # window rather than lost-entry window), then the source owner
        # unlinks its side; both halves journal on their own rank and
        # replay idempotently
        smds, dmds = self.ranks[s_rank], self.ranks[d_rank]
        ent = smds.stat(src)
        sp, sn = smds._resolve(src)
        dp, dn = dmds._resolve(dst)
        if dn in dmds._read_dir(dp):
            raise FSError(f"exists: {dst}")
        for ino in ([ent["ino"]] if ent["type"] != "dir"
                    else smds.subtree_inos(src)):
            smds._flush_and_drop_caps(ino)
            # locks drop with the move too (the inode's lock state
            # lives on its subtree owner, which is changing) — a
            # stranded source-rank entry would both stop excluding and
            # be unreleasable through routing
            smds._locks.pop(ino, None)
        dmds._journal_and_apply({"op": "link_dentry", "parent": dp,
                                 "name": dn, "ent": ent})
        smds._journal_and_apply({"op": "unlink", "parent": sp,
                                 "name": sn})

    # -------------------------------------------- sessions / caps / locks --
    # CephFSClient quacks against this cluster exactly as against one
    # MDS: sessions exist on every rank (a client may touch any
    # subtree), caps/locks live on the subtree owner and are routed.
    def open_session(self, client: str, flush_cb=None,
                     now=None) -> None:
        for m in self.ranks:
            m.open_session(client, flush_cb, now)

    def renew_session(self, client: str, now=None) -> None:
        for m in self.ranks:
            m.renew_session(client, now)

    def evict_expired(self, now=None) -> List[str]:
        evicted: List[str] = []
        for m in self.ranks:
            evicted.extend(m.evict_expired(now))
        return sorted(set(evicted))

    def acquire_caps(self, client: str, path: str, want: str,
                     now=None) -> str:
        return self._routed("acquire_caps_path", path, client, want,
                            now)

    def release_caps(self, client: str, path: str) -> None:
        return self._routed("release_caps_path", path, client)

    def caps_of(self, path: str) -> Dict[str, str]:
        return self.mds_for(path).caps_of(path)

    def setlk(self, path: str, owner: str,
              exclusive: bool = True) -> bool:
        return self._routed("setlk", path, owner, exclusive)

    def getlk(self, path: str) -> Dict[str, bool]:
        return self.mds_for(path).getlk(path)

    def unlock(self, path: str, owner: str) -> None:
        return self.mds_for(path).unlock(path, owner)

    def release_owner(self, owner: str) -> int:
        return sum(m.release_owner(owner) for m in self.ranks)

    # -------------------------------------------------------- migration --
    def migrate(self, path: str, to_rank: int) -> None:
        """Move subtree authority (Migrator export/import).  The MDSMap
        write is the commit point; caps/locks under the subtree are
        flushed and dropped on the source (clients reacquire against
        the new owner — the reconnect shape)."""
        p = normalize(path)
        src_rank = self.mdsmap.auth_rank(p)
        if src_rank == to_rank:
            return
        src, dst = self.ranks[src_rank], self.ranks[to_rank]
        src.export_subtree(p, to_rank)          # journals EExport, flushes
        dst.import_subtree(p, src_rank)         # journals EImport
        self.mdsmap.set_auth(p, to_rank)        # ← commit point
        # balancer bookkeeping follows the subtree to the new rank
        self.load.pop(p, None)

    def subtree_map(self) -> Dict[str, int]:
        """The `ceph mds dump`-style view: subtree → owning rank."""
        return dict(self.mdsmap.subtrees)


class MDBalancer:
    """Move the hottest subtree off the busiest rank (MDBalancer.cc's
    load-driven export, reduced to the request-count heuristic)."""

    def __init__(self, cluster: MDSCluster, min_requests: int = 16):
        self.cluster = cluster
        self.min_requests = min_requests

    def rank_loads(self) -> Dict[int, int]:
        loads = {r: 0 for r in range(len(self.cluster.ranks))}
        for subtree, n in self.cluster.load.items():
            loads[self.cluster.mdsmap.auth_rank(subtree)] += n
        return loads

    def rebalance(self) -> List[Tuple[str, int]]:
        """One balancing pass; returns [(subtree, new_rank)] moved."""
        loads = self.rank_loads()
        if len(loads) < 2:
            return []
        busiest = max(loads, key=lambda r: loads[r])
        coolest = min(loads, key=lambda r: loads[r])
        if loads[busiest] - loads[coolest] < 2 * self.min_requests:
            return []
        # hottest top-level subtree currently owned by the busiest rank
        candidates = sorted(
            ((n, p) for p, n in self.cluster.load.items()
             if p != "/" and
             self.cluster.mdsmap.auth_rank(p) == busiest),
            reverse=True)
        moved = []
        for n, p in candidates:
            if n < self.min_requests:
                break
            # move only if it strictly improves the imbalance (a
            # subtree is the migration granularity — a dominant one
            # still moves, it just must not make things worse)
            before = loads[busiest] - loads[coolest]
            after = abs((loads[coolest] + n) - (loads[busiest] - n))
            if after >= before:
                continue
            self.cluster.migrate(p, coolest)
            moved.append((p, coolest))
            loads[busiest] -= n
            loads[coolest] += n
        return moved
