"""MDSMap — the multi-MDS cluster map (ranks + subtree authority).

The role of the reference's MDSMap (src/mds/MDSMap.h: which ranks are
in/active, max_mds) plus the subtree-authority table the reference
keeps distributed in each CDir's subtree auth (src/mds/MDCache.cc
subtree map, displayed by `ceph mds dump`): here it is one explicit,
durable table {normalized dir path -> rank} with longest-prefix
resolution, persisted in the metadata pool ("mdsmap" object, the
MDSMonitor-held map's role collapsed onto the pool — the repo's mon
quorum governs OSD/pool maps; the fs-internal map rides the same
replicated storage).

Authority resolution: a path is served by the rank owning its longest
matching subtree prefix; "/" is always present and owned by rank 0
unless delegated, so resolution is total.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

MDSMAP_OID = "mdsmap"


def normalize(path: str) -> str:
    parts = [p for p in path.split("/") if p]
    return "/" + "/".join(parts)


class MDSMap:
    """Durable rank/subtree-authority map."""

    def __init__(self, meta_ioctx, n_ranks: int = 1):
        self.meta = meta_ioctx
        self.epoch = 1
        self.n_ranks = n_ranks
        self.subtrees: Dict[str, int] = {"/": 0}
        self._load_or_create()

    # ----------------------------------------------------------- persist --
    def _load_or_create(self) -> None:
        try:
            blob = self.meta.read(MDSMAP_OID)
        except KeyError:
            # ObjectNotFound only: a transient pool error must NOT
            # fall into the create branch and clobber the durable map
            self._save()
            return
        d = json.loads(bytes(blob).decode())
        self.epoch = d["epoch"]
        # ranks may grow across restarts (max_mds raised); never shrink
        # below what the stored subtree table references
        self.n_ranks = max(self.n_ranks, d["n_ranks"])
        self.subtrees = {k: int(v) for k, v in d["subtrees"].items()}

    def _save(self) -> None:
        self.meta.write_full(MDSMAP_OID, json.dumps(
            {"epoch": self.epoch, "n_ranks": self.n_ranks,
             "subtrees": self.subtrees}).encode())

    # --------------------------------------------------------- authority --
    def auth_rank(self, path: str) -> int:
        """Longest-prefix subtree match (total: '/' always resolves)."""
        p = normalize(path)
        best, best_len = 0, -1
        for prefix, rank in self.subtrees.items():
            if p == prefix or prefix == "/" or \
                    p.startswith(prefix + "/"):
                if len(prefix) > best_len:
                    best, best_len = rank, len(prefix)
        return best

    def set_auth(self, path: str, rank: int) -> None:
        """Delegate a subtree to `rank` (bumps the epoch, durable)."""
        if not 0 <= rank < self.n_ranks:
            raise ValueError(f"no such rank {rank}")
        p = normalize(path)
        self.subtrees[p] = rank
        # absorb now-redundant deeper entries owned by the same rank
        for sub in [s for s in self.subtrees
                    if s != p and s.startswith(p + "/")
                    and self.subtrees[s] == rank]:
            del self.subtrees[sub]
        self.epoch += 1
        self._save()

    def subtrees_of(self, rank: int) -> List[str]:
        return sorted(p for p, r in self.subtrees.items() if r == rank)

    def reload(self) -> None:
        self._load_or_create()
