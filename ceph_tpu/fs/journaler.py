"""Journaler — append-only journal over RADOS objects.

The src/journal/ role (Journaler/ObjectRecorder/JournalMetadata, used
by rbd-mirror and, in spirit, the MDS's MDLog): an ordered stream of
entries recorded into a chain of fixed-capacity journal objects, with
a small header object tracking the active chain and trim position.
Entries are length-prefixed and CRC-protected; replay walks the chain
in order and stops at a torn tail; trim drops whole objects behind the
commit position.
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import Iterator, Tuple

_ENT = struct.Struct("<IIQ")      # len, crc, seq


class Journaler:
    def __init__(self, ioctx, name: str, object_bytes: int = 1 << 16):
        self.ioctx = ioctx
        self.name = name
        self.object_bytes = object_bytes
        self._load_header()

    # ------------------------------------------------------------ header --
    def _header_oid(self) -> str:
        return f"journal.{self.name}.header"

    def _obj_oid(self, idx: int) -> str:
        return f"journal.{self.name}.{idx:08x}"

    def _load_header(self) -> None:
        try:
            h = json.loads(self.ioctx.read(self._header_oid()).decode())
        except Exception:
            h = {"first": 0, "active": 0, "seq": 0}
        self.first = h["first"]          # oldest live journal object
        self.active = h["active"]        # object being appended
        self.seq = h["seq"]              # next entry sequence number

    def _save_header(self) -> None:
        self.ioctx.write_full(self._header_oid(), json.dumps(
            {"first": self.first, "active": self.active,
             "seq": self.seq}).encode())

    # ------------------------------------------------------------- append --
    def append(self, payload: bytes) -> int:
        """Record one entry; returns its sequence number.  The entry is
        durable in the journal object BEFORE the header advances."""
        try:
            cur = self.ioctx.read(self._obj_oid(self.active))
        except Exception:
            cur = b""
        if len(cur) + _ENT.size + len(payload) > self.object_bytes and cur:
            self.active += 1
            cur = b""
        seq = self.seq
        rec = _ENT.pack(len(payload), zlib.crc32(payload), seq) + payload
        self.ioctx.write_full(self._obj_oid(self.active), cur + rec)
        self.seq = seq + 1
        self._save_header()
        return seq

    # ------------------------------------------------------------- replay --
    def replay(self) -> Iterator[Tuple[int, bytes]]:
        """Yield (seq, payload) in order from the trim position; torn
        or corrupt tails end the replay (crash semantics)."""
        idx = self.first
        while True:
            try:
                blob = self.ioctx.read(self._obj_oid(idx))
            except Exception:
                return
            off = 0
            while off + _ENT.size <= len(blob):
                ln, crc, seq = _ENT.unpack_from(blob, off)
                payload = blob[off + _ENT.size:off + _ENT.size + ln]
                if len(payload) != ln or zlib.crc32(payload) != crc:
                    return                      # torn tail
                yield seq, payload
                off += _ENT.size + ln
            idx += 1

    # --------------------------------------------------------------- trim --
    def trim_to(self, seq: int) -> int:
        """Drop whole journal objects whose every entry is < seq
        (committed); returns objects removed."""
        removed = 0
        idx = self.first
        while idx < self.active:
            try:
                blob = self.ioctx.read(self._obj_oid(idx))
            except Exception:
                break
            last = -1
            off = 0
            while off + _ENT.size <= len(blob):
                ln, _crc, s = _ENT.unpack_from(blob, off)
                last = s
                off += _ENT.size + ln
            if last >= seq:
                break
            self.ioctx.remove(self._obj_oid(idx))
            idx += 1
            removed += 1
        self.first = idx
        self._save_header()
        return removed
