"""Device-resident EC shard staging (the HBM tier) + bitsliced at-rest
default.

VERDICT r3 missing #2: the flagship bitsliced kernel must be the
cluster's own data path — pools default to layout=bitsliced, shards are
staged on device as plane words, and ingest/degraded-read/recovery run
device-to-device (reference analog: jerasure packet layout at rest,
src/erasure-code/jerasure/ErasureCodeJerasure.cc:162; ECBackend shard
store, src/osd/ECBackend.cc:934,1015).
"""
import numpy as np
import pytest

from ceph_tpu.cluster.osdmap import OSDMap, PGPool, POOL_ERASURE
from ceph_tpu.cluster.simulator import ClusterSim
from ceph_tpu.placement.crush_map import (RULE_CHOOSELEAF_INDEP,
                                          RULE_EMIT, RULE_TAKE, Rule)
from tests.test_xla_mapper import TYPE_HOST, build_cluster


def make_sim(k=4, m=2, pg_num=16):
    cmap, root = build_cluster(n_hosts=8, osds_per_host=2, seed=3)
    cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                              (RULE_CHOOSELEAF_INDEP, 0, TYPE_HOST),
                              (RULE_EMIT, 0, 0)]))
    om = OSDMap(cmap)
    om.mark_all_in_up()
    om.add_pool(PGPool(id=1, name="ec", type=POOL_ERASURE, size=k + m,
                       pg_num=pg_num, crush_rule=0,
                       erasure_code_profile="p"))
    sim = ClusterSim(om)
    sim.create_ec_profile("p", {"plugin": "jax", "k": str(k),
                                "m": str(m)})
    return sim


def payload(n=40000, seed=7):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, n, dtype=np.uint8).tobytes()


def test_default_profile_is_bitsliced():
    sim = make_sim()
    assert sim.ec_profiles["p"]["layout"] == "bitsliced"
    codec = sim.codec_for(sim.osdmap.pools[1])
    assert codec.layout == "bitsliced"
    sim.shutdown()


def test_explicit_bytes_layout_respected():
    sim = make_sim()
    sim.create_ec_profile("compat", {"plugin": "jax", "k": "4",
                                     "m": "2", "layout": "bytes"})
    assert sim.ec_profiles["compat"]["layout"] == "bytes"
    sim.shutdown()


def test_put_stages_plane_words_on_device():
    sim = make_sim()
    data = payload()
    placed = sim.put(1, "obj", data)
    assert len(placed) == 6
    staged = sum(o.dev.stats()["entries"] for o in sim.osds)
    assert staged == 6          # every shard has an HBM copy
    assert sim.get(1, "obj") == data
    # reads hit the staging tier, not the durable bytes
    hits = sum(o.dev.hits for o in sim.osds)
    assert hits >= 4
    sim.shutdown()


def test_degraded_read_decodes_on_device():
    sim = make_sim()
    data = payload()
    placed = sim.put(1, "obj", data)
    for osd in placed[:2]:
        sim.kill_osd(osd)
    assert sim.get(1, "obj") == data
    sim.shutdown()


def test_eager_writethrough_keeps_durable_bytes_current():
    sim = make_sim()
    data = payload()
    sim.put(1, "obj", data)
    # durable tier holds the same plane-word bytes as the staging
    pool = sim.osdmap.pools[1]
    pg = sim.object_pg(pool, "obj")
    up = sim.pg_up(pool, pg)
    for shard, osd in enumerate(up):
        host = sim.osds[osd].objectstore.read((1, pg), f"{shard}:obj")
        dev = sim.osds[osd].get_device((1, pg, "obj", shard))
        assert host == np.asarray(dev).tobytes()
    sim.shutdown()


def test_staged_mode_defers_durability_until_flush():
    sim = make_sim()
    sim.staging_flush = "staged"
    data = payload()
    pool = sim.osdmap.pools[1]
    sim.put(1, "obj", data)
    pg = sim.object_pg(pool, "obj")
    up = sim.pg_up(pool, pg)
    # nothing durable yet
    assert not sim.osds[up[0]].objectstore.exists((1, pg), "0:obj")
    # but fully readable from the staging tier
    assert sim.get(1, "obj") == data
    flushed = sim.flush_all()
    assert flushed == 6
    assert sim.osds[up[0]].objectstore.exists((1, pg), "0:obj")
    # post-flush: entries clean, durable bytes match
    host = sim.osds[up[0]].objectstore.read((1, pg), "0:obj")
    dev = sim.osds[up[0]].get_device((1, pg, "obj", 0))
    assert host == np.asarray(dev).tobytes()
    sim.shutdown()


def test_crash_loses_unflushed_staging_and_recovery_rebuilds():
    sim = make_sim()
    sim.staging_flush = "staged"
    data = payload()
    placed = sim.put(1, "obj", data)
    victim = placed[0]
    sim.kill_osd(victim)        # crash: dirty staging on victim is gone
    assert sim.osds[victim].dev.stats()["entries"] == 0
    # survivors still decode the object
    assert sim.get(1, "obj") == data
    # mark out -> CRUSH maps the slot to a replacement; recovery
    # re-places the lost shard onto the new up set
    sim.out_osd(victim)
    stats = sim.recover_all(1)
    assert stats["shards_rebuilt"] + stats["shards_copied"] >= 1
    assert sim.get(1, "obj") == data
    sim.shutdown()


def test_external_byte_poke_invalidates_staged_copy():
    sim = make_sim()
    data = payload()
    sim.put(1, "obj", data)
    sim.get(1, "obj")           # warm the staging tier
    pool = sim.osdmap.pools[1]
    pg = sim.object_pg(pool, "obj")
    up = sim.pg_up(pool, pg)
    # overwrite shard 0's bytes out-of-band (objectstore surgery role)
    key = (1, pg, "obj", 0)
    new_bytes = np.zeros_like(
        np.frombuffer(sim.osds[up[0]].objectstore.read((1, pg),
                                                       "0:obj"),
                      dtype=np.uint8))
    sim.osds[up[0]].store[key] = new_bytes
    got = sim.osds[up[0]].get_device(key)
    assert np.asarray(got).tobytes() == new_bytes.tobytes()
    sim.shutdown()


def test_staging_disabled_matches_host_path():
    from ceph_tpu.common.options import config
    sim = make_sim()
    data = payload()
    config().set("osd_device_staging", False)
    try:
        sim.put(1, "obj", data)
        assert sim.get(1, "obj") == data
        assert sum(o.dev.stats()["entries"] for o in sim.osds) == 0
    finally:
        config().set("osd_device_staging", True)
    # staged write is readable after re-enabling (bytes are the truth)
    assert sim.get(1, "obj") == data
    sim.shutdown()


def test_device_client_put_get_roundtrip():
    """put_from_device/get_to_device: payload never leaves the device
    domain between client and shards (TPU-native client shape)."""
    import jax.numpy as jnp
    sim = make_sim()
    data = payload(50000)
    dev = jnp.asarray(np.frombuffer(data, dtype=np.uint8))
    placed = sim.put_from_device(1, "obj", dev)
    assert len(placed) == 6
    out = sim.get_to_device(1, "obj")
    assert np.asarray(out).tobytes() == data
    # interoperates with the host-byte surface
    assert sim.get(1, "obj") == data
    sim.shutdown()


def test_device_client_degraded_get():
    import jax.numpy as jnp
    sim = make_sim()
    sim.staging_flush = "staged"
    data = payload(30000, seed=5)
    dev = jnp.asarray(np.frombuffer(data, dtype=np.uint8))
    placed = sim.put_from_device(1, "obj", dev)
    for osd in placed[:2]:
        sim.kill_osd(osd)
    out = sim.get_to_device(1, "obj")
    assert np.asarray(out).tobytes() == data
    sim.shutdown()


def test_layered_codec_pool_keeps_host_path():
    """lrc/shec/clay codecs have no device kernels: pools using them
    must still work (capability-gated staging), host path end-to-end."""
    sim = make_sim()
    sim.create_ec_profile("clayp", {"plugin": "clay", "k": "4",
                                    "m": "2"})
    sim.osdmap.add_pool(PGPool(id=2, name="clay", type=POOL_ERASURE,
                               size=7, pg_num=8, crush_rule=0,
                               erasure_code_profile="clayp"))
    data = payload(20000, seed=9)
    placed = sim.put(2, "obj", data)
    assert sim.get(2, "obj") == data
    sim.kill_osd(placed[0])
    assert sim.get(2, "obj") == data
    # device-client surface degrades to host path, still correct
    import jax.numpy as jnp
    dev = jnp.asarray(np.frombuffer(data, dtype=np.uint8))
    sim.put_from_device(2, "obj2", dev)
    assert np.asarray(sim.get_to_device(2, "obj2")).tobytes() == data
    sim.shutdown()


def test_batched_put_get_many():
    """put_many/get_many: N objects through one encode / one gather
    dispatch, bytes identical to per-object ops."""
    import jax.numpy as jnp
    sim = make_sim()
    sim.staging_flush = "staged"
    U = 4096
    k = 4
    S = 4                       # 4 stripes x 16 KiB stripe width
    obj = S * k * U
    rng = np.random.default_rng(21)
    raw = rng.integers(0, 256, 3 * obj, dtype=np.uint8)
    batch = jnp.asarray(raw).reshape(3, S, k, U)
    names = ["a", "b", "c"]
    placed = sim.put_many_from_device(1, names, batch)
    assert all(len(p) == 6 for p in placed.values())
    out = sim.get_many_to_device(1, names)
    assert np.asarray(out).tobytes() == raw.tobytes()
    # individual reads agree
    for i, nm in enumerate(names):
        assert sim.get(1, nm) == raw[i * obj:(i + 1) * obj].tobytes()
    # degraded member falls back to the decode path inside get_many
    victims = placed["b"][:2]
    for o in victims:
        sim.kill_osd(o)
    out2 = sim.get_many_to_device(1, names)
    assert np.asarray(out2).tobytes() == raw.tobytes()
    # recovery still works over batched-put range refs
    for o in victims:
        sim.out_osd(o)
    sim.recover_all(1)
    for i, nm in enumerate(names):
        assert sim.get(1, nm) == raw[i * obj:(i + 1) * obj].tobytes()
    sim.shutdown()


def test_rmw_overwrite_coherent_with_staging():
    sim = make_sim()
    data = bytearray(payload())
    sim.put(1, "obj", bytes(data))
    patch = payload(5000, seed=11)
    sim.write(1, "obj", 8192, patch)
    data[8192:8192 + len(patch)] = patch
    assert sim.get(1, "obj") == bytes(data)
    sim.shutdown()


def test_recovery_irregular_refs_fallback():
    """Recovery over shards whose HBM staging was dropped (re-uploaded
    axis-0 refs — the 'irregular' composition): the per-member
    fallback path must rebuild byte-exact rather than silently skip
    (a NameError hid here until this test)."""
    import jax.numpy as jnp
    import numpy as np
    from tests.test_simulator import make_sim
    sim = make_sim(n_hosts=20, osds_per_host=2)
    sim.staging_flush = "staged"
    k, U, S = 4, 1 << 16, 4
    names = [f"ir{i}" for i in range(6)]
    block = jnp.arange(k * (U // 4), dtype=jnp.int32
                       ).reshape(1, k, U // 4)
    payload = jnp.tile(block, (6 * S, 1, 1))
    res = sim.put_many_from_device(2, names, payload)
    sim.flush_all()
    for o in sim.osds:
        o.dev.clear()          # force re-upload (axis-0) refs
    victims = sorted({o for p in res.values() for o in p})[:2]
    for o in victims:
        sim.kill_osd(o)
        sim.out_osd(o)
    st = sim.recover_all(2)
    assert st["shards_rebuilt"] > 0, st
    for i, nm in enumerate(names):
        assert sim.get(2, nm) == np.asarray(
            payload[i * S:(i + 1) * S]).tobytes(), nm
