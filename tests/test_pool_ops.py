"""Live pool create/rm + osd in through the mon quorum.

Reference roles: OSDMonitor::prepare_new_pool / prepare_pool_op
(`ceph osd pool create/rm`), `ceph osd in` — pool lifecycle rides
committed map incrementals so every subscriber learns it atomically.
"""
import io

import pytest

from ceph_tpu.tools.ceph_cli import main as ceph_main
from ceph_tpu.tools.vstart import Vstart, build_cluster_dir

N_OSDS = 4


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("poolops") / "cluster")
    build_cluster_dir(d, n_osds=N_OSDS, osds_per_host=2, fsync=False)
    v = Vstart(d)
    v.start(N_OSDS, hb_interval=0.25)
    yield d, v
    v.stop()


def run_ceph(d, *words):
    out = io.StringIO()
    rc = ceph_main(["--dir", d, *words], out=out)
    return rc, out.getvalue()


def test_pool_create_io_and_rm(cluster):
    d, v = cluster
    from ceph_tpu.client.remote import RemoteCluster
    rc, txt = run_ceph(d, "osd", "pool", "create", "bucketdata", "8")
    assert rc == 0 and "created" in txt
    rc, txt = run_ceph(d, "osd", "pool", "ls")
    assert "bucketdata" in txt.splitlines()
    # the new pool serves I/O immediately (map incremental reached
    # daemons and clients)
    c = RemoteCluster(d)
    new_pid = next(p.id for p in c.osdmap.pools.values()
                   if p.name == "bucketdata")
    assert c.put(new_pid, "obj", b"fresh-pool" * 50) >= 2
    assert c.get(new_pid, "obj") == b"fresh-pool" * 50
    # same-spec re-create is idempotent (a retried lost-reply create
    # must not report failure); a DIFFERENT spec conflicts
    rc, txt = run_ceph(d, "osd", "pool", "create", "bucketdata", "8")
    assert rc == 0 and "already exists" in txt
    rc, txt = run_ceph(d, "osd", "pool", "create", "bucketdata", "32")
    assert rc == 1 and "different spec" in txt
    # removal propagates too, and is idempotent
    rc, txt = run_ceph(d, "osd", "pool", "rm", "bucketdata")
    assert rc == 0
    rc, txt = run_ceph(d, "osd", "pool", "rm", "bucketdata")
    assert rc == 0
    c.refresh_map()
    assert all(p.name != "bucketdata" for p in c.osdmap.pools.values())

    # a NEW pool never reuses the dead pool's id, so it can never see
    # its data (code-review finding: id reuse exposed deleted objects)
    rc, txt = run_ceph(d, "osd", "pool", "create", "successor", "8")
    assert rc == 0
    c.refresh_map()
    succ = next(p.id for p in c.osdmap.pools.values()
                if p.name == "successor")
    assert succ > new_pid
    assert c.list_objects(succ) == []
    from ceph_tpu.client.remote import RemoteObjectMissing
    with pytest.raises((RemoteObjectMissing, IOError)):
        c.get(succ, "obj")
    # OSD stores purge the dead pool's collections (map-driven PG
    # teardown) within a few heartbeat intervals
    import time
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if all(not c.osd_call(o, {"cmd": "list_pg",
                                  "coll": [new_pid, pg]})
               for o in range(N_OSDS) for pg in range(8)):
            break
        time.sleep(0.5)
    leftovers = [c.osd_call(o, {"cmd": "list_pg",
                                "coll": [new_pid, pg]})
                 for o in range(N_OSDS) for pg in range(8)]
    assert all(not x for x in leftovers), leftovers
    run_ceph(d, "osd", "pool", "rm", "successor")
    c.close()


def test_pool_survives_mon_restart(cluster):
    """A pool committed via incrementals must replay from the mon
    store on restart (Monitor.open catch-up)."""
    d, v = cluster
    rc, txt = run_ceph(d, "osd", "pool", "create", "durablepool", "8")
    assert rc == 0
    v.kill9("mon.0")
    v.start_mon(0)
    rc, txt = run_ceph(d, "osd", "pool", "ls")
    assert "durablepool" in txt.splitlines()
    run_ceph(d, "osd", "pool", "rm", "durablepool")


def test_osd_out_and_in(cluster):
    d, v = cluster
    rc, _ = run_ceph(d, "osd", "out", "2")
    assert rc == 0
    from ceph_tpu.client.remote import RemoteCluster
    c = RemoteCluster(d)
    assert int(c.osdmap.osd_weight[2]) == 0
    rc, _ = run_ceph(d, "osd", "in", "2")
    assert rc == 0
    c.refresh_map()
    assert int(c.osdmap.osd_weight[2]) == 0x10000
    c.close()
