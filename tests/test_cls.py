"""In-OSD object classes: lock + refcount (src/cls/ + ClassHandler)."""
import json

import pytest

from ceph_tpu.cluster.class_handler import ClsError
from tests.test_snaps import make_sim


@pytest.fixture(scope="module")
def sim():
    return make_sim()


def _lock(sim, oid, name, typ="exclusive", cookie=""):
    return sim.exec_cls(1, oid, "lock", "lock", json.dumps(
        {"name": name, "type": typ, "cookie": cookie}).encode())


def test_exclusive_lock_contention(sim):
    _lock(sim, "locked", "client-a")
    with pytest.raises(ClsError):
        _lock(sim, "locked", "client-b")
    info = json.loads(sim.exec_cls(1, "locked", "lock", "info").decode())
    assert info["type"] == "exclusive"
    assert info["holders"] == [{"name": "client-a", "cookie": ""}]
    # unlock by the wrong holder fails; right holder succeeds
    with pytest.raises(ClsError):
        sim.exec_cls(1, "locked", "lock", "unlock",
                     json.dumps({"name": "client-b"}).encode())
    sim.exec_cls(1, "locked", "lock", "unlock",
                 json.dumps({"name": "client-a"}).encode())
    _lock(sim, "locked", "client-b")        # now free


def test_shared_locks_and_break(sim):
    _lock(sim, "shared", "r1", typ="shared")
    _lock(sim, "shared", "r2", typ="shared")
    with pytest.raises(ClsError):
        _lock(sim, "shared", "w1", typ="exclusive")
    # break_lock evicts a dead client (the recovery path)
    sim.exec_cls(1, "shared", "lock", "break_lock",
                 json.dumps({"name": "r1"}).encode())
    info = json.loads(sim.exec_cls(1, "shared", "lock", "info").decode())
    assert [h["name"] for h in info["holders"]] == ["r2"]


def test_refcount_lifecycle(sim):
    sim.put(1, "counted", b"shared payload")
    assert sim.exec_cls(1, "counted", "refcount", "get", b"tagA") == b"1"
    assert sim.exec_cls(1, "counted", "refcount", "get", b"tagB") == b"2"
    assert json.loads(sim.exec_cls(1, "counted", "refcount",
                                   "read").decode()) == ["tagA", "tagB"]
    assert sim.exec_cls(1, "counted", "refcount", "put", b"tagA") == b"1"
    # last put removes the object on the primary (in-OSD delete)
    assert sim.exec_cls(1, "counted", "refcount", "put", b"tagB") == b"0"
    pool = sim.osdmap.pools[1]
    pg = sim.object_pg(pool, "counted")
    up = sim.pg_up(pool, pg)
    assert not sim.osds[up[0]].objectstore.exists((1, pg), "0:counted")


def test_unknown_method_rejected(sim):
    with pytest.raises(ClsError):
        sim.exec_cls(1, "x", "nope", "nothing")


def test_librados_exec_surface(sim):
    from ceph_tpu.client.rados import Rados
    from ceph_tpu.cluster.monitor import Monitor
    ioctx = Rados(sim, Monitor(sim.osdmap)).connect().open_ioctx("rep")
    _lock(sim, "via-api", "x")
    info = json.loads(ioctx.exec("via-api", "lock", "info").decode())
    assert info["holders"][0]["name"] == "x"
