"""DR drill — two-zone sever/failover/heal with a hard gate.

ISSUE 18 (c): the drill converges on seeds 0-1 (sim tier) and under
the composed kill+powercycle chaos inside zone A (live tier), the
gate is provably falsifiable (one seeded lost-bilog entry turns it
red), and the seeded workload schedule is same-seed deterministic.
The smoke marker rides scripts/check_dr.py so CI covers the script
path without a separate job.
"""
import io

import pytest

from ceph_tpu.cluster.dr_drill import (DrillConfig, drill_main,
                                       run_drill)
from ceph_tpu.common import faults


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.reset()


@pytest.mark.parametrize("seed", [0, 1])
def test_dr_drill_green(seed):
    """Sever -> failover -> heal -> converge, gated HARD: every acked
    ETag readable in both zones, zero double-applies, zero full-sync
    restarts, lag p99 read from merged histograms, the sever
    provably bit, and the mid-catch-up reshard cut a generation."""
    r = run_drill(DrillConfig(seed=seed))
    assert r["ok"], r["failures"]
    assert r["converged"] and r["sever_verified"] and r["resharded"]
    assert r["lag_samples"] > 0 and r["lag_p99_s"] is not None
    assert sum(a["double_applies"] for a in r["agents"].values()) == 0
    assert sum(a["full_syncs"] for a in r["agents"].values()) == 0
    assert sum(a["gen_cutovers"] for a in r["agents"].values()) >= 1


def test_dr_drill_schedule_deterministic():
    """Same seed, same drill: the workload schedule digest (every
    (phase, zone, op, key, size) tuple) reproduces exactly."""
    cfg = dict(seed=5, phase_ops=12, keys=8, reshard_to=0)
    a = run_drill(DrillConfig(**cfg))
    b = run_drill(DrillConfig(**cfg))
    assert a["schedule_digest"] == b["schedule_digest"]
    assert a["ok"] and b["ok"]
    # and a different seed actually yields a different schedule
    c = run_drill(DrillConfig(seed=6, phase_ops=12, keys=8,
                              reshard_to=0))
    assert c["schedule_digest"] != a["schedule_digest"]


def test_dr_drill_falsifiable_on_lost_bilog():
    """One acked write whose bilog append is seeded away MUST turn
    the convergence gate red (exit nonzero, naming the lost key) —
    a gate that cannot fail proves nothing."""
    buf = io.StringIO()
    rc = drill_main(["--seed", "0", "--lose-bilog"], out=buf)
    assert rc != 0
    assert "lost-canary" in buf.getvalue()


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1])
def test_dr_drill_chaos_live_zone(seed):
    """The composed soak: zone A runs live OSD daemons and eats a
    kill9 AND a powercycle (power_loss + torn WAL + reboot) during
    cross-zone catch-up; the same hard gate must still hold.  Slow
    tier (live daemons, ~14 s/seed) like the thrasher soaks."""
    r = run_drill(DrillConfig(seed=seed, chaos=True))
    assert r["ok"], r["failures"]
    assert len(r["chaos"]) == 2, r["chaos"]
    assert {k for k, _ in r["chaos"]} == {"kill", "powercycle"}
    assert sum(a["double_applies"] for a in r["agents"].values()) == 0


@pytest.mark.smoke
def test_check_dr_smoke():
    """The CI smoke (scripts/check_dr.py riding pytest): the cheap
    determinism leg here; the green/falsifiable legs run as the
    dedicated tests above (the script builds its own zones when run
    standalone)."""
    import scripts.check_dr as cd
    assert cd._check_determinism() == 0
