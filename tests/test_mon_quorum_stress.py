"""Threaded quorum stress: concurrent elections racing commit traffic.

The VERDICT r4 consensus-safety contract: with candidates campaigning
from multiple threads, partitions coming and going, and client commit
traffic in flight, there must be EXACTLY ONE committed history — for
every version, all ranks hold the same value; every acknowledged
propose survives at exactly one version; applies happen in version
order on every rank.  Reference: src/mon/Paxos.h:57-88 (collect /
begin / commit with the mandatory phase-2 re-accept on recovery),
src/mon/Elector.h:37 (one persisted vote per epoch).

The prior quorum tests (test_mon_quorum.py) are single-threaded and
sequential; this file is the adversarial-interleaving tier.
"""
import random
import threading
import time
from typing import Dict, List, Tuple

from ceph_tpu.cluster.kv import MemDB
from ceph_tpu.cluster.mon_quorum import (NotLeader, QuorumNode,
                                         decode_decree, encode_decree)

N = 5
RUN_SECONDS = 2.5


class ChaosNet:
    """In-process wire with injected delays and partitions."""

    def __init__(self, seed: int):
        self.nodes: Dict[int, QuorumNode] = {}
        self.down = set()
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()

    def send(self, rank, msg):
        with self._rng_lock:
            delay = self._rng.random() * 0.002
            unreachable = rank in self.down
        if delay > 0.0005:
            time.sleep(delay)
        if unreachable or rank not in self.nodes:
            raise IOError(f"mon.{rank} unreachable")
        return self.nodes[rank].handle(msg)


def _build(seed: int):
    net = ChaosNet(seed)
    applied: Dict[int, List[Tuple[int, bytes]]] = {r: [] for r in
                                                   range(N)}
    for r in range(N):
        def mk_apply(rr):
            return lambda v, blob: applied[rr].append((v, bytes(blob)))
        net.nodes[r] = QuorumNode(r, N, MemDB(), mk_apply(r), net.send)
    return net, applied


def _log_of(node: QuorumNode) -> List[bytes]:
    return [node._get_entry(v) for v in range(1, node.committed + 1)]


def test_concurrent_elections_one_history():
    seed = 20260731
    net, applied = _build(seed)
    stop = threading.Event()
    acked: List[bytes] = []
    acked_lock = threading.Lock()
    counter = [0]

    def elector(rank: int):
        rng = random.Random(seed * 31 + rank)
        node = net.nodes[rank]
        while not stop.is_set():
            time.sleep(rng.random() * 0.08)
            # campaign when leaderless, and occasionally out of spite
            # (the concurrent-candidate interleavings under test)
            if node.leader is None or rng.random() < 0.25:
                try:
                    node.start_election()
                except Exception:
                    pass

    def client(cid: int):
        rng = random.Random(seed * 77 + cid)
        while not stop.is_set():
            time.sleep(rng.random() * 0.02)
            leaders = [n for n in net.nodes.values()
                       if n.leader == n.rank]
            if not leaders:
                continue
            node = rng.choice(leaders)
            with acked_lock:
                counter[0] += 1
                val = encode_decree("x", n=counter[0], c=cid)
            try:
                ok = node.propose(val)
            except (NotLeader, Exception):
                continue
            if ok:
                with acked_lock:
                    acked.append(val)

    def partitioner():
        rng = random.Random(seed * 13)
        while not stop.is_set():
            time.sleep(rng.random() * 0.15)
            # partition a strict minority so progress stays possible
            sz = rng.randint(0, (N - 1) // 2)
            cut = set(rng.sample(range(N), sz))
            with net._rng_lock:
                net.down = cut
            time.sleep(rng.random() * 0.15)
            with net._rng_lock:
                net.down = set()

    threads = ([threading.Thread(target=elector, args=(r,))
                for r in range(N)] +
               [threading.Thread(target=client, args=(c,))
                for c in range(2)] +
               [threading.Thread(target=partitioner)])
    for t in threads:
        t.start()
    time.sleep(RUN_SECONDS)
    stop.set()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive(), "stress thread hung"
    net.down = set()

    # convergence: elect a stable leader and land one sentinel so
    # every rank syncs to a common committed point
    sentinel = encode_decree("sentinel", n=-1)
    deadline = time.monotonic() + 15
    done = False
    while time.monotonic() < deadline and not done:
        for r in range(N):
            node = net.nodes[r]
            try:
                if node.start_election() and node.propose(sentinel):
                    node.start_election()    # victory syncs laggards
                    done = True
                    break
            except Exception:
                continue
    assert done, "cluster failed to converge after chaos stopped"

    logs = {r: _log_of(net.nodes[r]) for r in range(N)}
    commits = {r: net.nodes[r].committed for r in range(N)}
    # 1. exactly one committed history: common prefix is identical
    floor = min(commits.values())
    for v in range(floor):
        vals = {r: logs[r][v] for r in range(N)}
        assert len(set(vals.values())) == 1, \
            f"version {v + 1} diverged: " + repr({
                r: decode_decree(b) for r, b in vals.items()})
    # ...and beyond the floor, any rank that HAS a committed version
    # agrees with every other rank that has it
    ceil = max(commits.values())
    for v in range(floor, ceil):
        vals = {r: logs[r][v] for r in range(N) if commits[r] > v}
        assert len(set(vals.values())) == 1, f"tail {v + 1} diverged"
    # 2. every acknowledged propose survived, exactly once, on every
    # rank that reached it
    full = logs[max(commits, key=commits.get)]
    for val in acked:
        assert full.count(val) == 1, \
            f"acked value lost/duplicated: {decode_decree(val)}"
    # sentinel landed
    assert full.count(sentinel) == 1
    # 3. applies happened strictly in version order with the committed
    # values (no thread interleaving reordered or double-applied)
    for r in range(N):
        versions = [v for v, _ in applied[r]]
        assert versions == sorted(set(versions)), \
            f"rank {r} applied out of order: {versions[:20]}..."
        for v, blob in applied[r]:
            assert logs[r][v - 1] == blob, \
                f"rank {r} applied a value that is not the log's v{v}"


def test_deposed_leader_commit_refused_by_epoch():
    """The r4 docstring claim, now true: a deposed leader's COMMIT
    (not just begin) carries a stale epoch and is refused."""
    net, applied = _build(7)
    net_nodes = net.nodes
    assert net_nodes[0].start_election()
    e_old = net_nodes[0].election_epoch
    # depose rank 0 without it noticing
    net.down.add(0)
    assert any(net_nodes[1].start_election() for _ in range(3))
    net.down.discard(0)
    # old leader pushes a commit with its stale epoch straight at a
    # peer: must be ignored (no commit, no apply)
    stale = encode_decree("stale", n=9)
    net_nodes[2].handle({"q": "commit", "epoch": e_old, "version": 1,
                         "value": stale, "leader": 0})
    assert net_nodes[2].committed == 0
    assert applied[2] == []


def test_collect_reaccepts_under_new_epoch():
    """The recovered tail is re-accepted on a majority with the NEW
    epoch before committing: after recovery, the surviving acceptors
    hold the entry stamped with the recovering leader's epoch."""
    net, applied = _build(11)
    nodes = net.nodes
    assert nodes[0].start_election()
    e1 = nodes[0].election_epoch
    value = encode_decree("acked", n=42)
    # leader stores + wins majority accepts, dies before any commit
    nodes[0]._store_entry(1, value, e1)
    for r in (1, 2):
        assert nodes[r].handle({"q": "begin", "epoch": e1,
                                "version": 1, "value": value,
                                "leader": 0})["accepted"]
    net.down.add(0)
    assert any(nodes[3].start_election() for _ in range(3))
    e2 = nodes[3].election_epoch
    assert e2 > e1
    # recovered AND committed everywhere reachable
    for r in (1, 2, 3, 4):
        assert nodes[r].committed == 1
        assert nodes[r]._get_entry(1) == value
    # the acceptors' stored epoch for v1 is the NEW epoch (the
    # re-accept round ran), not the old one
    assert nodes[3]._entry_epoch(1) == e2
    reaccepted = [r for r in (1, 2, 4)
                  if nodes[r]._entry_epoch(1) == e2]
    assert len(reaccepted) + 1 >= nodes[3].quorum(), \
        "re-accept under the new epoch did not reach a majority"


def test_minority_tail_cannot_split_history():
    """The exact divergence the r4 review called out: two successive
    recoveries of DIFFERENT minority tails at the same version must
    not commit both.  With phase-2 re-accept, the first recovery
    stamps its choice on a majority at the new epoch, so the second
    recovery is forced to the same value."""
    net, applied = _build(23)
    nodes = net.nodes
    # epoch e1: rank0 self-accepts A at v1, reaches only rank1
    assert nodes[0].start_election()
    e1 = nodes[0].election_epoch
    a = encode_decree("A", n=1)
    nodes[0]._store_entry(1, a, e1)
    assert nodes[1].handle({"q": "begin", "epoch": e1, "version": 1,
                            "value": a, "leader": 0})["accepted"]
    # rank0+1 vanish; rank2 wins e2, self-accepts B at v1, reaches
    # only rank3, then 2+3 vanish too (B is a higher-epoch minority
    # tail than A)
    net.down |= {0, 1}
    assert any(nodes[2].start_election() for _ in range(3))
    e2 = nodes[2].election_epoch
    b = encode_decree("B", n=2)
    nodes[2]._store_entry(1, b, e2)
    assert nodes[3].handle({"q": "begin", "epoch": e2, "version": 1,
                            "value": b, "leader": 2})["accepted"]
    net.down = {2, 3}
    # recovery #1: rank1 campaigns with {0,1,4} — sees only A
    assert any(nodes[1].start_election() for _ in range(5))
    assert nodes[1].committed == 1
    first = nodes[1]._get_entry(1)
    assert first == a
    # recovery #2: full network back; rank 4 campaigns with everyone,
    # including rank2/3 whose B-tail has the higher ACCEPT epoch —
    # but A was re-accepted at a newer epoch still, so A must win
    net.down = set()
    assert any(nodes[4].start_election() for _ in range(5))
    for r in range(N):
        assert nodes[r].committed == 1
        assert nodes[r]._get_entry(1) == first, \
            f"rank {r} committed a second value at v1"
