"""neorados async client + dashboard mgr module.

Reference roles: src/neorados/ (asio-native async RADOS API),
src/pybind/mgr/dashboard (REST API layer).
"""
import asyncio
import http.client
import json

import pytest

from ceph_tpu.client.neorados import AsyncRados
from ceph_tpu.client.rados import ObjectNotFound, Rados
from ceph_tpu.cluster.monitor import Monitor
from ceph_tpu.mgr import MgrModuleHost
from ceph_tpu.mgr import dashboard_module
from tests.test_snaps import make_sim


def test_async_rados_over_sim():
    sim = make_sim()
    rados = Rados(sim, Monitor(sim.osdmap)).connect()

    async def flow():
        async with AsyncRados(rados) as ar:
            io = await ar.open_ioctx("rep")
            await io.write_full("a", b"alpha")
            # concurrent fan-out (the neorados point): 16 writes then
            # 16 reads gathered at once
            await asyncio.gather(*[
                io.write_full(f"o{i}", bytes([i]) * 64)
                for i in range(16)])
            datas = await asyncio.gather(*[io.read(f"o{i}")
                                           for i in range(16)])
            assert [d[:1] for d in datas] == \
                [bytes([i]) for i in range(16)]
            assert await io.read("a") == b"alpha"
            st = await io.stat("a")
            assert st.size == 5
            names = await io.list_objects()
            assert "a" in names and "o7" in names
            await io.remove("a")
            with pytest.raises(ObjectNotFound):
                await io.read("a")

    asyncio.run(flow())


def test_async_rados_over_daemons(tmp_path):
    """Same awaitable surface against a real process cluster."""
    from ceph_tpu.client.remote import RemoteCluster
    from ceph_tpu.tools.vstart import Vstart, build_cluster_dir
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=4, osds_per_host=2, fsync=False)
    v = Vstart(d)
    v.start(4, hb_interval=0.25)
    try:
        rc = RemoteCluster(d)

        async def flow():
            async with AsyncRados(rc) as ar:
                io = await ar.open_ioctx("rep")
                await asyncio.gather(*[
                    io.write_full(f"w{i}", bytes([i]) * 256)
                    for i in range(8)])
                datas = await asyncio.gather(*[io.read(f"w{i}")
                                               for i in range(8)])
                assert all(datas[i] == bytes([i]) * 256
                           for i in range(8))

        asyncio.run(flow())
        rc.close()
    finally:
        v.stop()


def test_dashboard_api():
    sim = make_sim()
    host = MgrModuleHost(sim)
    dashboard_module.register(host)
    dash = host.enable("dashboard")
    sim.put(1, "obj", b"z" * 500)
    port = dash.start_http()
    try:
        def get(path):
            # 60s: /api/pgs compiles the batched mapper on first
            # hit — a cold-cache compile on a 1-core host blows a
            # 10s budget (pre-existing flake)
            c = http.client.HTTPConnection("127.0.0.1", port,
                                           timeout=60)
            c.request("GET", path)
            r = c.getresponse()
            body = r.read()
            c.close()
            return r.status, body
        st, body = get("/api/summary")
        s = json.loads(body)
        assert st == 200 and s["health"]["status"] == "HEALTH_OK"
        assert "dashboard" in s["mgr_modules"]
        st, body = get("/api/pools")
        pools = json.loads(body)
        assert any(p["objects"] >= 1 for p in pools)
        st, body = get("/api/osds")
        assert all(o["up"] for o in json.loads(body))
        # health flips on a kill
        sim.kill_osd(0)
        st, body = get("/api/health")
        h = json.loads(body)
        assert h["status"] == "HEALTH_WARN" and h["checks"]
        sim.revive_osd(0)
        st, body = get("/")
        assert st == 200 and b"dashboard" in body
        assert get("/api/nope")[0] == 404
    finally:
        dash.stop_http()


def test_dashboard_pg_perf_crush_config():
    """The r5 dashboard endpoints: PG state rollup reacts to a kill,
    perf carries live counters, crush shows the tree, config carries
    provenance."""
    sim = make_sim()
    host = MgrModuleHost(sim)
    dashboard_module.register(host)
    dash = host.enable("dashboard")
    sim.put(1, "obj", b"z" * 500)
    port = dash.start_http()
    try:
        def get(path):
            c = http.client.HTTPConnection("127.0.0.1", port,
                                           timeout=60)
            c.request("GET", path)
            r = c.getresponse()
            body = r.read()
            c.close()
            return r.status, json.loads(body)
        st, pgs = get("/api/pgs")
        assert st == 200
        total = sum(pgs["states"].values())
        assert total == sum(len(v) for v in pgs["pgs"].values())
        assert pgs["states"]["active+clean"] == total
        # a killed OSD leaves holes in every PG mapping it: the map
        # pipeline reports them as undersized+degraded
        sim.kill_osd(0)
        _, pgs2 = get("/api/pgs")
        assert pgs2["states"]["active+undersized+degraded"] > 0
        # EC shard positions survive as nulls so the missing SHARD is
        # identifiable (ceph pg dump keeps NONE in place)
        assert any(r["state"] == "active+undersized+degraded" and
                   None in r["up"]
                   for rows in pgs2["pgs"].values() for r in rows)
        sim.revive_osd(0)
        # every OSD down -> PGs report DOWN, not active-anything
        for o in range(len(sim.osds)):
            sim.osdmap.mark_down(o)
        _, pgs3 = get("/api/pgs")
        assert pgs3["states"]["down"] > 0
        assert pgs3["states"]["active+clean"] == 0
        for o in range(len(sim.osds)):
            sim.osdmap.osd_up[o] = True
        sim.osdmap.bump_epoch()
        st, perf = get("/api/perf")
        assert st == 200 and isinstance(perf, dict) and perf
        st, crush = get("/api/crush")
        assert st == 200 and any("host" in ln for ln in crush["tree"])
        st, cfg = get("/api/config")
        assert st == 200
        assert "erasure_code_default_layout" in cfg
        assert cfg["erasure_code_default_layout"]["value"] == \
            "bitsliced"
        assert "source" in cfg["erasure_code_default_layout"] or \
            any("default" in str(v).lower()
                for v in cfg["erasure_code_default_layout"].values())
    finally:
        dash.stop_http()
