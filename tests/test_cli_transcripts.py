"""Golden CLI transcripts — cram-style byte-exact pinning.

The reference pins `crushtool`/`osdmaptool` behavior with ~60 cram `.t`
files (src/test/cli/crushtool/*.t: lines `  $ cmd` followed by the
expected stdout, byte-exact).  Same format here: transcripts live in
tests/cli/*.t, run with CWD tests/cli so data file paths are relative.

Regenerate after an intentional output change with:
    CEPH_TPU_REGEN_TRANSCRIPTS=1 python -m pytest tests/test_cli_transcripts.py
"""
import os
import subprocess
import sys
from pathlib import Path

import pytest

CLI_DIR = Path(__file__).parent / "cli"
TRANSCRIPTS = sorted(CLI_DIR.glob("*.t"))
REGEN = os.environ.get("CEPH_TPU_REGEN_TRANSCRIPTS") == "1"


def parse_transcript(text):
    """-> list of (command, expected_output_lines)."""
    blocks = []
    cmd = None
    out = []
    for line in text.splitlines():
        if line.startswith("  $ "):
            if cmd is not None:
                blocks.append((cmd, out))
            cmd = line[4:]
            out = []
        elif line.startswith("  > ") and cmd is not None and not out:
            cmd += "\n" + line[4:]
        elif line.startswith("  ") and cmd is not None:
            out.append(line[2:])
        # comment / blank lines between blocks are ignored
    if cmd is not None:
        blocks.append((cmd, out))
    return blocks


def run_command(cmd: str) -> str:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    root = str(Path(__file__).parent.parent)
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        cmd, shell=True, cwd=str(CLI_DIR), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, timeout=600)
    return proc.stdout


@pytest.mark.parametrize("path", TRANSCRIPTS,
                         ids=[p.name for p in TRANSCRIPTS])
def test_transcript(path):
    text = path.read_text()
    blocks = parse_transcript(text)
    assert blocks, f"{path.name}: no command blocks"
    if REGEN:
        lines = []
        for cmd, _ in blocks:
            first, *rest = cmd.split("\n")
            lines.append(f"  $ {first}")
            lines.extend(f"  > {r}" for r in rest)
            got = run_command(cmd)
            lines.extend("  " + ln for ln in got.splitlines())
            lines.append("")
        path.write_text("\n".join(lines).rstrip("\n") + "\n")
        pytest.skip(f"regenerated {path.name}")
    for cmd, expected in blocks:
        got = run_command(cmd).splitlines()
        assert got == expected, (
            f"{path.name}: transcript mismatch for {cmd!r}\n"
            f"--- expected ---\n" + "\n".join(expected) +
            "\n--- got ---\n" + "\n".join(got))
