"""KeyValueDB abstraction + RBD-style block images.

Reference surfaces: src/kv/KeyValueDB.h + memdb, src/librbd/ (image
directory, header objects, striped data objects, resize/trim)."""
import numpy as np
import pytest

from ceph_tpu.client import Rados
from ceph_tpu.client.rbd import RBD, Image, ImageExists, ImageNotFound
from ceph_tpu.cluster.kv import MemDB, WriteBatch
from ceph_tpu.cluster.monitor import Monitor
from tests.test_simulator import make_sim


# ------------------------------------------------------------------- kv ----

def test_kv_batch_and_iterate():
    db = MemDB()
    db.submit(WriteBatch().set("osdmap", "3", b"e3")
              .set("osdmap", "1", b"e1").set("osdmap", "2", b"e2")
              .set("config", "a", b"x"))
    assert db.get("osdmap", "2") == b"e2"
    assert db.keys("osdmap") == ["1", "2", "3"]       # ordered
    assert [k for k, _ in db.iterate("osdmap", start="2")] == ["2", "3"]
    db.submit(WriteBatch().rm("osdmap", "1"))
    assert not db.exists("osdmap", "1")
    db.submit(WriteBatch().rm_prefix("osdmap"))
    assert db.keys("osdmap") == []
    assert db.get("config", "a") == b"x"              # other prefix safe


def test_kv_prefixes_isolated():
    db = MemDB()
    db.set("p1", "k", b"1")
    db.set("p2", "k", b"2")
    assert db.get("p1", "k") == b"1" and db.get("p2", "k") == b"2"


# ------------------------------------------------------------------ rbd ----

@pytest.fixture()
def ioctx():
    sim = make_sim()
    mon = Monitor(sim.osdmap)
    return Rados(sim, mon).connect().open_ioctx("ec")


def test_rbd_create_list_remove(ioctx):
    rbd = RBD(ioctx)
    rbd.create("img1", size=1 << 20, order=16)    # 64 KiB objects
    rbd.create("img2", size=1 << 18, order=16)
    assert rbd.list() == ["img1", "img2"]
    with pytest.raises(ImageExists):
        rbd.create("img1", size=1)
    rbd.remove("img2")
    assert rbd.list() == ["img1"]
    with pytest.raises(ImageNotFound):
        rbd.remove("img2")
    with pytest.raises(ImageNotFound):
        Image(ioctx, "img2")


def test_rbd_io_across_object_boundaries(ioctx):
    rbd = RBD(ioctx)
    rbd.create("disk", size=1 << 20, order=16)
    img = Image(ioctx, "disk")
    rng = np.random.default_rng(3)
    blob = rng.integers(0, 256, size=200_000).astype(np.uint8).tobytes()
    off = (1 << 16) - 777                # straddles several 64K objects
    img.write(off, blob)
    assert img.read(off, len(blob)) == blob
    # sparse region reads as zeros
    assert img.read(0, 100) == b"\0" * 100
    # overwrite inside
    img.write(off + 1000, b"PATCH")
    got = img.read(off, len(blob))
    want = bytearray(blob)
    want[1000:1005] = b"PATCH"
    assert got == bytes(want)
    with pytest.raises(ValueError):
        img.write((1 << 20) - 2, b"toolong")


def test_rbd_resize(ioctx):
    rbd = RBD(ioctx)
    rbd.create("vol", size=1 << 18, order=16)     # 4 x 64K objects
    img = Image(ioctx, "vol")
    img.write(0, b"head")
    img.write((1 << 18) - 8, b"tail-end")
    img.resize(1 << 16)                           # shrink to 1 object
    assert img.size() == 1 << 16
    img2 = Image(ioctx, "vol")                    # reopen: persisted
    assert img2.size() == 1 << 16
    assert img2.read(0, 4) == b"head"
    img2.resize(1 << 18)                          # grow again
    # trimmed range is sparse zeros now
    assert img2.read((1 << 18) - 8, 8) == b"\0" * 8


def test_monitor_persists_to_kv():
    """Monitor commits land in the MonitorDBStore prefixes."""
    import json as _json
    sim = make_sim()
    mon = Monitor(sim.osdmap)
    inc = mon.next_incremental()
    inc.new_up[3] = False
    assert mon.commit_incremental(inc)
    mon.config_set("fastmap_extra_tries", 5)
    from ceph_tpu.common import config
    from ceph_tpu.common.options import LEVEL_FILE
    config().clear("fastmap_extra_tries", LEVEL_FILE)
    epochs = mon.db.keys("osdmap")
    assert len(epochs) == 1
    rec = _json.loads(mon.db.get("osdmap", epochs[0]).decode())
    assert rec["new_up"] == {"3": False}
    assert _json.loads(mon.db.get("config",
                                  "fastmap_extra_tries").decode()) == 5
    assert len(mon.db.keys("paxos")) == mon.paxos.version


def test_rbd_prefix_overlap_and_unaligned_shrink(ioctx):
    """Image names where one is a dot-prefix of another must not
    interfere, and a non-aligned shrink zero-truncates the boundary
    object (no stale bytes after a later grow)."""
    rbd = RBD(ioctx)
    rbd.create("a", size=1 << 18, order=16)
    rbd.create("a.b", size=1 << 18, order=16)
    img_ab = Image(ioctx, "a.b")
    img_ab.write(0, b"dotted")
    rbd.remove("a")                     # must not trip over a.b's oids
    assert Image(ioctx, "a.b").read(0, 6) == b"dotted"
    # unaligned shrink
    rbd.create("v", size=1 << 18, order=16)
    img = Image(ioctx, "v")
    img.write((1 << 16), b"X" * 5000)   # object 1 bytes 0..5000
    img.resize((1 << 16) + 100)         # keep 100 bytes of object 1
    img.resize(1 << 18)
    assert img.read((1 << 16) + 100, 200) == b"\0" * 200
    assert img.read(1 << 16, 100) == b"X" * 100
