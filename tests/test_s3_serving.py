"""S3Serve (ISSUE 14): sharded bucket indexes, per-tenant QoS, the
serving harness's SLO gate, and the composed-chaos soak.

Tiers covered here:

  * pure units — zipf/op-schedule determinism, the SLO gate's pass
    and failure paths, the dmClock per-tenant reservation floor;
  * in-process gateway — listing identity across shard counts,
    measured hot-bucket op-concurrency (1 shard serializes, N shards
    overlap), online reshard;
  * live daemons — `radosgw-admin bucket reshard` / `bucket limit
    check` over a process cluster, the serve smoke (gate green +
    falsifiable, `smoke` marker), and the composed
    netsplit+powercycle+kill chaos soak (seeds 0-1, zero
    acked-write loss).
"""
import io
import json
import threading
import time

import pytest

from ceph_tpu.msg.scheduler import MClockScheduler, QoS, tenant_class
from ceph_tpu.rgw.serving import (ServeConfig, TenantSpec, ZipfKeys,
                                  default_tenants, draw_op,
                                  evaluate_gate, run_serve,
                                  worker_rngs)


# ------------------------------------------------------------- zipf --

def test_zipf_same_seed_identical_sequence():
    a = ZipfKeys(64, 0.99, seed=7)
    b = ZipfKeys(64, 0.99, seed=7)
    seq_a = [a.next_index() for _ in range(500)]
    seq_b = [b.next_index() for _ in range(500)]
    assert seq_a == seq_b
    c = ZipfKeys(64, 0.99, seed=8)
    assert seq_a != [c.next_index() for _ in range(500)]


def test_zipf_skews_toward_hot_ranks():
    z = ZipfKeys(64, 0.99, seed=0)
    seq = [z.next_index() for _ in range(4000)]
    assert all(0 <= r < 64 for r in seq)
    # rank 0 must be the clear hot key, and the head must dominate
    counts = [seq.count(r) for r in range(64)]
    assert counts[0] == max(counts)
    assert sum(counts[:8]) > sum(counts[32:])


def test_op_schedule_deterministic_per_seed():
    """Same seed => identical (op, key) sequence per worker AND
    identical per-tenant op mix — the exact production draw
    (serving.draw_op / worker_rngs), not a test re-implementation."""
    t = TenantSpec("alice", clients=3, n_keys=32)

    def schedule(seed, widx, n=200):
        rng, zipf = worker_rngs(seed, t, widx)
        return [draw_op(t, widx, rng, zipf) for _ in range(n)]

    for widx in range(t.clients):
        assert schedule(0, widx) == schedule(0, widx)
    assert schedule(0, 0) != schedule(1, 0)
    # workers draw DIFFERENT schedules (not one stream cloned)
    assert schedule(0, 0) != schedule(0, 1)
    # mutation single-writer slicing: worker w only mutates ranks
    # congruent to w (mod clients)
    for widx in range(t.clients):
        for op, key in schedule(0, widx):
            if op != "get":
                rank = int(key[-5:])
                assert rank % t.clients == widx
    # the op mix is deterministic and covers the whole verb set
    ops = [op for op, _ in schedule(0, 1, n=400)]
    assert {"get", "put", "delete", "multipart"} <= set(ops)


# -------------------------------------------------------------- gate --

def test_gate_green_and_every_failure_path():
    tenants = [TenantSpec("gold", min_share=0.2, slo_p99_s=1.0,
                          slo_p999_s=2.0)]
    good = {"gold": {"p99_s": 0.5, "p999_s": 1.0, "share": 0.5,
                     "attempted": 100, "errors": 0}}
    assert evaluate_gate(good, tenants) == []
    # p99 breach
    b = evaluate_gate({"gold": dict(good["gold"], p99_s=3.0)},
                      tenants)
    assert [x["metric"] for x in b] == ["p99_s"]
    # p999 breach
    b = evaluate_gate({"gold": dict(good["gold"], p999_s=9.0)},
                      tenants)
    assert [x["metric"] for x in b] == ["p999_s"]
    # share (QoS floor) breach carries the measured value
    b = evaluate_gate({"gold": dict(good["gold"], share=0.05)},
                      tenants)
    assert b[0]["metric"] == "share" and b[0]["got"] == 0.05
    # error budget
    b = evaluate_gate({"gold": dict(good["gold"], errors=7)},
                      tenants)
    assert b[0]["metric"] == "error_frac"
    # data loss is tenant-agnostic and unconditional
    b = evaluate_gate(good, tenants, data_loss=["k1: gone"])
    assert b[0]["metric"] == "data_loss"


def test_gate_relaxations_scale_latency_and_errors_not_loss():
    tenants = [TenantSpec("t", slo_p99_s=1.0, slo_p999_s=2.0)]
    m = {"t": {"p99_s": 5.0, "p999_s": 9.0, "share": 1.0,
               "attempted": 100, "errors": 5}}
    assert evaluate_gate(m, tenants)                 # strict: fails
    # chaos relaxation: x10 latency + 10% error budget => green...
    assert evaluate_gate(m, tenants, slo_factor=10.0,
                         error_budget=0.10) == []
    # ...but data loss stays a hard zero at ANY relaxation
    assert evaluate_gate(m, tenants, slo_factor=1e9,
                         error_budget=1.0,
                         data_loss=["lost"])


def test_starved_default_profile_is_gate_red_shaped():
    """The --starve profile's whole point: the reserved tenant keeps
    its share floor while losing its QoS — the profile must carry a
    floor that its starved offered-load share cannot meet."""
    starved = {t.name: t for t in default_tenants(starve=True)}
    assert starved["gold"].min_share > 0
    assert starved["gold"].qos_res == 0.0
    assert starved["gold"].clients < starved["bronze"].clients / 4


# ------------------------------------------------- dmClock tenants --

def test_scheduler_tenant_classes_vivify_and_background_raises():
    s = MClockScheduler()
    s.enqueue("a", klass=tenant_class("alice"))      # auto-vivifies
    assert tenant_class("alice") in s.qos
    with pytest.raises(KeyError):
        s.enqueue("x", klass="background_nonsense")


def test_reserved_tenant_holds_floor_under_noisy_backlog():
    """The QoS invariant the harness asserts, deterministically at
    the scheduler: with both tenants holding a deep backlog, the
    reserved tenant's share of dequeue slots stays at (about) its
    reservation — the noisy tenant's 20x weight cannot push it
    below the r floor."""
    s = MClockScheduler()
    s.set_qos(tenant_class("gold"), QoS(reservation=0.4, weight=0.5))
    s.set_qos(tenant_class("noisy"), QoS(reservation=0.0,
                                         weight=10.0))
    for i in range(200):
        s.enqueue(("g", i), klass=tenant_class("gold"))
        s.enqueue(("n", i), klass=tenant_class("noisy"))
    first = [s.dequeue()[0] for _ in range(100)]
    gold = sum(1 for k in first if k == tenant_class("gold"))
    # r=0.4 guarantees ~40 of the first 100 slots; allow slack for
    # tag rounding but the floor must hold
    assert gold >= 35, f"reserved tenant got {gold}/100 slots"
    # and with r=0 the same tenant IS starved by the noisy weight
    s2 = MClockScheduler()
    s2.set_qos(tenant_class("gold"), QoS(reservation=0.0,
                                         weight=0.5))
    s2.set_qos(tenant_class("noisy"), QoS(reservation=0.0,
                                          weight=10.0))
    for i in range(200):
        s2.enqueue(("g", i), klass=tenant_class("gold"))
        s2.enqueue(("n", i), klass=tenant_class("noisy"))
    first2 = [s2.dequeue()[0] for _ in range(100)]
    gold2 = sum(1 for k in first2 if k == tenant_class("gold"))
    assert gold2 < gold, (
        f"removing the reservation did not reduce the share "
        f"({gold2} vs {gold}) — the floor test proves nothing")


# ------------------------------------------- sharded bucket index --

class _SlowDictIoctx:
    """Dict-backed IoCtx whose reads/writes sleep: lock-held index
    RMW windows become measurable, so shard-parallelism shows up as
    wall-clock op-concurrency even under the GIL (sleeps overlap)."""

    def __init__(self, delay=0.004):
        self.objs = {}
        self.delay = delay
        self._lock = threading.Lock()

    def read(self, oid):
        time.sleep(self.delay)
        with self._lock:
            if oid not in self.objs:
                raise KeyError(oid)
            return self.objs[oid]

    def write_full(self, oid, data):
        time.sleep(self.delay)
        with self._lock:
            self.objs[oid] = bytes(data)

    def remove(self, oid):
        with self._lock:
            self.objs.pop(oid, None)

    def list_objects(self):
        with self._lock:
            return sorted(self.objs)


def _hot_bucket_wall(num_shards, n_threads=8, puts=3):
    from ceph_tpu.rgw import RGWGateway
    gw = RGWGateway(_SlowDictIoctx())
    b = gw.create_bucket("hot", num_shards=num_shards)
    errs = []

    def writer(w):
        try:
            for i in range(puts):
                b.put_object(f"w{w}-{i}", b"x" * 64)
        except Exception as e:          # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=writer, args=(w,))
          for w in range(n_threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    assert not errs
    listing = [c["key"]
               for c in b.list_objects(max_keys=1000)["contents"]]
    return wall, listing


def test_hot_bucket_sharding_concurrency_and_listing_identity():
    """The acceptance pair: N index shards admit concurrent writers
    to ONE bucket (measured wall-clock speedup over the 1-shard
    layout, where the single index lock serializes every RMW), and
    listing output is IDENTICAL across shard counts."""
    wall1, listing1 = _hot_bucket_wall(1)
    wall8, listing8 = _hot_bucket_wall(8)
    assert listing1 == listing8
    assert listing1 == sorted(f"w{w}-{i}"
                              for w in range(8) for i in range(3))
    # 8 shards must beat 1 shard clearly; keep slack for scheduler
    # noise (the serialized path is ~8x the critical-section work)
    assert wall8 < wall1 / 1.8, (
        f"no concurrency win: 1 shard {wall1:.3f}s vs "
        f"8 shards {wall8:.3f}s")


def test_shard_placement_stable_and_counts_sum():
    from ceph_tpu.rgw import RGWGateway
    gw = RGWGateway(_SlowDictIoctx(delay=0.0))
    b = gw.create_bucket("b", num_shards=5)
    keys = [f"k{i}" for i in range(60)]
    for k in keys:
        b.put_object(k, b"v")
    counts = b.shard_entry_counts()
    assert sum(counts) == len(keys) and len(counts) == 5
    # every key reads back through its own shard (placement stable)
    for k in keys:
        assert b.get_object(k)[0] == b"v"
    # limit check sees the layout and flags a hot shard
    rows = gw.bucket_limit_check(max_entries_per_shard=10)
    row = next(r for r in rows if r["bucket"] == "b")
    assert row["num_shards"] == 5
    assert row["fill_status"] in ("WARN", "OVER")


def test_online_reshard_preserves_entries_and_redirects_writes():
    from ceph_tpu.rgw import RGWGateway
    gw = RGWGateway(_SlowDictIoctx(delay=0.0))
    b = gw.create_bucket("r", num_shards=1)
    for i in range(30):
        b.put_object(f"k{i:02d}", f"v{i}".encode())
    before = [c["key"]
              for c in b.list_objects(max_keys=1000)["contents"]]
    st = gw.reshard_bucket("r", 4)
    assert st["entries"] == 30 and st["num_shards"] == 4 \
        and st["old_num_shards"] == 1
    nb = gw.bucket("r")
    assert nb.num_shards() == 4
    after = [c["key"]
             for c in nb.list_objects(max_keys=1000)["contents"]]
    assert after == before
    for i in range(30):
        assert nb.get_object(f"k{i:02d}")[0] == f"v{i}".encode()
    # new writes land in the new layout; legacy single-object oid is
    # gone (old generation dropped)
    nb.put_object("post-reshard", b"new")
    assert "rgw.index.r" not in gw.ioctx.objs
    assert sum(nb.shard_entry_counts()) == 31
    # a STALE handle (created pre-reshard) refreshes its layout
    # within the TTL and serves the new generation
    b._LAYOUT_TTL_S = 0.0
    assert b.get_object("post-reshard")[0] == b"new"
    # resharding down also works and stays listing-identical
    gw.reshard_bucket("r", 2)
    nb2 = gw.bucket("r")
    assert [c["key"] for c in
            nb2.list_objects(max_keys=1000)["contents"]] == \
        sorted(before + ["post-reshard"])


# ------------------------------------------------- live daemon CLI --

@pytest.fixture(scope="module")
def serve_cluster(tmp_path_factory):
    from ceph_tpu.tools.vstart import Vstart, build_cluster_dir
    d = str(tmp_path_factory.mktemp("s3serve") / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False,
                      qos_tenants={"gold": {"res": 0.4, "wgt": 2.0,
                                            "lim": 0.0}})
    v = Vstart(d)
    v.start(3, hb_interval=0.25)
    yield d, v
    v.stop()


def test_bucket_reshard_and_limit_check_over_daemons(serve_cluster):
    """The admin/CLI satellite, live: `radosgw-admin bucket reshard`
    + `bucket limit check` against a daemon-backed gateway, wired
    through both radosgw_admin and the `ceph rgw` passthrough."""
    from ceph_tpu.client.remote import RemoteCluster
    from ceph_tpu.client.remote_ioctx import RemoteIoCtx
    from ceph_tpu.rgw import RGWGateway
    from ceph_tpu.tools.ceph_cli import main as ceph_main
    from ceph_tpu.tools.radosgw_admin import main as rgw_main
    d, _v = serve_cluster
    rc = RemoteCluster(d)
    try:
        io_ = RemoteIoCtx(rc, "rep")
        gw = RGWGateway(io_)
        b = gw.create_bucket("wire-shards", num_shards=2)
        for i in range(12):
            b.put_object(f"obj{i:02d}", b"payload-%d" % i)
        buf = io.StringIO()
        assert rgw_main(["bucket", "limit", "check",
                         "--max-entries", "4"],
                        ioctx=io_, out=buf) == 0
        rows = {r["bucket"]: r for r in json.loads(buf.getvalue())}
        assert rows["wire-shards"]["num_shards"] == 2
        assert rows["wire-shards"]["fill_status"] in ("WARN", "OVER")
        buf = io.StringIO()
        assert rgw_main(["bucket", "reshard", "--bucket",
                         "wire-shards", "--num-shards", "6"],
                        ioctx=io_, out=buf) == 0
        st = json.loads(buf.getvalue())
        assert st["entries"] == 12 and st["num_shards"] == 6
        nb = gw.bucket("wire-shards")
        assert [c["key"] for c in
                nb.list_objects(max_keys=100)["contents"]] == \
            [f"obj{i:02d}" for i in range(12)]
        for i in range(12):
            assert nb.get_object(f"obj{i:02d}")[0] == \
                b"payload-%d" % i
        # the `ceph rgw POOL ...` passthrough reaches the same truth
        buf = io.StringIO()
        assert ceph_main(["--dir", d, "rgw", "rep", "bucket",
                          "stats", "--bucket", "wire-shards"],
                         out=buf) == 0
        stats = json.loads(buf.getvalue())
        assert stats["wire-shards"]["num_objects"] == 12
        assert stats["wire-shards"]["num_shards"] == 6
    finally:
        rc.close()


def test_tenant_identity_reaches_daemon_scheduler(serve_cluster):
    """S3-auth-shaped tenant identity propagates client -> objecter
    -> OSD dispatch: after ops under set_tenant, every daemon's
    scheduler reports dequeues in that tenant's dmClock class (the
    spec-configured gold class included)."""
    from ceph_tpu.client.remote import RemoteCluster
    d, _v = serve_cluster
    rc = RemoteCluster(d)
    try:
        rc.set_tenant("gold")
        for i in range(6):
            rc.put(1, f"tenant-obj-{i}", b"x" * 512)
            assert rc.get(1, f"tenant-obj-{i}") == b"x" * 512
        rc.set_tenant(None)
        total = 0
        for o in range(3):
            st = rc.osd_call(o, {"cmd": "status"})
            sched = st["scheduler"]
            assert tenant_class("gold") in sched["classes"]
            total += sched["dequeued"].get(tenant_class("gold"), 0)
        assert total > 0, "no daemon dispatched in the tenant class"
    finally:
        rc.close()


# ------------------------------------------------------ serve smoke --

@pytest.mark.smoke
def test_check_serving_smoke():
    """The CI smoke (scripts/check_serving.py riding pytest): the
    in-process sharding semantics leg; the live gate legs run as the
    two tests below against the shared module cluster (the script
    builds its own clusters when run standalone)."""
    import scripts.check_serving as cs
    assert cs._check_sharding_semantics() == 0


def test_serve_gate_green_on_default_config(serve_cluster):
    """The live gate, green path: per-tenant p99s come back from the
    mon's cluster histogram merge (samples > 0) and every tenant's
    dmClock class dispatched on the daemons."""
    d, v = serve_cluster
    cfg = ServeConfig(seed=0, n_osds=3, index_shards=4,
                      bucket="green", tenants=[
                          TenantSpec("gold", clients=2, ops=30,
                                     qos_res=0.4, min_share=0.05),
                          TenantSpec("bronze", clients=3, ops=45,
                                     qos_res=0.0, qos_wgt=4.0)])
    r = run_serve(cfg, cluster_dir=d, vstart=v)
    assert r["ok"], r["breaches"]
    for name, m in r["tenants"].items():
        assert m["samples"] and m["p99_s"] is not None, (name, m)
    shares = r["scheduler"]["tenant_shares"]
    assert shares.get("gold") and shares.get("bronze"), shares


def test_serve_starved_config_exits_red():
    """The falsifiability leg, live: the reserved tenant stripped of
    its QoS but keeping its share floor — the gate MUST report the
    per-tenant breach and the run must be red.  Own cluster on
    purpose: the starved profile's qos_tenants spec (gold res 0,
    wgt 0.01) must reach the daemons — a shared cluster's gold
    reservation would blunt the starvation this test proves."""
    tenants = default_tenants(starve=True)
    for t in tenants:
        t.ops = max(10, int(t.ops * 0.4))
    cfg = ServeConfig(seed=0, n_osds=3, index_shards=4,
                      tenants=tenants)
    r = run_serve(cfg)
    assert not r["ok"]
    breach = next(b for b in r["breaches"]
                  if b["tenant"] == "gold" and b["metric"] == "share")
    assert breach["got"] < breach["bound"]


# ------------------------------------------------------- chaos soak --

@pytest.fixture(scope="module")
def chaos_cluster(tmp_path_factory):
    """fsync=True cluster for the power-loss events (an acked write
    must be ON MEDIA for the zero-loss invariant to be meaningful);
    both chaos seeds share it, healing in between."""
    from ceph_tpu.tools.vstart import Vstart, build_cluster_dir
    d = str(tmp_path_factory.mktemp("s3chaos") / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=True,
                      qos_tenants={"gold": {"res": 0.4, "wgt": 2.0,
                                            "lim": 0.0}})
    v = Vstart(d)
    v.start(3, hb_interval=0.25)
    yield d, v
    v.stop()


@pytest.mark.parametrize("seed", [0, 1])
def test_chaos_serve_green(chaos_cluster, seed):
    """The capstone: the serving workload stays green under the
    COMPOSED thrashers — kill/revive + netsplit + powercycle under
    real multi-tenant traffic — within SLO-relaxed bounds and with
    zero acked-write loss (seeds 0-1)."""
    d, v = chaos_cluster
    cfg = ServeConfig(
        seed=seed, n_osds=3, index_shards=4, chaos=True,
        bucket=f"chaos{seed}",
        tenants=[
            TenantSpec("gold", clients=2, ops=40, qos_res=0.4,
                       min_share=0.05),
            TenantSpec("bronze", clients=3, ops=60, qos_res=0.0,
                       qos_wgt=4.0)])
    r = run_serve(cfg, cluster_dir=d, vstart=v)
    assert r["data_loss"] == [], r["data_loss"]
    assert r["ok"], r["breaches"]
    # all three fault shapes really ran under traffic
    kinds = {k for k, _ in r["chaos_log"]}
    assert kinds == {"kill", "netsplit", "powercycle"}
    # real traffic flowed under the whole schedule (the budgets are
    # floors; the window closes when budget AND schedule are done)
    assert r["total_ops"] >= 60
