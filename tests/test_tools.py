"""CLI tools + multi-chip sharding tests (models the reference's cram-style
CLI transcripts, src/test/cli/crushtool/*.t, and the mesh scale-out)."""
import json

import numpy as np
import pytest

from ceph_tpu.placement.crush_map import (
    RULE_CHOOSELEAF_FIRSTN, RULE_EMIT, RULE_TAKE, Rule, WEIGHT_ONE)
from tests.test_xla_mapper import TYPE_HOST, build_cluster


@pytest.fixture(scope="module")
def map_spec(tmp_path_factory):
    cmap, root = build_cluster(n_hosts=4, osds_per_host=3)
    cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                              (RULE_CHOOSELEAF_FIRSTN, 0, TYPE_HOST),
                              (RULE_EMIT, 0, 0)], name="replicated_rule"))
    p = tmp_path_factory.mktemp("maps") / "map.json"
    p.write_text(json.dumps(cmap.to_spec()))
    return str(p), cmap


def test_crushtool_test_mode(map_spec, capsys):
    from ceph_tpu.tools import crushtool
    path, cmap = map_spec
    rc = crushtool.main(["--infn", path, "--test", "--min-x", "0",
                         "--max-x", "63", "--num-rep", "3",
                         "--show-utilization", "--show-statistics",
                         "--show-bad-mappings"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "num_osds_mapped 12" in out
    assert "size 3:\t64/64" in out


def test_crushtool_scalar_matches_batched(map_spec, capsys):
    from ceph_tpu.tools import crushtool
    path, _ = map_spec
    crushtool.main(["--infn", path, "--test", "--max-x", "31",
                    "--num-rep", "3", "--show-mappings"])
    batched = capsys.readouterr().out
    crushtool.main(["--infn", path, "--test", "--max-x", "31",
                    "--num-rep", "3", "--show-mappings", "--scalar"])
    scalar = capsys.readouterr().out
    assert batched == scalar


def test_crushtool_roundtrip_spec(map_spec, capsys):
    from ceph_tpu.tools import crushtool
    path, cmap = map_spec
    rc = crushtool.main(["--infn", path, "--dump"])
    assert rc == 0
    spec = json.loads(capsys.readouterr().out)
    assert spec == cmap.to_spec()


def test_osdmaptool_test_map_pgs(map_spec, tmp_path, capsys):
    from ceph_tpu.tools import osdmaptool
    path, cmap = map_spec
    cluster = {
        "crush": cmap.to_spec(),
        "pools": [{"id": 1, "type": 1, "size": 3, "pg_num": 64,
                   "crush_rule": 0},
                  {"id": 2, "type": 3, "size": 4, "pg_num": 32,
                   "crush_rule": 0}],
        "osds": {"down": [], "out": []},
    }
    p = tmp_path / "cluster.json"
    p.write_text(json.dumps(cluster))
    rc = osdmaptool.main([str(p), "--test-map-pgs"])
    assert rc == 0
    cap = capsys.readouterr()
    assert "96 pgs" in cap.err       # timing line -> stderr (goldens)
    assert "total replicas 320" in cap.out


def test_ec_bench_json(capsys):
    from ceph_tpu.tools import ec_bench
    rc = ec_bench.main(["--plugin", "jax", "--workload", "encode",
                        "-k", "4", "-m", "2", "--size", "65536",
                        "--iterations", "2", "--batch", "4", "--json"])
    assert rc == 0
    result = json.loads(capsys.readouterr().out)
    assert result["KB"] == 2 * 4 * 4 * result["chunk_size"] // 1024
    assert result["GBps"] > 0
    rc = ec_bench.main(["--plugin", "jerasure", "--workload", "decode",
                        "-k", "4", "-m", "2", "--size", "16384",
                        "--iterations", "1", "--erasures", "2", "--json"])
    assert rc == 0
    result = json.loads(capsys.readouterr().out)
    assert len(result["erased"]) == 2


def test_sharded_map_batch_matches_single():
    from ceph_tpu.parallel.mesh import make_mesh
    from ceph_tpu.placement.xla_mapper import XlaMapper
    cmap, root = build_cluster(n_hosts=4, osds_per_host=3)
    cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                              (RULE_CHOOSELEAF_FIRSTN, 0, TYPE_HOST),
                              (RULE_EMIT, 0, 0)]))
    weights = [WEIGHT_ONE] * cmap.max_devices
    mapper = XlaMapper(cmap)
    xs = np.arange(101)   # deliberately not divisible by 8
    plain = mapper.map_batch(0, xs, 3, weights)
    mesh = make_mesh(8)
    sharded = mapper.map_batch(0, xs, 3, weights, mesh=mesh)
    assert np.array_equal(plain, sharded)


def test_distributed_encode_step_matches_host():
    import jax.numpy as jnp
    from ceph_tpu.ops import gf
    from ceph_tpu.parallel.mesh import distributed_encode_step, make_mesh
    mesh = make_mesh(8)
    parity = gf.vandermonde_parity(4, 2)
    bitmat = jnp.asarray(gf.gf8_bitmatrix(parity))
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(16, 4, 256), dtype=np.uint8)
    out, total = distributed_encode_step(mesh, bitmat, jnp.asarray(data))
    want = np.stack([gf.gf_matmul(parity, d) for d in data])
    assert np.array_equal(np.asarray(out), want)
    assert int(total) == int(data.astype(np.int64).sum())


def test_distributed_xor_encode_step_matches_host():
    """The flagship masked-XOR kernel sharded over the virtual mesh
    produces exactly the single-device result (stripe-axis sharding +
    replicated masks + psum counter)."""
    import jax.numpy as jnp
    import numpy as np
    from ceph_tpu.ops import gf, gf2, xor_kernel
    from ceph_tpu.parallel.mesh import (distributed_xor_encode_step,
                                        make_mesh)
    mesh = make_mesh()
    rng = np.random.default_rng(5)
    B = gf.gf8_bitmatrix(gf.vandermonde_parity(4, 2))
    masks = gf2.bitmatrix_masks(B)
    words = rng.integers(-(1 << 31), 1 << 31, size=(16, 32, 64),
                         dtype=np.int32)
    out, total = distributed_xor_encode_step(mesh, masks, words)
    want = np.asarray(xor_kernel.xor_matmul_w32(masks, words))
    assert np.array_equal(np.asarray(out), want)
    assert int(total) == int(words.astype(np.int64).sum())
