"""RGW metadata reads under transient device errors (ISSUE 6 satellite).

The `Bucket._read_index` bug class: a TRANSIENT read failure (injected
EIO, degraded EC read mid-recovery, a cut connection) swallowed into
``{}`` turns a full bucket index into "empty" — a spurious NoSuchKey
on GET, and the next index write would rebuild from {} and orphan
every object in the bucket.  The fix retries with ExpBackoff and
raises after exhaustion; only genuine absence returns the default.
"""
import pytest

from ceph_tpu.client.rados import Rados
from ceph_tpu.cluster.monitor import Monitor
from ceph_tpu.common import faults
from ceph_tpu.rgw import RGWError, RGWGateway
from ceph_tpu.rgw.gateway import _read_json
from tests.test_snaps import make_sim


@pytest.fixture(autouse=True)
def _clean_registry():
    yield
    faults.reset()


def test_index_read_survives_injected_eio():
    """device.eio on every shard of the bucket-index object fails the
    first read attempt outright (k=2, m=1: one EC read attempt costs 3
    shard reads, all injected); the retry must land and the object
    stay VISIBLE — the old code returned NoSuchKey here."""
    sim = make_sim(k=2, m=1)
    try:
        rados = Rados(sim, Monitor(sim.osdmap)).connect()
        # one attempt per logical read: the objecter's own retry loop
        # must not mask the IOError this regression test is about
        rados._objecter.max_retries = 1
        io = rados.open_ioctx("ec")
        gw = RGWGateway(io)
        b = gw.create_bucket("fragile")
        b.put_object("precious.bin", b"do not lose me" * 100)
        fires0 = faults.fire_counts().get("device.eio", 0)
        # 3 fires = every shard of the index object EIOs once: the
        # whole first decode attempt fails with IOError
        faults.arm("device.eio", mode="always", count=3)
        data, ent = b.get_object("precious.bin")
        assert data == b"do not lose me" * 100
        assert faults.fire_counts()["device.eio"] - fires0 >= 3, \
            "EIO was never injected — the test exercised nothing"
    finally:
        sim.shutdown()


def test_read_json_taxonomy():
    """Absent object -> default; persistent IOError -> raises (never
    the default); transient IOError -> retried through."""

    class FlakyIoctx:
        def __init__(self, fail, payload=b'{"k": 1}'):
            self.fail = fail
            self.reads = 0
            self.payload = payload

        def read(self, oid):
            self.reads += 1
            if self.reads <= self.fail:
                raise IOError("transient")
            return self.payload

    class AbsentIoctx:
        def read(self, oid):
            raise KeyError(oid)

    assert _read_json(AbsentIoctx(), "x", {"d": 1}, "t") == {"d": 1}
    flaky = FlakyIoctx(fail=2)
    assert _read_json(flaky, "x", {}, "t") == {"k": 1}
    assert flaky.reads == 3                  # two retries, then through
    with pytest.raises(RGWError):
        _read_json(FlakyIoctx(fail=99), "x", {}, "t")
