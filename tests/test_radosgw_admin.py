"""radosgw-admin CLI + durable RGW user store.

Reference roles: src/rgw/rgw_admin.cc (user/bucket/gc/realm command
families), src/rgw/rgw_user.cc (user records + access-key index).
"""
import io
import json

import pytest

from ceph_tpu.client.rados import Rados
from ceph_tpu.cluster.monitor import Monitor
from ceph_tpu.rgw import RGWGateway
from ceph_tpu.rgw.users import UserError, UserStore
from ceph_tpu.tools.radosgw_admin import main as adm
from tests.test_snaps import make_sim


@pytest.fixture()
def ioctx():
    sim = make_sim()
    return Rados(sim, Monitor(sim.osdmap)).connect().open_ioctx("rep")


def run(ioctx, *args):
    out = io.StringIO()
    rc = adm(list(args), ioctx=ioctx, out=out)
    return rc, out.getvalue()


def test_user_lifecycle(ioctx):
    rc, txt = run(ioctx, "user", "create", "--uid", "alice",
                  "--display-name", "Alice")
    assert rc == 0
    rec = json.loads(txt)
    assert rec["uid"] == "alice" and rec["keys"][0]["access_key"]
    # duplicate refused
    rc, txt = run(ioctx, "user", "create", "--uid", "alice")
    assert rc == 1 and "UserAlreadyExists" in txt
    rc, txt = run(ioctx, "user", "list")
    assert json.loads(txt) == ["alice"]
    rc, txt = run(ioctx, "key", "create", "--uid", "alice")
    assert rc == 0
    rc, txt = run(ioctx, "user", "info", "--uid", "alice")
    assert len(json.loads(txt)["keys"]) == 2
    rc, txt = run(ioctx, "user", "rm", "--uid", "alice")
    assert rc == 0
    rc, txt = run(ioctx, "user", "info", "--uid", "alice")
    assert rc == 1


def test_user_store_feeds_sigv4_frontend(ioctx):
    """Users created by the admin CLI authenticate against the S3
    frontend; suspension revokes them."""
    from ceph_tpu.rgw.auth_s3 import sign_request, verify_request
    store = UserStore(ioctx)
    rec = store.create("bob")
    ak = rec["keys"][0]["access_key"]
    sk = rec["keys"][0]["secret_key"]
    users = store.auth_users()
    assert users[ak]["secret"] == sk
    headers = {"Host": "x",
               **sign_request("GET", "/b/o", "", {"Host": "x"}, b"",
                              ak, sk)}
    assert verify_request("GET", "/b/o", "", headers, b"", users)
    # key lookup index resolves, suspension hides the user
    assert store.lookup_access_key(ak)["uid"] == "bob"
    store.suspend("bob")
    assert store.lookup_access_key(ak) is None
    assert ak not in store.auth_users()
    # swift view exists too
    store.suspend("bob", False)
    assert f"bob:swift" in store.swift_users()


def test_bucket_and_gc_commands(ioctx):
    gw = RGWGateway(ioctx)
    b = gw.create_bucket("data")
    b.put_object("a", b"x" * 100)
    b.put_object("b", b"y" * 50)
    rc, txt = run(ioctx, "bucket", "list")
    assert json.loads(txt) == ["data"]
    rc, txt = run(ioctx, "bucket", "stats", "--bucket", "data")
    st = json.loads(txt)["data"]
    assert st["num_objects"] == 2 and st["size"] == 150
    # gc: overwrite orphans the old generation, process reclaims
    b.put_object("a", b"z" * 100)
    rc, txt = run(ioctx, "gc", "list")
    assert rc == 0
    rc, txt = run(ioctx, "gc", "process")
    assert rc == 0


def test_realm_command_family(ioctx):
    rc, txt = run(ioctx, "realm", "create", "--realm", "earth")
    assert rc == 0
    rc, txt = run(ioctx, "zonegroup", "create", "--realm", "earth",
                  "--rgw-zonegroup", "us", "--master")
    assert rc == 0 and json.loads(txt)["name"] == "us"
    rc, txt = run(ioctx, "zone", "create", "--realm", "earth",
                  "--rgw-zonegroup", "us", "--rgw-zone", "us-east",
                  "--master")
    assert rc == 0
    # the reference spelling commits too
    rc, txt = run(ioctx, "period", "update", "--commit",
                  "--realm", "earth")
    p = json.loads(txt)
    assert rc == 0 and p["epoch"] == 1
    assert p["zonegroups"]["us"]["master_zone"] == "us-east"
    rc, txt = run(ioctx, "period", "list", "--realm", "earth")
    assert json.loads(txt) == [p["period_id"]]
    rc, txt = run(ioctx, "period", "get", "--realm", "earth")
    assert json.loads(txt)["period_id"] == p["period_id"]


def test_failed_command_does_not_create_realm(ioctx):
    """An unknown command must not durably mint a default realm as a
    side effect (code-review finding)."""
    with pytest.raises(SystemExit):
        run(ioctx, "user", "frobnicate", "--uid", "x")
    assert not any(o.startswith("rgw.realm.")
                   for o in ioctx.list_objects())
    with pytest.raises(SystemExit):
        run(ioctx, "user")                    # missing subcommand


def test_corrupt_user_record_not_clobbered(ioctx):
    """A torn/invalid user record reads as CorruptUser, and create()
    refuses to overwrite it (code-review finding)."""
    store = UserStore(ioctx)
    store.create("carol")
    ioctx.write_full("rgw.user.carol", b"{torn-json")
    with pytest.raises(UserError, match="CorruptUser"):
        store.info("carol")
    with pytest.raises(UserError, match="CorruptUser"):
        store.create("carol")                 # no silent regeneration
