"""cephtpu-lint — the analysis framework itself plus the tier-1 gate.

Per rule family: at least one fixture-verified TRUE POSITIVE, a
negative (clean idiom stays clean), plus framework tests for
``# noqa: CTL###`` suppression, baseline round-trip, the registry's
EC-plugin-style contract, and finally the gate: the real tree must be
lint-clean against the committed baseline on every pytest run.
"""
import json
import pathlib
import textwrap

import pytest

from ceph_tpu.analysis import baseline as baseline_mod
from ceph_tpu.analysis import runner
from ceph_tpu.analysis.core import Finding, LintError
from ceph_tpu.analysis.registry import RuleRegistry

REPO = pathlib.Path(__file__).resolve().parents[1]


def write(tmp, rel, src):
    p = tmp / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    return p


def lint(tmp, select=None, paths=None, evidence=None, baseline=None):
    return runner.run(str(tmp), paths=paths or ["."],
                      evidence_paths=evidence or [],
                      select=select, baseline=baseline)


def rules_of(res):
    return [f.rule for f in res.findings]


# ------------------------------------------- CTL1xx: JAX hot paths ---

def test_ctl101_host_sync_in_jit_positive_and_negative(tmp_path):
    write(tmp_path, "mod.py", """\
        import jax
        import jax.numpy as jnp
        import numpy as np

        def helper(y):
            return float(np.asarray(y).sum())     # hot via f

        @jax.jit
        def f(x):
            x.block_until_ready()
            return helper(x) + jnp.sum(x)

        def host_only(x):
            return np.asarray(x).item()           # not jit-reachable
        """)
    res = lint(tmp_path, select=["CTL101"])
    msgs = [f.msg for f in res.findings]
    assert len(res.findings) == 2, msgs
    assert any("block_until_ready" in m for m in msgs)
    assert any("numpy.asarray" in m for m in msgs)
    assert all(f.line < 12 for f in res.findings), \
        "host_only() is not jit-reachable and must stay clean"


def test_ctl102_tracer_branch_and_static_arg_exemption(tmp_path):
    write(tmp_path, "mod.py", """\
        import functools
        import jax

        @jax.jit
        def f(x):
            if x > 0:                 # tracer branch
                return x
            return -x

        @functools.partial(jax.jit, static_argnames=("n",))
        def g(x, n):
            if n > 2:                 # static: legitimate
                return x * n
            return x
        """)
    res = lint(tmp_path, select=["CTL102"])
    assert rules_of(res) == ["CTL102"]
    assert res.findings[0].line == 6
    assert "x" in res.findings[0].msg


def test_ctl103_jit_per_call(tmp_path):
    write(tmp_path, "mod.py", """\
        import jax

        def per_call(x):
            return jax.jit(lambda v: v + 1)(x)    # fresh wrapper

        _hoisted = jax.jit(lambda v: v + 1)       # fine

        def cached(x):
            return _hoisted(x)
        """)
    res = lint(tmp_path, select=["CTL103"])
    assert rules_of(res) == ["CTL103"]
    assert res.findings[0].line == 4


def test_ctl110_blocking_call_in_callback_context(tmp_path):
    """ISSUE 7: completion callbacks run on stream reader threads —
    a callback that blocks (socket RTT, future wait, sleep) stalls
    every completion pipelined behind it.  Deferral through an
    engine's submit() is the sanctioned escape hatch."""
    write(tmp_path, "cluster/ao.py", """\
        import time

        def issue(pool, sock, engine, meta):
            def _cb(result, exc):
                if exc is not None:
                    sock.connect(("peer", 1))      # blocks reader
                    helper()

            def _fin(result, exc):
                engine.submit(lambda: sock.sendall(meta))  # deferred

            pool.submit(meta, cb=_cb)
            pool.submit(meta, cb=_fin)

        def helper():
            time.sleep(0.5)                        # via _cb: flagged

        def unregistered(sock):
            sock.recv(4096)                        # never a callback
        """)
    res = lint(tmp_path, select=["CTL110"])
    assert rules_of(res) == ["CTL110", "CTL110"]
    assert sorted(f.line for f in res.findings) == [6, 16]
    assert any("connect" in f.msg for f in res.findings)
    assert any("time.sleep" in f.msg for f in res.findings)


def test_ctl110_done_callbacks_and_result_wait(tmp_path):
    write(tmp_path, "cluster/comp.py", """\
        def hang(comp, other):
            comp.set_complete_callback(lambda c: other.result())

        def fine(comp, log):
            comp.add_done_callback(lambda c: log.append(c))
        """)
    res = lint(tmp_path, select=["CTL110"])
    assert rules_of(res) == ["CTL110"]
    assert res.findings[0].line == 2
    assert "result" in res.findings[0].msg


def test_ctl120_per_shard_blocking_recovery_loop(tmp_path):
    """ISSUE 11: a recovery/backfill sweep that fetches or pushes one
    shard per blocking round trip pays an RTT per shard — the 0.002
    GB/s wire-recovery floor.  Async submit-all-then-gather and bulk
    frames are the sanctioned shapes."""
    write(tmp_path, "cluster/rec.py", """\
        def recover_pg(client, peers, shards, coll):
            for s in shards:
                client.call({"cmd": "get_shard", "coll": coll,
                             "oid": s})                    # flagged
            for s in shards:
                client._peer_req(1, {"cmd": "put_shard",
                                     "coll": coll, "oid": s,
                                     "data": b""})         # flagged
            for attempt in range(3):
                client.osd_call(0, {"cmd": "recover_pg",
                                    "coll": coll})         # per-PG: ok
            fan = [client.call_async(0, {"cmd": "get_shard",
                                         "coll": coll, "oid": s})
                   for s in shards]                        # async: ok
            for s in shards:
                client._peer_req(1, {"cmd": "get_objects",
                                     "coll": coll,
                                     "oids": [s]})         # bulk: ok
            return fan

        def scrub_pg(client, shards, coll):
            for s in shards:
                client.call({"cmd": "digest_shard", "coll": coll,
                             "oid": s})    # not a recovery fn: ok
        """)
    res = lint(tmp_path, select=["CTL120"])
    assert rules_of(res) == ["CTL120", "CTL120"], res.findings
    assert sorted(f.line for f in res.findings) == [3, 6]
    assert all("RTT per shard" in f.msg for f in res.findings)


def test_ctl120_scope_and_noqa(tmp_path):
    # outside cluster//client/ the rule does not apply
    write(tmp_path, "tools/rec.py", """\
        def recover_stuff(client, shards, coll):
            for s in shards:
                client.call({"cmd": "get_shard", "coll": coll,
                             "oid": s})
        """)
    write(tmp_path, "client/rec.py", """\
        def backfill(client, shards, coll):
            for s in shards:
                client.call({"cmd": "get_shard",  # noqa: CTL120
                             "coll": coll, "oid": s})
        """)
    res = lint(tmp_path, select=["CTL120"])
    assert rules_of(res) == [], res.findings


# --------------------------------------- CTL2xx: dtype invariants ---

def test_ctl201_implicit_dtype_scoped_to_ops_placement(tmp_path):
    src = """\
        import jax.numpy as jnp
        BAD = jnp.arange(8)
        ALSO_BAD = jnp.arange(1, 8)         # stop is NOT a dtype
        GOOD = jnp.arange(8, dtype=jnp.uint8)
        ALSO_GOOD = jnp.zeros((4,), dtype=jnp.int32)
        POS_GOOD = jnp.zeros((4,), jnp.int32)   # positional dtype
        """
    write(tmp_path, "ops/gfx.py", src)
    write(tmp_path, "placement/mapx.py", src)
    write(tmp_path, "other/hostx.py", src)      # out of scope
    res = lint(tmp_path, select=["CTL201"])
    assert rules_of(res) == ["CTL201"] * 4
    assert {f.path for f in res.findings} == \
        {"ops/gfx.py", "placement/mapx.py"}
    assert sorted(f.line for f in res.findings) == [2, 2, 3, 3]


def test_ctl202_unpinned_param_ingest_in_ops(tmp_path):
    write(tmp_path, "ops/ing.py", """\
        import jax.numpy as jnp

        def encode(data):
            return jnp.asarray(data)              # caller dtype leaks

        def encode_pinned(data):
            return jnp.asarray(data, jnp.uint8)   # positional dtype

        def local_ok():
            staged = [1, 2]
            return jnp.asarray(staged)            # not a parameter
        """)
    res = lint(tmp_path, select=["CTL202"])
    assert rules_of(res) == ["CTL202"]
    assert res.findings[0].line == 4


# ------------------------------------------- CTL3xx: concurrency ---

def test_ctl301_cross_module_lock_order_inversion(tmp_path):
    write(tmp_path, "cluster/locks_a.py", """\
        from ceph_tpu.common.lockdep import LockdepLock
        A = LockdepLock("fix.a")
        B = LockdepLock("fix.b")

        def forward():
            with A:
                with B:
                    pass
        """)
    write(tmp_path, "cluster/locks_b.py", """\
        from ceph_tpu.common.lockdep import LockdepLock
        A = LockdepLock("fix.a")
        B = LockdepLock("fix.b")

        def reverse():
            with B:
                with A:
                    pass
        """)
    res = lint(tmp_path, select=["CTL301"])
    assert rules_of(res) == ["CTL301"]
    assert "fix.a" in res.findings[0].msg and \
        "fix.b" in res.findings[0].msg

    # consistent order across both modules: clean
    (tmp_path / "cluster/locks_b.py").write_text(textwrap.dedent("""\
        from ceph_tpu.common.lockdep import LockdepLock
        A = LockdepLock("fix.a")
        B = LockdepLock("fix.b")

        def same_way():
            with A:
                with B:
                    pass
        """))
    assert not lint(tmp_path, select=["CTL301"]).findings


def test_ctl302_raw_lock_scope_and_exemptions(tmp_path):
    raw = """\
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
        """
    write(tmp_path, "cluster/svc.py", raw)
    write(tmp_path, "cluster/bluestore.py", raw)   # engine-exempt
    write(tmp_path, "common/subst.py", raw)        # out of scope
    write(tmp_path, "msg/fan.py", """\
        from ceph_tpu.common.lockdep import LockdepLock

        class Fan:
            def __init__(self):
                self._lock = LockdepLock("msg.fan")   # the fix
        """)
    res = lint(tmp_path, select=["CTL302"])
    assert [(f.path, f.rule) for f in res.findings] == \
        [("cluster/svc.py", "CTL302")]


# --------------------------------- CTL4xx: perf/config hygiene ---

def test_ctl401_undeclared_config_key(tmp_path):
    write(tmp_path, "pkg/options.py", """\
        TABLE = (
            Option("declared_knob", "int", 4),
        )
        """)
    write(tmp_path, "pkg/user.py", """\
        from .options import config

        def f():
            a = config().get("declared_knob")
            b = config().get("misspelled_knob")
            return a, b
        """)
    res = lint(tmp_path, select=["CTL401"])
    assert rules_of(res) == ["CTL401"]
    assert "misspelled_knob" in res.findings[0].msg
    assert res.findings[0].line == 5


def test_ctl402_perf_type_conflict_across_modules(tmp_path):
    write(tmp_path, "pkg/m1.py", """\
        from ceph_tpu.common.perf_counters import perf as _perf
        pc = _perf("grp")

        def f():
            pc.inc("mixed")
            pc.inc("clean_counter")
        """)
    write(tmp_path, "pkg/m2.py", """\
        from ceph_tpu.common.perf_counters import perf as _perf

        class C:
            def __init__(self):
                self._pc = _perf("grp")

            def g(self):
                self._pc.tinc("mixed", 0.5)    # clash with m1 inc
                self._pc.tinc("clean_avg", 0.5)
        """)
    res = lint(tmp_path, select=["CTL402"])
    assert rules_of(res) == ["CTL402"]
    assert "grp.mixed" in res.findings[0].msg


def test_ctl403_read_never_written(tmp_path):
    write(tmp_path, "pkg/reader.py", """\
        from ceph_tpu.common.perf_counters import perf

        def peek():
            return (perf("grp").get("stale_name"),
                    perf("grp").get("live_name"))
        """)
    write(tmp_path, "pkg/writer.py", """\
        from ceph_tpu.common.perf_counters import perf

        def bump():
            perf("grp").inc("live_name")
        """)
    res = lint(tmp_path, select=["CTL403"])
    assert rules_of(res) == ["CTL403"]
    assert "grp.stale_name" in res.findings[0].msg


# ------------------------------------ CTL5xx: admin registry ---

def test_ctl501_dispatch_without_register(tmp_path):
    write(tmp_path, "pkg/srv.py", """\
        def wire(server):
            server.register("perf dump", lambda a: {})
        """)
    write(tmp_path, "pkg/cli.py", """\
        def call(sock):
            return admin_request(sock, {"prefix": "perf dmup"})
        """)
    res = lint(tmp_path, select=["CTL501"])
    assert rules_of(res) == ["CTL501"]
    assert "perf dmup" in res.findings[0].msg


def test_ctl502_register_without_dispatch_tests_count(tmp_path):
    write(tmp_path, "pkg/srv.py", """\
        def wire(server):
            server.register("exercised", lambda a: {})
            server.register("lonely", lambda a: {})
        """)
    write(tmp_path, "tests/test_srv.py", """\
        def test_cmd(srv):
            assert srv.handle({"prefix": "exercised"})
        """)
    res = lint(tmp_path, select=["CTL502"], paths=["pkg"],
               evidence=["tests"])
    assert rules_of(res) == ["CTL502"]
    assert "lonely" in res.findings[0].msg


# ------------------------------------ CTL6xx: faultpoint closure ---

def test_ctl601_fire_without_declare(tmp_path):
    write(tmp_path, "pkg/site.py", """\
        from ceph_tpu.common import faults

        faults.declare("wire.drop", "declared and fired: clean")

        def send():
            if faults.fire("wire.drop") is not None:
                return None
            if faults.fire("wire.dorp") is not None:   # typo
                return None
            return 1
        """)
    res = lint(tmp_path, select=["CTL601"])
    assert rules_of(res) == ["CTL601"]
    assert "wire.dorp" in res.findings[0].msg
    assert res.findings[0].line == 8


def test_ctl601_declare_anywhere_in_tree_counts(tmp_path):
    write(tmp_path, "pkg/decl.py", """\
        from ceph_tpu.common import faults
        faults.declare("dev.eio", "declared here")
        """)
    write(tmp_path, "pkg/site.py", """\
        from ceph_tpu.common import faults

        def read():
            return faults.fire("dev.eio")
        """)
    assert not lint(tmp_path, select=["CTL601"]).findings


def test_ctl602_fire_in_jit_reachable_code(tmp_path):
    write(tmp_path, "pkg/kern.py", """\
        import jax
        from ceph_tpu.common import faults

        faults.declare("kern.bad", "inside a traced path")
        faults.declare("kern.ok", "at the dispatch boundary")

        def helper(x):
            if faults.fire("kern.bad") is not None:   # hot via f
                return x
            return x + 1

        @jax.jit
        def f(x):
            return helper(x)

        def dispatch(x):
            if faults.fire("kern.ok") is not None:    # host side: fine
                return None
            return f(x)
        """)
    res = lint(tmp_path, select=["CTL602"])
    assert rules_of(res) == ["CTL602"]
    assert res.findings[0].line == 8
    assert "jit-reachable" in res.findings[0].msg


def test_ctl603_swallowed_ioerror_to_default(tmp_path):
    """The _read_index bug class: except IOError -> return {} in an
    IO-facing dir fabricates 'absent' state from a transient error."""
    write(tmp_path, "rgw/gw.py", """\
        def read_index(ioctx, oid):
            try:
                return ioctx.read(oid)
            except IOError:
                return {}

        def read_meta(ioctx, oid):
            try:
                return ioctx.read(oid)
            except (OSError, ValueError):
                return None

        def read_ok(ioctx, oid):
            try:
                return ioctx.read(oid)
            except KeyError:          # genuine absence: not flagged
                return {}

        def read_loud(ioctx, oid):
            try:
                return ioctx.read(oid)
            except IOError:
                raise RuntimeError("index unreadable")
        """)
    res = lint(tmp_path, select=["CTL603"])
    assert rules_of(res) == ["CTL603", "CTL603"]
    assert [f.line for f in res.findings] == [4, 10]
    assert "lost-object" in res.findings[0].msg


def test_ctl603_scoped_to_io_facing_dirs(tmp_path):
    """cluster/ (and everything outside client//rgw//msg/) keeps its
    local error conventions — the rule is about the wire/device
    boundary dirs the ISSUE names."""
    code = """\
        def read(store, oid):
            try:
                return store.read(oid)
            except IOError:
                return {}
        """
    write(tmp_path, "cluster/store.py", code)
    assert not lint(tmp_path, select=["CTL603"]).findings
    write(tmp_path, "client/remote.py", code)
    res = lint(tmp_path, select=["CTL603"])
    assert rules_of(res) == ["CTL603"]


def test_ctl603_noqa_suppresses(tmp_path):
    write(tmp_path, "msg/wire.py", """\
        def probe(sock):
            try:
                return sock.recv(1)
            except OSError:  # noqa: CTL603 -- poller retries next tick
                return None
        """)
    assert not lint(tmp_path, select=["CTL603"]).findings


def test_ctl604_store_write_bypasses_blockdev(tmp_path):
    """ISSUE 9: a direct write in a BlockDevice-owned store module is
    invisible to the CrashDev recorder — the exact bug class that
    invalidates the power-loss harness."""
    write(tmp_path, "cluster/bluestore.py", """\
        import os

        def bad_patch(fd, data, off):
            os.pwrite(fd, data, off)          # bypasses the recorder

        def bad_log(path, rec):
            with open(path, "ab") as f:       # raw append log
                f.write(rec)

        def bad_flip(tmp, final):
            os.replace(tmp, final)            # unrecorded rename

        def fine_read(path):
            with open(path, "rb") as f:       # reads are harmless
                return f.read()

        def fine_default(path):
            return open(path).read()          # mode omitted: read
        """)
    res = lint(tmp_path, select=["CTL604"])
    assert rules_of(res) == ["CTL604", "CTL604", "CTL604"]
    assert [f.line for f in res.findings] == [4, 7, 11]
    assert "barrier API" in res.findings[0].msg


def test_ctl604_scoped_to_store_modules(tmp_path):
    """Only the BlockDevice-owned store modules are in scope —
    blockdev.py itself (the door) and the rest of cluster/ keep
    their raw I/O."""
    code = """\
        import os

        def writer(fd, data):
            os.pwrite(fd, data, 0)
        """
    write(tmp_path, "cluster/blockdev.py", code)
    write(tmp_path, "cluster/daemon.py", code)
    write(tmp_path, "tools/exporter.py", code)
    assert not lint(tmp_path, select=["CTL604"]).findings
    write(tmp_path, "cluster/wal_kv.py", code)
    res = lint(tmp_path, select=["CTL604"])
    assert rules_of(res) == ["CTL604"]
    assert res.findings[0].path.endswith("wal_kv.py")


def test_ctl604_noqa_suppresses(tmp_path):
    write(tmp_path, "cluster/filestore.py", """\
        import os

        def surgery(fd):
            os.ftruncate(fd, 0)  # noqa: CTL604 -- mkfs-time wipe
        """)
    assert not lint(tmp_path, select=["CTL604"]).findings


def test_ctl605_marker_before_completion(tmp_path):
    """ISSUE 18: a sync agent that persists its replication marker
    while an async apply is still in flight acks an entry the crash
    may lose forever — the gather must come first."""
    write(tmp_path, "rgw/agent.py", """\
        def _save_state(ioctx, state):
            ioctx.write_full("rgw.sync.b.z", state)

        def bad_pump(self, engine, shards):
            comps = []
            for s in shards:
                comps.append(engine.submit(self.apply, key=s))
            _save_state(self.ioctx, self.state)   # apply in flight
            for c in comps:
                c.result()

        def good_pump(self, engine, shards):
            comps = []
            for s in shards:
                comps.append(engine.submit(self.apply, key=s))
            for c in comps:
                c.result()
            _save_state(self.ioctx, self.state)   # after the gather
        """)
    res = lint(tmp_path, select=["CTL605"])
    assert rules_of(res) == ["CTL605"]
    assert res.findings[0].line == 8
    assert "unresolved" in res.findings[0].msg


def test_ctl605_resolves_wrapper_through_program_graph(tmp_path):
    """A bland-named wrapper around the persist helper is the same
    commit point: the whole-program graph resolves one hop."""
    write(tmp_path, "rgw/agent.py", """\
        from rgw.markers import checkpoint

        def pump(self, engine, shards):
            for s in shards:
                engine.submit(self.apply, key=s)
            checkpoint(self)              # wraps the marker persist
        """)
    write(tmp_path, "rgw/markers.py", """\
        def checkpoint(agent):
            _commit_marker(agent)

        def _commit_marker(agent):
            agent.ioctx.write_full(agent.oid, agent.state)
        """)
    res = lint(tmp_path, select=["CTL605"])
    assert rules_of(res) == ["CTL605"]
    assert "checkpoint" in res.findings[0].msg


def test_ctl605_scoped_and_clean_without_submit(tmp_path):
    """No pending submission -> no finding; and modules outside the
    rgw//sync scope keep their conventions."""
    write(tmp_path, "rgw/agent.py", """\
        def _advance_applied(self, seq):
            self.ioctx.write_full(self.oid, seq)

        def apply_entry(self, ent):
            self.dst.apply_put(ent)
            self._advance_applied(ent["seq"])   # after the apply
        """)
    assert not lint(tmp_path, select=["CTL605"]).findings
    write(tmp_path, "cluster/batch.py", """\
        def flush(self, engine, items):
            for it in items:
                engine.submit(self.push, key=it)
            self.save_state()                 # out of CTL605 scope
        """)
    assert not lint(tmp_path, select=["CTL605"]).findings


def test_ctl605_noqa_suppresses(tmp_path):
    write(tmp_path, "rgw/agent.py", """\
        def pump(self, engine, shards):
            for s in shards:
                engine.submit(self.apply, key=s)
            self._save_state(self.state)  # noqa: CTL605 -- replays dedup
        """)
    assert not lint(tmp_path, select=["CTL605"]).findings


# ------------------------------ CTL7xx: trace-context propagation ---

def test_ctl701_raw_send_without_trace_context(tmp_path):
    """ISSUE 10: a raw wire send building a data-path request without
    propagating the active trace context leaves a silent hole in the
    cross-process trace (the silent-trace-gap bug class)."""
    write(tmp_path, "cluster/svc.py", """\
        def fanout(self, peer, coll, oid, data):
            self.peer_client(peer).call({
                "cmd": "put_shard", "coll": coll,
                "oid": oid, "data": data})

        def pull(self, m, coll, oid):
            return self._peer_req(m, {"cmd": "get_shard",
                                      "coll": coll, "oid": oid})
        """)
    res = lint(tmp_path, select=["CTL701"])
    assert rules_of(res) == ["CTL701", "CTL701"]
    assert [f.line for f in res.findings] == [2, 7]
    assert "tracer.stamp" in res.findings[0].msg


def test_ctl701_negatives(tmp_path):
    """Stamped sends, explicit tctx, control commands, stamping
    chokepoints and out-of-scope dirs are all clean."""
    write(tmp_path, "cluster/good.py", """\
        from ..common import tracer as _trace

        def stamped(self, peer, coll, oid, data):
            self.peer_client(peer).call(_trace.stamp({
                "cmd": "put_shard", "coll": coll,
                "oid": oid, "data": data}))

        def carried(self, peer, ctx):
            self.peer_client(peer).call({
                "cmd": "get_shard", "tctx": ctx})

        def control(self, mon):
            mon.call({"cmd": "get_map"})

        def chokepoint(self, osd, coll, oid, data):
            # osd_call routes through AsyncObjecter's central stamp
            self.osd_call(osd, {"cmd": "put_object", "coll": coll,
                                "oid": oid, "data": data})
        """)
    write(tmp_path, "tools/out_of_scope.py", """\
        def raw(self, c):
            c.call({"cmd": "put_shard", "coll": [1, 0], "oid": "x"})
        """)
    assert not lint(tmp_path, select=["CTL701"]).findings


def test_ctl701_noqa_suppresses(tmp_path):
    write(tmp_path, "client/probe.py", """\
        def probe(self, c):
            return c.call(
                {"cmd": "digest_shard",  # noqa: CTL701 -- probe only
                 "coll": [1, 0], "oid": "x"})
        """)
    assert not lint(tmp_path, select=["CTL701"]).findings


# --------------------------- whole-program call graph (CTLint v2) ---

def test_cross_module_jit_reachability_via_from_import(tmp_path):
    """CTL101 whole-program: the host sync lives one module away
    from the jit root, resolved through `from .x import f`."""
    write(tmp_path, "pkg/__init__.py", "")
    write(tmp_path, "pkg/helpers.py", """\
        import numpy as np

        def mix(y):
            return float(np.asarray(y).sum())     # hot via pkg.entry

        def cold(y):
            return np.asarray(y)                  # not reached
        """)
    write(tmp_path, "pkg/entry.py", """\
        import jax
        from .helpers import mix

        @jax.jit
        def f(x):
            return mix(x)
        """)
    res = lint(tmp_path, select=["CTL101"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("pkg/helpers.py", 4)], res.findings


def test_cross_module_resolution_import_alias(tmp_path):
    """`from .b import helper as h` and `import pkg.b as bb` both
    resolve across modules; an AMBIGUOUS obj.attr call falls back to
    the module-local name match (never cross-module)."""
    write(tmp_path, "pkg/__init__.py", "")
    write(tmp_path, "pkg/b.py", """\
        import numpy as np

        def helper(y):
            return np.asarray(y).item()
        """)
    write(tmp_path, "pkg/a.py", """\
        import jax
        from .b import helper as h

        @jax.jit
        def f(x):
            return h(x)
        """)
    res = lint(tmp_path, select=["CTL101"])
    # .item() and numpy.asarray both fire — both one module away
    assert {f.path for f in res.findings} == {"pkg/b.py"}

    # ambiguous: dt.helper(x) in a module with NO local helper must
    # not leak to pkg.b's helper
    write(tmp_path, "pkg/a.py", """\
        import jax

        @jax.jit
        def f(dt, x):
            return dt.helper(x)
        """)
    res = lint(tmp_path, select=["CTL101"])
    assert not res.findings, res.findings


def test_self_method_resolution_is_class_precise(tmp_path):
    """`self._m()` resolves to the ENCLOSING class's method: a
    same-named method on a sibling class stays cold."""
    write(tmp_path, "pkg/mod.py", """\
        import jax
        import numpy as np

        class Hot:
            @jax.jit
            def run(self, x):
                return self._m(x)

            def _m(self, x):
                return np.asarray(x).item()       # hot via run

        class Cold:
            def _m(self, x):
                return np.asarray(x).item()       # must stay cold
        """)
    res = lint(tmp_path, select=["CTL101"])
    assert sorted({f.line for f in res.findings}) == [10], \
        res.findings


def test_ctl602_fire_in_jit_cross_module(tmp_path):
    write(tmp_path, "pkg/__init__.py", "")
    write(tmp_path, "pkg/inner.py", """\
        from ceph_tpu.common import faults

        faults.declare("x.bad", "fired under a trace, one mod away")

        def helper(x):
            if faults.fire("x.bad") is not None:
                return x
            return x + 1
        """)
    write(tmp_path, "pkg/kern.py", """\
        import jax
        from .inner import helper

        @jax.jit
        def f(x):
            return helper(x)
        """)
    res = lint(tmp_path, select=["CTL602"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("pkg/inner.py", 6)]


def test_ctl110_callback_blocks_in_another_module(tmp_path):
    """The callback is registered in one module and blocks in the
    helper module it calls — invisible to the v1 module-local
    graph."""
    write(tmp_path, "cluster/__init__.py", "")
    write(tmp_path, "cluster/slowpath.py", """\
        import time

        def drain(sock):
            time.sleep(0.5)                        # flagged
        """)
    write(tmp_path, "cluster/engine.py", """\
        from .slowpath import drain

        def wire(pool, sock, meta):
            def _cb(result, exc):
                drain(sock)

            pool.submit(meta, cb=_cb)
        """)
    res = lint(tmp_path, select=["CTL110"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("cluster/slowpath.py", 4)]
    assert "time.sleep" in res.findings[0].msg


def test_ctl120_per_shard_send_via_helper(tmp_path):
    """The blocking per-shard send hides in a helper the recovery
    loop calls — the widened graph follows the call."""
    write(tmp_path, "cluster/__init__.py", "")
    write(tmp_path, "cluster/push.py", """\
        def push_one(client, coll, oid, data):
            client.call({"cmd": "put_shard", "coll": coll,
                         "oid": oid, "data": data})
        """)
    write(tmp_path, "cluster/rec.py", """\
        from .push import push_one

        def backfill_pg(client, coll, items):
            for oid, data in items:
                push_one(client, coll, oid, data)
        """)
    res = lint(tmp_path, select=["CTL120"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("cluster/push.py", 2)]
    assert "via helper 'push_one'" in res.findings[0].msg

    # the same helper reached from a NON-loop call stays clean
    write(tmp_path, "cluster/rec.py", """\
        from .push import push_one

        def backfill_pg(client, coll, item):
            push_one(client, coll, item[0], item[1])
        """)
    assert not lint(tmp_path, select=["CTL120"]).findings


def test_ctl701_var_flow_and_wrapper(tmp_path):
    """CTL701 v2: a dict bound to a name and sent later, and a dict
    handed to a cross-module wrapper that forwards to a raw send,
    are both gaps; stamping either way is clean."""
    write(tmp_path, "cluster/__init__.py", "")
    write(tmp_path, "cluster/w.py", """\
        def fanout(conn, req):
            return conn.call(req)

        def fanout_stamped(conn, req, tracer):
            return conn.call(tracer.stamp(req))
        """)
    write(tmp_path, "cluster/u.py", """\
        from .w import fanout, fanout_stamped

        def direct_var(conn, coll, oid):
            req = {"cmd": "get_shard", "coll": coll, "oid": oid}
            return conn.call(req)                    # flagged

        def via_wrapper(conn, coll, oid):
            return fanout(conn, {"cmd": "put_shard", "coll": coll,
                                 "oid": oid, "data": b""})  # flagged

        def via_stamping_wrapper(conn, coll, oid, tr):
            return fanout_stamped(conn, {"cmd": "put_shard",
                                         "coll": coll, "oid": oid,
                                         "data": b""}, tr)  # clean

        def var_stamped(conn, coll, oid, tracer):
            req = {"cmd": "get_shard", "coll": coll, "oid": oid}
            req = tracer.stamp(req)
            return conn.call(req)                    # clean
        """)
    res = lint(tmp_path, select=["CTL701"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("cluster/u.py", 5), ("cluster/u.py", 8)], res.findings
    assert "raw-send wrapper 'fanout'" in res.findings[1].msg


def test_ctl701_incrementally_built_dict_is_a_gap(tmp_path):
    """A dict built across statements and then sent raw is still a
    gap; only a real `x["tctx"] = ...` assignment counts as
    stamping (regression: any subscript-assign used to mask it)."""
    write(tmp_path, "cluster/inc.py", """\
        def gap(conn, coll, oid):
            req = {"cmd": "get_shard", "coll": coll}
            req["oid"] = oid
            return conn.call(req)                  # flagged

        def stamped(conn, coll, oid, ctx):
            req = {"cmd": "get_shard", "coll": coll, "oid": oid}
            req["tctx"] = ctx
            return conn.call(req)                  # clean
        """)
    res = lint(tmp_path, select=["CTL701"])
    assert [f.line for f in res.findings] == [4], res.findings


def test_ctl702_set_on_rate_counter_all_receiver_shapes(tmp_path):
    """CTL702: a `.set()` on a RATE_COUNTERS pair is flagged through
    every receiver shape the tree uses (direct `_perf("g")` call,
    `self.X = _perf("g")` attr), while inc-only use and unlisted
    keys stay clean."""
    write(tmp_path, "mgr/metrics_history.py", """\
        RATE_COUNTERS = (
            ("osd.io", "wr_ops"),
            ("jit", "compiles"),
        )
        """)
    write(tmp_path, "daemon.py", """\
        from perf_counters import perf as _perf

        class OSD:
            def __init__(self):
                self._pc_io = _perf("osd.io")

            def on_write(self):
                self._pc_io.inc("wr_ops")

            def load_stats(self, n):
                self._pc_io.set("wr_ops", n)       # gauge retype

        def restore(v):
            _perf("jit").set("compiles", v)        # gauge retype

        def on_compile():
            pc = _perf("jit")
            pc.inc("compiles")

        def depth_gauge(d):
            _perf("osd.io").set("queue_depth", d)  # key not listed
        """)
    res = lint(tmp_path, select=["CTL702"])
    assert rules_of(res) == ["CTL702", "CTL702"], res.findings
    hits = {(f.path, f.line) for f in res.findings}
    assert hits == {("daemon.py", 11), ("daemon.py", 14)}, hits
    assert all("monotonic (inc-only)" in f.msg for f in res.findings)


def test_ctl702_listed_counter_without_inc_site(tmp_path):
    """A RATE_COUNTERS entry nothing increments is a finding anchored
    at the declaration — the history ring would query a counter that
    never moves."""
    write(tmp_path, "mgr/metrics_history.py", """\
        RATE_COUNTERS = (
            ("osd.io", "wr_ops"),
            ("jit", "compiles"),
        )
        """)
    write(tmp_path, "osd.py", """\
        from perf_counters import perf

        def on_write():
            perf("osd.io").inc("wr_ops")
        """)
    res = lint(tmp_path, select=["CTL702"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("mgr/metrics_history.py", 1)], res.findings
    assert "jit.compiles" in res.findings[0].msg
    assert "no .inc() declaration site" in res.findings[0].msg


def test_ctl702_noqa_and_inc_only_tree_clean(tmp_path):
    write(tmp_path, "mgr/metrics_history.py", """\
        RATE_COUNTERS = (("osd.io", "wr_ops"),)
        """)
    write(tmp_path, "osd.py", """\
        from perf_counters import perf

        def on_write():
            perf("osd.io").inc("wr_ops")

        def restore(v):
            perf("osd.io").set("wr_ops", v)  # noqa: CTL702
        """)
    assert not lint(tmp_path, select=["CTL702"]).findings


def test_ctl120_recovery_named_helper_without_own_loop(tmp_path):
    """A recovery-NAMED helper whose blocking send is straight-line
    (no loop of its own) is still one RTT per iteration of the
    caller's loop — reported once, at the send site (regression:
    recovery-named helpers were skipped entirely)."""
    write(tmp_path, "cluster/rec.py", """\
        def _recover_one(client, coll, oid):
            client.call({"cmd": "get_shard", "coll": coll,
                         "oid": oid})

        def recover_pg(client, coll, oids):
            for oid in oids:
                _recover_one(client, coll, oid)
        """)
    res = lint(tmp_path, select=["CTL120"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("cluster/rec.py", 2)], res.findings
    assert "via helper '_recover_one'" in res.findings[0].msg


# ------------------------- CTL8xx: wire-protocol contract closure ---

PROTO_DAEMON = """\
    class Daemon:
        def _handle(self, entity, req):
            cmd = req["cmd"]
            if cmd == "put_thing":
                return (req["coll"], req["data"],
                        req.get("attrs"))
            if cmd == "get_thing":
                return req["oid"]
            if cmd == "lonely_arm":
                return req["x"]
            raise ValueError(cmd)
    """


def test_ctl801_sent_but_unhandled_and_dead_arm(tmp_path):
    write(tmp_path, "cluster/daemon.py", PROTO_DAEMON)
    write(tmp_path, "client/c.py", """\
        def go(conn, coll, data):
            conn.call({"cmd": "put_thing", "coll": coll,
                       "data": data})
            conn.call({"cmd": "get_thing", "oid": "o"})
            conn.call({"cmd": "typo_thing", "oid": "o"})
        """)
    res = lint(tmp_path, select=["CTL801"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("client/c.py", 5), ("cluster/daemon.py", 9)], res.findings
    assert "typo_thing" in res.findings[0].msg
    assert "lonely_arm" in res.findings[1].msg
    assert "dead protocol surface" in res.findings[1].msg


def test_ctl801_test_exercise_counts_and_noqa(tmp_path):
    """An arm poked only by a test is NOT dead (tests are exercise
    evidence), and a parameterized send (literal cmd as a call
    argument) counts as exercised."""
    write(tmp_path, "cluster/daemon.py", PROTO_DAEMON)
    write(tmp_path, "client/c.py", """\
        def go(conn, coll, data):
            conn.call({"cmd": "put_thing", "coll": coll,
                       "data": data})
            return conn.probe("get_thing")
        """)
    write(tmp_path, "tests/test_d.py", """\
        def test_arm(d):
            assert d._handle("x", {"cmd": "lonely_arm", "x": 1})
        """)
    res = lint(tmp_path, select=["CTL801"], paths=["cluster",
                                                   "client"],
               evidence=["tests"])
    assert not res.findings, res.findings

    write(tmp_path, "client/bad.py", """\
        def go(conn):
            conn.call({"cmd": "typo2",  # noqa: CTL801 -- vapor cmd
                       "oid": "o"})
        """)
    res = lint(tmp_path, select=["CTL801"], paths=["cluster",
                                                   "client"],
               evidence=["tests"])
    assert not res.findings and len(res.noqa) == 1


def test_ctl802_mutating_send_outside_chokepoint(tmp_path):
    write(tmp_path, "cluster/svc.py", """\
        def replicate(self, peer, coll, oid, data):
            self.peer_client(peer).call({
                "cmd": "put_shard", "coll": coll,
                "oid": oid, "data": data})           # flagged

        def replicate_choke(self, peer, coll, oid, data):
            self._peer_req(peer, {"cmd": "put_shard", "coll": coll,
                                  "oid": oid, "data": data})  # ok

        def replicate_stamped(self, c, coll, oid, data, sid, seq):
            c.call({"cmd": "put_shard", "coll": coll, "oid": oid,
                    "data": data, "session": sid, "seq": seq})  # ok

        def read_path(self, c, coll, oid):
            return c.call({"cmd": "get_shard", "coll": coll,
                           "oid": oid})              # reads exempt
        """)
    res = lint(tmp_path, select=["CTL802"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("cluster/svc.py", 2)], res.findings
    assert "apply it twice" in res.findings[0].msg


def test_ctl802_replay_set_read_from_tree(tmp_path):
    """The mutating set comes from the tree's own _REPLAY_CMDS
    declaration when present — the contract and the lint share one
    source of truth."""
    write(tmp_path, "cluster/daemon.py", """\
        _REPLAY_CMDS = frozenset(("my_mutation",))
        """)
    write(tmp_path, "cluster/svc.py", """\
        def go(self, c, coll):
            c.call({"cmd": "my_mutation", "coll": coll})   # flagged
            c.call({"cmd": "put_shard", "coll": coll,
                    "oid": "o", "data": b""})   # not in tree's set
        """)
    res = lint(tmp_path, select=["CTL802"])
    assert [f.line for f in res.findings] == [2], res.findings
    assert "my_mutation" in res.findings[0].msg


def test_ctl803_sender_omits_required_key(tmp_path):
    write(tmp_path, "cluster/daemon.py", PROTO_DAEMON)
    write(tmp_path, "client/c.py", """\
        def good(conn, coll, data):
            conn.call({"cmd": "put_thing", "coll": coll,
                       "data": data})       # attrs is req.get: ok

        def short(conn, coll):
            conn.call({"cmd": "put_thing", "coll": coll})  # flagged

        def open_keys(conn, coll, extra):
            conn.call({"cmd": "put_thing", "coll": coll,
                       **extra})            # open key set: quiet
        """)
    res = lint(tmp_path, select=["CTL803"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("client/c.py", 6)], res.findings
    assert "'data'" in res.findings[0].msg
    assert "KeyError" in res.findings[0].msg


def test_ctl803_any_arm_satisfied_is_clean(tmp_path):
    """Two daemons handle the same cmd with different required keys:
    satisfying one arm is legitimate (mon vs osd 'status')."""
    write(tmp_path, "cluster/a.py", """\
        class A:
            def _handle(self, entity, req):
                cmd = req["cmd"]
                if cmd == "shared":
                    return req["akey"]
        """)
    write(tmp_path, "cluster/b.py", """\
        class B:
            def _handle(self, entity, req):
                cmd = req["cmd"]
                if cmd == "shared":
                    return req["bkey"]
        """)
    write(tmp_path, "client/c.py", """\
        def go(conn):
            conn.call({"cmd": "shared", "akey": 1})   # satisfies A
            conn.call({"cmd": "shared"})              # satisfies none
        """)
    res = lint(tmp_path, select=["CTL803"])
    assert [f.line for f in res.findings] == [3], res.findings


def test_ctl804_duplicate_declare_and_undeclared_arm(tmp_path):
    write(tmp_path, "pkg/a.py", """\
        from ceph_tpu.common import faults
        faults.declare("dup.point", "first declare: canonical")
        faults.declare("solo.point", "declared once: clean")
        """)
    write(tmp_path, "pkg/b.py", """\
        from ceph_tpu.common import faults
        faults.declare("dup.point", "second declare: drift")

        def arm_it(asok):
            admin_request(asok, {
                "prefix": "fault_injection", "action": "arm",
                "name": "ghost.point", "mode": "always"})
            faults.arm("solo.point", mode="always")
        """)
    res = lint(tmp_path, select=["CTL804"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("pkg/b.py", 2), ("pkg/b.py", 5)], res.findings
    assert "more than once" in res.findings[0].msg
    assert "ghost.point" in res.findings[1].msg


def test_ctl804_noqa_suppresses(tmp_path):
    write(tmp_path, "pkg/a.py", """\
        from ceph_tpu.common import faults
        faults.declare("p.x", "one")
        """)
    write(tmp_path, "pkg/b.py", """\
        from ceph_tpu.common import faults
        faults.declare("p.x", "one")  # noqa: CTL804 -- mirror module
        """)
    res = lint(tmp_path, select=["CTL804"])
    assert not res.findings and len(res.noqa) == 1


# ------------------------------------------- framework behavior ---

def test_noqa_inline_suppression(tmp_path):
    write(tmp_path, "cluster/svc.py", """\
        import threading

        L1 = threading.Lock()  # noqa: CTL302 -- leaf lock, measured
        L2 = threading.Lock()  # noqa
        L3 = threading.Lock()  # noqa: CTL999 (wrong code: still fires)
        L4 = threading.Lock()  # noqa: E402
        """)
    # a flake8-style code list must NOT blanket-suppress CTL rules
    res = lint(tmp_path, select=["CTL302"])
    assert [f.line for f in res.findings] == [5, 6]
    assert len(res.noqa) == 2


def test_baseline_round_trip(tmp_path):
    mod = write(tmp_path, "cluster/svc.py", """\
        import threading
        L = threading.Lock()
        """)
    res = lint(tmp_path, select=["CTL302"])
    assert len(res.findings) == 1

    bpath = tmp_path / "lint_baseline.json"
    baseline_mod.save(str(bpath), res.findings)
    data = json.loads(bpath.read_text())
    assert [e["rule"] for e in data["findings"]] == ["CTL302"]

    # baselined: reported separately, not a failure
    res2 = lint(tmp_path, select=["CTL302"], baseline=str(bpath))
    assert not res2.findings and len(res2.baselined) == 1 and \
        not res2.stale_baseline

    # the finding moves lines -> still matched (identity is msg-based)
    mod.write_text("import threading\n# pushed down\nL = "
                   "threading.Lock()\n")
    res3 = lint(tmp_path, select=["CTL302"], baseline=str(bpath))
    assert not res3.findings and len(res3.baselined) == 1

    # fixed for real -> the baseline entry goes stale (visible rot)
    mod.write_text("from ceph_tpu.common.lockdep import "
                   "LockdepLock\nL = LockdepLock('x')\n")
    res4 = lint(tmp_path, select=["CTL302"], baseline=str(bpath))
    assert not res4.findings and res4.stale_baseline

    # a run scoped to ANOTHER family cannot see CTL302 findings, so
    # the entry is out of scope — not stale
    res5 = lint(tmp_path, select=["CTL1"], baseline=str(bpath))
    assert not res5.stale_baseline


def test_write_baseline_select_preserves_other_families(tmp_path):
    """`--write-baseline --select CTL3` must not silently drop the
    other families' grandfathered entries."""
    import io
    write(tmp_path, "cluster/svc.py",
          "import threading\nL = threading.Lock()\n")
    write(tmp_path, "ops/gfx.py",
          "import jax.numpy as jnp\nA = jnp.arange(8)\n")
    bpath = tmp_path / "base.json"
    baseline_mod.save(str(bpath), [
        ("CTL201", "ops/gfx.py",
         "jnp.arange() without dtype= materializes int64/float64 "
         "under jax_enable_x64 (emulated 64-bit ops on TPU) — pin "
         "the dtype")])
    out = io.StringIO()
    rc = runner.main(["--root", str(tmp_path), "--write-baseline",
                      "--baseline", str(bpath),
                      "--select", "CTL302", "."], out=out)
    assert rc == 0
    entries = baseline_mod.load(str(bpath))
    rules = sorted(r for r, _, _ in entries)
    assert rules == ["CTL201", "CTL302"], rules


def test_registry_mirrors_plugin_contract():
    reg = RuleRegistry.instance()
    ids = reg.names()
    # one rule family minimum per invariant class, CTL1xx..CTL9xx
    # plus the CTL10xx ShardCheck family ("CTL100" prefix — a bare
    # "CTL10" would also match the CTL10x rules)
    for family in ("CTL1", "CTL2", "CTL3", "CTL4", "CTL5", "CTL6",
                   "CTL7", "CTL8", "CTL9", "CTL100"):
        assert any(r.startswith(family) for r in ids), family
    with pytest.raises(LintError, match="already registered"):
        reg.add("CTL301", type(reg.factory("CTL301")))
    with pytest.raises(LintError, match="version"):
        reg.add("CTL999", type(reg.factory("CTL301")),
                version="0.0.0-elsewhere")
    with pytest.raises(LintError, match="unknown lint rule"):
        reg.factory("CTL888")
    with pytest.raises(LintError, match="no rules match"):
        reg.create(["XYZ9"])


def test_cli_json_and_check_exit_codes(tmp_path, capsys):
    import io
    write(tmp_path, "cluster/svc.py",
          "import threading\nL = threading.Lock()\n")
    out = io.StringIO()
    rc = runner.main(["--root", str(tmp_path), "--json", "--check",
                      "--select", "CTL302", "."], out=out)
    assert rc == 1
    payload = json.loads(out.getvalue())
    assert payload["count"] == 1
    assert payload["findings"][0]["rule"] == "CTL302"
    assert "CTL302" in payload["rules"]

    out = io.StringIO()
    rc = runner.main(["--root", str(tmp_path), "--check",
                      "--select", "CTL301", "."], out=out)
    assert rc == 0


def test_syntax_error_is_a_finding(tmp_path):
    write(tmp_path, "broken.py", "def f(:\n")
    res = lint(tmp_path)
    assert [f.rule for f in res.findings] == ["CTL000"]


def test_check_fails_on_stale_baseline(tmp_path):
    """A baseline entry that no longer fires anywhere silently
    shrinks the gate — `--check` must fail on it, not just report."""
    import io
    write(tmp_path, "cluster/clean.py", "X = 1\n")
    bpath = tmp_path / "base.json"
    baseline_mod.save(str(bpath), [
        ("CTL302", "cluster/clean.py",
         "threading.Lock() in a daemon-plane module bypasses "
         "lockdep order checking — use common.lockdep.LockdepLock")])
    out = io.StringIO()
    rc = runner.main(["--root", str(tmp_path), "--check",
                      "--baseline", str(bpath), "."], out=out)
    assert rc == 1
    assert "stale baseline entry" in out.getvalue()
    # remove the stale entry -> the gate is green again
    baseline_mod.save(str(bpath), [])
    out = io.StringIO()
    rc = runner.main(["--root", str(tmp_path), "--check",
                      "--baseline", str(bpath), "."], out=out)
    assert rc == 0


def test_cli_rule_alias_filters_families(tmp_path):
    """`ceph lint --rule CTL###` — the triage-friendly alias of
    --select."""
    import io
    write(tmp_path, "cluster/svc.py",
          "import threading\nL = threading.Lock()\n")
    out = io.StringIO()
    rc = runner.main(["--root", str(tmp_path), "--json",
                      "--rule", "CTL3", "."], out=out)
    assert rc == 0
    payload = json.loads(out.getvalue())
    assert [f["rule"] for f in payload["findings"]] == ["CTL302"]
    out = io.StringIO()
    rc = runner.main(["--root", str(tmp_path), "--json",
                      "--rule", "CTL1", "."], out=out)
    assert json.loads(out.getvalue())["findings"] == []


def test_cli_graph_dump(tmp_path):
    """`ceph lint --graph module.fn` answers who-reaches-this /
    what-this-reaches from the whole-program graph."""
    import io
    write(tmp_path, "pkg/__init__.py", "")
    write(tmp_path, "pkg/b.py", """\
        def leaf(x):
            return x + 1
        """)
    write(tmp_path, "pkg/a.py", """\
        from .b import leaf

        def mid(x):
            return leaf(x)

        def top(x):
            return mid(x)
        """)
    out = io.StringIO()
    rc = runner.main(["--root", str(tmp_path), "--graph", "b.leaf",
                      "pkg"], out=out)
    assert rc == 0
    text = out.getvalue()
    assert "pkg.b.leaf" in text
    assert "< pkg.a.mid" in text          # direct caller
    assert "2 transitive" in text         # mid + top reach it
    out = io.StringIO()
    assert runner.main(["--root", str(tmp_path), "--graph",
                        "no.such.fn", "pkg"], out=out) == 2


def test_full_tree_lint_wall_time_budget():
    """The interprocedural graph must not make the tier-1 gate
    unaffordable: one full-tree run (every rule, whole-program graph
    included, shared through the per-run Program cache) stays under
    the 30 s CI budget."""
    import time as _time
    t0 = _time.perf_counter()
    res = runner.run(
        str(REPO),
        baseline=str(REPO / "scripts" / "lint_baseline.json"))
    elapsed = _time.perf_counter() - t0
    assert res.program is not None
    assert elapsed < 30.0, \
        f"full-tree lint took {elapsed:.1f}s — past the CI budget"


# --------------------------------------- CTL9xx: serving paths ---

def test_ctl901_full_index_read_on_request_path(tmp_path):
    """Direct positive: a per-request gateway op loading the whole
    bucket index; negative: the shard read and the listing merge."""
    write(tmp_path, "rgw/gw.py", """\
        class Bucket:
            def _read_index(self):
                merged = {}
                for s in range(self.num_shards()):
                    merged.update(self._read_index_shard(s))
                return merged

            def _read_index_shard(self, s):
                return self.io.read(f"idx.{s}")

            def get_object(self, key):
                return self._read_index()[key]

            def head_object(self, key):
                return self._read_index_shard(0)[key]

            def list_objects(self):
                return sorted(self._read_index())
        """)
    res = lint(tmp_path, select=["CTL901"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("rgw/gw.py", 12)], res.findings
    assert "get_object" in res.findings[0].msg
    assert "shard" in res.findings[0].msg


def test_ctl901_reaches_through_helper_and_scope_and_noqa(tmp_path):
    """Interprocedural positive (the wrapper shape), out-of-scope
    module stays clean, and # noqa suppresses."""
    write(tmp_path, "rgw/gw.py", """\
        class Bucket:
            def _read_index(self):
                return dict(self.io.read("idx"))

            def _lookup(self, key):
                return self._read_index().get(key)

            def delete_object(self, key):
                return self._lookup(key)

            def put_object(self, key, data):
                return self._read_index()  # noqa: CTL901
        """)
    write(tmp_path, "cluster/other.py", """\
        def get_object(store):
            return store._read_index()
        """)
    res = lint(tmp_path, select=["CTL901"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("rgw/gw.py", 8)], res.findings
    assert "via" in res.findings[0].msg


@pytest.mark.smoke
def test_check_static_smoke():
    """scripts/check_static.py end to end: the seeded fixture tree's
    violations are all caught AND the real tree is clean inside the
    budget — the gate catches what it claims to catch."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_static", str(REPO / "scripts" / "check_static.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([]) == 0


# --------------------------------- CTL130: wire hot-path copies ---

def test_ctl130_copy_patterns_in_msg(tmp_path):
    """Positives: bytes(payload), b''.join, + concatenation inside
    msg/; negative: non-payload bytes() and out-of-scope modules
    stay clean."""
    write(tmp_path, "msg/wire.py", """\
        def send(sock, meta, data):
            payload = bytes(data)
            frame = b"".join([meta, payload])
            return sock.send(meta + data)

        def header(n):
            return bytes(n)               # allocation, not a copy

        def small(sock, hdr):
            return bytes(hdr)             # not a payload name
        """)
    write(tmp_path, "cluster/store.py", """\
        def persist(data):
            return bytes(data)            # out of CTL130 scope
        """)
    res = lint(tmp_path, select=["CTL130"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("msg/wire.py", 2), ("msg/wire.py", 3), ("msg/wire.py", 4)], \
        res.findings


def test_ctl130_objecter_fanout_and_helper_and_noqa(tmp_path):
    """The client fan-out is in scope — directly and through a
    helper over the whole-program graph — and # noqa suppresses."""
    write(tmp_path, "client/remote.py", """\
        def _pack(data):
            return bytes(data)

        def fanout(aio, writes):
            for tgt, data in writes:
                aio.call_async(tgt, {"data": _pack(data)})

        def fanout_justified(aio, tgt, data):
            buf = bytes(data)  # noqa: CTL130 — snapshot by design
            aio.call_async(tgt, {"data": buf})

        def host_side(data):
            return bytes(data)            # never reaches the wire
        """)
    res = lint(tmp_path, select=["CTL130"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("client/remote.py", 2)], res.findings
    assert "reached from 'fanout'" in res.findings[0].msg


# ------------------------------ CTL131: reply-direction rescans ---

def test_ctl131_reply_rescan_and_chokepoint(tmp_path):
    """Positive: a reply sender that rescans payload bytes; negative:
    the combine chokepoint (calls crc32_combine) and a non-reply
    sender stay clean."""
    write(tmp_path, "cluster/srv.py", """\
        import zlib

        def send_reply(conn, rid, data):
            crc = zlib.crc32(data)
            return prepare_frame(conn, MSG_REPLY, rid, [data], crc)

        def send_reply_folded(conn, rid, data, csums):
            crc = crc32_combine(0, csums.combined, len(data))
            return prepare_frame(conn, MSG_REPLY_SG, rid, [data], crc)

        def send_request(conn, rid, data):
            crc = zlib.crc32(data)        # request lane: CTL130 turf
            return prepare_frame(conn, MSG_REQ, rid, [data], crc)
        """)
    res = lint(tmp_path, select=["CTL131"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("cluster/srv.py", 4)], res.findings
    assert "fold" in res.findings[0].msg


def test_ctl131_reaches_helper_scope_and_noqa(tmp_path):
    """Interprocedural: a scan inside a helper the reply sender
    reaches over the program graph; # noqa suppresses; msg/-external
    modules are out of scope."""
    write(tmp_path, "msg/srv.py", """\
        def _digest(data):
            return crcutil.Csums.scan(data)

        def push_reply(ring, rid, data):
            ring.put(data, _digest(data).combined)
            return MSG_REPLY_SG

        def push_reply_counted(ring, rid, data):
            cs = crcutil.Csums.scan(data)  # noqa: CTL131 — counted fallback
            return ring.put(data, cs.combined)
        """)
    write(tmp_path, "rgw/gw.py", """\
        import zlib

        def send_reply(conn, data):
            crc = zlib.crc32(data)        # rgw/: out of scope
            return prepare_frame(conn, MSG_REPLY, 0, [data], crc)
        """)
    res = lint(tmp_path, select=["CTL131"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("msg/srv.py", 2)], res.findings
    assert "reached from 'push_reply'" in res.findings[0].msg


def test_ctl131_real_tree_reply_lane_is_scan_clean():
    """The RingReply reply lane itself: zero un-noqa'd rescans in
    msg/ + cluster/ — the fold chokepoint is the only sender-side
    crc source."""
    res = runner.run(str(REPO),
                     paths=["ceph_tpu/msg", "ceph_tpu/cluster"],
                     select=["CTL131"])
    assert not res.findings, "\n".join(
        f.render() for f in res.findings)


def test_ctl130_real_tree_hot_path_is_view_clean():
    """The refactored wire spine itself: zero un-noqa'd copy
    patterns in msg/ + the async objecter (the tree gate covers
    this too; asserted separately so a scoped run shows it)."""
    res = runner.run(str(REPO),
                     paths=["ceph_tpu/msg", "ceph_tpu/cluster",
                            "ceph_tpu/client"],
                     select=["CTL130"])
    assert not res.findings, "\n".join(
        f.render() for f in res.findings)


# ------------------------- CTL10xx: ShardCheck (SPMD/mesh axes) ---

def test_ctl1001_unbound_axis_across_modules(tmp_path):
    """The headline ShardCheck case: the collective lives in a
    DIFFERENT module than the shard_map site, its axis name resolves
    through an import, and the statically-resolved mesh does not bind
    it.  CI's single-device CPU mesh traces this fine; a real mesh
    raises NameError deep inside pjit."""
    write(tmp_path, "parallel/__init__.py", "")
    write(tmp_path, "parallel/mesh.py", """\
        SHARD_AXIS = "shard"
        STRIPE_AXIS = "stripe"
        """)
    write(tmp_path, "parallel/body.py", """\
        import jax
        from .mesh import SHARD_AXIS, STRIPE_AXIS

        def count(x):
            return jax.lax.psum(x, STRIPE_AXIS)   # mesh is 1-D!

        def total(x):
            return jax.lax.psum(x, SHARD_AXIS)
        """)
    write(tmp_path, "parallel/plane.py", """\
        import jax
        import numpy as np
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from .mesh import SHARD_AXIS
        from .body import count, total

        MESH = Mesh(np.array(jax.devices()), (SHARD_AXIS,))

        def build():
            bad = shard_map(count, mesh=MESH,
                            in_specs=(P(SHARD_AXIS),),
                            out_specs=P(SHARD_AXIS))
            good = shard_map(total, mesh=MESH,
                             in_specs=(P(SHARD_AXIS),),
                             out_specs=P(SHARD_AXIS))
            return bad, good
        """)
    res = lint(tmp_path, select=["CTL1001"])
    assert [(f.path, f.rule) for f in res.findings] == \
        [("parallel/body.py", "CTL1001")], res.findings
    msg = res.findings[0].msg
    assert "'stripe'" in msg and "not bound" in msg
    assert "'shard'" in msg        # the bound axes are named


def test_ctl1001_hardcoded_literal_and_noqa(tmp_path):
    """Axis string literals outside parallel/mesh.py are flagged even
    when they happen to spell a real axis — the 2-D mesh rename must
    be a one-edit change — and a 4-digit ``# noqa: CTL1001``
    suppresses."""
    write(tmp_path, "parallel/__init__.py", "")
    write(tmp_path, "parallel/mesh.py", 'SHARD_AXIS = "shard"\n')
    write(tmp_path, "parallel/plane.py", """\
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from .mesh import SHARD_AXIS

        def bad(x):
            return jax.lax.psum(x, "shard")

        def justified(x):
            return jax.lax.psum(x, "shard")  # noqa: CTL1001 — perf A/B

        def build(mesh):
            a = shard_map(bad, mesh=mesh,
                          in_specs=(P(SHARD_AXIS),),
                          out_specs=P(SHARD_AXIS))
            b = shard_map(justified, mesh=mesh,
                          in_specs=(P(SHARD_AXIS),),
                          out_specs=P(SHARD_AXIS))
            return a, b
        """)
    res = lint(tmp_path, select=["CTL1001"])
    assert len(res.findings) == 1, res.findings
    assert "hardcoded axis string 'shard'" in res.findings[0].msg
    assert len(res.noqa) == 1, "4-digit noqa code must parse"


def test_ctl1002_trace_time_mutation_positive_and_negative(tmp_path):
    """Host-state mutation reachable from jit: self attrs, captured
    dicts/lists, perf-counter .inc(), print().  Local containers and
    ``x.at[i].set()`` functional updates stay clean, as does the same
    code when it is not jit-reachable."""
    write(tmp_path, "mod.py", """\
        import jax

        COUNTS = {}
        EVENTS = []

        class Plane:
            def step(self, x):
                self.calls = 1                # trace-time attr write
                COUNTS["step"] = 1            # captured dict write
                EVENTS.append(x)              # captured list append
                print("step")                 # trace-time print
                return x

            def cold(self, x):
                self.calls = 0                # not jit-reachable
                return x

        @jax.jit
        def f(x, pc):
            pc.inc("calls")                   # counter lies per-trace
            local = []
            local.append(x)                   # local: fine
            y = x.at[0].set(1.0)              # functional: fine
            p = Plane()
            return p.step(y)
        """)
    res = lint(tmp_path, select=["CTL1002"])
    lines = sorted(f.line for f in res.findings)
    assert lines == [8, 9, 10, 11, 20], res.findings
    msgs = " | ".join(f.msg for f in res.findings)
    assert "trace" in msgs
    assert ".inc()" in msgs and "print()" in msgs


def test_ctl1002_trace_time_counter_demonstrably_miscounts(tmp_path):
    """The lie CTL1002 exists to catch, shown at runtime: a host
    counter incremented inside a jitted function counts TRACES, not
    calls — three invocations, one increment — and the static rule
    flags exactly that shape."""
    import jax
    import jax.numpy as jnp

    counts = {"calls": 0}

    @jax.jit
    def step(x):
        counts["calls"] += 1
        return x + 1

    for _ in range(3):
        step(jnp.ones((2,))).block_until_ready()
    assert counts["calls"] == 1, \
        "the trace-time increment ran once for three calls"

    write(tmp_path, "mod.py", """\
        import jax

        COUNTS = {"calls": 0}

        @jax.jit
        def step(x):
            COUNTS["calls"] += 1
            return x + 1
        """)
    res = lint(tmp_path, select=["CTL1002"])
    assert rules_of(res) == ["CTL1002"], res.findings
    assert "trace" in res.findings[0].msg


def test_ctl1003_per_device_sync_through_helper(tmp_path):
    """Tracer casts and device_get in shard_map-reachable code —
    including across a module boundary — are per-device host round
    trips; shape-derived casts and non-reachable host code stay
    clean."""
    write(tmp_path, "parallel/__init__.py", "")
    write(tmp_path, "parallel/mesh.py", 'SHARD_AXIS = "shard"\n')
    write(tmp_path, "parallel/helper.py", """\
        import jax

        def pull(x):
            return jax.device_get(x)          # reached from body()

        def fine(x):
            return int(x.shape[0])            # static shape math

        def host_entry(x):
            return jax.device_get(x)          # never shard-reached
        """)
    write(tmp_path, "parallel/plane.py", """\
        import jax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from .mesh import SHARD_AXIS
        from .helper import pull, fine

        def body(x):
            n = int(x)                        # tracer cast
            y = fine(x)
            return pull(y)

        def build(mesh):
            return shard_map(body, mesh=mesh,
                             in_specs=(P(SHARD_AXIS),),
                             out_specs=P(SHARD_AXIS))
        """)
    res = lint(tmp_path, select=["CTL1003"])
    assert sorted((f.path, f.line) for f in res.findings) == \
        [("parallel/helper.py", 4), ("parallel/plane.py", 8)], \
        res.findings
    msgs = " | ".join(f.msg for f in res.findings)
    assert "jax.device_get" in msgs and "int() cast" in msgs
    assert "shard_map-reachable" in msgs


def test_ctl1004_spec_arity_and_unknown_axis(tmp_path):
    """in_specs arity vs parameters, out_specs arity vs returns, and
    a PartitionSpec axis the resolved mesh does not carry."""
    write(tmp_path, "parallel/__init__.py", "")
    write(tmp_path, "parallel/mesh.py", """\
        SHARD_AXIS = "shard"
        STRIPE_AXIS = "stripe"
        """)
    write(tmp_path, "parallel/plane.py", """\
        import jax
        import numpy as np
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        from .mesh import SHARD_AXIS, STRIPE_AXIS

        MESH = Mesh(np.array(jax.devices()), (SHARD_AXIS,))

        def body(a, b):
            return a + b

        def arity():
            return shard_map(body, mesh=MESH,
                             in_specs=(P(SHARD_AXIS),),
                             out_specs=P(SHARD_AXIS))

        def badaxis():
            return shard_map(body, mesh=MESH,
                             in_specs=(P(SHARD_AXIS),
                                       P(STRIPE_AXIS)),
                             out_specs=P(SHARD_AXIS))

        def outarity():
            return shard_map(body, mesh=MESH,
                             in_specs=(P(SHARD_AXIS), P()),
                             out_specs=(P(SHARD_AXIS), P()))

        def clean():
            return shard_map(body, mesh=MESH,
                             in_specs=(P(SHARD_AXIS), P()),
                             out_specs=P(SHARD_AXIS))
        """)
    res = lint(tmp_path, select=["CTL1004"])
    msgs = sorted(f.msg for f in res.findings)
    assert len(msgs) == 3, res.findings
    assert any("in_specs carries 1 spec(s)" in m and
               "takes 2 positional" in m for m in msgs)
    assert any("PartitionSpec axis 'stripe'" in m and
               "does not exist" in m for m in msgs)
    assert any("out_specs carries 2 spec(s)" in m and
               "returns 1 value(s)" in m for m in msgs)


def test_ctl1005_unreduced_total_and_bad_ppermute(tmp_path):
    """A per-shard jnp.sum() returned through a replicated out_spec
    with no psum reads one device's partial as the cluster total; the
    psum'd twin is clean.  A literal ppermute permutation repeating a
    source is flagged wherever it sits."""
    write(tmp_path, "parallel/__init__.py", "")
    write(tmp_path, "parallel/mesh.py", 'SHARD_AXIS = "shard"\n')
    write(tmp_path, "parallel/plane.py", """\
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from .mesh import SHARD_AXIS

        def bad(x):
            rows = jnp.sum(x)
            return x, rows

        def good(x):
            rows = jax.lax.psum(jnp.sum(x), SHARD_AXIS)
            return x, rows

        def build(mesh):
            a = shard_map(bad, mesh=mesh,
                          in_specs=(P(SHARD_AXIS),),
                          out_specs=(P(SHARD_AXIS), P()))
            b = shard_map(good, mesh=mesh,
                          in_specs=(P(SHARD_AXIS),),
                          out_specs=(P(SHARD_AXIS), P()))
            return a, b

        def shifty(x):
            perm = [(0, 1), (0, 2)]
            return jax.lax.ppermute(x, SHARD_AXIS, perm=perm)
        """)
    res = lint(tmp_path, select=["CTL1005"])
    assert sorted((f.path, f.line) for f in res.findings) == \
        [("parallel/plane.py", 9), ("parallel/plane.py", 26)], \
        res.findings
    msgs = " | ".join(f.msg for f in res.findings)
    assert "cluster total" in msgs and "bijection" in msgs


def test_ctl1006_process_rank_in_traced_code(tmp_path):
    """jax.process_index()/process_count() inside jit/shard_map-
    reachable code traces a DIFFERENT program per host (the classic
    multi-host divergence); the same read host-side — outside the
    traced path — is the blessed pattern and stays clean, and a
    ``# noqa: CTL1006`` suppresses."""
    write(tmp_path, "parallel/__init__.py", "")
    write(tmp_path, "parallel/mesh.py", 'SHARD_AXIS = "shard"\n')
    write(tmp_path, "parallel/plane.py", """\
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        from .mesh import SHARD_AXIS

        def bad(x):
            if jax.process_index() == 0:
                x = x + 1
            return x

        def justified(x):
            r = jax.process_count()  # noqa: CTL1006 — debug build
            return x * r

        def good(x):
            return jax.lax.psum(x, SHARD_AXIS)

        def build(mesh):
            a = shard_map(bad, mesh=mesh,
                          in_specs=(P(SHARD_AXIS),),
                          out_specs=P(SHARD_AXIS))
            b = shard_map(justified, mesh=mesh,
                          in_specs=(P(SHARD_AXIS),),
                          out_specs=P(SHARD_AXIS))
            c = shard_map(good, mesh=mesh,
                          in_specs=(P(SHARD_AXIS),),
                          out_specs=P(SHARD_AXIS))
            return a, b, c

        @jax.jit
        def stepped(x):
            return bad(x)

        def host_side():
            # rank reads OUTSIDE traced code are the blessed pattern
            return jax.process_index(), jax.process_count()
        """)
    res = lint(tmp_path, select=["CTL1006"])
    assert [(f.path, f.line) for f in res.findings] == \
        [("parallel/plane.py", 8)], res.findings
    assert "trace-time constant" in res.findings[0].msg
    assert "parallel.multihost" in res.findings[0].msg
    assert len(res.noqa) == 1, "noqa'd rank read must suppress"


def test_misspelled_axis_in_real_data_plane_is_caught(tmp_path):
    """Acceptance: deliberately misspell a collective axis name in a
    copy of the REAL parallel/data_plane.py and `ceph lint` reports it
    statically — the failure mode that otherwise only a multi-device
    TPU host would surface."""
    import io as _io
    real = (REPO / "ceph_tpu" / "parallel" /
            "data_plane.py").read_text()
    assert "), SHARD_AXIS)" in real, \
        "expected a psum(..., SHARD_AXIS) collective site"
    broken = real.replace("), SHARD_AXIS)", "), 'shrad')", 1)
    write(tmp_path, "parallel/data_plane.py", broken)
    write(tmp_path, "parallel/mesh.py",
          (REPO / "ceph_tpu" / "parallel" / "mesh.py").read_text())
    res = lint(tmp_path, select=["CTL1001"])
    assert res.findings, "misspelled axis must be caught"
    assert all(f.path == "parallel/data_plane.py"
               for f in res.findings), res.findings
    assert any("'shrad'" in f.msg and "not bound" in f.msg
               for f in res.findings), res.findings

    # and through the operator CLI: `ceph lint` passes the flags
    # straight to the runner, so the same check gates interactively
    from ceph_tpu.tools.ceph_cli import main as ceph_main
    buf = _io.StringIO()
    rc = ceph_main(["lint", ".", "--root", str(tmp_path),
                    "--select", "CTL1001", "--baseline", "none",
                    "--check"], out=buf)
    assert rc == 1
    assert "shrad" in buf.getvalue()


def test_cli_sarif_output(tmp_path):
    """--sarif emits the GitHub code-scanning subset of SARIF 2.1.0:
    tool metadata with every registered rule, error-level results with
    repo-relative locations."""
    import io as _io
    write(tmp_path, "cluster/svc.py", """\
        import threading
        L = threading.Lock()
        """)
    buf = _io.StringIO()
    rc = runner.main(["--root", str(tmp_path), "--sarif",
                      "--select", "CTL302", "--baseline", "none",
                      "."], out=buf)
    assert rc == 0
    doc = json.loads(buf.getvalue())
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "cephtpu-lint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"CTL302", "CTL1001", "CTL1005"} <= rule_ids
    result = run["results"][0]
    assert result["ruleId"] == "CTL302"
    assert result["level"] == "error"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "cluster/svc.py"
    assert loc["artifactLocation"]["uriBaseId"] == "SRCROOT"
    assert loc["region"]["startLine"] == 2


# ----------------------------------------------- the tier-1 gate ---

def test_tree_is_lint_clean():
    """`scripts/lint.py --check` equivalent, run on every pytest run:
    a new violation anywhere in ceph_tpu/ or scripts/ fails the suite
    before review.  The committed baseline is capped small so every
    grandfathered exception stays reviewable."""
    res = runner.run(
        str(REPO),
        baseline=str(REPO / "scripts" / "lint_baseline.json"))
    assert not res.findings, "new lint findings:\n" + \
        "\n".join(f.render() for f in res.findings)
    assert len(res.baselined) <= 10, \
        "baseline grew past the 10-entry budget — fix, don't hide"
    assert not res.stale_baseline, \
        f"stale baseline entries: {res.stale_baseline}"
