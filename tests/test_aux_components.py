"""Aux components: compressor plugins, tree dumper / CrushLocation,
tracer spans, librados-style client, mClock scheduler, peering machine.

Reference surfaces: src/compressor/ + PluginRegistry.cc,
src/crush/CrushTreeDumper.h + CrushLocation.cc, src/common/tracer.h,
src/librados/, src/osd/scheduler/mClockScheduler.cc,
src/osd/PeeringState.h."""
import numpy as np
import pytest

from tests.test_simulator import make_sim


# ------------------------------------------------------------ compressor ---

def test_compressor_roundtrip_all():
    from ceph_tpu.common.compressor import CompressorError, compressors
    reg = compressors()
    payload = b"the quick brown fox " * 500
    for name in ("zlib", "lzma", "bz2"):
        c = reg.factory(name)
        z = c.compress(payload)
        assert len(z) < len(payload)
        assert c.decompress(z) == payload
    with pytest.raises(CompressorError):
        reg.factory("nope")
    with pytest.raises(CompressorError):
        reg.factory("zlib").decompress(b"garbage!")


def test_compressor_registry_rejects_dupes():
    from ceph_tpu.common.compressor import (CompressorError,
                                            CompressorRegistry)
    r = CompressorRegistry()
    with pytest.raises(CompressorError):
        r.add("zlib", lambda: None)


# ------------------------------------------------- tree dump / location ----

def test_crush_location_and_tree_dump():
    from ceph_tpu.placement.compiler import compile_crushmap
    from ceph_tpu.placement.treedump import crush_location, tree_dump
    text = open("tests/cli/basic.crush").read()
    m = compile_crushmap(text)
    loc = crush_location(m, 0)
    assert loc == {"host": "host-a", "root": "default"}
    loc4 = crush_location(m, 5)
    assert loc4["host"] == "host-c"
    out = tree_dump(m)
    assert "root default" in out and "host host-a" in out
    assert "osd.5" in out
    # children indented under parents
    lines = out.splitlines()
    root_i = next(i for i, l in enumerate(lines) if "root default" in l)
    host_i = next(i for i, l in enumerate(lines) if "host host-a" in l)
    assert host_i > root_i


def test_tree_dump_skips_class_shadows():
    from ceph_tpu.placement.compiler import compile_crushmap
    from ceph_tpu.placement.treedump import tree_dump
    m = compile_crushmap(open("tests/cli/classes.crush").read())
    out = tree_dump(m)
    assert "~ssd" not in out and "~hdd" not in out


# ----------------------------------------------------------------- tracer --

def test_tracer_spans_nest():
    from ceph_tpu.common.tracer import tracer
    t = tracer()
    t.reset()
    with t.start_span("op", pool=1) as root:
        with t.start_span("encode") as child:
            pass
        with t.start_span("fanout"):
            pass
    spans = t.dump()
    assert len(spans) == 3
    by_name = {s["name"]: s for s in spans}
    assert by_name["encode"]["parent_id"] == root.span_id
    assert by_name["fanout"]["trace_id"] == root.trace_id
    assert by_name["op"]["parent_id"] is None
    assert by_name["op"]["tags"] == {"pool": 1}
    assert all(s["duration_s"] >= 0 for s in spans)


# ----------------------------------------------------------------- client --

def test_rados_client_api():
    from ceph_tpu.client import IoCtx, ObjectNotFound, Rados
    from ceph_tpu.cluster.monitor import Monitor
    sim = make_sim()
    mon = Monitor(sim.osdmap)
    cluster = Rados(sim, mon).connect()
    assert set(cluster.pool_list()) == {"rep", "ec"}
    io = cluster.open_ioctx("ec")
    data = bytes(range(256)) * 64
    io.write_full("obj1", data)
    assert io.read("obj1") == data
    assert io.read("obj1", length=16, offset=256) == data[256:272]
    io.write("obj1", b"patch", offset=100)
    assert io.read("obj1", length=5, offset=100) == b"patch"
    st = io.stat("obj1")
    assert st.size == len(data)
    assert io.list_objects() == ["obj1"]
    # aio
    f = io.aio_write_full("obj2", b"async-bytes")
    f.result(timeout=10)
    assert io.aio_read("obj2").result(timeout=10) == b"async-bytes"
    io.remove("obj2")
    with pytest.raises(ObjectNotFound):
        io.read("obj2")
    with pytest.raises(ObjectNotFound):
        io.stat("missing")
    assert cluster.cluster_stat()["num_objects"] == 1
    assert cluster.health() in ("HEALTH_OK", "HEALTH_WARN")
    cluster.shutdown()


# -------------------------------------------------------------- scheduler --

def test_mclock_classes_share_by_weight():
    from ceph_tpu.msg.scheduler import (CLASS_BEST_EFFORT, CLASS_CLIENT,
                                        CLASS_RECOVERY, MClockScheduler)
    s = MClockScheduler()
    for i in range(60):
        s.enqueue(("c", i), CLASS_CLIENT)
        s.enqueue(("r", i), CLASS_RECOVERY)
        s.enqueue(("b", i), CLASS_BEST_EFFORT)
    drained = [s.dequeue() for _ in range(120)]
    assert all(d is not None for d in drained)
    counts = {}
    for klass, _ in drained:
        counts[klass] = counts.get(klass, 0) + 1
    # client (weight 2, res 1) must dominate; best-effort (limit 1) least
    assert counts[CLASS_CLIENT] > counts[CLASS_RECOVERY] \
        >= counts.get(CLASS_BEST_EFFORT, 0)
    # full drain leaves nothing
    while s.dequeue() is not None:
        pass
    assert len(s) == 0 and s.dequeue() is None


def test_mclock_reservation_floors_starved_class():
    from ceph_tpu.msg.scheduler import (CLASS_CLIENT, CLASS_RECOVERY,
                                        MClockScheduler, QoS)
    s = MClockScheduler({CLASS_RECOVERY: QoS(reservation=0.5, weight=0.1,
                                             limit=10.0)})
    for i in range(200):
        s.enqueue(("c", i), CLASS_CLIENT)
    for i in range(20):
        s.enqueue(("r", i), CLASS_RECOVERY)
    got_r = sum(1 for _ in range(100)
                if (s.dequeue() or ("", 0))[0] == CLASS_RECOVERY)
    # reservation 0.5/vt guarantees recovery service despite weight 0.1
    assert got_r >= 10


def test_mclock_unknown_class():
    from ceph_tpu.msg.scheduler import MClockScheduler
    s = MClockScheduler()
    with pytest.raises(KeyError):
        s.enqueue("x", "warp-speed")


# ---------------------------------------------------------------- peering --

def test_peering_clean_path():
    from ceph_tpu.cluster.peering import (CLEAN, GET_INFO, GET_LOG,
                                          GET_MISSING, PGStateMachine)
    sim = make_sim()
    sim.put(2, "obj", b"payload" * 100)
    pool = sim.osdmap.pools[2]
    pg = sim.object_pg(pool, "obj")
    m = PGStateMachine(sim, 2, pg)
    res = m.peer()
    assert res.state == CLEAN
    for st in (GET_INFO, GET_LOG, GET_MISSING):
        assert st in res.history
    assert res.missing_osds == []


def test_peering_recovers_after_failure():
    from ceph_tpu.cluster.peering import (CLEAN, RECOVERING,
                                          PeeringCoordinator)
    sim = make_sim()
    rng = np.random.default_rng(23)
    for i in range(6):
        sim.put(2, f"p{i}", rng.integers(0, 256, 20000)
                .astype(np.uint8).tobytes())
    placed = sim.put(2, "p0", rng.integers(0, 256, 20000)
                     .astype(np.uint8).tobytes())
    victim = placed[0]
    sim.kill_osd(victim)
    # write to p0 itself: the victim IS in its up set, so its replica
    # lags the PG log while down
    sim.write(2, "p0", 10, b"while-down")
    sim.revive_osd(victim)
    coord = PeeringCoordinator(sim, 2)
    results = coord.handle_map_change()
    states = coord.states()
    assert states.get(CLEAN, 0) == len(results)
    assert any(RECOVERING in r.history or "Backfilling" in r.history
               for r in results.values())
    # data still reads after the full re-peer
    assert sim.get(2, "p0")[10:20] == b"while-down"
    assert sim.scrub(2) == []


# -------------------------------------------------------- lrc crush rule ---

def test_lrc_locality_rule_generation():
    """LRC generates a locality-aware CRUSH rule: each local group
    lands inside one locality bucket, chunks across failure domains
    within it — local repairs never cross localities."""
    from ceph_tpu.ec import instance as ec_registry
    from ceph_tpu.ec.plugin_lrc import lrc_crush_rule
    from ceph_tpu.placement import scalar_mapper
    from ceph_tpu.placement.builder import build_flat_cluster
    from ceph_tpu.placement.crush_map import ITEM_NONE, WEIGHT_ONE
    # 4 racks x 5 hosts x 2 osds; LRC k=4 m=2 l=3 -> 8 chunks, 2 groups
    # of 4 chunks each (needs >= 4 hosts per rack)
    cmap, root = build_flat_cluster(n_racks=4, n_hosts=20,
                                    osds_per_host=2, seed=9,
                                    weight_jitter=False)
    cmap.type_names.update({0: "osd", 1: "host", 2: "rack", 10: "root"})
    cmap.bucket_names.setdefault(root, "default")
    codec = ec_registry().factory(
        "lrc", {"k": "4", "m": "2", "l": "3",
                "crush-locality": "rack", "crush-failure-domain": "host"})
    ruleno = lrc_crush_rule(codec, cmap)
    weights = [WEIGHT_ONE] * cmap.max_devices
    # host->rack index so we can check group locality
    host_rack = {}
    for b in cmap.buckets:
        if b is not None and b.type == 2:
            for it in b.items:
                host_rack[it] = b.id
    osd_host = {}
    for b in cmap.buckets:
        if b is not None and b.type == 1:
            for it in b.items:
                osd_host[it] = b.id
    n = codec.get_chunk_count()
    groups = len(codec.layers) - 1
    per_group = n // groups
    placed_any = 0
    for x in range(64):
        out = scalar_mapper.do_rule(cmap, ruleno, x, n, weights)
        if len(out) != n or any(o == ITEM_NONE for o in out):
            continue
        placed_any += 1
        for g in range(groups):
            chunk_osds = out[g * per_group:(g + 1) * per_group]
            racks = {host_rack[osd_host[o]] for o in chunk_osds}
            assert len(racks) == 1, f"group {g} spans racks {racks}"
            hosts = [osd_host[o] for o in chunk_osds]
            assert len(set(hosts)) == len(hosts), "hosts collide"
    assert placed_any > 48          # rule actually places


def test_cluster_admin_commands():
    """The `ceph daemon`/`ceph tell` command surface over a live
    cluster: status/df/osd tree/pg dump/scrub/snap ls/health through
    the AdminServer registry (admin_socket.cc role)."""
    import json
    from ceph_tpu.common.admin import AdminServer
    from ceph_tpu.cluster.admin_commands import register_cluster_commands
    from ceph_tpu.cluster.monitor import Monitor
    from tests.test_snaps import make_sim
    sim = make_sim()
    mon = Monitor(sim.osdmap)
    srv = AdminServer()
    register_cluster_commands(srv, sim, mon)
    sim.put(1, "adm1", b"x" * 700)
    sim.put(2, "adm2", b"y" * 9000)
    sim.snap_create(1, "s1")
    st = srv.handle({"prefix": "status"})["result"]
    assert st["osds"]["up"] == st["osds"]["total"] == 8
    assert st["objects"] == 2
    df = srv.handle({"prefix": "df"})["result"]
    assert df[1]["bytes"] == 700 and df[2]["bytes"] == 9000
    tree = srv.handle({"prefix": "osd tree"})["result"]
    assert "host" in tree and "osd.0" in tree
    pgd = srv.handle({"prefix": "pg dump", "pool": 1})["result"]
    assert len(pgd["pgs"]) == sim.osdmap.pools[1].pg_num
    sc = srv.handle({"prefix": "scrub", "pool": 2})["result"]
    assert sum(r["objects"] for r in sc) == 1
    assert all(r["inconsistent"] == [] for r in sc)
    snaps = srv.handle({"prefix": "snap ls", "pool": 1})["result"]
    assert list(snaps.values()) == ["s1"]
    health = srv.handle({"prefix": "health"})["result"]
    assert isinstance(health, list)
    # full JSON round trip (the socket serving format)
    out = json.loads(srv.handle_json('{"prefix": "df"}'))
    assert out["result"]["1"]["objects"] == 1   # JSON keys stringify
