"""Process-model cluster: real daemons, real SIGKILL, cephx auth.

The VERDICT r2 Missing-#2/#3 contract: a vstart-analog launches mon +
N OSD *processes* exchanging typed envelopes (authenticated, MAC'd);
the chaos tier kills >=2 OSD processes with SIGKILL, the mon detects
the failures through peer heartbeat reports, and restarted daemons
recover against their durable stores with zero acknowledged-write
loss.  Reference roles: src/vstart.sh, src/ceph_osd.cc:540-551,
qa/tasks/ceph_manager.py (Thrasher), src/auth/cephx/CephxProtocol.h.
"""
import os
import time

import numpy as np
import pytest

from ceph_tpu.common import auth as cx
from ceph_tpu.tools.vstart import Vstart, build_cluster_dir

N_OSDS = 6


def wait_for_state(fn, polls=240, tick=0.25, desc="state"):
    """Deterministic wait-for-state (ISSUE 9 flake fix): the budget
    is a bounded number of POLLS, and a connection error — a daemon
    mid-reboot, a mon failing over — costs one poll instead of
    aborting the wait or burning the whole wall-clock window.  Under
    multi-suite CPU contention the old `time.monotonic() deadline`
    loops expired while starved daemons were still converging."""
    for _ in range(polls):
        try:
            if fn():
                return True
        except (OSError, IOError):
            pass
        time.sleep(tick)
    raise AssertionError(f"cluster never reached {desc} "
                         f"within {polls} polls")


@pytest.fixture
def cluster(tmp_path):
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=N_OSDS, osds_per_host=2, fsync=False)
    v = Vstart(d)
    v.start(N_OSDS, hb_interval=0.25)
    yield d, v
    v.stop()


def _client(d):
    from ceph_tpu.client.remote import RemoteCluster
    return RemoteCluster(d)


def test_daemon_slow_ops_roll_up_to_mon(tmp_path, monkeypatch):
    """ISSUE 2 satellite (PR 1's known gap): each OSD process owns its
    own OpTracker, so its slow ops used to be visible only on its own
    asok.  Now the OSD heartbeat reports slow_ops_summary() to the mon
    (report_slow_ops) and the mon's SLOW_OPS health check covers the
    whole daemon cluster.  complaint_time=0 via env (inherited by the
    spawned daemons) makes every tracked op count as slow."""
    monkeypatch.setenv("CEPH_TPU_OP_TRACKER_COMPLAINT_TIME", "0")
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(3, hb_interval=0.25)
    try:
        rc = _client(d)
        for i in range(4):
            assert rc.put(1, f"slow{i}", b"x" * 512) >= 1
        deadline = time.monotonic() + 30
        codes = {}
        while time.monotonic() < deadline:
            h = rc.mon_call({"cmd": "health"})
            codes = {c["code"]: c for c in h["checks"]}
            if "SLOW_OPS" in codes:
                break
            time.sleep(0.3)
        assert "SLOW_OPS" in codes, f"no rollup; checks: {codes}"
        assert h["status"] in ("HEALTH_WARN", "HEALTH_ERR")
        # attribution names the reporting daemon(s), not "unknown"
        assert "osd." in codes["SLOW_OPS"]["summary"]
        rc.close()
    finally:
        v.stop()


def test_replicated_io_and_sigkill_recovery(cluster):
    d, v = cluster
    rc = _client(d)
    rng = np.random.default_rng(1)
    blobs = {f"obj{i}": rng.integers(0, 256, 4000,
                                     dtype=np.uint8).tobytes()
             for i in range(12)}
    for name, data in blobs.items():
        assert rc.put(1, name, data) >= 2
    # converge to FULL replication before killing: a put may have
    # acked 2/3 under load (a starved peer dropped the fan-out), and
    # SIGKILLing exactly those two holders would make the object
    # legitimately unreadable until they return — the root of the old
    # kill9-timing flake, not a degraded-read bug.  A recovery pass
    # alone is NOT proof: a spuriously-marked-down member (starvation
    # + missed heartbeats) is invisible to that pass, so the gate
    # demands all OSDs up AND a presence digest from every mapped
    # member of every object's PG.
    def fully_replicated():
        rc.refresh_map()
        if rc.status()["n_up"] < N_OSDS:
            return False
        rc.recover_pool(1)
        pool = rc.osdmap.pools[1]
        for name in blobs:
            pg = rc._pg_for(pool, name)
            for m in [o for o in rc._up(pool, pg) if o >= 0]:
                if rc.osd_call(m, {"cmd": "digest_shard",
                                   "coll": [1, pg],
                                   "oid": f"0:{name}"}) is None:
                    return False
        return True
    wait_for_state(fully_replicated, polls=60,
                   desc="full replication before kill9")
    # SIGKILL two OSD processes (the Thrasher kill_osd)
    v.kill9("osd.1")
    v.kill9("osd.3")
    assert not v.alive("osd.1") and not v.alive("osd.3")
    # peers' heartbeat reports drive the mon to mark them down —
    # deterministic wait-for-state (poll budget), not a wall deadline
    wait_for_state(lambda: rc.status()["n_up"] <= N_OSDS - 2,
                   desc="SIGKILLed OSDs marked down")
    # degraded reads: every object still served.  Under CPU
    # contention the mon can SPURIOUSLY mark starved-but-alive OSDs
    # down (missed heartbeats) faster than they re-announce, leaving
    # a PG transiently without a mapped live member — a poll-budget
    # wait per object, not a single-shot sweep
    rc.refresh_map()
    for name, data in blobs.items():
        wait_for_state(
            lambda n=name, d=data: rc.get(1, n) == d,
            polls=120, desc=f"degraded read of {name}")
    # degraded writes keep flowing; the client path retries through
    # its per-primary (session, seq) stamp, so a write that races a
    # rebooting daemon REPLAYS instead of double-applying or failing
    for i in range(6):
        assert rc.put(1, f"degraded{i}", blobs["obj0"]) >= 1
    # restart the killed daemons against their durable stores
    v.start_osd(1, hb_interval=0.25)
    v.start_osd(3, hb_interval=0.25)
    wait_for_state(lambda: rc.status()["n_up"] == N_OSDS,
                   desc="revived OSDs back up")
    rc.refresh_map()
    # primary-driven peering recovery re-replicates everything; the
    # revived OSDs' gaps are covered by the pg logs, so they catch up
    # by LOG DELTA (not backfill) — the PeeringState contract.
    # recovery itself talks to every member, so a member still
    # replaying its store can drop the first sweep — bounded retry
    stats = None
    for _ in range(6):
        try:
            stats = rc.recover_pool(1)
            break
        except (OSError, IOError):
            time.sleep(0.5)
            rc.refresh_map()
    assert stats is not None, "recovery never completed a sweep"
    assert stats["copied"] > 0
    assert stats["modes"]["delta"] > 0
    assert stats["modes"]["backfill"] == 0
    for name, data in blobs.items():
        assert rc.get(1, name) == data
    for i in range(6):
        assert rc.get(1, f"degraded{i}") == blobs["obj0"]
    rc.close()


def test_ec_io_across_processes(tmp_path):
    d = str(tmp_path / "ec_cluster")
    build_cluster_dir(
        d, n_osds=6, osds_per_host=1, fsync=False,
        pools=[{"id": 1, "name": "rep", "type": 1, "size": 3,
                "pg_num": 8, "crush_rule": 0},
               {"id": 2, "name": "ec", "type": 3, "size": 6,
                "pg_num": 8, "crush_rule": 1,
                "erasure_code_profile": "default"}])
    v = Vstart(d)
    v.start(6, hb_interval=0.25)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d, ec_profiles={
            "default": {"plugin": "jax", "k": "4", "m": "2",
                        "layout": "bitsliced"}})
        rng = np.random.default_rng(2)
        data = rng.integers(0, 256, 30000, dtype=np.uint8).tobytes()
        # under a loaded host a daemon can exceed one wire timeout or
        # drop a heartbeat (mon briefly marks it down and the up set
        # maps 5/6 shards until it re-boots); writes are idempotent,
        # so retry with map refreshes until every shard acks
        acks = 0
        for _ in range(10):
            acks = rc.put(2, "big", data)
            if acks == 6:
                break
            time.sleep(1.5)
            rc.refresh_map()
        assert acks == 6
        assert rc.get(2, "big") == data
        # kill two shard holders: k=4 survivors still decode
        v.kill9("osd.0")
        v.kill9("osd.5")
        assert rc.get(2, "big") == data
        rc.close()
    finally:
        v.stop()


def test_snapshots_over_the_wire(cluster):
    """VERDICT r3 next #3: snapshots work against daemons — pool snap
    state committed mon-side, client-driven COW (make_writeable role),
    snap reads resolve through the SnapSet attr."""
    d, v = cluster
    rc = _client(d)
    v1 = b"version-one" * 100
    v2 = b"version-TWO" * 100
    rc.put(1, "snappy", v1)
    sid = rc.snap_create(1, "s1")
    assert rc.snap_lookup(1, "s1") == sid
    rc.put(1, "snappy", v2)              # COW preserves v1 as a clone
    assert rc.get(1, "snappy") == v2
    assert rc.get_snap(1, "snappy", sid) == v1
    # a second snapshot without further writes reads the current head
    sid2 = rc.snap_create(1, "s2")
    assert rc.get_snap(1, "snappy", sid2) == v2
    # the full wire snap surface: ls sees both, remove drops one
    # (committed mon state, CTL801 closure: every arm exercised)
    ls = rc.snap_ls(1)
    assert {int(s) for s in ls["snaps"]} >= {sid, sid2}
    rc.snap_remove(1, "s2")
    ls2 = rc.snap_ls(1)
    assert str(sid2) not in ls2["snaps"]
    assert str(sid) in ls2["snaps"]
    # snapshots (and the removal) survive a mon restart
    v.kill9("mon")
    v.start_mon()
    time.sleep(0.5)
    rc2 = _client(d)
    assert rc2.snap_lookup(1, "s1") == sid
    assert rc2.get_snap(1, "snappy", sid) == v1
    assert str(sid2) not in rc2.snap_ls(1)["snaps"]
    rc2.close()
    rc.close()


def test_clay_ranged_repair_over_the_wire_mixed_shapes(tmp_path):
    """Wire-tier minimum-bandwidth (clay) repair against live
    daemons, with MIXED plan shapes in one PG sweep: one object
    repairs through the ranged sub-chunk path (async rebuilt-shard
    push gathered after the loop), another lost an EXTRA shard
    out-of-band and must take the full-decode path in the same
    `_recover_ec_pg_move` call.  Regression: the push-gather loop
    once rebound the shard-fetch dict (`fetched`), crashing exactly
    this mixed sweep."""
    d = str(tmp_path / "clay_cluster")
    profs = {"cp": {"plugin": "clay", "k": "2", "m": "2", "d": "3"}}
    build_cluster_dir(
        d, n_osds=6, osds_per_host=1, fsync=False,
        pools=[{"id": 1, "name": "clay", "type": 3, "size": 4,
                "pg_num": 2, "crush_rule": 1,
                "erasure_code_profile": "cp"}])
    v = Vstart(d)
    v.start(6, hb_interval=0.25)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d, ec_profiles=profs)
        pool = rc.osdmap.pools[1]
        # two objects in the SAME PG: one stays single-loss
        # (ranged), one loses an extra shard (full decode)
        names = ["ranged0"]
        pg = rc._pg_for(pool, names[0])
        i = 0
        while len(names) < 2:
            cand = f"mixed{i}"
            i += 1
            if rc._pg_for(pool, cand) == pg:
                names.append(cand)
        rng = np.random.default_rng(23)
        datas = {n: rng.integers(0, 256, 30_000,
                                 dtype=np.uint8).tobytes()
                 for n in names}
        for n in names:
            assert rc.put(1, n, datas[n]) >= 3
        up = rc._up(pool, pg)
        victim = up[1]
        # out-of-band second loss for the mixed object only: shard 2
        # deleted from its live holder
        rc.osd_call(up[2], {"cmd": "delete_shard", "coll": [1, pg],
                            "oid": f"2:{names[1]}"})
        v.kill9(f"osd.{victim}")
        wait_for_state(lambda: rc.status()["n_up"] <= 5,
                       desc="clay victim marked down")
        rc.mon_call({"cmd": "mark_out", "osd": victim})
        rc.refresh_map()
        st = rc.recover_ec_pool(1)
        assert st.get("unrecoverable", 0) == 0, st
        assert st.get("ranged_repairs", 0) >= 1, st
        assert st.get("shards_rebuilt", 0) >= 2, st
        for n in names:
            assert rc.get(1, n) == datas[n], n
        rc.close()
    finally:
        v.stop()


def test_scrub_over_the_wire(cluster):
    """VERDICT r3 next #3: scrub runs against daemons — cross-replica
    digest compare on the primary, inconsistent copy repaired from
    the majority."""
    d, v = cluster
    rc = _client(d)
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, 5000, dtype=np.uint8).tobytes()
    for i in range(4):
        rc.put(1, f"scr{i}", data)
    # converge first: a replica write may have raced under load (put
    # acks a majority); recovery heals it so the baseline is clean
    rc.recover_pool(1)
    clean = rc.scrub_pool(1)
    assert clean["objects"] >= 4
    assert clean["inconsistent"] == []
    # wire-level store fsck on live daemons: clean before the
    # corruption below (the asok store_fsck twin, CTL801 closure)
    assert rc.osd_fsck(0) == []
    # corrupt ONE replica of one object out-of-band (direct shard
    # write to a non-primary member — the objectstore-surgery shape)
    pool = rc.osdmap.pools[1]
    pg = rc._pg_for(pool, "scr0")
    up = rc._up(pool, pg)
    victim = up[1]
    rc.osd_client(victim).call({
        "cmd": "put_shard", "coll": [1, pg], "oid": "0:scr0",
        "data": b"\x00" * len(data)})
    # a spurious markdown between the corruption and the scrub can
    # re-home the PG onto an empty substitute (1-vs-1 digest tie, no
    # safe majority) — scrub's membership is only meaningful on a
    # whole map, so converge like the other ISSUE 9 flake fixes

    def scrub_flags_victim():
        rc.refresh_map()
        if rc.status()["n_up"] < N_OSDS:
            return False
        dirty = rc.scrub_pool(1)
        bad = [i for i in dirty["inconsistent"]
               if i["oid"] == "0:scr0"]
        return bool(bad) and victim in bad[0]["bad_members"]
    wait_for_state(scrub_flags_victim, polls=40,
                   desc="scrub flagging the corrupted replica")
    # repair from the majority, then verify clean + readable
    fixed = rc.scrub_pool(1, repair=True)
    assert fixed["repaired"] >= 1
    assert rc.scrub_pool(1)["inconsistent"] == []
    assert rc.get(1, "scr0") == data
    rc.close()


def test_auth_rejections(cluster):
    d, v = cluster
    from ceph_tpu.cluster.daemon import WireClient
    # 1. unknown entity: mon refuses the secret handshake
    with pytest.raises(cx.AuthError):
        WireClient(os.path.join(d, "mon.sock"), "client.evil",
                   secret=b"\x00" * 32)
    # 2. wrong secret for a real entity
    with pytest.raises(cx.AuthError):
        WireClient(os.path.join(d, "mon.sock"), "client.admin",
                   secret=b"\x00" * 32)
    # 3. forged ticket: an OSD rejects a ticket not sealed by its key
    ring = cx.Keyring.load(os.path.join(d, "keyring.client"))
    fake_ring = cx.Keyring.generate(["osd.0", "client.admin"])
    forged, box = cx.TicketServer(fake_ring).grant("client.admin",
                                                   "osd.0")
    key = cx.open_key_box(fake_ring.secret("client.admin"), box)
    with pytest.raises((cx.AuthError, IOError)):
        WireClient(os.path.join(d, "osd.0.sock"), "client.admin",
                   ticket=forged, session_key=key)
    # 4. the real path still works afterwards
    rc = _client(d)
    rc.put(1, "authed", b"ticket holders only")
    assert rc.get(1, "authed") == b"ticket holders only"
    rc.close()


def test_ticket_cannot_cross_services(cluster):
    """A ticket granted for osd.0 must be rejected by osd.1 (sealed
    under the wrong service secret)."""
    d, v = cluster
    ring = cx.Keyring.load(os.path.join(d, "keyring.client"))
    from ceph_tpu.cluster.daemon import WireClient
    mon = WireClient(os.path.join(d, "mon.sock"), "client.admin",
                     secret=ring.secret("client.admin"))
    grant = mon.call({"cmd": "get_ticket", "service": "osd.0"})
    key = cx.open_key_box(ring.secret("client.admin"), grant["key_box"])
    with pytest.raises((cx.AuthError, IOError)):
        WireClient(os.path.join(d, "osd.1.sock"), "client.admin",
                   ticket=grant["ticket"], session_key=key)
    mon.close()


def test_osd_cannot_boot_another_osd(cluster):
    """Entity checks on mon commands: osd.2's session may not announce
    osd.4 up."""
    d, v = cluster
    ring = cx.Keyring.load(os.path.join(d, "keyring.mon"))
    from ceph_tpu.cluster.daemon import WireClient
    c = WireClient(os.path.join(d, "mon.sock"), "osd.2",
                   secret=ring.secret("osd.2"))
    with pytest.raises((cx.AuthError, PermissionError)):
        c.call({"cmd": "osd_boot", "osd": 4})
    c.close()


def test_mon_sigkill_restart_preserves_cluster_state(cluster):
    """SIGKILL the MON process: a restarted mon recovers epochs,
    up/down state and auth from its durable store (MonitorDBStore
    recovery in the process model), and clients keep working."""
    d, v = cluster
    rc = _client(d)
    rc.put(1, "pre-crash", b"written before the mon died")
    # force some committed map history (mark an osd out)
    rc.mon.call({"cmd": "mark_out", "osd": 5})
    epoch_before = rc.status()["epoch"]
    v.kill9("mon")
    assert not v.alive("mon")
    # OSDs and existing client connections keep serving object IO
    # (the mon is not on the data path)
    assert rc.get(1, "pre-crash") == b"written before the mon died"
    v.start_mon()
    rc2 = _client(d)
    st = rc2.status()
    assert st["epoch"] >= epoch_before        # nothing rolled back
    assert rc2.osdmap.osd_weight[5] == 0      # committed out survived
    # full auth + IO cycle against the restarted mon
    rc2.put(1, "post-restart", b"mon is back")
    assert rc2.get(1, "post-restart") == b"mon is back"
    rc.close()
    rc2.close()


@pytest.mark.slow
def test_process_thrasher_combined(tmp_path):
    """The process-level Thrasher: randomized OSD SIGKILL/restart plus
    one mon kill mid-stream, interleaved replicated AND EC writes, and
    a full verification pass at the end — zero acknowledged-write loss
    across the whole drill."""
    import random
    from ceph_tpu.client.remote import RemoteCluster
    d = str(tmp_path / "thrash")
    build_cluster_dir(
        d, n_osds=6, osds_per_host=2, fsync=False,
        pools=[{"id": 1, "name": "rep", "type": 1, "size": 3,
                "pg_num": 8, "crush_rule": 0},
               {"id": 2, "name": "ec", "type": 3, "size": 5,
                "pg_num": 8, "crush_rule": 1,
                "erasure_code_profile": "p"}])
    v = Vstart(d)
    v.start(6, hb_interval=0.25)
    rng = random.Random(7)
    nprng = np.random.default_rng(7)
    acked = {}
    try:
        rc = RemoteCluster(d, ec_profiles={
            "p": {"plugin": "jax", "k": "3", "m": "2",
                  "layout": "bitsliced"}})
        down = set()
        for step in range(30):
            action = rng.random()
            if action < 0.2 and len(down) < 2:
                victim = rng.choice([i for i in range(6)
                                     if i not in down])
                v.kill9(f"osd.{victim}")
                down.add(victim)
            elif action < 0.35 and down:
                back = down.pop()
                v.start_osd(back, hb_interval=0.25)
            if step == 15:                  # unconditional: the mon
                # kill must actually happen mid-stream
                v.kill9("mon")
                v.start_mon()
                rc.close()
                rc = RemoteCluster(d, ec_profiles={
                    "p": {"plugin": "jax", "k": "3", "m": "2",
                          "layout": "bitsliced"}})
            pool = 1 if rng.random() < 0.5 else 2
            name = f"t{step}"
            data = nprng.integers(0, 256, rng.randrange(500, 8000),
                                  dtype=np.uint8).tobytes()
            try:
                rc.refresh_map()
                rc.put(pool, name, data)
                acked[(pool, name)] = data
            except IOError:
                pass              # unacked writes carry no promise
        # heal: restart everything, recover both pools
        for back in list(down):
            v.start_osd(back, hb_interval=0.25)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and rc.status()["n_up"] < 6:
            time.sleep(0.3)
        rc.refresh_map()
        rc.recover_pool(1)
        rc.recover_ec_pool(2)
        assert len(acked) >= 20, f"thrasher acked only {len(acked)}"
        for (pool, name), data in acked.items():
            assert rc.get(pool, name) == data, (pool, name)
        rc.close()
    finally:
        v.stop()


def test_recovery_heals_member_stamped_current_without_data(cluster):
    """ISSUE 9 triage find (exposed by the contention soak): a past
    recovery pass whose peer listing/log fetch FAILED could stamp a
    member current (log_sync with an empty tail advanced
    last_complete past the member's own log head) while neither data
    nor entries landed — after which every pass read it as 'clean'
    and the objects were unreachable to recovery forever.  The fix is
    twofold: failed peer calls abort the pass instead of reading as
    'holds nothing', and the recovery baseline clamps last_complete
    to the member's own head, HEALING already-poisoned members."""
    d, v = cluster
    rc = _client(d)
    pool = rc.osdmap.pools[1]
    pg = rc._pg_for(pool, "heal-me")
    members = [o for o in rc._up(pool, pg) if o >= 0]
    prim, victim = members[0], members[-1]
    # the write lands ONLY on the primary (the victim's fan-out was
    # "dropped"): no entry, no object on the victim
    rc.osd_call(prim, {"cmd": "put_object", "coll": [1, pg],
                       "oid": "0:heal-me", "data": b"H" * 3000,
                       "replicas": [prim]})
    head = rc.osd_call(prim, {"cmd": "pg_info",
                              "coll": [1, pg]})["head"]
    # poison the victim the way the old bug did: a log_sync with an
    # EMPTY tail advances last_complete to the authority's head while
    # neither data nor entries land — current-on-paper, empty-handed
    rc.osd_call(victim, {"cmd": "log_sync", "coll": [1, pg],
                         "entries": [], "head": head})
    assert rc.osd_call(victim, {"cmd": "digest_shard",
                                "coll": [1, pg],
                                "oid": "0:heal-me"}) is None
    inf = rc.osd_call(victim, {"cmd": "pg_info", "coll": [1, pg]})
    assert tuple(inf["last_complete"]) >= tuple(head)
    # recovery must NOT read the poisoned member as clean
    stats = rc.osd_call(prim, {
        "cmd": "recover_pg", "coll": [1, pg], "members": members})
    assert stats["mode"].get(str(victim)) != "clean"
    assert rc.osd_call(victim, {"cmd": "digest_shard",
                                "coll": [1, pg],
                                "oid": "0:heal-me"}) is not None
    rc.close()


def test_recovery_reservations_gate_concurrent_backfills(cluster):
    """ISSUE 11 (c): PGs recover CONCURRENTLY, but no OSD ever holds
    more than osd_max_backfills reservations per role — the peak
    counts on every daemon's status prove the cap held — while
    client reads keep completing during the sweep (recovery rides
    the background_recovery dmClock class) and every deferred PG
    requeues to completion (zero data loss)."""
    import threading
    d, v = cluster
    rc = _client(d)
    rng = np.random.default_rng(7)
    blobs = {f"rsv{i}": rng.integers(0, 256, 3000,
                                     dtype=np.uint8).tobytes()
             for i in range(16)}
    for name, data in blobs.items():
        assert rc.put(1, name, data) >= 2
    v.kill9("osd.2")
    wait_for_state(lambda: rc.status()["n_up"] <= N_OSDS - 1,
                   desc="killed OSD marked down")
    rc.mon_call({"cmd": "mark_out", "osd": 2})
    rc.refresh_map()
    # client IO interleaved with the concurrent recovery sweep
    stop = threading.Event()
    reader_failures = []

    def reader():
        rd = _client(d)
        names = sorted(blobs)
        i = 0
        while not stop.is_set():
            nm = names[i % len(names)]
            try:
                if rd.get(1, nm) != blobs[nm]:
                    reader_failures.append(nm)
            except (OSError, IOError):
                pass      # transient during map churn: retried next
            i += 1
        rd.close()
    t = threading.Thread(target=reader, daemon=True)
    t.start()
    try:
        stats = None
        for _ in range(6):
            try:
                stats = rc.recover_pool(1)
                break
            except (OSError, IOError):
                time.sleep(0.5)
                rc.refresh_map()
    finally:
        stop.set()
        t.join(10)
    assert stats is not None and "deferred_pgs" not in stats, stats
    assert not reader_failures, reader_failures
    # the reservation invariant: held drained to zero, peaks within
    # the osd_max_backfills cap (default 1) on BOTH roles
    for o in range(N_OSDS):
        if o == 2:
            continue
        st = rc.osd_call(o, {"cmd": "status"})
        resv = st["recovery_reservations"]
        assert resv["held"] == {"local": 0, "remote": 0}, (o, resv)
        for role, peak in resv["peak"].items():
            assert peak <= 1, (o, role, resv)
    # at least one daemon actually took reservations (the gate ran)
    peaks = sum(
        sum(rc.osd_call(o, {"cmd": "status"}
                        )["recovery_reservations"]["peak"].values())
        for o in range(N_OSDS) if o != 2)
    assert peaks > 0
    for name, data in blobs.items():
        assert rc.get(1, name) == data
    rc.close()


@pytest.mark.smoke
def test_check_recovery_script():
    """The recovery smoke script (ISSUE 11 CI hook), run in-process:
    sim-tier whole-OSD rebuild with zero loss + stage breakdown,
    process-tier reservation-counter consistency."""
    import importlib.util
    import pathlib
    path = pathlib.Path(__file__).resolve().parents[1] / "scripts" \
        / "check_recovery.py"
    spec = importlib.util.spec_from_file_location(
        "check_recovery", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0


def test_lrc_wire_recovery_rebuilds_with_sub_k_plan(tmp_path):
    """ISSUE 11 review regression: an LRC local-group decode plan is
    SMALLER than k by design — the wire sweep must decode from it
    rather than calling the object unrecoverable (the old
    `len(shards) < k` gate), and the decode-fetch byte counter must
    reflect the sub-k read."""
    d = str(tmp_path / "lrc_cluster")
    profs = {"pl": {"plugin": "lrc", "k": "4", "m": "2", "l": "3"}}
    build_cluster_dir(
        d, n_osds=10, osds_per_host=1, fsync=False,
        pools=[{"id": 1, "name": "lrc", "type": 3, "size": 8,
                "pg_num": 4, "crush_rule": 1,
                "erasure_code_profile": "pl"}])
    v = Vstart(d)
    v.start(10, hb_interval=0.25)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d, ec_profiles=profs)
        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, 40_000, dtype=np.uint8).tobytes()
        assert rc.put(1, "lw0", data) >= 7
        pool = rc.osdmap.pools[1]
        up = rc._up(pool, rc._pg_for(pool, "lw0"))
        victim = up[0]
        v.kill9(f"osd.{victim}")
        wait_for_state(lambda: rc.status()["n_up"] <= 9,
                       desc="LRC victim marked down")
        rc.mon_call({"cmd": "mark_out", "osd": victim})
        rc.refresh_map()
        st = rc.recover_ec_pool(1)
        assert st.get("shards_rebuilt", 0) >= 1, st
        assert st.get("unrecoverable", 0) == 0, st
        # the decode read fewer than k full shards (local-group plan)
        codec = rc.codec_for(pool)
        plan = codec.minimum_to_decode(
            {0}, set(range(codec.get_chunk_count())) - {0})
        assert len(plan) < codec.k
        assert rc.get(1, "lw0") == data
        rc.close()
    finally:
        v.stop()


def test_kill9_reboot_keeps_history_rates_sane(tmp_path, monkeypatch):
    """ISSUE 16 satellite: a SIGKILLed-and-rebooted OSD restarts its
    in-process perf counters from zero, so its next report_perf
    delivery goes BACKWARDS.  The mon's metrics-history layer must
    count that as a reset and clamp the interval to rate 0.0 — the
    `ceph telemetry history` wire series stays consistent, never a
    negative rate, across the reboot."""
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(3, hb_interval=0.25)
    try:
        rc = _client(d)

        def hist():
            return rc.mon_call({"cmd": "cluster_stats",
                                "history": {"counter":
                                            "osd.io.wr_ops"}})

        for i in range(16):
            assert rc.put(1, f"h{i}", b"x" * 4096) >= 1

        # wr_ops is a PRIMARY-side counter, so only OSDs that primary
        # a written PG ever report it — demand two such reporters,
        # each with a real multi-sample series
        def two_reporters_sampled():
            q = hist()
            live = [s for s in q["series"].values()
                    if len(s["samples"]) >= 2
                    and s["samples"][-1][1] > 0]
            return len(live) >= 2
        wait_for_state(two_reporters_sampled,
                       desc="multi-sample history on two OSDs")
        q0 = hist()
        victim = max(q0["series"],
                     key=lambda k: q0["series"][k]["samples"][-1][1])
        vid = int(victim.split(".")[1])
        assert q0["series"][victim]["resets"] == 0

        v.kill9(victim)
        assert not v.alive(victim)
        v.start_osd(vid, hb_interval=0.25)
        wait_for_state(lambda: rc.status()["n_up"] >= 3,
                       desc="rebooted OSD back up")

        # fresh counters start at zero; keep writing NEW names until
        # the rebooted primary counts one (fewer than its pre-kill
        # total, so the delivery goes backwards) and the mon counts
        # the reset.  One put per poll keeps the budget bounded.
        n_extra = [0]

        def reset_counted():
            rc.refresh_map()
            rc.put(1, f"r{n_extra[0]}", b"y" * 4096)
            n_extra[0] += 1
            q = hist()
            s = q["series"].get(victim)
            return bool(s) and s["resets"] >= 1 and \
                q["counter_resets"] >= 1
        wait_for_state(reset_counted, polls=60,
                       desc="reboot counted as reset")

        q = hist()
        s = q["series"][victim]
        rates = [r for _, r in s["rates"]]
        assert rates, "no rates derived across the reboot"
        assert all(r >= 0.0 for r in rates), \
            f"negative rate across reboot: {rates}"
        # the daemon filter narrows the wire reply to the victim
        qf = rc.mon_call({"cmd": "cluster_stats",
                          "history": {"counter": "osd.io.wr_ops",
                                      "daemon": victim}})
        assert set(qf["series"]) == {victim}
        rc.close()
    finally:
        v.stop()
