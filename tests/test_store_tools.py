"""kvstore-tool + monstore-tool — offline store surgery CLIs.

Reference roles: src/tools/kvstore_tool.cc (ceph-kvstore-tool),
src/tools/ceph_monstore_tool.cc.
"""
import io
import json

from ceph_tpu.cluster.kv import WriteBatch
from ceph_tpu.cluster.wal_kv import WalDB
from ceph_tpu.tools.kvstore_tool import main as kv_main
from ceph_tpu.tools.monstore_tool import main as mon_main


def run(main, *args, data_in=None):
    out = io.StringIO()
    if data_in is not None:
        rc = main(list(args), out=out, data_in=data_in)
    else:
        rc = main(list(args), out=out)
    return rc, out.getvalue()


def test_kvstore_tool_crud_and_stats(tmp_path):
    p = str(tmp_path / "db")
    db = WalDB(p, fsync=False)
    db.submit(WriteBatch().set("a", "k1", b"v1").set("b", "k2", b"word"))
    db.close()
    rc, txt = run(kv_main, p, "list")
    assert rc == 0 and "a\tk1" in txt and "b\tk2" in txt
    rc, txt = run(kv_main, p, "list", "a")
    assert "k1" in txt and "k2" not in txt
    rc, txt = run(kv_main, p, "get", "b", "k2")
    assert rc == 0 and txt == "word"
    rc, txt = run(kv_main, p, "set", "c", "k3", "-", data_in=b"new")
    assert rc == 0
    rc, txt = run(kv_main, p, "get", "c", "k3")
    assert txt == "new"
    rc, txt = run(kv_main, p, "rm", "a", "k1")
    assert rc == 0
    rc, txt = run(kv_main, p, "get", "a", "k1")
    assert rc == 1
    rc, txt = run(kv_main, p, "stats")
    assert rc == 0 and "TOTAL" in txt
    rc, txt = run(kv_main, p, "compact")
    assert rc == 0
    # surgery survives: reopen and check
    db2 = WalDB(p, fsync=False)
    assert db2.get("c", "k3") == b"new"
    assert db2.get("a", "k1") is None
    db2.close()


def test_monstore_tool_on_a_real_mon_store(tmp_path):
    """Build a durable mon store via the Monitor itself, then inspect
    it offline."""
    from ceph_tpu.cluster.monitor import Monitor
    from tests.test_snaps import make_sim
    sim = make_sim()
    p = str(tmp_path / "mon-store")
    db = WalDB(p, fsync=False)
    mon = Monitor(sim.osdmap, db=db)
    for _ in range(3):
        inc = mon.next_incremental()
        inc.new_weight[0] = 0x8000
        assert mon.commit_incremental(inc)
    db.close()
    rc, txt = run(mon_main, p, "summary")
    assert rc == 0 and "osdmap epochs: 3" in txt
    rc, txt = run(mon_main, p, "dump-keys")
    assert rc == 0 and "osdmap" in txt
    rc, txt = run(mon_main, p, "get-osdmap")
    assert rc == 0
    blob = json.loads(txt)
    assert blob["new_weight"]["0"] == 0x8000
    rc, txt = run(mon_main, p, "dump-paxos")
    assert rc == 0 and "osdmap" in txt
