"""L0 substrate: options registry, perf counters, admin server.

Reference surfaces: src/common/options.cc (typed table),
src/common/config.{h,cc} (layering + observers),
src/common/perf_counters.h (counter types + perf dump),
src/common/admin_socket.{h,cc} (JSON command socket).
"""
import json
import os

import pytest

from ceph_tpu.common import (AdminServer, Option, OptionError, Options,
                             admin_request, config, perf)
from ceph_tpu.common.options import (LEVEL_ENV, LEVEL_FILE, LEVEL_RUNTIME,
                                     TYPE_BOOL, TYPE_INT, TYPE_STR)


def make_opts():
    return Options([
        Option("alpha", TYPE_INT, 10, "an int", min=0, max=100),
        Option("beta", TYPE_BOOL, False, "a bool"),
        Option("gamma", TYPE_STR, "x", "an enum",
               enum_values=("x", "y", "z")),
    ])


def test_defaults_and_typing():
    o = make_opts()
    assert o.get("alpha") == 10
    assert o.get("beta") is False
    assert o.set("alpha", "42") == 42          # string coerced to int
    assert o.get("alpha") == 42
    assert o.set("beta", "yes") is True


def test_bounds_and_enum_rejected():
    o = make_opts()
    with pytest.raises(OptionError):
        o.set("alpha", 101)
    with pytest.raises(OptionError):
        o.set("alpha", -1)
    with pytest.raises(OptionError):
        o.set("gamma", "w")
    with pytest.raises(OptionError):
        o.set("nope", 1)
    with pytest.raises(OptionError):
        o.get("nope")


def test_layering_precedence():
    o = make_opts()
    o.set("alpha", 20, level=LEVEL_FILE)
    assert o.get("alpha") == 20
    o.set("alpha", 30, level=LEVEL_ENV)
    assert o.get("alpha") == 30
    o.set("alpha", 40, level=LEVEL_RUNTIME)
    assert o.get("alpha") == 40
    o.clear("alpha", LEVEL_RUNTIME)
    assert o.get("alpha") == 30
    o.clear("alpha", LEVEL_ENV)
    assert o.get("alpha") == 20


def test_env_var_layer(monkeypatch):
    o = make_opts()
    monkeypatch.setenv("CEPH_TPU_ALPHA", "55")
    assert o.get("alpha") == 55
    # env beats file (documented precedence: default < file < env)
    o.set("alpha", 20, level=LEVEL_FILE)
    assert o.get("alpha") == 55
    # malformed env fails loudly (silently dropping an operator setting
    # is worse than a crash) but dump() stays alive
    monkeypatch.setenv("CEPH_TPU_ALPHA", "banana")
    with pytest.raises(OptionError):
        o.get("alpha")
    assert "invalid" in str(o.dump()["alpha"]["value"])
    # runtime beats env
    monkeypatch.setenv("CEPH_TPU_ALPHA", "55")
    o.set("alpha", 60)
    assert o.get("alpha") == 60


def test_observer_fires():
    o = make_opts()
    seen = []
    o.observe("alpha", lambda k, v: seen.append((k, v)))
    o.set("alpha", 5)
    assert seen == [("alpha", 5)]


def test_load_file(tmp_path):
    o = make_opts()
    p = tmp_path / "conf.json"
    p.write_text(json.dumps({"alpha": 33, "gamma": "z"}))
    o.load_file(str(p))
    assert o.get("alpha") == 33
    assert o.get("gamma") == "z"


def test_dump_provenance():
    o = make_opts()
    o.set("alpha", 12)
    d = o.dump()
    assert d["alpha"]["value"] == 12 and d["alpha"]["source"] == "runtime"
    assert d["beta"]["source"] == "default"


def test_global_table_has_framework_knobs():
    c = config()
    for name in ("lookup_strategy", "fastmap_enabled",
                 "fastmap_extra_tries", "straw2_select",
                 "ec_table_cache_size", "mapper_max_lanes_per_call"):
        assert name in c.names()
    # round-1 env aliases preserved
    assert c.schema("lookup_strategy").env_var() == "CEPH_TPU_LOOKUP"
    assert c.schema("fastmap_enabled").env_var() == "CEPH_TPU_FASTMAP"


# ------------------------------------------------------------- counters ----

def test_counters_basics():
    pc = perf("test.group1")
    pc.inc("dispatches")
    pc.inc("dispatches", 4)
    pc.set("batch_lanes", 1024)
    pc.tinc("map_s", 0.5)
    pc.tinc("map_s", 1.5)
    d = pc.dump()
    assert d["dispatches"] == 5
    assert d["batch_lanes"] == 1024
    assert d["map_s"]["avgcount"] == 2
    assert abs(d["map_s"]["avgtime"] - 1.0) < 1e-9


def test_counters_timer_and_reset():
    pc = perf("test.group2")
    with pc.time("op_s"):
        pass
    assert pc.dump()["op_s"]["avgcount"] == 1
    pc.reset()
    assert pc.dump()["op_s"]["avgcount"] == 0


def test_collection_dump_groups():
    perf("test.group3").inc("x")
    allg = perf().dump()
    assert "test.group3" in allg and allg["test.group3"]["x"] >= 1


def test_counters_disabled(monkeypatch):
    config().set("perf_counters_enabled", False)
    try:
        pc = perf("test.group4")
        pc.inc("n")
        assert pc.dump().get("n", 0) == 0
    finally:
        config().set("perf_counters_enabled", True)


# ---------------------------------------------------------------- admin ----

def test_admin_inprocess_commands():
    srv = AdminServer()
    assert srv.handle({"prefix": "config get",
                       "key": "fastmap_enabled"})["result"]
    r = srv.handle({"prefix": "config set", "key": "fastmap_extra_tries",
                    "value": 10})
    assert r["result"]["success"] and \
        config().get("fastmap_extra_tries") == 10
    config().clear("fastmap_extra_tries")
    assert "error" in srv.handle({"prefix": "bogus"})
    assert "perf dump" in srv.handle({"prefix": "help"})["result"]


def test_admin_unix_socket(tmp_path):
    srv = AdminServer()
    path = str(tmp_path / "admin.sock")
    srv.serve(path)
    try:
        r = admin_request(path, {"prefix": "config get",
                                 "key": "straw2_select"})
        assert r["result"]["straw2_select"] in ("approx", "exact")
        r2 = admin_request(path, {"prefix": "perf dump"})
        assert "result" in r2
    finally:
        srv.close()


# ----------------------------------------------------- leveled logging --

def test_dout_leveled_logging():
    """dout/ldout analog: per-subsystem log+gather levels, recent ring
    (src/log/SubsystemMap.h + Log.cc roles)."""
    from ceph_tpu.common.log import Log
    lines = []
    log = Log(writer=lines.append)
    log.set_level("osd", 10, 20)
    log.dout("osd", 5, "emitted")               # <= log level
    log.dout("osd", 15, "gathered only")        # <= gather, > log
    log.dout("osd", 25, "dropped")              # > gather
    log.dout("crush", 4, "default subsys")      # default level 5
    assert [l for l in lines if "emitted" in l]
    assert not [l for l in lines if "gathered only" in l]
    recent = "\n".join(log.dump_recent())
    assert "gathered only" in recent and "dropped" not in recent
    assert "default subsys" in recent
    assert log.should_gather("osd", 20) and not log.should_gather("osd", 21)
    assert log.emitted == 2 and log.gathered == 3


# ------------------------------------------------------------- lockdep --

def test_lockdep_detects_inversion():
    """Lock-order cycle detection (src/common/lockdep.cc role)."""
    import threading
    import pytest
    from ceph_tpu.common import lockdep
    lockdep.reset()
    lockdep.enable()
    try:
        a = lockdep.LockdepLock("ld_a")
        b = lockdep.LockdepLock("ld_b")
        c = lockdep.LockdepLock("ld_c")
        with a:
            with b:
                pass                    # records a -> b
        with b:
            with c:
                pass                    # records b -> c
        # transitive inversion: c then a closes the cycle a->b->c->a
        with c:
            with pytest.raises(lockdep.LockOrderError):
                a.acquire()
        # recursive re-acquire of an RLock is fine
        with a:
            with a:
                pass
        # a DIFFERENT thread respects the same global order graph
        err = []

        def other():
            try:
                with b:
                    a.acquire()
                    a.release()
            except lockdep.LockOrderError as e:
                err.append(e)
        t = threading.Thread(target=other)
        t.start(); t.join()
        assert err, "inversion by another thread went undetected"
    finally:
        lockdep.disable()
        lockdep.reset()


def test_lockdep_unwinds_held_stack_on_exception():
    """Held-lock bookkeeping must unwind when a `with` body raises: a
    stale held entry would poison every later order check on this
    thread (phantom edges, false inversions) — the exception path the
    runtime checker's own `with` protocol has to get right."""
    import pytest
    from ceph_tpu.common import lockdep
    lockdep.enable()
    a = lockdep.LockdepLock("ld_exc_a")
    b = lockdep.LockdepLock("ld_exc_b")
    with pytest.raises(ValueError, match="boom"):
        with a:
            with b:
                assert lockdep.held_locks() == ["ld_exc_a",
                                                "ld_exc_b"]
                raise ValueError("boom")
    assert lockdep.held_locks() == []
    # the a -> b edge recorded before the raise survives the unwind:
    # the opposite order is still an inversion
    with b:
        with pytest.raises(lockdep.LockOrderError):
            a.acquire()
        # a failed acquire must leave no phantom held entry either
        assert lockdep.held_locks() == ["ld_exc_b"]
    assert lockdep.held_locks() == []
    # and the locks stay usable in the recorded order
    with a:
        with b:
            pass
