"""ObjectStore transaction interface + checksummed memstore backend.

Reference surfaces: src/os/ObjectStore.h + Transaction.h (atomic op
lists), src/os/memstore/, BlueStore per-block checksums (EIO on
mismatch) + fsck."""
import numpy as np
import pytest

from ceph_tpu.cluster.objectstore import (ChecksumError, MemStore,
                                          ObjectStoreError, Transaction)
from tests.test_simulator import make_sim

C = (1, 0)      # collection = (pool, pg)


def test_txn_write_read_roundtrip():
    st = MemStore()
    st.apply_transaction(
        Transaction().write_full(C, "a", b"hello").setattr(
            C, "a", "k", b"v").omap_set(C, "a", "idx", b"1"))
    assert st.read(C, "a") == b"hello"
    assert st.getattr(C, "a", "k") == b"v"
    assert st.omap_get(C, "a", "idx") == b"1"
    assert st.stat(C, "a")["size"] == 5
    assert st.list_objects(C) == ["a"]
    assert st.list_collections() == [C]


def test_txn_partial_write_and_truncate():
    st = MemStore()
    st.apply_transaction(Transaction().write_full(C, "o", b"0123456789"))
    st.apply_transaction(Transaction().write(C, "o", 3, b"abc"))
    assert st.read(C, "o") == b"012abc6789"
    st.apply_transaction(Transaction().write(C, "o", 12, b"xy"))
    assert st.read(C, "o") == b"012abc6789\0\0xy"
    st.apply_transaction(Transaction().truncate(C, "o", 4))
    assert st.read(C, "o") == b"012a"


def test_txn_atomic_rollback():
    """One bad op rolls back the WHOLE transaction."""
    st = MemStore()
    st.apply_transaction(Transaction().write_full(C, "keep", b"v1"))
    txn = (Transaction().write_full(C, "keep", b"v2")
           .write_full(C, "other", b"new")
           .remove(C, "never-existed"))       # fails
    with pytest.raises(ObjectStoreError):
        st.apply_transaction(txn)
    assert st.read(C, "keep") == b"v1"        # untouched
    assert not st.exists(C, "other")


def test_checksum_detects_corruption():
    st = MemStore()
    st.apply_transaction(Transaction().write_full(C, "c", b"payload"))
    st.corrupt(C, "c")
    with pytest.raises(ChecksumError):
        st.read(C, "c")
    assert st.fsck() == [(C, "c")]


def test_remove_and_multiple_colls():
    st = MemStore()
    st.apply_transaction(Transaction().write_full(C, "x", b"1")
                         .write_full((2, 5), "y", b"2"))
    st.apply_transaction(Transaction().remove(C, "x"))
    assert not st.exists(C, "x")
    assert st.read((2, 5), "y") == b"2"


def test_sim_osd_serves_no_bad_bytes():
    """A shard failing its checksum reads as MISSING: the EC path
    decodes from other shards instead of returning garbage."""
    sim = make_sim()
    data = bytes(range(256)) * 100
    sim.put(2, "chk", data)
    pool = sim.osdmap.pools[2]
    pg = sim.object_pg(pool, "chk")
    up = sim.pg_up(pool, pg)
    osd = sim.osds[up[0]]
    osd.objectstore.corrupt((2, pg), "0:chk")
    assert osd.get((2, pg, "chk", 0)) is None        # EIO -> missing
    assert sim.get(2, "chk") == data                  # decoded around
    assert osd.objectstore.fsck() == [((2, pg), "0:chk")]
