"""Erasure-code layer tests: roundtrips over all erasure patterns, plugin
registry semantics, fast paths, and JAX-vs-NumPy bit-exactness.

Models the reference suites src/test/erasure-code/TestErasureCode*.cc
(per-plugin roundtrip + profile validation) and TestErasureCodePlugin*.cc
(registry failure modes).
"""
import itertools

import numpy as np
import pytest

from ceph_tpu import ec
from ceph_tpu.ec.interface import ErasureCodeError
from ceph_tpu.ops import gf


def _codec(plugin, **profile):
    prof = {k: str(v) for k, v in profile.items()}
    return ec.instance().factory(plugin, prof)


def _roundtrip_all_patterns(codec, k, m, chunk=256, max_patterns=None,
                            rng_seed=0):
    rng = np.random.default_rng(rng_seed)
    data = rng.integers(0, 256, size=(k, chunk)).astype(np.uint8)
    parity = codec.encode_chunks(data)
    assert parity.shape == (m, chunk)
    full = np.concatenate([data, parity])
    patterns = []
    for nerase in range(1, m + 1):
        patterns.extend(itertools.combinations(range(k + m), nerase))
    if max_patterns:
        patterns = patterns[:max_patterns]
    for lost in patterns:
        avail = [i for i in range(k + m) if i not in lost]
        rebuilt = codec.decode_chunks(avail, full[avail], list(lost))
        assert np.array_equal(rebuilt, full[list(lost)]), \
            f"pattern {lost} failed"


@pytest.mark.parametrize("plugin,profile", [
    ("jerasure", dict(technique="reed_sol_van", k=4, m=2)),
    ("jerasure", dict(technique="reed_sol_van", k=8, m=3)),
    ("jerasure", dict(technique="reed_sol_van", k=5, m=3, w=16)),
    ("jerasure", dict(technique="reed_sol_r6_op", k=6, m=2)),
    ("jerasure", dict(technique="cauchy_orig", k=4, m=3)),
    ("jerasure", dict(technique="cauchy_good", k=8, m=3)),
    ("isa", dict(technique="cauchy", k=8, m=3)),
    ("isa", dict(technique="reed_sol_van", k=7, m=2)),
    ("jax", dict(technique="reed_sol_van", k=4, m=2)),
    ("jax", dict(technique="reed_sol_van", k=8, m=3)),
    ("jax", dict(technique="cauchy", k=8, m=4)),
])
def test_roundtrip_all_erasure_patterns(plugin, profile):
    codec = _codec(plugin, **profile)
    _roundtrip_all_patterns(codec, profile["k"], profile["m"])


def test_encode_decode_full_api():
    codec = _codec("jax", technique="reed_sol_van", k=4, m=2)
    payload = bytes(range(256)) * 5 + b"tail"
    chunks = codec.encode(set(range(6)), payload)
    assert len(chunks) == 6
    size = codec.get_chunk_size(len(payload))
    assert all(len(c) == size for c in chunks.values())
    # lose chunks 1 and 4, decode everything wanted
    survivors = {i: chunks[i] for i in (0, 2, 3, 5)}
    out = codec.decode({0, 1, 2, 3}, survivors, size)
    data = np.concatenate([out[i] for i in range(4)]).tobytes()
    assert data[:len(payload)] == payload
    assert codec.decode_concat(survivors).tobytes()[:len(payload)] == payload


def test_minimum_to_decode():
    codec = _codec("jax", k=4, m=2)
    # all wanted available -> plan reads exactly those
    plan = codec.minimum_to_decode({0, 1}, {0, 1, 2, 3, 4, 5})
    assert set(plan) == {0, 1}
    # chunk 0 lost -> need any 4 of the rest
    plan = codec.minimum_to_decode({0}, {1, 2, 3, 4, 5})
    assert len(plan) == 4 and 0 not in plan
    with pytest.raises(ErasureCodeError):
        codec.minimum_to_decode({0}, {1, 2, 3})
    assert all(v == [(0, 1)] for v in plan.values())


def test_batched_encode_matches_single():
    codec = _codec("jax", k=4, m=2)
    rng = np.random.default_rng(1)
    batch = rng.integers(0, 256, size=(7, 4, 128)).astype(np.uint8)
    out = codec.encode_chunks_batch(batch)
    assert out.shape == (7, 2, 128)
    for i in range(7):
        assert np.array_equal(out[i], codec.encode_chunks(batch[i]))


def test_batched_decode_matches_single():
    codec = _codec("jax", k=4, m=2)
    rng = np.random.default_rng(2)
    batch = rng.integers(0, 256, size=(5, 4, 64)).astype(np.uint8)
    parity = codec.encode_chunks_batch(batch)
    full = np.concatenate([batch, parity], axis=1)
    avail = [0, 2, 4, 5]
    rebuilt = codec.decode_chunks_batch(avail, full[:, avail], [1, 3])
    assert np.array_equal(rebuilt, full[:, [1, 3]])


def test_jax_matches_numpy_oracle():
    """The device kernel must be bit-identical to the table-math oracle."""
    jx = _codec("jax", technique="reed_sol_van", k=8, m=3)
    jr = _codec("jerasure", technique="reed_sol_van", k=8, m=3)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, size=(8, 1024)).astype(np.uint8)
    assert np.array_equal(jx.encode_chunks(data), jr.encode_chunks(data))


def test_isa_xor_fast_path():
    codec = _codec("isa", technique="reed_sol_van", k=5, m=2)
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, size=(5, 64)).astype(np.uint8)
    full = np.concatenate([data, codec.encode_chunks(data)])
    # single data erasure with parity0 available -> XOR path
    avail = [0, 1, 3, 4, 5, 6]
    rebuilt = codec.decode_chunks(avail, full[avail], [2])
    assert np.array_equal(rebuilt[0], full[2])


def test_decode_table_cache_reuse():
    codec = _codec("jax", k=4, m=2)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, size=(4, 32)).astype(np.uint8)
    full = np.concatenate([data, codec.encode_chunks(data)])
    avail = [0, 1, 4, 5]
    codec.decode_chunks(avail, full[avail], [2, 3])
    misses0 = codec._cache.misses
    codec.decode_chunks(avail, full[avail], [2, 3])
    assert codec._cache.misses == misses0
    assert codec._cache.hits >= 1


def test_registry_failure_modes():
    reg = ec.instance()
    with pytest.raises(ErasureCodeError):
        reg.factory("no_such_plugin", {})
    with pytest.raises(ErasureCodeError):
        reg.add("bad_version_plugin", lambda p: None, version="0.0.0-other")
    # duplicate registration rejected
    with pytest.raises(ErasureCodeError):
        reg.add("jax", lambda p: None)
    with pytest.raises(ErasureCodeError):
        reg.preload(["jax", "missing"])
    reg.preload(["jax", "jerasure", "isa"])


def test_profile_validation():
    with pytest.raises(ErasureCodeError):
        _codec("jerasure", technique="nope")
    with pytest.raises(ErasureCodeError):
        _codec("jerasure", technique="reed_sol_van", k="abc")
    with pytest.raises(ErasureCodeError):
        _codec("jerasure", technique="reed_sol_van", k=0)
    with pytest.raises(ErasureCodeError):
        _codec("jerasure", technique="reed_sol_r6_op", m=3)
    with pytest.raises(ErasureCodeError):
        _codec("jax", k=200, m=100)
    # liberation family: implemented as bitmatrix codecs (m=2 only)
    codec = _codec("jerasure", technique="liberation", k=4, m=2)
    assert codec.get_chunk_count() == 6
    with pytest.raises(ErasureCodeError):
        _codec("jerasure", technique="liberation", k=4, m=3)


def test_chunk_size_alignment():
    codec = _codec("jax", k=4, m=2)
    for width in (1, 100, 511, 512, 4096, 1 << 20):
        cs = codec.get_chunk_size(width)
        assert cs * 4 >= width
        assert cs % 128 == 0  # device-lane alignment


def test_w16_wide_field():
    codec = _codec("jerasure", technique="reed_sol_van", k=5, m=3, w=16)
    _roundtrip_all_patterns(codec, 5, 3, chunk=64)
