"""Durable tier: WalDB + FileStore crash consistency.

The VERDICT r2 Missing-#1 contract: crash (including kill -9) at any
point leaves both stores mountable with exactly the committed batches,
fsck clean, zero loss of acknowledged writes.  Reference roles:
RocksDBStore WAL (src/kv/RocksDBStore.cc), MonitorDBStore
(src/mon/MonitorDBStore.h), BlueStore fsck/csum
(src/os/bluestore/BlueStore.cc).
"""
import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from ceph_tpu.cluster.filestore import FileStore
from ceph_tpu.cluster.kv import WriteBatch
from ceph_tpu.cluster.objectstore import (ChecksumError, ObjectStoreError,
                                          Transaction)
from ceph_tpu.cluster.wal_kv import WalDB


# ------------------------------------------------------------------ WalDB --

def test_waldb_basic_persistence(tmp_path):
    p = str(tmp_path / "kv")
    db = WalDB(p, fsync=False)
    db.submit(WriteBatch().set("a", "k1", b"v1").set("b", "k2", b"v2"))
    db.submit(WriteBatch().rm("a", "k1").set("a", "k3", b"v3"))
    db.close()
    db2 = WalDB(p, fsync=False)
    assert db2.get("a", "k1") is None
    assert db2.get("b", "k2") == b"v2"
    assert db2.get("a", "k3") == b"v3"
    assert db2.keys("a") == ["k3"]


def test_waldb_torn_tail_discarded(tmp_path):
    p = str(tmp_path / "kv")
    db = WalDB(p, fsync=False)
    db.submit(WriteBatch().set("p", "good", b"yes"))
    db.close()
    # simulate a crash mid-append: garbage partial record at the tail
    with open(os.path.join(p, "wal.log"), "ab") as f:
        f.write(b"\x31\x4c\x41\x57" + b"partial-record-no-crc")
    db2 = WalDB(p, fsync=False)
    assert db2.get("p", "good") == b"yes"
    # the store keeps working after tail truncation
    db2.submit(WriteBatch().set("p", "more", b"data"))
    db2.close()
    db3 = WalDB(p, fsync=False)
    assert db3.get("p", "more") == b"data"


def test_waldb_batch_atomicity_in_log(tmp_path):
    """A batch is one WAL record: either every op replays or none."""
    p = str(tmp_path / "kv")
    db = WalDB(p, fsync=False)
    db.submit(WriteBatch().set("x", "a", b"1"))
    db.close()
    wal = os.path.join(p, "wal.log")
    size_one = os.path.getsize(wal)
    db = WalDB(p, fsync=False)
    db.submit(WriteBatch().set("x", "b", b"2").set("x", "c", b"3"))
    db.close()
    # cut the second record in half
    with open(wal, "r+b") as f:
        f.truncate(size_one + (os.path.getsize(wal) - size_one) // 2)
    db2 = WalDB(p, fsync=False)
    assert db2.get("x", "a") == b"1"
    assert db2.get("x", "b") is None and db2.get("x", "c") is None


def test_waldb_compaction_preserves_state(tmp_path):
    p = str(tmp_path / "kv")
    db = WalDB(p, fsync=False, compact_bytes=1 << 10)
    for i in range(200):
        db.submit(WriteBatch().set("n", f"k{i:04d}", bytes([i % 256]) * 50))
    db.submit(WriteBatch().rm("n", "k0000"))
    db.close()
    db2 = WalDB(p, fsync=False)
    assert db2.get("n", "k0000") is None
    assert db2.get("n", "k0199") == bytes([199]) * 50
    assert len(db2.keys("n")) == 199
    # compaction actually ran (wal restarted small)
    assert os.path.getsize(os.path.join(p, "wal.log")) < (1 << 11)


def test_waldb_rm_prefix_replay(tmp_path):
    p = str(tmp_path / "kv")
    db = WalDB(p, fsync=False)
    db.submit(WriteBatch().set("a", "1", b"x").set("b", "1", b"y"))
    db.submit(WriteBatch().rm_prefix("a"))
    db.close()
    db2 = WalDB(p, fsync=False)
    assert db2.keys("a") == [] and db2.keys("b") == ["1"]


# --------------------------------------------------------------- FileStore --

def test_filestore_basic_roundtrip(tmp_path):
    p = str(tmp_path / "store")
    fs = FileStore(p, fsync=False)
    txn = Transaction()
    txn.write((1, 0), "obj1", 0, b"hello world")
    txn.setattr((1, 0), "obj1", "ver", b"1")
    txn.omap_set((1, 0), "obj1", "snap", b"0")
    fs.apply_transaction(txn)
    fs.close()
    fs2 = FileStore(p, fsync=False)
    assert fs2.read((1, 0), "obj1") == b"hello world"
    assert fs2.getattr((1, 0), "obj1", "ver") == b"1"
    assert fs2.omap_get((1, 0), "obj1", "snap") == b"0"
    assert fs2.list_objects((1, 0)) == ["obj1"]
    assert fs2.list_collections() == [(1, 0)]
    assert fs2.fsck() == []
    fs2.close()


def test_filestore_partial_writes_overlay(tmp_path):
    fs = FileStore(str(tmp_path / "s"), fsync=False)
    rng = np.random.default_rng(3)
    ref = bytearray(1000)
    fs.apply_transaction(Transaction().write((1, 1), "o", 0, bytes(1000)))
    for _ in range(30):
        off = int(rng.integers(0, 900))
        ln = int(rng.integers(1, 100))
        data = bytes(rng.integers(0, 256, ln, dtype=np.uint8))
        ref[off:off + ln] = data
        fs.apply_transaction(Transaction().write((1, 1), "o", off, data))
    assert fs.read((1, 1), "o") == bytes(ref)
    # extent chains were compacted along the way
    assert len(fs._get_meta((1, 1), "o").extents) <= fs.compact_extents + 1
    assert fs.read((1, 1), "o", 100, 50) == bytes(ref[100:150])
    fs.close()


def test_filestore_truncate_remove_write_full(tmp_path):
    fs = FileStore(str(tmp_path / "s"), fsync=False)
    c = (2, 3)
    fs.apply_transaction(Transaction().write(c, "o", 0, b"x" * 100))
    fs.apply_transaction(Transaction().truncate(c, "o", 40))
    assert fs.read(c, "o") == b"x" * 40
    fs.apply_transaction(Transaction().truncate(c, "o", 60))
    assert fs.read(c, "o") == b"x" * 40 + b"\0" * 20
    fs.apply_transaction(Transaction().write_full(c, "o", b"new"))
    assert fs.read(c, "o") == b"new"
    fs.apply_transaction(Transaction().remove(c, "o"))
    assert not fs.exists(c, "o")
    with pytest.raises(ObjectStoreError):
        fs.read(c, "o")
    fs.close()


def test_filestore_txn_rollback_on_invalid_op(tmp_path):
    """A failing op aborts the WHOLE transaction (nothing hits disk)."""
    fs = FileStore(str(tmp_path / "s"), fsync=False)
    c = (1, 0)
    fs.apply_transaction(Transaction().write(c, "keep", 0, b"base"))
    txn = Transaction()
    txn.write(c, "keep", 0, b"MUTATED")
    txn.truncate(c, "missing", 10)       # invalid: no such object
    with pytest.raises(ObjectStoreError):
        fs.apply_transaction(txn)
    assert fs.read(c, "keep") == b"base"
    fs.close()


def test_filestore_corruption_detected(tmp_path):
    fs = FileStore(str(tmp_path / "s"), fsync=False)
    c = (1, 0)
    fs.apply_transaction(Transaction().write(c, "o", 0, b"A" * 256))
    fs.corrupt(c, "o", offset=17)
    with pytest.raises(ChecksumError):
        fs.read(c, "o")
    assert fs.fsck() == [(c, "o")]
    fs.close()


_CRASH_CHILD = textwrap.dedent("""
    import os, sys, signal
    sys.path.insert(0, {repo!r})
    from ceph_tpu.cluster.filestore import FileStore
    from ceph_tpu.cluster.objectstore import Transaction
    fs = FileStore({path!r}, fsync=True, fsck_on_mount=False)
    i = 0
    while True:
        txn = Transaction()
        txn.write((1, 0), f"obj{{i % 7}}", (i % 13) * 64,
                  bytes([i % 256]) * 256)
        txn.omap_set((1, 0), f"obj{{i % 7}}", "last", str(i).encode()) \\
            if i % 3 == 0 and i > 0 else txn.touch((1, 0), f"obj{{i % 7}}")
        fs.apply_transaction(txn)
        print(i, flush=True)          # ack AFTER the commit returned
        i += 1
""")


def test_filestore_survives_kill9(tmp_path):
    """kill -9 mid-write-storm: remount sees every ACKNOWLEDGED txn,
    fsck is clean, and the store keeps serving writes — the crash
    contract MemStore could never provide."""
    path = str(tmp_path / "crash_store")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-c",
         _CRASH_CHILD.format(repo=repo, path=path)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    acked = -1
    for line in proc.stdout:
        acked = int(line.strip())
        if acked >= 25:
            break
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()
    assert acked >= 25
    fs = FileStore(path, fsync=True)          # fsck_on_mount=True default
    # every acknowledged transaction must be present: replay the child's
    # write pattern and check the final acknowledged state per object
    for i in range(acked + 1):
        oid = f"obj{i % 7}"
        assert fs.exists((1, 0), oid), (i, oid)
    # the highest acked write to each object is intact
    by_obj = {}
    for i in range(acked + 1):
        by_obj[f"obj{i % 7}"] = i
    for oid, i in by_obj.items():
        off = (i % 13) * 64
        got = fs.read((1, 0), oid, off, 256)
        assert got == bytes([i % 256]) * 256, (oid, i)
    assert fs.fsck() == []
    # still writable after the crash
    fs.apply_transaction(Transaction().write((1, 0), "post", 0, b"ok"))
    assert fs.read((1, 0), "post") == b"ok"
    fs.close()


def test_waldb_survives_kill9(tmp_path):
    """Same contract for the raw KV (the mon store's seam)."""
    path = str(tmp_path / "crash_kv")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {repo!r})
        from ceph_tpu.cluster.wal_kv import WalDB
        from ceph_tpu.cluster.kv import WriteBatch
        db = WalDB({path!r}, fsync=True, compact_bytes=1 << 14)
        i = 0
        while True:
            db.submit(WriteBatch().set("epoch", f"e{{i:06d}}",
                                       str(i).encode() * 20))
            print(i, flush=True)
            i += 1
    """)
    proc = subprocess.Popen([sys.executable, "-c", child],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    acked = -1
    for line in proc.stdout:
        acked = int(line.strip())
        if acked >= 60:                  # crosses >=1 compaction
            break
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait()
    assert acked >= 60
    db = WalDB(path, fsync=True)
    for i in range(acked + 1):
        assert db.get("epoch", f"e{i:06d}") == str(i).encode() * 20, i
    db.close()


# ------------------------------------------------------- durable monitor --

def test_monitor_state_survives_restart(tmp_path):
    """Mon commits map epochs + config into WalDB; a fresh process
    mounts the store and recovers the same cluster state
    (MonitorDBStore role, src/mon/MonitorDBStore.h)."""
    from ceph_tpu.cluster.monitor import Monitor
    from ceph_tpu.cluster.osdmap import OSDMap, PGPool, POOL_REPLICATED
    from ceph_tpu.placement.builder import build_flat_cluster
    from ceph_tpu.placement.crush_map import (
        RULE_CHOOSELEAF_FIRSTN, RULE_EMIT, RULE_TAKE, Rule)
    from ceph_tpu.placement.builder import TYPE_HOST

    def base_map():
        cmap, root = build_flat_cluster(n_hosts=4, osds_per_host=2)
        cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                                  (RULE_CHOOSELEAF_FIRSTN, 0, TYPE_HOST),
                                  (RULE_EMIT, 0, 0)]))
        m = OSDMap(cmap)
        m.mark_all_in_up()
        m.add_pool(PGPool(id=1, name="p", type=POOL_REPLICATED, size=3,
                          pg_num=16, crush_rule=0))
        return m

    p = str(tmp_path / "monstore")
    db = WalDB(p, fsync=False)
    mon = Monitor(base_map(), db=db)
    inc = mon.next_incremental()
    inc.new_up[3] = False
    assert mon.commit_incremental(inc)
    inc2 = mon.next_incremental()
    inc2.new_weight[5] = 0
    assert mon.commit_incremental(inc2)
    assert mon.config_set("fastmap_extra_tries", 6)
    epoch_before = mon.osdmap.epoch
    up_before, prim_before = mon.osdmap.map_pgs_batch(1)
    db.close()

    db2 = WalDB(p, fsync=False)
    mon2 = Monitor.open(base_map(), db2)
    assert mon2.osdmap.epoch == epoch_before
    assert not mon2.osdmap.osd_up[3]
    assert mon2.osdmap.osd_weight[5] == 0
    assert mon2.config_get("fastmap_extra_tries") == 6
    assert mon2.paxos.version >= 3
    up_after, prim_after = mon2.osdmap.map_pgs_batch(1)
    assert (up_before == up_after).all()
    assert (prim_before == prim_after).all()
    db2.close()


def test_filestore_remove_kills_same_txn_rows(tmp_path):
    """setattr/omap_set staged earlier in the SAME txn must die with a
    later remove — no phantom metadata on recreation."""
    fs = FileStore(str(tmp_path / "s"), fsync=False)
    c = (1, 0)
    txn = Transaction()
    txn.write(c, "o", 0, b"x")
    txn.setattr(c, "o", "k", b"phantom")
    txn.omap_set(c, "o", "mk", b"phantom2")
    txn.remove(c, "o")
    fs.apply_transaction(txn)
    assert not fs.exists(c, "o")
    assert fs.kv.get("xattr", "1.0/o\x00k") is None
    assert fs.kv.get("omap", "1.0/o\x00mk") is None
    fs.apply_transaction(Transaction().write(c, "o", 0, b"fresh"))
    with pytest.raises(KeyError):
        fs.getattr(c, "o", "k")
    fs.close()


def test_filestore_same_txn_write_then_truncate(tmp_path):
    """Writes staged earlier in the SAME txn are clipped by a later
    truncate — no resurrected bytes on regrow."""
    fs = FileStore(str(tmp_path / "s"), fsync=False)
    c = (1, 0)
    txn = Transaction()
    txn.write(c, "o", 0, b"B" * 100)
    txn.truncate(c, "o", 50)
    fs.apply_transaction(txn)
    assert fs.read(c, "o") == b"B" * 50
    fs.apply_transaction(Transaction().truncate(c, "o", 100))
    assert fs.read(c, "o") == b"B" * 50 + b"\0" * 50
    fs.close()


def test_filestore_gc_reclaims_log_space(tmp_path):
    """Sustained overwrites must not grow the data log without bound:
    generation GC rewrites live bytes and the store stays correct
    across a remount."""
    p = str(tmp_path / "s")
    fs = FileStore(p, fsync=False, gc_min_bytes=1 << 16)
    c = (1, 0)
    rng = np.random.default_rng(8)
    final = {}
    for i in range(200):
        oid = f"o{i % 5}"
        data = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
        fs.apply_transaction(Transaction().write_full(c, oid, data))
        final[oid] = data
    log_size = os.path.getsize(fs._data_path)
    live = 5 * 4096
    assert log_size <= fs.gc_factor * live + (1 << 16), \
        f"log {log_size} vs live {live}: gc never ran"
    for oid, data in final.items():
        assert fs.read(c, oid) == data
    assert fs.fsck() == []
    fs.close()
    fs2 = FileStore(p, fsync=False)     # survives remount w/ fsck
    for oid, data in final.items():
        assert fs2.read(c, oid) == data
    fs2.close()


def test_objectstore_tool_surgery(tmp_path, capsys):
    """Offline store surgery (ceph-objectstore-tool role): list, info,
    export from one store, import into another, remove, fsck rc."""
    import json as _json
    from ceph_tpu.tools import objectstore_tool as ot
    a = str(tmp_path / "osd_a")
    b = str(tmp_path / "osd_b")
    fs = FileStore(a, fsync=False)
    txn = Transaction()
    txn.write((3, 1), "2:blob", 0, b"surgical payload " * 50)
    txn.setattr((3, 1), "2:blob", "ver", b"7")
    txn.omap_set((3, 1), "2:blob", "snap", b"2")
    fs.apply_transaction(txn)
    fs.close()
    FileStore(b, fsync=False).close()          # empty target store
    assert ot.main(["--store", a, "list-pgs"]) == 0
    assert capsys.readouterr().out.strip() == "3.1"
    assert ot.main(["--store", a, "list", "--pg", "3.1"]) == 0
    assert "2:blob" in capsys.readouterr().out
    assert ot.main(["--store", a, "info", "--pg", "3.1",
                    "--oid", "2:blob"]) == 0
    info = _json.loads(capsys.readouterr().out)
    assert info["size"] == 850 and info["n_xattrs"] == 1 \
        and info["n_omap"] == 1
    exp = str(tmp_path / "obj.json")
    assert ot.main(["--store", a, "export", "--pg", "3.1",
                    "--oid", "2:blob", "--file", exp]) == 0
    capsys.readouterr()
    assert ot.main(["--store", b, "import", "--pg", "3.1",
                    "--oid", "2:blob", "--file", exp]) == 0
    capsys.readouterr()
    fb = FileStore(b, fsync=False)
    assert fb.read((3, 1), "2:blob") == b"surgical payload " * 50
    assert fb.getattr((3, 1), "2:blob", "ver") == b"7"
    assert fb.omap_get((3, 1), "2:blob", "snap") == b"2"
    fb.close()
    assert ot.main(["--store", a, "remove", "--pg", "3.1",
                    "--oid", "2:blob"]) == 0
    capsys.readouterr()
    assert ot.main(["--store", a, "fsck"]) == 0
    out = _json.loads(capsys.readouterr().out)
    assert out["bad_objects"] == []
