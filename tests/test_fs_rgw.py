"""MDS slice (journaled dirtree over RADOS) + RGW slice (S3 gateway).

VERDICT r2 missing #8.  Reference roles: src/mds/ (MDCache/MDLog),
src/journal/ (Journaler), src/rgw/ (bucket index + S3 list semantics).
"""
import hashlib

import numpy as np
import pytest

from ceph_tpu.client.rados import Rados
from ceph_tpu.cluster.monitor import Monitor
from ceph_tpu.fs import MDS, CephFSClient, FSError, Journaler
from ceph_tpu.rgw import RGWError, RGWGateway
from tests.test_snaps import make_sim


@pytest.fixture(scope="module")
def rados():
    sim = make_sim()
    return Rados(sim, Monitor(sim.osdmap)).connect()


@pytest.fixture(scope="module")
def ioctx(rados):
    return rados.open_ioctx("rep")


# --------------------------------------------------------------- journal --

def test_journaler_append_replay_trim(ioctx):
    j = Journaler(ioctx, "t1", object_bytes=128)
    seqs = [j.append(f"entry-{i}".encode() * 4) for i in range(20)]
    assert seqs == list(range(20))
    j2 = Journaler(ioctx, "t1", object_bytes=128)    # reopen
    got = list(j2.replay())
    assert [s for s, _ in got] == seqs
    assert got[7][1] == b"entry-7" * 4
    assert j2.active > 0                     # chained multiple objects
    removed = j2.trim_to(15)
    assert removed > 0
    remaining = [s for s, _ in j2.replay()]
    assert remaining[-1] == 19 and 15 in remaining


# ------------------------------------------------------------------- MDS --

def test_mds_tree_and_file_io(ioctx, rados):
    data_ioctx = rados.open_ioctx("rep")
    fs = CephFSClient(MDS(ioctx, data_ioctx))
    fs.mkdir("/home")
    fs.mkdir("/home/alice")
    fs.write("/home/alice/hello.txt", b"hello metadata world")
    assert fs.read("/home/alice/hello.txt") == b"hello metadata world"
    assert fs.listdir("/home") == ["alice"]
    assert fs.listdir("/home/alice") == ["hello.txt"]
    assert fs.stat("/home/alice/hello.txt")["size"] == 20
    # offset write crossing the 64 KiB object boundary
    big = bytes(np.random.default_rng(4).integers(0, 256, 200_000,
                                                  dtype=np.uint8))
    fs.write("/home/alice/big.bin", big)
    assert fs.read("/home/alice/big.bin") == big
    fs.write("/home/alice/big.bin", b"SPLICE", offset=65530)
    want = bytearray(big)
    want[65530:65536] = b"SPLICE"
    assert fs.read("/home/alice/big.bin") == bytes(want)
    # rename across directories
    fs.mkdir("/archive")
    fs.rename("/home/alice/hello.txt", "/archive/greeting.txt")
    assert fs.listdir("/archive") == ["greeting.txt"]
    assert fs.read("/archive/greeting.txt") == b"hello metadata world"
    # unlink + rmdir with not-empty guard
    with pytest.raises(FSError):
        fs.rmdir("/home/alice")
    fs.unlink("/home/alice/big.bin")
    fs.rmdir("/home/alice")
    assert fs.listdir("/home") == []


def test_mds_journal_replay_recovers_tree(ioctx, rados):
    """An MDS that lost its dirfrags (but kept the journal) replays to
    the same tree — the MDLog write-ahead contract."""
    data_ioctx = rados.open_ioctx("rep")
    mds = MDS(ioctx, data_ioctx)
    fs = CephFSClient(mds)
    fs.mkdir("/proj")
    fs.write("/proj/a.txt", b"A")
    fs.write("/proj/b.txt", b"B")
    fs.rename("/proj/a.txt", "/proj/c.txt")
    # simulate dirfrag loss: delete every dirfrag object
    sim = ioctx._rados._sim
    for (pid, name) in list(sim.objects):
        if pid == ioctx.pool_id and name.startswith("dirfrag."):
            sim.delete(pid, name)
    mds2 = MDS(ioctx, data_ioctx)                 # replays the journal
    fs2 = CephFSClient(mds2)
    assert "proj" in fs2.listdir("/")
    assert fs2.listdir("/proj") == ["b.txt", "c.txt"]
    assert fs2.read("/proj/c.txt") == b"A"


# ------------------------------------------------------------------- RGW --

def test_rgw_bucket_and_object_flow(ioctx):
    gw = RGWGateway(ioctx)
    gw.create_bucket("photos")
    with pytest.raises(RGWError):
        gw.create_bucket("photos")
    b = gw.bucket("photos")
    payload = b"JPEGJPEG" * 512
    etag = b.put_object("2024/01/cat.jpg", payload,
                        metadata={"content-type": "image/jpeg"})
    assert etag == hashlib.md5(payload).hexdigest()
    data, ent = b.get_object("2024/01/cat.jpg")
    assert data == payload
    assert ent["meta"]["content-type"] == "image/jpeg"
    with pytest.raises(RGWError):
        b.get_object("missing.jpg")
    with pytest.raises(RGWError):
        gw.delete_bucket("photos")          # not empty
    b.delete_object("2024/01/cat.jpg")
    gw.delete_bucket("photos")
    assert "photos" not in gw.list_buckets()


def test_rgw_list_semantics(ioctx):
    gw = RGWGateway(ioctx)
    b = gw.create_bucket("listing")
    for k in ["a/1", "a/2", "b/1", "b/sub/2", "top"]:
        b.put_object(k, k.encode())
    # prefix + delimiter rolls common prefixes like S3
    r = b.list_objects(prefix="", delimiter="/")
    assert [c["key"] for c in r["contents"]] == ["top"]
    assert r["common_prefixes"] == ["a/", "b/"]
    r = b.list_objects(prefix="b/", delimiter="/")
    assert [c["key"] for c in r["contents"]] == ["b/1"]
    assert r["common_prefixes"] == ["b/sub/"]
    # pagination with marker + truncation flag
    r1 = b.list_objects(max_keys=2)
    assert r1["is_truncated"] and len(r1["contents"]) == 2
    r2 = b.list_objects(marker=r1["contents"][-1]["key"], max_keys=10)
    assert not r2["is_truncated"]
    assert [c["key"] for c in r1["contents"] + r2["contents"]] == \
        ["a/1", "a/2", "b/1", "b/sub/2", "top"]


def test_mds_file_locks(ioctx, rados):
    """Locker slice (src/mds/Locker.cc flock semantics): shared locks
    coexist, exclusive excludes, per-owner release + session cleanup."""
    data_ioctx = rados.open_ioctx("rep")
    mds = MDS(ioctx, data_ioctx)
    fs = CephFSClient(mds)
    fs.mkdir("/lk")
    fs.write("/lk/f", b"locked data")
    assert mds.setlk("/lk/f", "clientA", exclusive=True)
    assert not mds.setlk("/lk/f", "clientB", exclusive=True)
    assert not mds.setlk("/lk/f", "clientB", exclusive=False)
    assert mds.setlk("/lk/f", "clientA", exclusive=True)   # re-grant
    mds.unlock("/lk/f", "clientA")
    # shared holders coexist; exclusive blocked until all release
    assert mds.setlk("/lk/f", "r1", exclusive=False)
    assert mds.setlk("/lk/f", "r2", exclusive=False)
    assert not mds.setlk("/lk/f", "w", exclusive=True)
    assert mds.getlk("/lk/f") == {"r1": False, "r2": False}
    # session cleanup drops a dead client's locks everywhere
    fs.write("/lk/g", b"second")
    assert mds.setlk("/lk/g", "r1", exclusive=False)
    assert mds.release_owner("r1") == 2
    mds.unlock("/lk/f", "r2")
    assert mds.setlk("/lk/f", "w", exclusive=True)


def test_mds_locks_die_with_inode(ioctx, rados):
    mds = MDS(ioctx, rados.open_ioctx("rep"))
    fs = CephFSClient(mds)
    fs.mkdir("/lk2")
    fs.write("/lk2/gone", b"x")
    assert mds.setlk("/lk2/gone", "A", exclusive=True)
    ino = mds._lookup("/lk2/gone")["ino"]
    fs.unlink("/lk2/gone")
    assert ino not in mds._locks
    assert mds.release_owner("A") == 0      # nothing leaked


# ----------------------------------------------------- caps / leases ----

def test_caps_two_client_coherence(ioctx, rados):
    """VERDICT r3 next #7: two CephFSClients contend on one file —
    the exclusive writer buffers; the second client's open REVOKES the
    cache cap, the writer's dirty data flushes, and the reader sees
    it (Capability.h / Locker.cc revoke-on-conflict)."""
    mds = MDS(ioctx, rados.open_ioctx("rep"))
    a = CephFSClient(mds, "client.a")
    b = CephFSClient(mds, "client.b")
    a.write("/shared.txt", b"from-A-buffered")
    # A holds the exclusive cap and has NOT flushed: the MDS copy is
    # stale, A's buffer is the truth
    assert "c" in mds.caps_of("/shared.txt")["client.a"]
    assert mds.read_file("/shared.txt") == b""
    # B's read triggers the revoke -> A flushes -> B reads current
    assert b.read("/shared.txt") == b"from-A-buffered"
    assert "c" not in mds.caps_of("/shared.txt").get("client.a", "")
    # both now in shared mode: A's writes go through synchronously
    a.write("/shared.txt", b"SYNC", offset=0)
    assert b.read("/shared.txt")[:4] == b"SYNC"


def test_caps_writer_revokes_reader_cache(ioctx, rados):
    mds = MDS(ioctx, rados.open_ioctx("rep"))
    a = CephFSClient(mds, "client.a")
    b = CephFSClient(mds, "client.b")
    a.write("/f.txt", b"v1")
    a.flush()
    a.mds.release_caps("client.a", "/f.txt")
    # B reads alone -> gets the cache cap
    assert b.read("/f.txt") == b"v1"
    assert "c" in mds.caps_of("/f.txt")["client.b"]
    # A writes again: B's cache cap is revoked before the grant
    a.write("/f.txt", b"v2")
    a.flush()
    assert "c" not in mds.caps_of("/f.txt").get("client.b", "")
    assert b.read("/f.txt") == b"v2"     # no stale cache serve


def test_caps_lease_expiry_evicts(ioctx, rados):
    mds = MDS(ioctx, rados.open_ioctx("rep"))
    a = CephFSClient(mds, "client.a")
    a.write("/leased.txt", b"mine")
    a.flush()
    assert mds.setlk("/leased.txt", "client.a")
    t0 = 1000.0
    mds.renew_session("client.a", now=t0)
    # within the lease: still held
    assert mds.evict_expired(now=t0 + mds.LEASE_TTL / 2) == []
    assert mds.caps_of("/leased.txt").get("client.a")
    # past the lease: caps AND locks drop, session gone
    assert mds.evict_expired(now=t0 + mds.LEASE_TTL + 1) == \
        ["client.a"]
    assert mds.caps_of("/leased.txt") == {}
    assert mds.getlk("/leased.txt") == {}
    # an expired session cannot acquire caps until it reconnects
    import pytest as _pytest
    with _pytest.raises(FSError):
        mds.acquire_caps("client.a", "/leased.txt", "r",
                         now=t0 + mds.LEASE_TTL + 1)
    mds.open_session("client.a", now=t0 + mds.LEASE_TTL + 2)
    assert "r" in mds.acquire_caps("client.a", "/leased.txt", "r",
                                   now=t0 + mds.LEASE_TTL + 2)


def test_caps_evicted_client_reconnects_cold(ioctx, rados):
    """A lapsed client reconnects with a COLD cache: no stale serve
    (eviction drops its caps; its unflushed buffers are lost)."""
    mds = MDS(ioctx, rados.open_ioctx("rep"))
    a = CephFSClient(mds, "client.a")
    b = CephFSClient(mds, "client.b")
    a.write("/e.txt", b"v1")
    a.flush()
    assert a.read("/e.txt") == b"v1"          # cached under "c"
    # A's lease lapses; B (still live) rewrites the file
    t = 10_000.0
    mds.renew_session("client.b", now=t)
    mds._sessions["client.a"]["renewed"] = t - mds.LEASE_TTL - 1
    mds.evict_expired(now=t)
    b.write("/e.txt", b"v2")
    b.flush()
    mds.release_caps("client.b", "/e.txt")
    # A transparently reconnects and must NOT serve its stale v1
    mds._sessions.get("client.a") is None
    assert a.read("/e.txt") == b"v2"
