"""PGLog delta recovery + incremental OSDMap epochs.

Reference: src/osd/PGLog.{h,cc} (bounded log, missing sets,
delta-vs-backfill), doc/dev/osd_internals/log_based_pg.rst,
OSDMap::Incremental."""
import numpy as np
import pytest

from ceph_tpu.cluster.osdmap import Incremental
from ceph_tpu.cluster.pglog import (MissingSet, OP_DELETE, PGLog, ZERO)
from tests.test_simulator import make_sim


# ------------------------------------------------------------- unit: log ---

def test_log_append_and_versions():
    log = PGLog()
    e1 = log.append(1, "a")
    e2 = log.append(1, "b")
    e3 = log.append(2, "a")
    assert e1.version < e2.version < e3.version
    assert log.head == e3.version
    assert log.tail == ZERO


def test_missing_since_dedupes_latest():
    log = PGLog()
    log.append(1, "a")
    v = log.append(1, "b").version
    log.append(2, "a")
    ms = log.missing_since(v)
    assert set(ms.need) == {"a"}          # only ops after v; a deduped
    assert not ms.backfill
    ms0 = log.missing_since(ZERO)
    assert set(ms0.need) == {"a", "b"}


def test_missing_since_delete_wins():
    log = PGLog()
    start = log.append(1, "x").version
    log.append(1, "doomed")
    log.append(2, "doomed", op=OP_DELETE)
    ms = log.missing_since(start)
    assert "doomed" not in ms.need
    assert "doomed" in ms.deleted


def test_trim_forces_backfill():
    log = PGLog(max_entries=4)
    v0 = log.append(1, "o0").version
    for i in range(1, 8):
        log.append(1, f"o{i}")
    assert len(log.entries) == 4
    assert not log.covers(v0)
    assert log.missing_since(v0).backfill
    # a fresh replica at head needs nothing
    assert log.missing_since(log.head).need == {}


# ------------------------------------------------- unit: map incrementals --

def test_incremental_apply():
    sim = make_sim(n_hosts=4, osds_per_host=2)
    om = sim.osdmap
    e0 = om.epoch
    inc = Incremental(epoch=e0 + 1, new_up={3: False},
                      new_weight={2: 0},
                      new_pg_upmap_items={(1, 0): [(0, 1)]})
    om.apply_incremental(inc)
    assert om.epoch == e0 + 1
    assert not om.osd_up[3] and om.osd_weight[2] == 0
    assert om.pg_upmap_items[(1, 0)] == [(0, 1)]
    # wrong sequence rejected
    with pytest.raises(ValueError):
        om.apply_incremental(Incremental(epoch=e0 + 5))
    # removal entry
    om.apply_incremental(Incremental(epoch=e0 + 2,
                                     new_pg_upmap_items={(1, 0): None}))
    assert (1, 0) not in om.pg_upmap_items


# ----------------------------------------------------- sim: delta recovery --

def test_delta_recovery_only_touches_changed_objects():
    sim = make_sim()
    rng = np.random.default_rng(17)
    blobs = {f"d{i}": rng.integers(0, 256, size=20000).astype(np.uint8)
             .tobytes() for i in range(12)}
    for name, data in blobs.items():
        sim.put(2, name, data)
    # take an OSD down, modify a FEW objects, bring it back
    victim = sim.put(2, "d0", blobs["d0"])[0]
    sim.kill_osd(victim)
    changed = {}
    for name in ("d1", "d2"):
        blob = rng.integers(0, 256, size=500).astype(np.uint8).tobytes()
        sim.write(2, name, 100, blob)
        changed[name] = blob
    sim.revive_osd(victim)
    stats = sim.recover_delta(2)
    # the log names only the objects written while the OSD was down
    # (put of d0 happened before the kill)
    assert stats["backfill_pgs"] == 0
    assert 0 < stats["delta_objects"] <= 4
    # everything reads back
    for name, data in blobs.items():
        got = sim.get(2, name)
        if name in changed:
            assert got[100:600] == changed[name]
        else:
            assert got == data
    # second pass: nothing left to do
    stats2 = sim.recover_delta(2)
    assert stats2["delta_objects"] == 0


def test_delta_recovery_backfill_after_trim():
    sim = make_sim()
    rng = np.random.default_rng(19)
    sim.put(2, "bf", rng.integers(0, 256, size=9000).astype(np.uint8)
            .tobytes())
    placed = sim.put(2, "bf", rng.integers(0, 256, size=9000)
                     .astype(np.uint8).tobytes())
    victim = placed[0]
    sim.kill_osd(victim)
    # churn way past the log bound so the victim's version is trimmed
    for log in sim.pg_logs.values():
        log.max_entries = 4
    for i in range(30):
        sim.write(2, "bf", 10 * i, b"!")
    sim.revive_osd(victim)
    stats = sim.recover_delta(2)
    assert stats["backfill_pgs"] >= 1
    assert sim.scrub(2) == []


def test_replicated_delta_recovery():
    sim = make_sim()
    sim.put(1, "r0", b"alpha" * 100)
    placed = sim.put(1, "r1", b"beta" * 100)
    victim = placed[0]
    sim.kill_osd(victim)
    sim.write(1, "r1", 0, b"BETA")
    sim.revive_osd(victim)
    stats = sim.recover_delta(1)
    assert stats["delta_objects"] >= 1
    assert sim.get(1, "r1")[:4] == b"BETA"


def test_delete_applied_on_delta_recovery():
    """A replica that missed an OP_DELETE purges the object instead of
    resurrecting it via the stale-read fallback."""
    sim = make_sim()
    data = b"to-be-deleted" * 500
    placed = sim.put(2, "doomed", data)
    victim = placed[0]
    sim.kill_osd(victim)
    sim.delete(2, "doomed")
    assert ("doomed" not in
            {k[2] for o in sim.osds if o.alive for k in o.store})
    sim.revive_osd(victim)
    # the revived OSD still holds its stale shard
    assert any(k[2] == "doomed" for k in sim.osds[victim].store)
    stats = sim.recover_delta(2)
    assert stats.get("deletes_applied", 0) >= 1
    assert not any(k[2] == "doomed" for k in sim.osds[victim].store)


def test_later_write_does_not_hide_recovery_hole():
    """An OSD that missed a write must not have its last_complete
    bumped past the hole by a LATER write that does land on it —
    delta recovery would then believe the OSD is current and never
    rebuild the missing shards (latent data loss once enough other
    copies fail).  The netsplit soak hit exactly this: a sub-op
    dropped by msg.drop_op left an object at k shards, steady-state
    writes hid the gap, and the next single-OSD cut pushed the object
    below decodability."""
    sim = make_sim()
    pool = sim.osdmap.pools[2]
    # three objects in the SAME PG: shared up set, shared log
    names: list = []
    pg0 = None
    i = 0
    while len(names) < 3:
        nm = f"hole-{i}"
        i += 1
        pg = sim.object_pg(pool, nm)
        if pg0 is None:
            pg0 = pg
        if pg == pg0:
            names.append(nm)
    pre, hole, later = names
    rng = np.random.default_rng(23)
    data = {nm: rng.integers(0, 256, size=9000).astype(np.uint8)
            .tobytes() for nm in names}
    up = sim.pg_up(pool, pg0)
    victim = up[0]                      # home of shard 0 for all three
    sim.put(2, pre, data[pre])          # victim current through here
    sim.fail_osd(victim)                # undetected: map never moves
    sim.put(2, hole, data[hole])        # victim misses its shard
    sim.restart_osd(victim)             # back up, same map epoch
    sim.put(2, later, data[later])      # lands on victim again
    key = (2, pg0, hole, 0)
    assert not sim.osds[victim].has(key)
    stats = sim.recover_delta(2)
    # the log-driven pass must still see the victim's gap and repair it
    assert stats["delta_objects"] >= 1
    assert sim.osds[victim].has(key)
    # the endgame the hole would have caused: lose m OTHER holders and
    # the object must still decode from what recovery rebuilt
    for o in up[1:3]:
        sim.fail_osd(o)
    assert sim.get(2, hole) == data[hole]


def test_replicated_put_total_failure_preserves_old_version():
    sim = make_sim()
    import pytest as _pytest
    sim.put(1, "keep", b"version-1")
    pool = sim.osdmap.pools[1]
    pg = sim.object_pg(pool, "keep")
    up = sim.pg_up(pool, pg)
    for o in up:
        sim.fail_osd(o)              # undetected: map still routes here
    with _pytest.raises(IOError):
        sim.put(1, "keep", b"version-2")
    # old version intact on the (currently dead) up set
    sim.revive_osd(up[0])
    assert sim.get(1, "keep") == b"version-1"
