"""rjenkins1 hash vs golden vectors from the reference C implementation."""
import json
import os

import numpy as np
import pytest

from ceph_tpu.ops import hashing

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "hash_vectors.json")


@pytest.fixture(scope="module")
def vectors():
    return json.load(open(GOLDEN))


def _args(vals, i):
    a = vals[i]
    b = vals[(i + 7) % len(vals)]
    c = vals[(i + 13) % len(vals)]
    d = vals[(i + 19) % len(vals)]
    e = vals[(i + 23) % len(vals)]
    return a, b, c, d, e


def test_scalar_hashes(vectors):
    vals = vectors["inputs"]
    for i in range(len(vals)):
        a, b, c, d, e = _args(vals, i)
        assert hashing.hash1(a) == vectors["h1"][i]
        assert hashing.hash2(a, b) == vectors["h2"][i]
        assert hashing.hash3(a, b, c) == vectors["h3"][i]
        assert hashing.hash4(a, b, c, d) == vectors["h4"][i]
        assert hashing.hash5(a, b, c, d, e) == vectors["h5"][i]


def test_numpy_hashes_match_scalar(vectors):
    vals = np.array(vectors["inputs"], dtype=np.uint32)
    n = len(vals)
    b = vals[(np.arange(n) + 7) % n]
    c = vals[(np.arange(n) + 13) % n]
    h2 = hashing.np_hash2(vals, b)
    h3 = hashing.np_hash3(vals, b, c)
    assert h2.tolist() == vectors["h2"]
    assert h3.tolist() == vectors["h3"]


def test_jax_hashes_match_golden(vectors):
    jnp = pytest.importorskip("jax.numpy")
    vals = np.array(vectors["inputs"], dtype=np.uint32)
    n = len(vals)
    b = vals[(np.arange(n) + 7) % n]
    c = vals[(np.arange(n) + 13) % n]
    h2 = hashing.jx_hash2(jnp.asarray(vals), jnp.asarray(b))
    h3 = hashing.jx_hash3(jnp.asarray(vals), jnp.asarray(b), jnp.asarray(c))
    assert np.asarray(h2).tolist() == vectors["h2"]
    assert np.asarray(h3).tolist() == vectors["h3"]


def test_str_hash_rjenkins_golden():
    """Pinned to vectors from the compiled reference ceph_str_hash_rjenkins
    (src/common/ceph_hash.cc) — guards object->ps wire compatibility."""
    golden = {
        b"": 3175731469,
        b"a": 703514648,
        b"rbd_data.1234": 1649385036,
        b"obj-000017": 1304429757,
        b"benchmark_data_object_12345": 2206846135,
        b"0123456789ab": 2465405648,
        b"x": 3604590387,
    }
    for name, want in golden.items():
        assert hashing.str_hash_rjenkins(name) == want, name
