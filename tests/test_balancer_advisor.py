"""Balancer dry-run advisor (ISSUE 16): heat x utilization scoring and
upmap proposals as a REPORT — `ceph balancer eval` never actuates.

Pinned contracts:

  * on a skewed heat fixture the advisor proposes moves whose
    FROM-SCRATCH re-score is strictly lower than the current score;
  * the osdmap is never mutated (epoch, upmap tables bit-identical);
  * proposals respect CRUSH failure domains (a move never collapses
    two replicas onto one host) and never target an OSD already in
    the PG's up set;
  * empty heat -> score 0, no proposals (nothing to advise on).
"""
import pytest

from ceph_tpu.cluster.balancer import osd_ancestors, rule_failure_domain
from ceph_tpu.cluster.osdmap import OSDMap, PGPool, POOL_REPLICATED
from ceph_tpu.mgr.balancer_advisor import evaluate, imbalance_score
from ceph_tpu.placement.builder import build_flat_cluster
from ceph_tpu.placement.crush_map import (RULE_CHOOSELEAF_FIRSTN,
                                          RULE_EMIT, RULE_TAKE, Rule)


class FakeCS:
    """The two ClusterStats surfaces the advisor reads."""

    def __init__(self, heat_rows, df_rows):
        self._heat = heat_rows
        self._df = df_rows

    def pg_heat(self, pool=None, top=None):
        rows = [r for r in self._heat
                if pool is None or r["pool"] == pool]
        return rows[:top] if top else rows

    def osd_df(self):
        return self._df


def make_map(n_hosts=4, osds_per_host=2, pg_num=16, seed=3):
    cmap, root = build_flat_cluster(n_hosts=n_hosts,
                                    osds_per_host=osds_per_host,
                                    seed=seed)
    cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                              (RULE_CHOOSELEAF_FIRSTN, 0, 1),
                              (RULE_EMIT, 0, 0)]))
    om = OSDMap(cmap)
    om.mark_all_in_up()
    om.add_pool(PGPool(id=1, name="rep", type=POOL_REPLICATED, size=3,
                       pg_num=pg_num, crush_rule=0))
    return om


def skewed_cs(om, hot_osd=0, pool=1, base=1.0, hot=80.0):
    """Heat rows where every PG touching ``hot_osd`` burns hot."""
    p = om.pools[pool]
    rows = []
    for pg in range(p.pg_num):
        up, _, _, _ = om.pg_to_up_acting_osds(pool, pg)
        h = hot if hot_osd in up else base
        rows.append({"pgid": f"{pool}.{pg}", "pool": pool, "heat": h,
                     "wr_ops": h, "rd_ops": 0.0,
                     "wr_bytes": 0.0, "rd_bytes": 0.0})
    df = [{"daemon": f"osd.{o}", "utilization": 0.1}
          for o in range(om.max_osd)]
    return FakeCS(rows, df)


def frozen_state(om):
    return (om.epoch, dict(om.pg_upmap), dict(om.pg_upmap_items))


# ------------------------------------------------------------ scoring --

def test_imbalance_score_zero_when_proportional():
    shares = {0: 0.5, 1: 0.25, 2: 0.25}
    assert imbalance_score({0: 10.0, 1: 5.0, 2: 5.0}, shares) == 0.0
    assert imbalance_score({}, shares) == 0.0
    assert imbalance_score({0: 0.0, 1: 0.0}, {0: 0.5, 1: 0.5}) == 0.0


def test_imbalance_score_grows_with_skew():
    shares = {0: 0.5, 1: 0.5}
    mild = imbalance_score({0: 12.0, 1: 8.0}, shares)
    harsh = imbalance_score({0: 19.0, 1: 1.0}, shares)
    assert 0 < mild < harsh


# ---------------------------------------------------------- proposals --

def test_skewed_fixture_yields_strictly_better_dry_run():
    om = make_map()
    cs = skewed_cs(om)
    before = frozen_state(om)
    rep = evaluate(om, cs, max_moves=8)
    assert frozen_state(om) == before, "advisor mutated the osdmap"
    assert rep["epoch"] == om.epoch
    assert rep["score_before"] > 0
    assert rep["proposals"], "no moves proposed on a skewed fixture"
    assert rep["score_after"] < rep["score_before"]
    assert rep["moves"] == len(rep["proposals"])
    for p in rep["proposals"]:
        assert p["from"] != p["to"]
        assert p["heat"] > 0


def test_proposals_respect_failure_domains_and_up_sets():
    om = make_map()
    cs = skewed_cs(om)
    rep = evaluate(om, cs, max_moves=8)
    assert rep["proposals"]
    p1 = om.pools[1]
    dom = osd_ancestors(om.crush,
                        rule_failure_domain(om.crush, p1.crush_rule))
    for p in rep["proposals"]:
        pid, pg = (int(x) for x in p["pgid"].split("."))
        up, _, _, _ = om.pg_to_up_acting_osds(pid, pg)
        assert p["from"] in up
        assert p["to"] not in up
        # the post-move membership keeps one replica per failure domain
        moved = [p["to"] if o == p["from"] else o for o in up]
        doms = [int(dom[o]) for o in moved if 0 <= o < len(dom)]
        assert len(doms) == len(set(doms)), \
            f"move {p} collapses failure domains {doms}"


def test_empty_heat_is_a_noop_report():
    om = make_map()
    cs = FakeCS([], [{"daemon": f"osd.{o}", "utilization": 0.0}
                     for o in range(om.max_osd)])
    rep = evaluate(om, cs)
    assert rep["score_before"] == 0.0
    assert rep["score_after"] == 0.0
    assert rep["proposals"] == []
    assert rep["pgs_considered"] == 0


def test_pool_filter_restricts_consideration():
    om = make_map()
    om.add_pool(PGPool(id=2, name="other", type=POOL_REPLICATED,
                       size=3, pg_num=8, crush_rule=0))
    cs = skewed_cs(om, pool=1)
    rep = evaluate(om, cs, pool=2)
    assert rep["pgs_considered"] == 0      # pool 1 heat filtered out
    rep = evaluate(om, cs, pool=1)
    assert rep["pgs_considered"] == om.pools[1].pg_num


def test_already_upmapped_pgs_are_skipped():
    om = make_map()
    cs = skewed_cs(om)
    rep = evaluate(om, cs, max_moves=8)
    assert rep["proposals"]
    # pin every proposed PG with an existing upmap entry: the advisor
    # must not re-propose them (accepting a plan is a separate verb,
    # and double-proposing an applied move would thrash)
    for p in rep["proposals"]:
        pid, pg = (int(x) for x in p["pgid"].split("."))
        om.pg_upmap_items[(pid, pg)] = [(p["from"], p["to"])]
    rep2 = evaluate(om, cs, max_moves=8)
    hit = {p["pgid"] for p in rep["proposals"]} & \
        {p["pgid"] for p in rep2["proposals"]}
    assert not hit, f"re-proposed already-upmapped PGs {hit}"


def test_max_moves_bounds_the_plan():
    om = make_map()
    cs = skewed_cs(om)
    rep = evaluate(om, cs, max_moves=1)
    assert len(rep["proposals"]) <= 1
    rep0 = evaluate(om, cs, max_moves=0)
    assert rep0["proposals"] == []
    assert rep0["score_after"] == rep0["score_before"]
