"""RingReply (ISSUE 20) — fused ragged GF(2^8) encode + crc kernel.

What this file proves, falsifiably:

  * the fused single-traversal kernel (parity AND per-4KiB sub-crcs
    from one bit-unpack of the staged pool) is BIT-IDENTICAL to the
    unfused padded-rectangle comparator — parity bytes, data csums
    and parity csums alike — including 1-byte objects, exact-block
    objects and tail-block objects;
  * the crcs agree with the zlib oracle row by row (the fused crc leg
    is not self-consistent-but-wrong);
  * the ragged descriptor batch really avoids the padded rectangle's
    waste (``padding_avoided`` accounting is arithmetic, not vibes);
  * dispatch through the sharded data plane (1-D and 2-D mesh over
    the conftest-forced 8-device host) changes NOTHING about the
    bytes — mesh parallelism is an implementation detail;
  * the unfused comparator PAYS the separate host scan the fused path
    deletes (counted at the ``unfused`` site), so the perf claim is
    counter-backed.
"""
import os
import zlib

import numpy as np
import pytest

from ceph_tpu.common import crcutil
from ceph_tpu.common.options import config
from ceph_tpu.common.perf_counters import perf
from ceph_tpu.ops import gf, ragged_fused

K, M = 4, 2
SIZES = [1, 5, 700, 4096, 4097, 8192, 12289]


def _shards(rng, sizes, k=K):
    return [rng.integers(0, 256, (k, n), dtype=np.uint8)
            for n in sizes]


def _assert_identical(got: ragged_fused.RaggedResult,
                      want: ragged_fused.RaggedResult):
    assert len(got.parity) == len(want.parity)
    for i, (gp, wp) in enumerate(zip(got.parity, want.parity)):
        assert gp.shape == wp.shape, i
        assert (gp == wp).all(), f"object {i}: parity bytes diverge"
    for name, gl, wl in (("data", got.data_csums, want.data_csums),
                         ("parity", got.parity_csums,
                          want.parity_csums)):
        for i, (grow, wrow) in enumerate(zip(gl, wl)):
            for j, (g, w) in enumerate(zip(grow, wrow)):
                assert (g.block, g.subs, g.length, g.combined) == \
                    (w.block, w.subs, w.length, w.combined), \
                    f"object {i} {name} row {j} csums diverge"


def test_fused_bit_identical_to_padded_unfused():
    rng = np.random.default_rng(20)
    A = gf.isa_rs_parity(K, M)
    shards = _shards(rng, SIZES)
    fused = ragged_fused.encode(A, shards)
    padded = ragged_fused.encode_padded(A, shards)
    _assert_identical(fused, padded)


def test_fused_csums_match_zlib_oracle():
    rng = np.random.default_rng(21)
    A = gf.isa_rs_parity(K, M)
    shards = _shards(rng, [4097, 100, 8192])
    res = ragged_fused.encode(A, shards)
    T = ragged_fused.TILE
    for i, s in enumerate(shards):
        L = int(s.shape[1])
        for j in range(K):
            cs = res.data_csums[i][j]
            row = s[j].tobytes()
            assert cs.length == L and cs.block == T
            assert cs.subs == [zlib.crc32(row[o:o + T])
                               for o in range(0, L, T)]
            assert cs.combined == zlib.crc32(row)
        for j in range(M):
            cs = res.parity_csums[i][j]
            row = res.parity[i][j].tobytes()
            assert cs.subs == [zlib.crc32(row[o:o + T])
                               for o in range(0, L, T)]
            assert cs.combined == zlib.crc32(row)


def test_single_object_degenerate_batches():
    """1-byte and exact-tile single-object batches — the descriptor
    edge the padded comparator can't distinguish from its rectangle."""
    rng = np.random.default_rng(22)
    A = gf.isa_rs_parity(K, M)
    for n in (1, ragged_fused.TILE, ragged_fused.TILE + 1):
        shards = _shards(rng, [n])
        _assert_identical(ragged_fused.encode(A, shards),
                          ragged_fused.encode_padded(A, shards))


def test_padding_accounting_is_arithmetic():
    rng = np.random.default_rng(23)
    sizes = [1, 4096, 100_000, 257]
    batch = ragged_fused.pack(_shards(rng, sizes))
    T = batch.tile
    rect = len(sizes) * (K + M) * max(sizes)
    fused = sum(-(-n // T) for n in sizes) * (K + M) * T
    assert batch.rect_bytes(M) == rect
    assert batch.fused_bytes(M) == fused
    assert batch.padding_avoided(M) == rect - fused
    assert batch.padding_avoided(M) > 0
    # uniform exact-tile sizes: the descriptor layout costs nothing
    uni = ragged_fused.pack(_shards(rng, [T, T, T]))
    assert uni.padding_avoided(M) == 0


def test_unfused_comparator_pays_the_counted_scan():
    """The deleted pass is a COUNTER, not a narrative: encode_padded
    scans every data+parity row at the ``unfused`` site; the fused
    path's host traffic is at most the sub-tile tails."""
    rng = np.random.default_rng(24)
    A = gf.isa_rs_parity(K, M)
    shards = _shards(rng, [8192, 4097])
    pc = perf("wire.zero")
    u0 = pc.dump().get("scan_unfused_bytes", 0)
    t0 = pc.dump().get("scan_device_tail_bytes", 0)
    ragged_fused.encode_padded(A, shards)
    u1 = pc.dump().get("scan_unfused_bytes", 0)
    total = (K + M) * (8192 + 4097)
    assert u1 - u0 >= total, "unfused path stopped paying its scans"
    ragged_fused.encode(A, shards)
    t1 = pc.dump().get("scan_device_tail_bytes", 0)
    tails = (K + M) * (4097 % ragged_fused.TILE)
    assert pc.dump().get("scan_unfused_bytes", 0) == u1
    assert t1 - t0 == tails, "fused path host-scanned full blocks"


@pytest.fixture
def plane_1d():
    config().set("parallel_data_plane", True)
    yield
    config().clear("parallel_data_plane")
    config().clear("parallel_data_plane_devices")


@pytest.fixture
def plane_2d():
    config().set("parallel_data_plane", True)
    config().set("parallel_data_plane_stripes", 2)
    yield
    config().clear("parallel_data_plane")
    config().clear("parallel_data_plane_stripes")


def test_fused_on_1d_plane_bit_identical(plane_1d):
    rng = np.random.default_rng(25)
    A = gf.isa_rs_parity(K, M)
    shards = _shards(rng, SIZES)
    _assert_identical(ragged_fused.encode(A, shards),
                      ragged_fused.encode_padded(A, shards))


def test_fused_on_2d_plane_bit_identical(plane_2d):
    """(stripe, shard) 2-D mesh over the 8 host devices: the ragged
    block pool stripes across rows and the result is re-committed
    replicated — still bit-identical to the host oracle."""
    rng = np.random.default_rng(26)
    A = gf.isa_rs_parity(K, M)
    shards = _shards(rng, [1, 4097, 12289, 700])
    _assert_identical(ragged_fused.encode(A, shards),
                      ragged_fused.encode_padded(A, shards))


def test_fused_pallas_requires_tpu():
    from ceph_tpu.ops import gf_pallas
    rng = np.random.default_rng(27)
    A = gf.isa_rs_parity(K, M)
    if not gf_pallas.available():
        # explicit pallas request off-TPU falls back to the XLA route
        # (same contract as gf_pallas.gf8_matmul dispatch) — values
        # must still be the oracle's
        shards = _shards(rng, [4097])
        _assert_identical(
            ragged_fused.encode(A, shards, impl="pallas"),
            ragged_fused.encode_padded(A, shards))
        return
    shards = _shards(rng, SIZES)
    _assert_identical(ragged_fused.encode(A, shards, impl="pallas"),
                      ragged_fused.encode_padded(A, shards))


def test_zipf_profile_fused_wins_padding():
    """The S3Serve mixed-size shape (bench_ragged_fused's profile):
    zipf object sizes make the padded rectangle pay for the largest
    object ON EVERY ROW — the ragged batch's savings must be large
    and exact."""
    rng = np.random.default_rng(28)
    sizes = np.clip((rng.zipf(1.3, 32).astype(float) * 512
                     ).astype(np.int64), 1, 256 << 10).tolist()
    batch = ragged_fused.pack(_shards(rng, sizes))
    assert batch.padding_avoided(M) == \
        batch.rect_bytes(M) - batch.fused_bytes(M)
    if len(set(sizes)) > 1:
        assert batch.padding_avoided(M) > 0
