"""DurablePGLog: the process-tier PGLog bound to a FileStore.

Atomicity (entry + object in one txn), restart replay, delta vs
backfill decisions, merge_tail semantics.  Reference: src/osd/PGLog.h,
doc/dev/osd_internals/log_based_pg.rst.
"""
import pytest

from ceph_tpu.cluster.daemon_pglog import DurablePGLog
from ceph_tpu.cluster.filestore import FileStore
from ceph_tpu.cluster.objectstore import Transaction
from ceph_tpu.cluster.pglog import OP_DELETE


@pytest.fixture
def store(tmp_path):
    return FileStore(str(tmp_path / "fs"), fsync=False)


COLL = (1, 0)


def _write(store, log, version, oid, data=b"x"):
    txn = Transaction().write_full(COLL, oid, data)
    log.append_txn(txn, version, oid)
    store.apply_transaction(txn)


def test_append_and_restart_replay(store, tmp_path):
    log = DurablePGLog(store, COLL)
    _write(store, log, (1, 1), "a")
    _write(store, log, (1, 2), "b")
    _write(store, log, (2, 3), "a")
    assert log.log.head == (2, 3)
    assert log.last_complete == (2, 3)
    # reopen the store: the log reloads from omap rows
    store2 = FileStore(str(tmp_path / "fs"), fsync=False)
    log2 = DurablePGLog(store2, COLL)
    assert log2.log.head == (2, 3)
    assert log2.last_complete == (2, 3)
    assert [e.obj for e in log2.log.entries] == ["a", "b", "a"]
    # version assignment continues after the head
    assert log2.next_version(2) == (2, 4)
    assert log2.next_version(5) == (5, 1)


def test_lagging_lc_is_visible_and_delta_covered(store):
    log = DurablePGLog(store, COLL)
    for i in range(1, 6):
        _write(store, log, (1, i), f"o{i}")
    # a replica at (1,2) catches up by delta: log covers it
    assert log.covers((1, 2))
    after = log.entries_after((1, 2))
    assert [o for _, o, _ in after] == ["o3", "o4", "o5"]


def test_trim_forces_backfill(store):
    log = DurablePGLog(store, COLL, max_entries=3)
    for i in range(1, 8):
        _write(store, log, (1, i), f"o{i}")
    assert len(log.log.entries) == 3
    assert not log.covers((1, 1))     # trimmed past -> backfill
    assert log.covers((1, 4))


def test_trim_persists(store, tmp_path):
    log = DurablePGLog(store, COLL, max_entries=3)
    for i in range(1, 8):
        _write(store, log, (1, i), f"o{i}")
    store2 = FileStore(str(tmp_path / "fs"), fsync=False)
    log2 = DurablePGLog(store2, COLL, max_entries=3)
    assert len(log2.log.entries) == 3
    assert log2.log.tail == log.log.tail


def test_replica_gap_keeps_lc_behind(store):
    """A replica that missed an op must not advance last_complete
    past the gap (advance_lc gating)."""
    log = DurablePGLog(store, COLL)
    txn = Transaction().write_full(COLL, "a", b"x")
    log.append_txn(txn, (1, 1), "a", advance_lc=True)
    store.apply_transaction(txn)
    # op (1,2) missed; op (1,3) arrives with prev=(1,2)
    txn = Transaction().write_full(COLL, "c", b"x")
    log.append_txn(txn, (1, 3), "c",
                   advance_lc=log.last_complete >= (1, 2))
    store.apply_transaction(txn)
    assert log.last_complete == (1, 1)    # the gap stays visible
    assert log.log.head == (1, 3)


def test_merge_tail_adopts_authority(store):
    log = DurablePGLog(store, COLL)
    _write(store, log, (1, 1), "a")
    entries = [((1, 2), "b", 1), ((1, 3), "a", OP_DELETE)]
    txn = Transaction()
    log.merge_tail_txn(txn, entries, (1, 3))
    store.apply_transaction(txn)
    assert log.log.head == (1, 3)
    assert log.last_complete == (1, 3)
    assert [e.op for e in log.log.entries] == [1, 1, OP_DELETE]
