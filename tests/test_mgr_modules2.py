"""Telemetry + devicehealth mgr modules.

Reference roles: src/pybind/mgr/telemetry/module.py (opt-in anonymized
report, local spool when unreachable), src/pybind/mgr/devicehealth/
module.py (device metric history, life expectancy, health checks,
self-heal mark-out).
"""
import json

import pytest

from ceph_tpu.mgr import MgrModuleHost
from ceph_tpu.mgr import devicehealth_module, telemetry_module
from tests.test_snaps import make_sim


@pytest.fixture()
def host():
    sim = make_sim()
    h = MgrModuleHost(sim)
    telemetry_module.register(h)
    devicehealth_module.register(h)
    return h


# ------------------------------------------------------------- telemetry --

def test_telemetry_requires_opt_in(host):
    tel = host.enable("telemetry")
    with pytest.raises(RuntimeError):
        tel.send()
    # ticks do nothing while off
    for _ in range(10):
        tel.serve_tick()
    assert tel.spool == []


def test_telemetry_report_shape_and_spool(host):
    tel = host.enable("telemetry")
    tel.on()
    host.sim.put(1, "obj-secret-name", b"z" * 1000)
    rid = tel.send()
    assert rid == 1
    rep = tel.last_report()
    assert rep["osd"]["count"] > 0
    assert rep["total_objects"] >= 1
    assert rep["total_bytes"] >= 1000
    # anonymized: no object names anywhere in the payload
    assert "obj-secret-name" not in json.dumps(rep)
    # `telemetry show` renders without sending
    shown = json.loads(tel.show())
    assert shown["pools"] and len(tel.spool) == 1
    # periodic serve loop spools on its interval
    for _ in range(telemetry_module.TelemetryModule.INTERVAL_TICKS):
        tel.serve_tick()
    assert len(tel.spool) == 2
    assert tel.spool[1]["report_id"] == 2


# ----------------------------------------------------------- devicehealth --

def test_devicehealth_flap_and_error_verdicts(host):
    dh = host.enable("devicehealth")
    dh.scrape(now=1.0)
    assert dh.life_expectancy(0) == devicehealth_module.GOOD
    assert dh.checks() == {}
    # two down-flaps degrade the verdict to WARNING
    for t in range(2):
        host.sim.kill_osd(1)
        dh.scrape(now=2.0 + t)
        host.sim.revive_osd(1)
        dh.scrape(now=2.5 + t)
    assert dh.life_expectancy(1) == devicehealth_module.WARNING
    assert "DEVICE_HEALTH_WARN" in dh.checks()
    # scrub-found checksum errors mean FAILING
    dh.record_scrub_errors(2)
    dh.scrape(now=9.0)
    assert dh.life_expectancy(2) == devicehealth_module.FAILING
    assert "DEVICE_HEALTH" in dh.checks()
    # metric history is bounded
    for t in range(40):
        dh.scrape(now=10.0 + t)
    assert len(dh.metrics[0]) == dh.HISTORY


def test_devicehealth_self_heal_marks_out(host):
    dh = host.enable("devicehealth")
    dh.scrape(now=1.0)
    dh.record_scrub_errors(3)
    # self_heal off: verdict only, no map mutation
    assert dh.maybe_mark_out() == []
    assert int(host.sim.osdmap.osd_weight[3]) > 0
    dh.self_heal = True
    assert dh.maybe_mark_out() == [3]
    assert int(host.sim.osdmap.osd_weight[3]) == 0
    # idempotent: not marked out twice
    assert dh.maybe_mark_out() == []
