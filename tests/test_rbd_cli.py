"""`rbd` CLI over the librbd slice.

Reference role: src/tools/rbd/ (image lifecycle, snap family, clone
layering through the CLI).
"""
import io
import json

import pytest

from ceph_tpu.client.rados import Rados
from ceph_tpu.client.rbd import Image
from ceph_tpu.cluster.monitor import Monitor
from ceph_tpu.tools.rbd_cli import main as rbd_main
from tests.test_snaps import make_sim


@pytest.fixture()
def ioctx():
    sim = make_sim()
    return Rados(sim, Monitor(sim.osdmap)).connect().open_ioctx("rep")


def run(ioctx, *args):
    out = io.StringIO()
    rc = rbd_main(list(args), ioctx=ioctx, out=out)
    return rc, out.getvalue()


def test_image_lifecycle(ioctx):
    rc, txt = run(ioctx, "create", "disk", "--size", str(1 << 22))
    assert rc == 0
    rc, txt = run(ioctx, "ls")
    assert json.loads(txt) == ["disk"]
    rc, txt = run(ioctx, "create", "disk", "--size", "1024")
    assert rc == 1                            # duplicate
    rc, txt = run(ioctx, "info", "disk")
    info = json.loads(txt)
    assert info["size"] == 1 << 22 and info["parent"] is None
    rc, txt = run(ioctx, "resize", "disk", "--size", str(1 << 23))
    assert rc == 0
    assert json.loads(run(ioctx, "info", "disk")[1])["size"] == 1 << 23
    rc, txt = run(ioctx, "rm", "disk")
    assert rc == 0
    assert json.loads(run(ioctx, "ls")[1]) == []


def test_snap_and_clone_family(ioctx):
    run(ioctx, "create", "base", "--size", str(1 << 22))
    img = Image(ioctx, "base")
    img.write(0, b"golden-bytes")
    rc, _ = run(ioctx, "snap", "create", "base@gold")
    assert rc == 0
    assert json.loads(run(ioctx, "snap", "ls", "base")[1]) == ["gold"]
    # mutate, then roll back to the snap
    Image(ioctx, "base").write(0, b"BROKEN-BYTES")
    rc, _ = run(ioctx, "snap", "rollback", "base@gold")
    assert rc == 0
    assert Image(ioctx, "base").read(0, 12) == b"golden-bytes"
    # protect + clone + children + flatten
    rc, _ = run(ioctx, "snap", "protect", "base@gold")
    assert rc == 0
    rc, _ = run(ioctx, "clone", "base@gold", "child")
    assert rc == 0
    assert json.loads(run(ioctx, "children", "base@gold")[1]) \
        == ["child"]
    # children lists only the NAMED snap's clones
    run(ioctx, "snap", "create", "base@other")
    run(ioctx, "snap", "protect", "base@other")
    run(ioctx, "clone", "base@other", "child2")
    assert json.loads(run(ioctx, "children", "base@gold")[1]) \
        == ["child"]
    assert json.loads(run(ioctx, "children", "base@other")[1]) \
        == ["child2"]
    run(ioctx, "flatten", "child2")
    run(ioctx, "snap", "unprotect", "base@other")
    run(ioctx, "snap", "rm", "base@other")
    assert Image(ioctx, "child").read(0, 12) == b"golden-bytes"
    # protected snap cannot be removed while a child exists
    rc, txt = run(ioctx, "snap", "rm", "base@gold")
    assert rc == 1
    rc, _ = run(ioctx, "flatten", "child")
    assert rc == 0
    assert json.loads(run(ioctx, "info", "child")[1])["parent"] is None
    rc, _ = run(ioctx, "snap", "unprotect", "base@gold")
    assert rc == 0
    rc, _ = run(ioctx, "snap", "rm", "base@gold")
    assert rc == 0
