"""Messenger fault injection: ms_inject_socket_failures.

The reference's standard suite axis (ms_inject_socket_failures in
qa/suites/rados/** + src/common/options.cc): connections drop mid-op
at random and every client path must reconnect and retry.  Here the
wire server drops one in N requests without replying; the test runs a
replicated workload through the RemoteCluster and requires zero
client-visible failures AND proof that injections actually fired —
both via the legacy ``injected_failures`` status field and via the
faultpoint registry's fire counters on each daemon's admin socket
(the option is a registry client since ISSUE 3).
"""
import os
import time

import numpy as np
import pytest

from ceph_tpu.common.admin import admin_request
from ceph_tpu.tools.vstart import Vstart, build_cluster_dir

N_OSDS = 4


def _insist(fn, polls=40, tick=0.5):
    """Bounded retry against injected connection drops: with
    one-in-N socket failures armed, ANY wire call can lose its
    connection several times in a row under contention — and a
    reconnect storm can keep a daemon's accept backlog full (ECONNREFUSED)
    for seconds at a stretch.  The budget is polls, each tolerant of
    one drop/refusal (ISSUE 9 flake fix)."""
    last = None
    for _ in range(polls):
        try:
            return fn()
        except (OSError, IOError) as e:
            last = e
            time.sleep(tick)
    raise AssertionError(f"call kept failing under injection: {last}")


def test_workload_survives_socket_failures(tmp_path):
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=N_OSDS, osds_per_host=2, fsync=False,
                      ms_inject_socket_failures=6)
    v = Vstart(d)
    v.start(N_OSDS, hb_interval=0.5)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d)
        rng = np.random.default_rng(11)
        blobs = {}
        for i in range(25):
            name = f"inj{i}"
            data = rng.integers(0, 256, 2000, dtype=np.uint8).tobytes()
            assert rc.put(1, name, data) >= 1     # retries inside
            blobs[name] = data
        for name, data in blobs.items():
            assert rc.get(1, name) == data        # replica failover
        # injected fan-out drops leave degraded writes (acks < size),
        # and a heartbeat-driven primary flip can surface a replica
        # that missed them — recovery (peering log catch-up) is the
        # repair mechanism, exactly as in the reference's thrash suites
        # injections stay armed, so heartbeat drops keep flipping
        # primaries WHILE we verify: recover-then-list must be a
        # convergence loop (a just-flipped primary lists its store
        # before the next recovery pass tops it up), not a one-shot
        # listing completeness is only promised on a WHOLE map: a
        # spuriously-marked-down holder (starved heartbeats under
        # contention) remaps its PGs to members that never saw the
        # write, and recovery can only pull from MAPPED members — so
        # converge on passes where every OSD is up, and let flapped
        # members re-announce between passes
        ok = False
        detail = {}
        for _ in range(60):
            try:
                rc.refresh_map()
                st = rc.status()
                if st["n_up"] < N_OSDS:
                    detail = {"n_up": st["n_up"]}
                    time.sleep(0.5)
                    continue
                rc.recover_pool(1)
                listed = rc.list_objects(1)
                detail = {"n_up": st["n_up"],
                          "missing": sorted(set(blobs) - set(listed)),
                          "extra": sorted(set(listed) - set(blobs))}
                ok = not detail["missing"] and not detail["extra"]
            except (OSError, IOError) as e:
                detail = {"err": repr(e)}
            if ok:
                break
            time.sleep(0.5)
        assert ok, f"listing never converged: {detail}"
        # the drops really happened (otherwise this test proves nothing)
        injected = 0
        for osd in range(N_OSDS):

            def _status(o=osd):
                try:
                    return rc.osd_client(o).call({"cmd": "status"})
                except (OSError, IOError):
                    rc.drop_osd_client(o)     # dead connection: a
                    raise                     # fresh one next poll
            injected += int(_insist(_status).get(
                "injected_failures", 0))
        assert injected > 0, "no socket failures were injected"
        # and the registry agrees: each daemon's asok exposes the
        # wire.inject_socket_failures fire count (the option is a
        # faultpoint-registry client now).  Heartbeat/peer traffic
        # keeps dropping between samples, so the check is monotone:
        # sample the status field FIRST, then the fire count — fires
        # can only have grown past it, never lag it
        fired = 0
        for osd in range(N_OSDS):

            def _status(o=osd):
                try:
                    return rc.osd_client(o).call({"cmd": "status"})
                except (OSError, IOError):
                    rc.drop_osd_client(o)
                    raise
            daemon_injected = int(
                _insist(_status)["injected_failures"])
            st = admin_request(
                os.path.join(d, f"osd.{osd}.asok"),
                {"prefix": "fault_injection"})["result"]
            n = int(st["fire_counts"].get(
                "wire.inject_socket_failures", 0))
            fired += n
            assert n >= daemon_injected, \
                f"osd.{osd}: fire count {n} lags status field " \
                f"{daemon_injected}"
        assert fired > 0, "registry fire counters recorded nothing"
        # perf dump exports the same counter (the fires-are-counters
        # acceptance: tests can prove injections via `perf dump`)
        pd = admin_request(os.path.join(d, "osd.0.asok"),
                           {"prefix": "perf dump"})["result"]
        asok_fires = pd.get("faults", {}).get(
            "wire.inject_socket_failures", 0)
        st0 = admin_request(os.path.join(d, "osd.0.asok"),
                            {"prefix": "fault_injection"})["result"]
        # same monotone sampling: perf export first, registry second
        assert asok_fires > 0
        assert st0["fire_counts"].get(
            "wire.inject_socket_failures", 0) >= asok_fires
        # runtime arming over the asok: stall ONE op on osd.0 at the
        # get_shard phase (daemon.hang_op with a match filter + params
        # riding the registry), then prove it fired and the op still
        # completed — the chosen-phase crash/hang axis end to end
        r = admin_request(os.path.join(d, "osd.0.asok"), {
            "prefix": "fault_injection", "action": "arm",
            "name": "daemon.hang_op", "mode": "nth", "n": 1,
            "match": {"cmd": "get_shard"},
            "params": {"seconds": 0.2}})
        assert r["result"]["armed"] == "daemon.hang_op"

        def _probe():
            try:
                return rc.osd_client(0).call(
                    {"cmd": "get_shard", "coll": [1, 0],
                     "oid": "0:x"})
            except (OSError, IOError):
                rc.drop_osd_client(0)         # drops still armed
                raise
        _insist(_probe)
        st0 = admin_request(os.path.join(d, "osd.0.asok"),
                            {"prefix": "fault_injection"})["result"]
        assert st0["fire_counts"].get("daemon.hang_op", 0) >= 1
        rc.close()
    finally:
        v.stop()


def test_session_replay_applies_lost_reply_op_once(tmp_path):
    """Messenger session replay (ISSUE 6): a write whose REPLY frame
    is lost applies exactly once — the client reconnect-retry carries
    the same (session, seq), the daemon returns the recorded
    completion instead of re-applying.  Oracle: the PG log grows by
    exactly one entry per logical write.  Heartbeats are quieted
    (hb_interval=60) so the armed reply-drop deterministically hits
    OUR op's reply, not a peer ping's."""
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(3, hb_interval=60.0)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d)
        rc.put(1, "sess-obj", b"v1" * 400)
        pool = rc.osdmap.pools[1]
        pg = rc._pg_for(pool, "sess-obj")
        prim = [o for o in rc._up(pool, pg) if o >= 0][0]
        asok = os.path.join(d, f"osd.{prim}.asok")

        def log_len():
            r = rc.osd_call(prim, {"cmd": "pg_log", "coll": [1, pg],
                                   "after": [0, 0]})
            return len(r["entries"])

        n0 = log_len()
        # drop the NEXT reply frame this daemon sends (0x11 =
        # MSG_REPLY): the put applies, the completion vanishes
        admin_request(asok, {"prefix": "fault_injection",
                             "action": "arm",
                             "name": "wire.drop_frame",
                             "match": {"type": 0x11}, "count": 1})
        assert rc.put(1, "sess-obj", b"v2" * 400) >= 1
        assert rc.get(1, "sess-obj") == b"v2" * 400
        # the drop really happened AND the resend was dup-suppressed
        st = admin_request(asok, {"prefix":
                                  "fault_injection"})["result"]
        assert st["fire_counts"].get("wire.drop_frame", 0) >= 1
        pd = admin_request(asok, {"prefix": "perf dump"})["result"]
        assert pd.get("osd.session", {}).get("replay_dups", 0) >= 1
        # at-most-once: ONE new log entry for the lost-reply write
        assert log_len() == n0 + 1
        rc.close()
    finally:
        v.stop()


def test_async_overlapping_writes_commit_in_submission_order(tmp_path):
    """Async ordering (ISSUE 7): overlapping ``aio_write_full`` calls
    to ONE object commit in submission order — the completion engine
    serializes same-key ops (the librados per-object write-ordering
    contract), so the object's final bytes are the LAST submitted
    payload and every earlier completion lands before a later one
    starts.  Distinct objects ride the engine concurrently; results
    are byte-identical to the blocking path."""
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(3, hb_interval=60.0)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        from ceph_tpu.client.remote_ioctx import RemoteIoCtx
        rc = RemoteCluster(d)
        io = RemoteIoCtx(rc, "rep")
        payloads = [bytes([0x40 + i]) * (1200 + 7 * i)
                    for i in range(8)]
        comps = [io.aio_write_full("ord-obj", p) for p in payloads]
        # the librados completion surface, not bare futures
        assert comps[-1].wait_for_complete(30.0) == 0
        for i, c in enumerate(comps):
            c.get_return_value()          # raises on any op error
            assert c.is_complete()
            # same-key FIFO: by the time op i completed, every op
            # submitted before it had already completed
            assert all(comps[j].is_complete() for j in range(i))
        assert io.read("ord-obj") == payloads[-1]
        # concurrent distinct objects interleave freely but each
        # lands its own bytes (sync-read verification = the shims'
        # byte-identity contract on live daemons)
        many = {f"ord-{i}": bytes([i]) * 1500 for i in range(6)}
        cs = [io.aio_write_full(n, p) for n, p in many.items()]
        for c in cs:
            c.get_return_value()
        for n, p in many.items():
            assert io.read(n) == p
        rc.close()
    finally:
        v.stop()


def test_async_lost_reply_op_replays_at_most_once(tmp_path):
    """Session replay UNDER the async core (ISSUE 7): an async write
    whose REPLY frame is lost fails its stream; the async objecter's
    single fresh-stream resubmit replays the SAME (session, seq), and
    the daemon's dup table returns the recorded completion instead of
    re-applying.  Oracle: exactly one new PG-log entry for the
    lost-reply write, and the daemon counted a suppressed dup."""
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(3, hb_interval=60.0)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d)
        rc.put(1, "async-sess", b"v1" * 600)
        pool = rc.osdmap.pools[1]
        pg = rc._pg_for(pool, "async-sess")
        prim = [o for o in rc._up(pool, pg) if o >= 0][0]
        asok = os.path.join(d, f"osd.{prim}.asok")

        def log_len():
            r = rc.osd_call(prim, {"cmd": "pg_log", "coll": [1, pg],
                                   "after": [0, 0]})
            return len(r["entries"])

        n0 = log_len()
        admin_request(asok, {"prefix": "fault_injection",
                             "action": "arm",
                             "name": "wire.drop_frame",
                             "match": {"type": 0x11}, "count": 1})
        comp = rc.aio_put(1, "async-sess", b"v2" * 600)
        assert comp.get_return_value() >= 1   # acked despite the drop
        assert rc.get(1, "async-sess") == b"v2" * 600
        st = admin_request(asok, {"prefix":
                                  "fault_injection"})["result"]
        assert st["fire_counts"].get("wire.drop_frame", 0) >= 1
        pd = admin_request(asok, {"prefix": "perf dump"})["result"]
        assert pd.get("osd.session", {}).get("replay_dups", 0) >= 1
        # at-most-once: ONE new log entry for the lost-reply write
        assert log_len() == n0 + 1
        # and the client accounted the stream death -> resubmit
        from ceph_tpu.common.perf_counters import perf
        assert perf("objecter.wire").get("resubmits") >= 1
        rc.close()
    finally:
        v.stop()


@pytest.mark.smoke
def test_async_smoke_script_checks(tmp_path):
    """The CI async smoke (scripts/check_async.py), run in-process:
    completions fire, OpTracker carries dispatched_wire +
    stage_wire_to_done_s, sync and async results are byte-identical,
    and the stream pools striped — the check_observability pattern."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "check_async", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(
                __file__))), "scripts", "check_async.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from ceph_tpu.tools.vstart import Vstart, build_cluster_dir
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(3, hb_interval=60.0)
    try:
        assert mod.run_checks(d) == 0
    finally:
        v.stop()


def test_session_stale_replay_cannot_clobber_newer_write(tmp_path):
    """The replay-ordering hazard, driven manually: W1(seq1) applies,
    W2(seq2) supersedes it, then W1's replay (same session, seq 1)
    arrives — the daemon must return W1's RECORDED completion and
    leave W2's bytes in place (and append no third log entry)."""
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(3, hb_interval=60.0)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d)
        pool = rc.osdmap.pools[1]
        name = "manual-obj"
        pg = rc._pg_for(pool, name)
        members = [o for o in rc._up(pool, pg) if o >= 0]
        prim = members[0]
        w1 = {"cmd": "put_object", "coll": [1, pg],
              "oid": f"0:{name}", "data": b"ver-one" * 100,
              "replicas": members, "session": "manual-sid", "seq": 1}
        r1 = rc.osd_call(prim, dict(w1))
        r2 = rc.osd_call(prim, {**w1, "data": b"ver-two" * 100,
                                "seq": 2})
        assert r2["version"] != r1["version"]

        def log_len():
            r = rc.osd_call(prim, {"cmd": "pg_log", "coll": [1, pg],
                                   "after": [0, 0]})
            return len(r["entries"])

        n2 = log_len()
        replayed = rc.osd_call(prim, dict(w1))   # W1's replay
        assert replayed == r1                    # recorded completion
        assert log_len() == n2                   # nothing re-applied
        got = rc.osd_call(prim, {"cmd": "get_shard", "coll": [1, pg],
                                 "oid": f"0:{name}"})
        assert bytes(got) == b"ver-two" * 100
        # the daemon accounted the session machinery
        st = rc.osd_client(prim).call({"cmd": "status"})
        assert st["sessions"] >= 1
        rc.close()
    finally:
        v.stop()
