"""Messenger fault injection: ms_inject_socket_failures.

The reference's standard suite axis (ms_inject_socket_failures in
qa/suites/rados/** + src/common/options.cc): connections drop mid-op
at random and every client path must reconnect and retry.  Here the
wire server drops one in N requests without replying; the test runs a
replicated workload through the RemoteCluster and requires zero
client-visible failures AND proof that injections actually fired —
both via the legacy ``injected_failures`` status field and via the
faultpoint registry's fire counters on each daemon's admin socket
(the option is a registry client since ISSUE 3).
"""
import os

import numpy as np
import pytest

from ceph_tpu.common.admin import admin_request
from ceph_tpu.tools.vstart import Vstart, build_cluster_dir

N_OSDS = 4


def test_workload_survives_socket_failures(tmp_path):
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=N_OSDS, osds_per_host=2, fsync=False,
                      ms_inject_socket_failures=6)
    v = Vstart(d)
    v.start(N_OSDS, hb_interval=0.5)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d)
        rng = np.random.default_rng(11)
        blobs = {}
        for i in range(25):
            name = f"inj{i}"
            data = rng.integers(0, 256, 2000, dtype=np.uint8).tobytes()
            assert rc.put(1, name, data) >= 1     # retries inside
            blobs[name] = data
        for name, data in blobs.items():
            assert rc.get(1, name) == data        # replica failover
        # injected fan-out drops leave degraded writes (acks < size),
        # and a heartbeat-driven primary flip can surface a replica
        # that missed them — recovery (peering log catch-up) is the
        # repair mechanism, exactly as in the reference's thrash suites
        rc.refresh_map()
        rc.recover_pool(1)
        assert sorted(blobs) == rc.list_objects(1)
        # the drops really happened (otherwise this test proves nothing)
        injected = 0
        for osd in range(N_OSDS):
            for _ in range(4):                    # status itself can drop
                try:
                    st = rc.osd_client(osd).call({"cmd": "status"})
                    injected += int(st.get("injected_failures", 0))
                    break
                except (OSError, IOError):
                    rc.drop_osd_client(osd)
        assert injected > 0, "no socket failures were injected"
        # and the registry agrees: each daemon's asok exposes the
        # wire.inject_socket_failures fire count (the option is a
        # faultpoint-registry client now).  Heartbeat/peer traffic
        # keeps dropping between samples, so the check is monotone:
        # sample the status field FIRST, then the fire count — fires
        # can only have grown past it, never lag it
        fired = 0
        for osd in range(N_OSDS):
            daemon_injected = 0
            for _ in range(4):
                try:
                    daemon_injected = int(rc.osd_client(osd).call(
                        {"cmd": "status"})["injected_failures"])
                    break
                except (OSError, IOError):
                    rc.drop_osd_client(osd)
            st = admin_request(
                os.path.join(d, f"osd.{osd}.asok"),
                {"prefix": "fault_injection"})["result"]
            n = int(st["fire_counts"].get(
                "wire.inject_socket_failures", 0))
            fired += n
            assert n >= daemon_injected, \
                f"osd.{osd}: fire count {n} lags status field " \
                f"{daemon_injected}"
        assert fired > 0, "registry fire counters recorded nothing"
        # perf dump exports the same counter (the fires-are-counters
        # acceptance: tests can prove injections via `perf dump`)
        pd = admin_request(os.path.join(d, "osd.0.asok"),
                           {"prefix": "perf dump"})["result"]
        asok_fires = pd.get("faults", {}).get(
            "wire.inject_socket_failures", 0)
        st0 = admin_request(os.path.join(d, "osd.0.asok"),
                            {"prefix": "fault_injection"})["result"]
        # same monotone sampling: perf export first, registry second
        assert asok_fires > 0
        assert st0["fire_counts"].get(
            "wire.inject_socket_failures", 0) >= asok_fires
        # runtime arming over the asok: stall ONE op on osd.0 at the
        # get_shard phase (daemon.hang_op with a match filter + params
        # riding the registry), then prove it fired and the op still
        # completed — the chosen-phase crash/hang axis end to end
        r = admin_request(os.path.join(d, "osd.0.asok"), {
            "prefix": "fault_injection", "action": "arm",
            "name": "daemon.hang_op", "mode": "nth", "n": 1,
            "match": {"cmd": "get_shard"},
            "params": {"seconds": 0.2}})
        assert r["result"]["armed"] == "daemon.hang_op"
        for _ in range(6):                        # drops still armed
            try:
                rc.osd_client(0).call({"cmd": "get_shard",
                                       "coll": [1, 0], "oid": "0:x"})
                break
            except (OSError, IOError):
                rc.drop_osd_client(0)
        st0 = admin_request(os.path.join(d, "osd.0.asok"),
                            {"prefix": "fault_injection"})["result"]
        assert st0["fire_counts"].get("daemon.hang_op", 0) >= 1
        rc.close()
    finally:
        v.stop()
