"""Messenger fault injection: ms_inject_socket_failures.

The reference's standard suite axis (ms_inject_socket_failures in
qa/suites/rados/** + src/common/options.cc): connections drop mid-op
at random and every client path must reconnect and retry.  Here the
wire server drops one in N requests without replying; the test runs a
replicated workload through the RemoteCluster and requires zero
client-visible failures AND proof that injections actually fired —
both via the legacy ``injected_failures`` status field and via the
faultpoint registry's fire counters on each daemon's admin socket
(the option is a registry client since ISSUE 3).
"""
import os

import numpy as np
import pytest

from ceph_tpu.common.admin import admin_request
from ceph_tpu.tools.vstart import Vstart, build_cluster_dir

N_OSDS = 4


def test_workload_survives_socket_failures(tmp_path):
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=N_OSDS, osds_per_host=2, fsync=False,
                      ms_inject_socket_failures=6)
    v = Vstart(d)
    v.start(N_OSDS, hb_interval=0.5)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d)
        rng = np.random.default_rng(11)
        blobs = {}
        for i in range(25):
            name = f"inj{i}"
            data = rng.integers(0, 256, 2000, dtype=np.uint8).tobytes()
            assert rc.put(1, name, data) >= 1     # retries inside
            blobs[name] = data
        for name, data in blobs.items():
            assert rc.get(1, name) == data        # replica failover
        # injected fan-out drops leave degraded writes (acks < size),
        # and a heartbeat-driven primary flip can surface a replica
        # that missed them — recovery (peering log catch-up) is the
        # repair mechanism, exactly as in the reference's thrash suites
        rc.refresh_map()
        rc.recover_pool(1)
        assert sorted(blobs) == rc.list_objects(1)
        # the drops really happened (otherwise this test proves nothing)
        injected = 0
        for osd in range(N_OSDS):
            for _ in range(4):                    # status itself can drop
                try:
                    st = rc.osd_client(osd).call({"cmd": "status"})
                    injected += int(st.get("injected_failures", 0))
                    break
                except (OSError, IOError):
                    rc.drop_osd_client(osd)
        assert injected > 0, "no socket failures were injected"
        # and the registry agrees: each daemon's asok exposes the
        # wire.inject_socket_failures fire count (the option is a
        # faultpoint-registry client now).  Heartbeat/peer traffic
        # keeps dropping between samples, so the check is monotone:
        # sample the status field FIRST, then the fire count — fires
        # can only have grown past it, never lag it
        fired = 0
        for osd in range(N_OSDS):
            daemon_injected = 0
            for _ in range(4):
                try:
                    daemon_injected = int(rc.osd_client(osd).call(
                        {"cmd": "status"})["injected_failures"])
                    break
                except (OSError, IOError):
                    rc.drop_osd_client(osd)
            st = admin_request(
                os.path.join(d, f"osd.{osd}.asok"),
                {"prefix": "fault_injection"})["result"]
            n = int(st["fire_counts"].get(
                "wire.inject_socket_failures", 0))
            fired += n
            assert n >= daemon_injected, \
                f"osd.{osd}: fire count {n} lags status field " \
                f"{daemon_injected}"
        assert fired > 0, "registry fire counters recorded nothing"
        # perf dump exports the same counter (the fires-are-counters
        # acceptance: tests can prove injections via `perf dump`)
        pd = admin_request(os.path.join(d, "osd.0.asok"),
                           {"prefix": "perf dump"})["result"]
        asok_fires = pd.get("faults", {}).get(
            "wire.inject_socket_failures", 0)
        st0 = admin_request(os.path.join(d, "osd.0.asok"),
                            {"prefix": "fault_injection"})["result"]
        # same monotone sampling: perf export first, registry second
        assert asok_fires > 0
        assert st0["fire_counts"].get(
            "wire.inject_socket_failures", 0) >= asok_fires
        # runtime arming over the asok: stall ONE op on osd.0 at the
        # get_shard phase (daemon.hang_op with a match filter + params
        # riding the registry), then prove it fired and the op still
        # completed — the chosen-phase crash/hang axis end to end
        r = admin_request(os.path.join(d, "osd.0.asok"), {
            "prefix": "fault_injection", "action": "arm",
            "name": "daemon.hang_op", "mode": "nth", "n": 1,
            "match": {"cmd": "get_shard"},
            "params": {"seconds": 0.2}})
        assert r["result"]["armed"] == "daemon.hang_op"
        for _ in range(6):                        # drops still armed
            try:
                rc.osd_client(0).call({"cmd": "get_shard",
                                       "coll": [1, 0], "oid": "0:x"})
                break
            except (OSError, IOError):
                rc.drop_osd_client(0)
        st0 = admin_request(os.path.join(d, "osd.0.asok"),
                            {"prefix": "fault_injection"})["result"]
        assert st0["fire_counts"].get("daemon.hang_op", 0) >= 1
        rc.close()
    finally:
        v.stop()


def test_session_replay_applies_lost_reply_op_once(tmp_path):
    """Messenger session replay (ISSUE 6): a write whose REPLY frame
    is lost applies exactly once — the client reconnect-retry carries
    the same (session, seq), the daemon returns the recorded
    completion instead of re-applying.  Oracle: the PG log grows by
    exactly one entry per logical write.  Heartbeats are quieted
    (hb_interval=60) so the armed reply-drop deterministically hits
    OUR op's reply, not a peer ping's."""
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(3, hb_interval=60.0)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d)
        rc.put(1, "sess-obj", b"v1" * 400)
        pool = rc.osdmap.pools[1]
        pg = rc._pg_for(pool, "sess-obj")
        prim = [o for o in rc._up(pool, pg) if o >= 0][0]
        asok = os.path.join(d, f"osd.{prim}.asok")

        def log_len():
            r = rc.osd_call(prim, {"cmd": "pg_log", "coll": [1, pg],
                                   "after": [0, 0]})
            return len(r["entries"])

        n0 = log_len()
        # drop the NEXT reply frame this daemon sends (0x11 =
        # MSG_REPLY): the put applies, the completion vanishes
        admin_request(asok, {"prefix": "fault_injection",
                             "action": "arm",
                             "name": "wire.drop_frame",
                             "match": {"type": 0x11}, "count": 1})
        assert rc.put(1, "sess-obj", b"v2" * 400) >= 1
        assert rc.get(1, "sess-obj") == b"v2" * 400
        # the drop really happened AND the resend was dup-suppressed
        st = admin_request(asok, {"prefix":
                                  "fault_injection"})["result"]
        assert st["fire_counts"].get("wire.drop_frame", 0) >= 1
        pd = admin_request(asok, {"prefix": "perf dump"})["result"]
        assert pd.get("osd.session", {}).get("replay_dups", 0) >= 1
        # at-most-once: ONE new log entry for the lost-reply write
        assert log_len() == n0 + 1
        rc.close()
    finally:
        v.stop()


def test_session_stale_replay_cannot_clobber_newer_write(tmp_path):
    """The replay-ordering hazard, driven manually: W1(seq1) applies,
    W2(seq2) supersedes it, then W1's replay (same session, seq 1)
    arrives — the daemon must return W1's RECORDED completion and
    leave W2's bytes in place (and append no third log entry)."""
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(3, hb_interval=60.0)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d)
        pool = rc.osdmap.pools[1]
        name = "manual-obj"
        pg = rc._pg_for(pool, name)
        members = [o for o in rc._up(pool, pg) if o >= 0]
        prim = members[0]
        w1 = {"cmd": "put_object", "coll": [1, pg],
              "oid": f"0:{name}", "data": b"ver-one" * 100,
              "replicas": members, "session": "manual-sid", "seq": 1}
        r1 = rc.osd_call(prim, dict(w1))
        r2 = rc.osd_call(prim, {**w1, "data": b"ver-two" * 100,
                                "seq": 2})
        assert r2["version"] != r1["version"]

        def log_len():
            r = rc.osd_call(prim, {"cmd": "pg_log", "coll": [1, pg],
                                   "after": [0, 0]})
            return len(r["entries"])

        n2 = log_len()
        replayed = rc.osd_call(prim, dict(w1))   # W1's replay
        assert replayed == r1                    # recorded completion
        assert log_len() == n2                   # nothing re-applied
        got = rc.osd_call(prim, {"cmd": "get_shard", "coll": [1, pg],
                                 "oid": f"0:{name}"})
        assert bytes(got) == b"ver-two" * 100
        # the daemon accounted the session machinery
        st = rc.osd_client(prim).call({"cmd": "status"})
        assert st["sessions"] >= 1
        rc.close()
    finally:
        v.stop()
