"""Messenger fault injection: ms_inject_socket_failures.

The reference's standard suite axis (ms_inject_socket_failures in
qa/suites/rados/** + src/common/options.cc): connections drop mid-op
at random and every client path must reconnect and retry.  Here the
wire server drops one in N requests without replying; the test runs a
replicated workload through the RemoteCluster and requires zero
client-visible failures AND proof that injections actually fired.
"""
import numpy as np
import pytest

from ceph_tpu.tools.vstart import Vstart, build_cluster_dir

N_OSDS = 4


def test_workload_survives_socket_failures(tmp_path):
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=N_OSDS, osds_per_host=2, fsync=False,
                      ms_inject_socket_failures=6)
    v = Vstart(d)
    v.start(N_OSDS, hb_interval=0.5)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d)
        rng = np.random.default_rng(11)
        blobs = {}
        for i in range(25):
            name = f"inj{i}"
            data = rng.integers(0, 256, 2000, dtype=np.uint8).tobytes()
            assert rc.put(1, name, data) >= 1     # retries inside
            blobs[name] = data
        for name, data in blobs.items():
            assert rc.get(1, name) == data        # replica failover
        # injected fan-out drops leave degraded writes (acks < size),
        # and a heartbeat-driven primary flip can surface a replica
        # that missed them — recovery (peering log catch-up) is the
        # repair mechanism, exactly as in the reference's thrash suites
        rc.refresh_map()
        rc.recover_pool(1)
        assert sorted(blobs) == rc.list_objects(1)
        # the drops really happened (otherwise this test proves nothing)
        injected = 0
        for osd in range(N_OSDS):
            for _ in range(4):                    # status itself can drop
                try:
                    st = rc.osd_client(osd).call({"cmd": "status"})
                    injected += int(st.get("injected_failures", 0))
                    break
                except (OSError, IOError):
                    rc.drop_osd_client(osd)
        assert injected > 0, "no socket failures were injected"
        rc.close()
    finally:
        v.stop()
