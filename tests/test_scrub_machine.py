"""Chunked reservation-gated scrub statechart (scrub_machine.cc role)."""
import numpy as np
import pytest

from ceph_tpu.cluster.scrub_machine import (
    BUILD_MAPS, COMPARE_MAPS, FINISHED, NEW_CHUNK, RESERVING,
    ScrubMachine, ScrubReservations)
from tests.test_snaps import make_sim


@pytest.fixture(scope="module")
def loaded_sim():
    sim = make_sim()
    rng = np.random.default_rng(11)
    for i in range(24):
        sim.put(2, f"e{i}", rng.integers(0, 256, 6000,
                                         dtype=np.uint8).tobytes())
        sim.put(1, f"r{i}", rng.integers(0, 256, 3000,
                                         dtype=np.uint8).tobytes())
    return sim


def _pgs_with_objects(sim, pool_id):
    pool = sim.osdmap.pools[pool_id]
    pgs = set()
    for (pid, name) in sim.objects:
        if pid == pool_id and "@" not in name:
            pgs.add(sim.object_pg(pool, name))
    return sorted(pgs)


def test_state_sequence_and_chunking(loaded_sim):
    sim = loaded_sim
    pg = _pgs_with_objects(sim, 2)[0]
    m = ScrubMachine(sim, 2, pg, chunk_objects=1)
    m.start()
    assert m.state == RESERVING
    states = []
    while m.state != FINISHED:
        states.append(m.tick())
    assert states[0] == NEW_CHUNK              # reservation granted
    assert BUILD_MAPS in states and COMPARE_MAPS in states
    # chunk_objects=1 forces one chunk per object
    assert m.result.chunks == m.result.objects_scrubbed >= 1
    assert m.result.inconsistent == []


def test_reservations_serialize_overlapping_scrubs(loaded_sim):
    sim = loaded_sim
    pgs = _pgs_with_objects(sim, 2)
    res = ScrubReservations(max_scrubs=1)
    a = ScrubMachine(sim, 2, pgs[0], reservations=res)
    a.start()
    a.tick()                                   # holds its up set
    overlapping = None
    for pg in pgs[1:]:
        if set(a._reserved) & set(
                ScrubMachine(sim, 2, pg, reservations=res)._up()):
            overlapping = pg
            break
    assert overlapping is not None
    b = ScrubMachine(sim, 2, overlapping, reservations=res)
    b.start()
    b.tick()
    assert b.state == RESERVING                # blocked on the slots
    assert b.result.reserve_waits >= 1
    a.run_to_completion()                      # releases slots
    b.run_to_completion()
    assert b.state == FINISHED


def test_detects_corrupt_parity(loaded_sim):
    sim = loaded_sim
    pool = sim.osdmap.pools[2]
    name = next(n for (pid, n) in sim.objects
                if pid == 2 and "@" not in n)
    pg = sim.object_pg(pool, name)
    up = sim.pg_up(pool, pg)
    codec = sim.codec_for(pool)
    k = codec.get_data_chunk_count()
    # corrupt a parity shard ON DISK without updating its checksum...
    # scrub must notice via re-encode compare; use a VALID write of
    # wrong bytes (checksum-ok, content-wrong) to dodge the EIO path
    tgt = up[k]
    key = (2, pg, name, k)
    cur = sim.osds[tgt].get(key)
    bad = np.array(cur, dtype=np.uint8).copy()
    bad[0] ^= 0xFF
    sim.osds[tgt].put(key, bad)
    m = ScrubMachine(sim, 2, pg)
    r = m.run_to_completion()
    assert (name, k) in r.inconsistent
    # repair via recovery, then a re-scrub comes back clean
    sim.osds[tgt].delete(key)
    sim.recover_all(2)
    r2 = ScrubMachine(sim, 2, pg).run_to_completion()
    assert (name, k) not in r2.inconsistent


def test_preemption_on_concurrent_write(loaded_sim):
    sim = loaded_sim
    pool = sim.osdmap.pools[1]
    name = next(n for (pid, n) in sim.objects
                if pid == 1 and "@" not in n)
    pg = sim.object_pg(pool, name)
    m = ScrubMachine(sim, 1, pg, chunk_objects=2)
    m.start()
    m.tick()                                   # reserve
    m.tick()                                   # new chunk (snapshot ver)
    m.tick()                                   # build maps
    sim.put(1, name, b"concurrent write during scrub")
    m.tick()                                   # compare -> preempted
    assert m.result.preemptions == 1
    r = m.run_to_completion()
    assert r.inconsistent == []
    assert r.objects_scrubbed >= 1


def test_missing_shard_reported(loaded_sim):
    sim = loaded_sim
    pool = sim.osdmap.pools[2]
    name = next(n for (pid, n) in sim.objects
                if pid == 2 and "@" not in n and n.startswith("e"))
    pg = sim.object_pg(pool, name)
    up = sim.pg_up(pool, pg)
    sim.osds[up[1]].delete((2, pg, name, 1))
    r = ScrubMachine(sim, 2, pg).run_to_completion()
    assert (name, 1) in r.missing
    sim.recover_all(2)


def test_replicated_divergent_replica_detected(loaded_sim):
    """A corrupted copy on a NON-primary replica must flag the object
    inconsistent (per-replica digests, not a single any-OSD read)."""
    sim = loaded_sim
    pool = sim.osdmap.pools[1]
    name = next(n for (pid, n) in sim.objects
                if pid == 1 and "@" not in n and n.startswith("r"))
    pg = sim.object_pg(pool, name)
    up = sim.pg_up(pool, pg)
    # healthy first: no missing replicas, no inconsistency
    r0 = ScrubMachine(sim, 1, pg).run_to_completion()
    assert not [m for m in r0.missing if m[0] == name]
    assert not [i for i in r0.inconsistent if i[0] == name]
    # silently diverge replica #1 (valid checksum, wrong bytes)
    import numpy as np
    key = (1, pg, name, 0)
    cur = np.array(sim.osds[up[1]].get(key), dtype=np.uint8).copy()
    cur[0] ^= 0xFF
    sim.osds[up[1]].put(key, cur)
    r = ScrubMachine(sim, 1, pg).run_to_completion()
    assert (name, -1) in r.inconsistent
    # repair: recovery re-replicates from the primary... the divergent
    # copy is newer by version bookkeeping here, so repair directly
    sim.osds[up[1]].put(key, np.array(sim.osds[up[0]].get(key)))
    r2 = ScrubMachine(sim, 1, pg).run_to_completion()
    assert (name, -1) not in r2.inconsistent
