"""Multi-MDS: ranks, subtree authority, migration, balancer, caps.

The round-3 COVERAGE gap ("still single-MDS, no subtree migration").
Reference roles: src/mds/MDSMap.h (ranks), MDCache subtree auth,
Migrator.cc (export/import), MDBalancer.cc (load-driven moves),
MDSRank::forward (wrong-rank requests re-routed).
"""
import pytest

from ceph_tpu.client.rados import Rados
from ceph_tpu.cluster.monitor import Monitor
from ceph_tpu.fs import MDS, CephFSClient, FSError
from ceph_tpu.fs.mds import ForwardError
from ceph_tpu.fs.mdsmap import MDSMap
from ceph_tpu.fs.multimds import MDBalancer, MDSCluster
from tests.test_snaps import make_sim


@pytest.fixture()
def pools():
    sim = make_sim()
    rados = Rados(sim, Monitor(sim.osdmap)).connect()
    return rados.open_ioctx("rep"), rados.open_ioctx("rep")


def test_mdsmap_longest_prefix_auth(pools):
    meta, _ = pools
    m = MDSMap(meta, n_ranks=3)
    m.set_auth("/a", 1)
    m.set_auth("/a/deep", 2)
    assert m.auth_rank("/") == 0
    assert m.auth_rank("/b/x") == 0
    assert m.auth_rank("/a") == 1
    assert m.auth_rank("/a/file") == 1
    assert m.auth_rank("/a/deep") == 2
    assert m.auth_rank("/a/deep/er/still") == 2
    # durable: a reloaded map resolves identically, same epoch
    m2 = MDSMap(meta, n_ranks=3)
    assert m2.epoch == m.epoch
    assert m2.auth_rank("/a/deep/x") == 2
    with pytest.raises(ValueError):
        m.set_auth("/a", 99)


def test_wrong_rank_forwards(pools):
    meta, data = pools
    c = MDSCluster(meta, data, n_ranks=2)
    c.mkdir("/left")
    c.migrate("/left", 1)
    # direct hit on the wrong rank raises ForwardError with the owner
    with pytest.raises(ForwardError) as ei:
        c.ranks[0].mkdir("/left/sub")
    assert ei.value.rank == 1
    # the router follows the forward transparently
    c.mkdir("/left/sub")
    assert c.listdir("/left") == ["sub"]
    # and the owning rank serves it directly without forwarding
    c.ranks[1].mkdir("/left/sub2")
    assert sorted(c.listdir("/left")) == ["sub", "sub2"]


def test_migration_moves_authority_and_flushes_caps(pools):
    meta, data = pools
    c = MDSCluster(meta, data, n_ranks=2)
    c.mkdir("/proj")
    c.create("/proj/f")
    c.write_file("/proj/f", b"hello world")
    flushed = []
    c.open_session("alice", flush_cb=lambda ino, why:
                   flushed.append((ino, why)))
    got = c.acquire_caps("alice", "/proj/f", "rwc")
    assert "c" in got                       # loner gets the cache cap
    c.migrate("/proj", 1)
    # export flushed the buffered holder and dropped the cap state
    assert flushed, "cap holder was not flushed on export"
    assert c.caps_of("/proj/f") == {}
    assert c.subtree_map()["/proj"] == 1
    # IO continues against the new owner; reacquire works
    assert c.read_file("/proj/f") == b"hello world"
    assert "r" in c.acquire_caps("alice", "/proj/f", "r")
    c.write_file("/proj/f", b"HELLO WORLD")
    assert c.read_file("/proj/f") == b"HELLO WORLD"


def test_migration_survives_restart(pools):
    meta, data = pools
    c = MDSCluster(meta, data, n_ranks=2)
    c.mkdir("/stay")
    c.mkdir("/move")
    c.create("/move/f")
    c.write_file("/move/f", b"payload")
    c.migrate("/move", 1)
    # a fresh cluster over the same pools resumes the same authority
    c2 = MDSCluster(meta, data, n_ranks=2)
    assert c2.subtree_map()["/move"] == 1
    assert c2.mdsmap.auth_rank("/stay") == 0
    assert c2.read_file("/move/f") == b"payload"
    with pytest.raises(ForwardError):
        c2.ranks[0].create("/move/g")
    c2.create("/move/g")                     # routed to rank 1
    assert sorted(c2.listdir("/move")) == ["f", "g"]


def test_cross_rank_rename(pools):
    meta, data = pools
    c = MDSCluster(meta, data, n_ranks=2)
    c.mkdir("/a")
    c.mkdir("/b")
    c.migrate("/b", 1)
    c.create("/a/f")
    c.write_file("/a/f", b"crossing")
    c.rename("/a/f", "/b/f")
    assert c.listdir("/a") == []
    assert c.listdir("/b") == ["f"]
    assert c.read_file("/b/f") == b"crossing"
    # collision on the destination is refused before any mutation
    c.create("/a/g")
    c.create("/b/g")
    with pytest.raises(FSError):
        c.rename("/a/g", "/b/g")
    assert "g" in c.listdir("/a")


def test_two_clients_coherent_across_ranks(pools):
    meta, data = pools
    c = MDSCluster(meta, data, n_ranks=2)
    c.mkdir("/shared")
    c.migrate("/shared", 1)
    a = CephFSClient(c, client_id="a")
    b = CephFSClient(c, client_id="b")
    c.create("/shared/f")
    a.write("/shared/f", b"from-a")
    assert b.read("/shared/f") == b"from-a"   # revoke flushed a's buffer
    b.write("/shared/f", b"from-b")
    assert a.read("/shared/f") == b"from-b"


def test_cross_rank_rename_drops_locks(pools):
    """Lock state follows the dentry off the source rank (code-review
    finding: a stranded exclusive lock would both stop excluding and
    become unreleasable through routing)."""
    meta, data = pools
    c = MDSCluster(meta, data, n_ranks=2)
    c.mkdir("/a")
    c.mkdir("/b")
    c.migrate("/b", 1)
    c.create("/a/f")
    assert c.setlk("/a/f", owner="alice", exclusive=True)
    c.rename("/a/f", "/b/f")
    # the new owner rank has clean lock state; no phantom exclusion
    assert c.getlk("/b/f") == {}
    assert c.setlk("/b/f", owner="bob", exclusive=True)
    # and the SOURCE rank holds no stale entry for the moved inode
    ino = c.stat("/b/f")["ino"]
    assert ino not in c.ranks[0]._locks


def test_balancer_moves_hot_subtree(pools):
    meta, data = pools
    c = MDSCluster(meta, data, n_ranks=2)
    c.mkdir("/hot")
    c.mkdir("/cold")
    c.create("/hot/f")
    for _ in range(60):
        c.read_file("/hot/f")
    c.listdir("/cold")
    bal = MDBalancer(c, min_requests=16)
    assert bal.rank_loads()[0] > 60
    moved = bal.rebalance()
    assert ("/hot", 1) in moved
    assert c.subtree_map()["/hot"] == 1
    # served by the new rank; balance is now within threshold
    assert c.read_file("/hot/f") == b""
    assert bal.rebalance() == []


def test_single_mds_unaffected(pools):
    """rank=None keeps the legacy single-MDS behavior: no authority
    checks, legacy journal name."""
    meta, data = pools
    mds = MDS(meta, data)
    fs = CephFSClient(mds)
    fs.mkdir("/solo")
    fs.write("/solo/f", b"x")
    assert fs.read("/solo/f") == b"x"
    assert mds.journal.name == "mdlog"


def test_cross_rank_replica_read_no_forward(pools):
    """VERDICT r4 next #8: a read entering a NON-auth rank serves from
    its discovered replica (no forward); a mutation on the auth rank
    invalidates it; the lease expires without renewal."""
    meta, data = pools
    c = MDSCluster(meta, data, n_ranks=2, lease_s=5.0)
    c.mdsmap.set_auth("/a", 0)
    c.mkdir("/a")
    c.create("/a/f")
    c.write_file("/a/f", b"version-one")
    st0 = dict(c.replica_stats)
    # first cross-rank stat DISCOVERS a replica on rank 1
    ent = c.stat_via(1, "/a/f", now=100.0)
    assert ent["size"] == len(b"version-one")
    assert c.replica_stats["discovers"] == st0["discovers"] + 1
    # second read HITS the replica: no forward, no new discover, and
    # the whole file read is served by the non-auth rank
    assert c.read_file_via(1, "/a/f", now=101.0) == b"version-one"
    assert c.replica_stats["hits"] >= st0["hits"] + 1
    assert c.replica_stats["discovers"] == st0["discovers"] + 1
    # the auth rank sees NO request for the replica-served reads
    # (serve happens entirely on rank 1's cache + shared data pool)
    # mutation REVOKES the replica before applying
    c.write_file("/a/f", b"version-TWO!")
    assert c.replica_stats["invalidations"] >= st0["invalidations"] + 1
    # the next cross-rank read re-discovers and sees the new data
    assert c.read_file_via(1, "/a/f", now=102.0) == b"version-TWO!"
    assert c.replica_stats["discovers"] == st0["discovers"] + 2
    # lease expiry: beyond lease_s the replica drops and re-discovers
    before = c.replica_stats["expires"]
    c.stat_via(1, "/a/f", now=102.0 + 60.0)
    assert c.replica_stats["expires"] == before + 1
    assert c.replica_stats["discovers"] == st0["discovers"] + 3


def test_replica_invalidation_on_namespace_ops(pools):
    meta, data = pools
    c = MDSCluster(meta, data, n_ranks=2, lease_s=30.0)
    c.mdsmap.set_auth("/a", 0)
    c.mkdir("/a")
    c.create("/a/doomed")
    c.write_file("/a/doomed", b"bye")
    assert c.stat_via(1, "/a/doomed", now=10.0)["size"] == 3
    # unlink revokes; the stale replica must NOT keep serving
    c.unlink("/a/doomed")
    with pytest.raises(FSError):
        c.stat_via(1, "/a/doomed", now=11.0)
    # rename revokes src replica too
    c.create("/a/old")
    c.stat_via(1, "/a/old", now=12.0)
    c.rename("/a/old", "/a/new")
    with pytest.raises(FSError):
        c.stat_via(1, "/a/old", now=13.0)
    assert c.stat_via(1, "/a/new", now=14.0)["type"] == "file"


def test_dir_rename_revokes_child_replicas(pools):
    """A directory rename must revoke replicas of everything UNDER it
    (the code-review reproduction): path-keyed revocation alone left
    children serving a tree that no longer exists."""
    meta, data = pools
    c = MDSCluster(meta, data, n_ranks=2, lease_s=30.0)
    c.mdsmap.set_auth("/a", 0)
    c.mkdir("/a")
    c.mkdir("/a/d")
    c.create("/a/d/f")
    c.write_file("/a/d/f", b"inner")
    assert c.stat_via(1, "/a/d/f", now=1.0)["size"] == 5
    c.rename("/a/d", "/a/e")
    with pytest.raises(FSError):
        c.stat_via(1, "/a/d/f", now=2.0)
    assert c.stat_via(1, "/a/e/f", now=3.0)["size"] == 5
