"""ClusterTelemetry (ISSUE 10): cross-process distributed tracing +
mgr-style cluster stats aggregation.

Covers the two tentpole halves and their acceptance criteria:

  * tracer contracts — drop counting, buffer occupancy, leaked-span
    error tagging, slow-trace pinning, the disarmed dict-miss cost;
  * bucket-wise histogram merge — merged cluster p50/p99/p999 must
    equal the quantiles of the POOLED samples within one log2
    bucket's resolution (property test over seeds);
  * sim-tier slow-op auto-sampling — a slow op's end-to-end trace
    assembles with linked stages;
  * process tier — one slow wire op yields an assembled trace
    spanning >= 3 PROCESSES (client, primary daemon, replica
    daemons) with >= 5 linked stages, retrievable by op id via
    `ceph trace`, and the mon's cluster stats / Prometheus scrape
    agree with the per-daemon asok sources they aggregate.
"""
import os
import random
import time

import pytest

from ceph_tpu.common import tracer as tracing
from ceph_tpu.common.op_tracker import tracker
from ceph_tpu.common.options import config
from ceph_tpu.common.perf_counters import PerfHistogram, perf
from ceph_tpu.common.tracer import Tracer, assemble
from ceph_tpu.mgr.cluster_stats import (ClusterStats, merge_histograms,
                                        quantile)


@pytest.fixture(autouse=True)
def _fresh_tracer():
    """Tracing armed + an empty buffer per test; restore tracer AND
    op-tracker state after (both are process-global like the fault
    registry — leaked slow ops / a leaked complaint time would
    poison later suites' health checks).  Restore goes THROUGH
    set() because the op_tracker config cache is observer-fed and
    clear() alone does not notify (the test_op_tracker trk idiom)."""
    tracing.arm()
    tracing.tracer().reset()
    yield
    tracing.arm()
    tracing.tracer().reset()
    tracker().reset()
    config().set("op_tracker_complaint_time", 30.0)
    config().clear("op_tracker_complaint_time")


# ------------------------------------------------ histogram merging ---

@pytest.mark.parametrize("seed", [0, 1, 7])
def test_merged_quantiles_match_pooled_samples(seed):
    """Property: bucket-wise merge of N daemons' log2 histograms
    must yield p50/p99/p999 equal to the pooled samples' quantiles
    within ONE bucket's resolution (le bound covers the sample, and
    the bucket below does not — a 2x band for log2 buckets)."""
    r = random.Random(seed)
    pooled = []
    dumps = []
    for _daemon in range(5):
        h = PerfHistogram()
        for _ in range(400):
            # heavy-tailed latencies: microseconds to seconds
            v = 10 ** r.uniform(-6, 0.5)
            h.record(v)
            pooled.append(v)
        dumps.append(h.dump())
    merged = merge_histograms(dumps)
    assert merged["count"] == len(pooled)
    assert merged["sum"] == pytest.approx(sum(pooled), rel=1e-6)
    pooled.sort()
    for q in (0.5, 0.99, 0.999):
        est = quantile(merged, q)
        # the exact quantile of the pooled samples
        idx = min(len(pooled) - 1, int(q * len(pooled)))
        exact = pooled[idx]
        assert est is not None
        # one log2 bucket of resolution: the reported le bound is >=
        # the exact sample and within one bucket width above it
        assert est >= exact * (1 - 1e-9), (q, est, exact)
        assert est <= exact * 2 * (1 + 1e-9), (q, est, exact)


def test_merge_handles_empty_and_overflow_buckets():
    h = PerfHistogram(n_buckets=4)
    h.record(1e9)                      # lands in +Inf overflow
    merged = merge_histograms([h.dump(), {}, None])
    assert merged["count"] == 1
    assert merged["buckets"][-1][0] == "+Inf"
    # +Inf answers quantiles with the last finite bound (or None if
    # no finite bucket exists at all)
    assert quantile(merged, 0.5) is None
    h.record(h.base / 2)               # now one finite bucket too
    merged = merge_histograms([h.dump()])
    assert quantile(merged, 0.99) == pytest.approx(h.base)


# ----------------------------------------------------- tracer core ---

def test_span_buffer_drops_are_counted_with_occupancy():
    t = Tracer(max_spans=10)
    base = perf("tracer").get("spans_dropped") or 0
    for i in range(25):
        with t.start_span(f"s{i}"):
            pass
    d = t.dump_traces()
    assert d["occupancy"] <= 10
    assert t.spans_dropped > 0
    assert d["spans_dropped"] == t.spans_dropped
    assert (perf("tracer").get("spans_dropped") or 0) - base == \
        t.spans_dropped
    assert d["max_spans"] == 10


def test_pinned_trace_survives_buffer_trim():
    t = Tracer(max_spans=10)
    with t.start_span("keeper") as span:
        tid = span.trace_id
    t.pin_trace(tid)
    for i in range(50):
        with t.start_span(f"noise{i}"):
            pass
    kept = t.spans_for(tid)
    assert [s["name"] for s in kept] == ["keeper"]
    assert tid in t.dump_traces()["sampled"]


def test_exception_path_finishes_span_with_error_tag():
    """Regression (ISSUE 10 satellite): a context-managed span whose
    body raises must still finish — tagged error — instead of
    leaking."""
    t = Tracer()
    with pytest.raises(ValueError):
        with t.start_span("boom"):
            raise ValueError("x")
    spans = t.dump()
    assert len(spans) == 1
    assert spans[0]["tags"]["error"] == "ValueError"
    assert spans[0]["duration_s"] >= 0


def test_leaked_open_span_swept_with_error_tag():
    """A manually opened span abandoned on an exception path is
    force-finished by the leak sweep with error=leaked (and counted
    in dump_traces' open_spans until then)."""
    t = Tracer()
    t.span_open("leaky", osd=3)
    # young open spans are visible in the dump's health fields but
    # not yet swept (default leak age is minutes)
    assert t.dump_traces()["open_spans"] == 1
    assert t.finish_leaked(0.0) == 1
    spans = [s for s in t.dump() if s["name"] == "leaky"]
    assert spans and spans[0]["tags"]["error"] == "leaked"
    assert t.dump_traces()["open_spans"] == 0
    # a normal finish carries no error
    sp2 = t.span_open("fine")
    t.finish_span(sp2)
    fine = [s for s in t.dump() if s["name"] == "fine"]
    assert fine and "error" not in fine[0]["tags"]


def test_finish_after_leak_sweep_does_not_double_insert():
    """An op that stalls past the leak age and THEN completes must
    not land in the buffer twice: the sweep's error=leaked verdict
    stands and the late finish_span is a no-op."""
    t = Tracer()
    sp = t.span_open("stalled")
    assert t.finish_leaked(0.0) == 1
    t.finish_span(sp, error="IOError")       # late completion
    spans = [s for s in t.dump() if s["name"] == "stalled"]
    assert len(spans) == 1
    assert spans[0]["tags"]["error"] == "leaked"


def test_osd_df_and_df_skip_non_osd_reporters():
    """Clients report perf too (the sim tier's 'client' entity) but
    own no store — they must not fabricate `ceph osd df` rows or
    fold zeros into the RAW totals."""
    cs = ClusterStats()
    now = time.time()
    cs.ingest("client", {"ts": now, "perf": {}})
    cs.ingest("osd.0", {"ts": now, "perf": {},
                        "util": {"bytes": 10, "total_bytes": 100,
                                 "objects": 1, "pools": {}}})
    assert [r["daemon"] for r in cs.osd_df()] == ["osd.0"]
    assert cs.df()["total_bytes"] == 100
    assert "client" in cs.daemons()          # still a live reporter


def test_disarmed_tracing_costs_one_dict_miss():
    """Acceptance: 100k traced-path executions with tracing disarmed
    complete in << 1 s (the faultpoint dict-miss contract)."""
    tracing.disarm()
    try:
        t0 = time.perf_counter()
        for _ in range(100_000):
            tracing.stamp({"cmd": "put_shard"})
            with tracing.child_span("x"):
                pass
            with tracing.start_span("y"):
                pass
        dt = time.perf_counter() - t0
    finally:
        tracing.arm()
    assert dt < 1.0, f"disarmed trace sites cost {dt:.2f}s per 100k"
    assert tracing.tracer().dump_traces()["num_spans"] == 0


def test_stamp_propagates_active_context_and_assembles():
    t = tracing.tracer()
    with t.start_span("root") as root:
        req = tracing.stamp({"cmd": "put_shard"})
        assert req["tctx"] == [root.trace_id, root.span_id]
    # remote side: a linked child from the carried context
    with tracing.linked_span("remote.op", req["tctx"], osd=1):
        pass
    trees = assemble(t.dump())
    tree = trees[root.trace_id]
    assert tree["spans"] == 2
    assert tree["roots"][0]["name"] == "root"
    assert tree["roots"][0]["children"][0]["name"] == "remote.op"
    # no active span + disarmed-like absence: stamp leaves untouched
    clean = tracing.stamp({"cmd": "get_shard"})
    assert "tctx" not in clean


def test_assemble_surfaces_orphan_spans_as_roots():
    """A span whose parent never arrived (buffer churn on one
    daemon) must surface as an extra root, not vanish — a partial
    trace is still evidence."""
    spans = [
        {"trace_id": 9, "span_id": 1, "parent_id": None,
         "name": "a", "service": "client", "ts": 1.0,
         "duration_s": 0.5, "tags": {}},
        {"trace_id": 9, "span_id": 2, "parent_id": 777,
         "name": "orphan", "service": "osd.1", "ts": 1.1,
         "duration_s": 0.1, "tags": {}},
    ]
    tree = assemble(spans)[9]
    assert tree["spans"] == 2
    assert {r["name"] for r in tree["roots"]} == {"a", "orphan"}
    assert tree["services"] == ["client", "osd.1"]


# ----------------------------------------------- cluster stats core ---

def test_io_rates_from_counter_deltas():
    cs = ClusterStats()
    t0 = time.time() - 2.0
    cs.ingest("osd.0", {"ts": t0, "perf": {"osd.io": {
        "wr_ops": ("counter", 10), "wr_bytes": ("counter", 1000),
        "pool.1.wr_bytes": ("counter", 1000)}}})
    cs.ingest("osd.0", {"ts": t0 + 2.0, "perf": {"osd.io": {
        "wr_ops": ("counter", 30), "wr_bytes": ("counter", 5000),
        "pool.1.wr_bytes": ("counter", 5000)}}})
    io = cs.io_rates()
    assert io["cluster"]["wr_ops"] == pytest.approx(10.0)
    assert io["cluster"]["wr_bytes"] == pytest.approx(2000.0)
    assert io["pools"][1]["wr_bytes"] == pytest.approx(2000.0)
    assert io["daemons"]["osd.0"]["wr_ops"] == pytest.approx(10.0)


def test_cluster_stats_merges_and_renders_per_daemon_labels():
    cs = ClusterStats()
    now = time.time()
    total = 0
    for i in range(3):
        h = PerfHistogram()
        for j in range(100 * (i + 1)):
            h.record(1e-4 * (j + 1))
        total += h.count
        cs.ingest(f"osd.{i}", {
            "ts": now,
            "perf": {"op_tracker": {
                "stage_osd_to_device_s": ("histogram", h.dump())}},
            "util": {"bytes": 1 << 20, "total_bytes": 4 << 20,
                     "objects": 5,
                     "pools": {1: {"objects": 5, "bytes": 999}}}})
    qq = cs.merged_quantiles()
    fam = qq["op_tracker.stage_osd_to_device_s"]
    assert fam["count"] == total
    assert fam["p50"] is not None and fam["p999"] >= fam["p50"]
    rows = cs.osd_df()
    assert len(rows) == 3
    assert rows[0]["utilization"] == pytest.approx(0.25)
    df = cs.df()
    assert df["pools"][1]["objects"] == 15
    text = cs.render_prometheus()
    for i in range(3):
        assert f'ceph_daemon="osd.{i}"' in text
    assert "# TYPE ceph_cluster_op_tracker_stage_osd_to_device_s " \
        "histogram" in text
    assert 'quantile="0.99"' in text
    assert "ceph_osd_utilization" in text


def test_stale_reporters_age_out():
    cs = ClusterStats(stale_s=0.05)
    cs.ingest("osd.9", {"ts": time.time() - 10.0, "perf": {}})
    assert cs.daemons() == []
    cs.ingest("osd.8", {"ts": time.time(), "perf": {}})
    assert cs.daemons() == ["osd.8"]


# --------------------------------------------- sim-tier auto-sample ---

def _make_sim():
    from ceph_tpu.cluster.monitor import Monitor
    from ceph_tpu.cluster.objecter import Objecter
    from ceph_tpu.cluster.osdmap import (OSDMap, PGPool,
                                         POOL_REPLICATED)
    from ceph_tpu.cluster.simulator import ClusterSim
    from ceph_tpu.placement.builder import build_flat_cluster
    from ceph_tpu.placement.crush_map import (
        RULE_CHOOSELEAF_FIRSTN, RULE_EMIT, RULE_TAKE, Rule)
    cmap, root = build_flat_cluster(n_hosts=4, osds_per_host=2,
                                    seed=3)
    cmap.add_rule(Rule(steps=[(RULE_TAKE, root, 0),
                              (RULE_CHOOSELEAF_FIRSTN, 0, 1),
                              (RULE_EMIT, 0, 0)]))
    om = OSDMap(cmap)
    om.mark_all_in_up()
    om.add_pool(PGPool(id=1, name="rep", type=POOL_REPLICATED,
                       size=3, pg_num=16, crush_rule=0))
    sim = ClusterSim(om)
    mon = Monitor(sim.osdmap)
    return sim, mon, Objecter(sim, mon)


def test_sim_slow_op_auto_samples_linked_trace():
    """A slow sim-tier op pins its trace; assembly yields one tree
    with >= 5 linked stages (objecter root, queue, dispatch, device)
    and the slow ring's record maps op id -> trace id."""
    sim, mon, client = _make_sim()
    config().set("op_tracker_complaint_time", 0.01)
    for svc in sim.services:
        svc.inject_execute_delay = 0.02
    try:
        client.put(1, "laggard", b"l" * 2048)
    finally:
        for svc in sim.services:
            svc.inject_execute_delay = 0.0
        config().clear("op_tracker_complaint_time")
    rec = next(op for op in tracker().dump_historic_slow_ops()["ops"]
               if op.get("obj") == "laggard")
    tid = rec["trace_id"]
    assert tid in tracing.tracer().sampled_traces()
    tree = assemble(tracing.tracer().spans_for(tid))[tid]
    assert tree["spans"] >= 5
    names = set()

    def walk(n):
        names.add(n["name"])
        for c in n["children"]:
            walk(c)
    for r in tree["roots"]:
        walk(r)
    assert {"objecter.op", "osd.queue", "osd.dispatch",
            "device.dispatch"} <= names


# ------------------------------------------------- process tier ------

@pytest.mark.smoke
def test_slow_wire_op_assembles_cross_process_trace(tmp_path,
                                                    monkeypatch):
    """Acceptance: an op exceeding op_tracker_complaint_time on the
    wire tier produces ONE assembled cross-daemon trace with >= 5
    linked stages spanning >= 3 processes (client, primary OSD,
    replica OSDs), retrievable by op id via `ceph trace`; and the
    mon's cluster stats / Prometheus scrape agree with the
    per-daemon asok sources they aggregate."""
    from ceph_tpu.common.admin import admin_request
    from ceph_tpu.tools import ceph_cli
    from ceph_tpu.tools.vstart import Vstart, build_cluster_dir

    # daemons inherit slow-everything complaint time + tracing on
    monkeypatch.setenv("CEPH_TPU_OP_TRACKER_COMPLAINT_TIME", "0")
    d = str(tmp_path / "cluster")
    build_cluster_dir(d, n_osds=3, osds_per_host=1, fsync=False)
    v = Vstart(d)
    v.start(3, hb_interval=0.25)
    config().set("op_tracker_complaint_time", 0.0)
    try:
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d)
        rc.serve_admin()              # objecter.asok for `ceph trace`
        assert rc.put(1, "traced-obj", b"t" * 2048) >= 2
        rec = next(
            op for op in tracker().dump_historic_slow_ops()["ops"]
            if op.get("obj") == "traced-obj")
        tid = rec["trace_id"]
        assert tid in tracing.tracer().sampled_traces()

        # ---- collect spans from every process and assemble
        spans = list(tracing.tracer().dump_traces()["spans"])
        for i in range(3):
            r = admin_request(os.path.join(d, f"osd.{i}.asok"),
                              {"prefix": "dump_traces"})
            spans.extend(r["result"]["spans"])
        tree = assemble(s for s in spans
                        if s["trace_id"] == tid).get(tid)
        assert tree is not None, "no spans assembled for the slow op"
        assert tree["spans"] >= 5, tree
        # >= 3 PROCESSES: the client plus at least two OSD daemons
        services = set(tree["services"])
        assert "client" in services
        assert len([s for s in services
                    if s.startswith("osd.")]) >= 2, services
        # linked stages include the wire submit and daemon-side op +
        # dispatch stages
        flat = []

        def walk(n):
            flat.append(n["name"])
            for c in n["children"]:
                walk(c)
        for r_ in tree["roots"]:
            walk(r_)
        assert "objecter.wire_submit" in flat
        assert "osd.op" in flat and "osd.dispatch" in flat

        # ---- retrievable by op id over the admin sockets
        import io
        buf = io.StringIO()
        rcode = ceph_cli.main(
            ["--dir", d, "trace", str(rec["op_id"])], out=buf)
        assert rcode == 0, buf.getvalue()
        assert "osd." in buf.getvalue()
        assert f"{tid:x}" in buf.getvalue()

        # ---- cluster stats agree with the per-daemon asok sources
        deadline = time.monotonic() + 30
        fam_name = None
        while time.monotonic() < deadline:
            cs = rc.mon_call({"cmd": "cluster_stats",
                              "metrics": True})
            qq = cs.get("quantiles") or {}
            candidates = {k: v for k, v in qq.items()
                          if k.startswith("op_tracker.") and
                          v.get("count")}
            if candidates:
                fam_name, fam = sorted(candidates.items())[0]
                group, key = fam_name.rsplit(".", 1)
                src_count = 0
                for i in range(3):
                    p = admin_request(
                        os.path.join(d, f"osd.{i}.asok"),
                        {"prefix": "perf dump"})["result"]
                    src_count += ((p.get(group) or {})
                                  .get(key) or {}).get("count", 0)
                if src_count == fam["count"] and src_count > 0:
                    break
            time.sleep(0.3)
        else:
            raise AssertionError(
                f"cluster stats never agreed with asok sources "
                f"({fam_name})")
        assert fam["p50"] is not None and fam["p999"] is not None
        assert fam["p999"] >= fam["p50"]
        # the single cluster-wide scrape carries per-daemon labels
        # and merged families
        text = cs["prometheus"]
        assert 'ceph_daemon="osd.0"' in text
        assert "ceph_cluster_" in text and 'quantile="0.999"' in text
        # per-OSD utilization present and bounded
        rows = cs["osd_df"]
        assert len(rows) == 3
        assert all(0.0 <= r["utilization"] <= 1.0 for r in rows)
        # operator surfaces: `ceph osd df` and the `ceph -s` io line
        buf = io.StringIO()
        assert ceph_cli.main(["--dir", d, "osd", "df"],
                             out=buf) == 0
        assert "osd.0" in buf.getvalue()
        buf = io.StringIO()
        assert ceph_cli.main(["--dir", d, "status"], out=buf) == 0
        assert "io:" in buf.getvalue()
        rc.close()
    finally:
        # drop the env layer BEFORE clearing: clear() notifies
        # observers with the EFFECTIVE value, and with the env var
        # still set that would re-pin the op-tracker's cached
        # complaint time at 0 for the rest of the session
        monkeypatch.delenv("CEPH_TPU_OP_TRACKER_COMPLAINT_TIME",
                           raising=False)
        config().clear("op_tracker_complaint_time")
        v.stop()
