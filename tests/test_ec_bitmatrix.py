"""RAID-6 bitmatrix techniques: liberation / blaum_roth / liber8tion.

Reference surface: src/erasure-code/jerasure/ErasureCodeJerasure.h:192,
:229, :240 (bitmatrix techniques running XOR schedules over packet
regions).  Constructions re-derived in ec/bitmatrix_raid6.py; these
tests pin the MDS property over every 1- and 2-erasure pattern, the
liberation density bound, profile validation, and host/device path
agreement.
"""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec import instance
from ceph_tpu.ec.bitmatrix_raid6 import (blaum_roth_bitmatrix,
                                         liber8tion_bitmatrix,
                                         liberation_bitmatrix)
from ceph_tpu.ec.interface import ErasureCodeError

CONFIGS = [("liberation", 5, 7), ("blaum_roth", 6, 6),
           ("liber8tion", 8, 8)]


def _codec(tech, k, w):
    return instance().factory(
        "jerasure", {"technique": tech, "k": str(k), "m": "2",
                     "w": str(w)})


@pytest.mark.parametrize("tech,k,w", CONFIGS,
                         ids=[f"{t}-k{k}w{w}" for t, k, w in CONFIGS])
def test_all_erasure_patterns(tech, k, w):
    codec = _codec(tech, k, w)
    rng = np.random.default_rng(42)
    chunk = codec.get_chunk_size(1 << 13)
    assert chunk % (w * 4) == 0
    data = rng.integers(0, 256, size=(k, chunk), dtype=np.uint8)
    parity = codec.encode_chunks(data)
    assert parity.shape == (2, chunk)
    full = np.concatenate([data, parity], axis=0)
    n = k + 2
    for r in (1, 2):
        for er in itertools.combinations(range(n), r):
            avail = [c for c in range(n) if c not in er]
            out = codec.decode_chunks(avail, full[avail], list(er))
            assert np.array_equal(out, full[list(er)]), er


@pytest.mark.parametrize("tech,k,w", CONFIGS,
                         ids=[f"{t}-k{k}w{w}" for t, k, w in CONFIGS])
def test_device_batch_matches_host(tech, k, w):
    codec = _codec(tech, k, w)
    rng = np.random.default_rng(7)
    chunk = codec.get_chunk_size(1 << 12)
    data = rng.integers(0, 256, size=(3, k, chunk), dtype=np.uint8)
    batched = np.asarray(codec.encode_chunks_batch(data))
    for s in range(3):
        assert np.array_equal(batched[s], codec.encode_chunks(data[s]))
    # batched decode path for one signature
    parity = batched
    full = np.concatenate([data, parity], axis=1)
    er = [0, k]                   # one data + one parity chunk
    avail = [c for c in range(k + 2) if c not in er]
    dec = np.asarray(codec.decode_chunks_batch(avail, full[:, avail], er))
    assert np.array_equal(dec, full[:, er])


def test_liberation_density_is_minimal():
    """Plank's bound: a minimum-density RAID-6 bitmatrix Q has
    k*w + k - 1 ones; the searched liberation matrices meet it."""
    for k, w in [(3, 5), (5, 7), (7, 7), (11, 11)]:
        bm = liberation_bitmatrix(k, w)
        assert int(bm[w:].sum()) == k * w + k - 1, (k, w)


def test_blaum_roth_is_ring_powers():
    bm = blaum_roth_bitmatrix(4, 4)
    w = 4
    x0 = bm[w:, :w]
    assert np.array_equal(x0, np.eye(w, dtype=np.uint8))
    # X_1 = companion of 1+x+...+x^4; column w-1 all ones
    x1 = bm[w:, w:2 * w]
    assert x1[:, w - 1].all()


def test_profile_validation():
    with pytest.raises(ErasureCodeError):
        _codec("liberation", 4, 8)        # w must be prime
    with pytest.raises(ErasureCodeError):
        _codec("blaum_roth", 4, 7)        # w+1 must be prime
    with pytest.raises(ErasureCodeError):
        _codec("liber8tion", 9, 8)        # k <= 8
    with pytest.raises(ErasureCodeError):
        instance().factory("jerasure", {"technique": "liberation",
                                        "k": "4", "m": "3", "w": "7"})


def test_liber8tion_deterministic():
    a = liber8tion_bitmatrix(8, 8)
    b = liber8tion_bitmatrix(8, 8)
    assert np.array_equal(a, b)
    assert a.shape == (16, 64)
