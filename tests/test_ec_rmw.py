"""EC read-modify-write: partial-stripe overwrites, extent cache,
degraded overwrites (reference: ECBackend start_rmw / ECTransaction /
ExtentCache — src/osd/ECBackend.cc:1876, src/osd/ExtentCache.h)."""
import numpy as np
import pytest

from ceph_tpu.cluster.ec_rmw import ExtentCache, RmwPipeline, StripeInfo
from tests.test_simulator import make_sim

EC_POOL = 2


def test_stripe_info_math():
    si = StripeInfo(k=4, chunk_size=1024)
    assert si.stripe_width == 4096
    assert si.stripe_count(0) == 0
    assert si.stripe_count(1) == 1
    assert si.stripe_count(4096) == 1
    assert si.stripe_count(4097) == 2
    assert si.range_stripes(0, 4096) == (0, 0)
    assert si.range_stripes(4095, 2) == (0, 1)
    assert si.range_stripes(8192, 1) == (2, 2)
    with pytest.raises(ValueError):
        si.range_stripes(0, 0)


def oracle(store: bytearray, offset: int, data: bytes) -> None:
    if len(store) < offset + len(data):
        store.extend(b"\0" * (offset + len(data) - len(store)))
    store[offset:offset + len(data)] = data


def test_overwrite_roundtrips_random():
    """Random overwrite sequences == a plain byte-buffer oracle."""
    sim = make_sim()
    rng = np.random.default_rng(5)
    name = "rmw-1"
    first = rng.integers(0, 256, size=30000).astype(np.uint8).tobytes()
    sim.put(EC_POOL, name, first)
    store = bytearray(first)
    for _ in range(12):
        off = int(rng.integers(0, 40000))
        ln = int(rng.integers(1, 9000))
        blob = rng.integers(0, 256, size=ln).astype(np.uint8).tobytes()
        sim.write(EC_POOL, name, off, blob)
        oracle(store, off, blob)
        assert sim.get(EC_POOL, name) == bytes(store)


def test_overwrite_sub_chunk():
    """A few-byte overwrite inside one stripe only touches that stripe."""
    sim = make_sim()
    name = "rmw-2"
    pool = sim.osdmap.pools[EC_POOL]
    si = sim._sinfo(pool)
    data = bytes(range(256)) * (3 * si.stripe_width // 256)
    sim.put(EC_POOL, name, data)
    store = bytearray(data)
    sim.write(EC_POOL, name, si.stripe_width + 7, b"XYZZY")
    oracle(store, si.stripe_width + 7, b"XYZZY")
    assert sim.get(EC_POOL, name) == bytes(store)


def test_overwrite_extends_object():
    sim = make_sim()
    name = "rmw-3"
    sim.put(EC_POOL, name, b"hello world")
    sim.write(EC_POOL, name, 100_000, b"tail")
    got = sim.get(EC_POOL, name)
    assert got[:11] == b"hello world"
    assert got[100_000:] == b"tail"
    assert set(got[11:100_000]) <= {0}


def test_overwrite_write_before_put():
    sim = make_sim()
    sim.write(EC_POOL, "fresh", 10, b"abc")
    got = sim.get(EC_POOL, "fresh")
    assert got == b"\0" * 10 + b"abc"


def test_degraded_overwrite():
    """Overwrite with shards missing: old stripes decode, write lands."""
    sim = make_sim()
    rng = np.random.default_rng(9)
    name = "rmw-4"
    pool = sim.osdmap.pools[EC_POOL]
    si = sim._sinfo(pool)
    data = rng.integers(0, 256, size=2 * si.stripe_width + 100) \
        .astype(np.uint8).tobytes()
    placed = sim.put(EC_POOL, name, data)
    store = bytearray(data)
    # kill two shard holders (m=2 -> still recoverable)
    sim.kill_osd(placed[0])
    sim.kill_osd(placed[3])
    sim.extent_cache = ExtentCache()          # drop cached stripes
    sim._rmw.clear()
    blob = rng.integers(0, 256, size=200).astype(np.uint8).tobytes()
    off = si.stripe_width - 100               # spans stripes 0-1
    sim.write(EC_POOL, name, off, blob)
    oracle(store, off, blob)
    assert sim.get(EC_POOL, name) == bytes(store)


def test_extent_cache_skips_reread():
    sim = make_sim()
    rng = np.random.default_rng(11)
    name = "rmw-5"
    pool = sim.osdmap.pools[EC_POOL]
    si = sim._sinfo(pool)
    data = rng.integers(0, 256, size=2 * si.stripe_width) \
        .astype(np.uint8).tobytes()
    sim.put(EC_POOL, name, data)
    store = bytearray(data)
    h0 = sim.extent_cache.hits
    for i in range(4):   # repeated partial writes to the same stripe
        blob = bytes([i]) * 16
        sim.write(EC_POOL, name, 32 + i, blob)
        oracle(store, 32 + i, blob)
    assert sim.extent_cache.hits > h0
    assert sim.get(EC_POOL, name) == bytes(store)


def test_replicated_write_splice():
    sim = make_sim()
    sim.put(1, "r1", b"0123456789")
    sim.write(1, "r1", 3, b"abc")
    assert sim.get(1, "r1") == b"012abc6789"


def test_rmw_batched_encode_single_dispatch():
    """A many-stripe overwrite encodes in one batched call."""
    from ceph_tpu.common import perf
    sim = make_sim()
    rng = np.random.default_rng(13)
    name = "rmw-6"
    pool = sim.osdmap.pools[EC_POOL]
    si = sim._sinfo(pool)
    sim.put(EC_POOL, name, b"x" * (8 * si.stripe_width))
    pc = perf("ec.jax")
    before = pc.get("encode_dispatches") or 0
    blob = rng.integers(0, 256, size=6 * si.stripe_width) \
        .astype(np.uint8).tobytes()
    sim.write(EC_POOL, name, si.stripe_width + 10, blob)
    after = pc.get("encode_dispatches") or 0
    assert after - before == 1      # six stripes, one device encode
