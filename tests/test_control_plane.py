"""Control plane: Paxos-like consensus, mon map service + config db +
health, heartbeat failure detection, Objecter retry-on-map-change.

Reference surfaces: src/mon/Paxos.{h,cc}, OSDMonitor (map publication,
prepare_failure), ConfigMonitor, HealthMonitor, OSD heartbeats
(OSD.cc:5327), Objecter (_calc_target/resend, Objecter.cc:2688)."""
import numpy as np
import pytest

from ceph_tpu.cluster.heartbeat import HeartbeatConfig, HeartbeatMonitor
from ceph_tpu.cluster.monitor import Monitor, QuorumModel
from ceph_tpu.cluster.objecter import Objecter, TooManyRetries
from ceph_tpu.cluster.osdmap import Incremental
from tests.test_simulator import make_sim


# --------------------------------------------------------------- paxos ----

def test_paxos_commits_with_majority():
    p = QuorumModel(n_ranks=3)
    assert p.propose("a") and p.propose("b")
    assert p.committed == ["a", "b"]
    assert p.version == 2


def test_paxos_minority_cannot_commit():
    p = QuorumModel(n_ranks=3)
    p.reachable[1] = False
    assert p.propose("ok")              # 2/3 is still a quorum
    p.reachable[2] = False
    assert not p.propose("nope")        # 1/3 is not
    assert p.committed == ["ok"]


def test_paxos_new_leader_supersedes():
    p = QuorumModel(n_ranks=3)
    p.propose("v1")
    old_pn = p.accepted_pn[0]
    p.elect(leader=1)
    assert p.propose("v2")
    assert p.accepted_pn[0] > old_pn
    assert p.committed == ["v1", "v2"]


def test_paxos_single_rank():
    p = QuorumModel(n_ranks=1)
    assert p.propose("solo")


# ------------------------------------------------------------- monitor ----

def test_mon_map_service_incrementals():
    sim = make_sim()
    mon = Monitor(sim.osdmap)
    e0 = sim.osdmap.epoch
    inc = mon.next_incremental()
    inc.new_up[5] = False
    assert mon.commit_incremental(inc)
    inc2 = mon.next_incremental()
    inc2.new_weight[4] = 0
    assert mon.commit_incremental(inc2)
    assert sim.osdmap.epoch == e0 + 2
    got = mon.get_incrementals(e0)
    assert [i.epoch for i in got] == [e0 + 1, e0 + 2]
    assert mon.get_incrementals(e0 + 2) == []
    # consensus log recorded both commits
    assert mon.paxos.version == 2


def test_mon_config_db():
    from ceph_tpu.common import config
    sim = make_sim()
    mon = Monitor(sim.osdmap)
    assert mon.config_set("fastmap_extra_tries", 12)
    assert mon.config_get("fastmap_extra_tries") == 12
    try:
        assert config().get("fastmap_extra_tries") == 12
    finally:
        from ceph_tpu.common.options import LEVEL_FILE
        config().clear("fastmap_extra_tries", LEVEL_FILE)
    # unknown keys commit mon-side without poisoning the registry
    assert mon.config_set("osd_special_knob", "on")
    assert mon.config_get("osd_special_knob") == "on"


def test_mon_health_checks():
    sim = make_sim()
    mon = Monitor(sim.osdmap)
    assert mon.health_status(sim) == "HEALTH_OK"
    sim.kill_osd(0)
    sim.out_osd(1)
    checks = {c.code for c in mon.health(sim)}
    assert "OSD_DOWN" in checks and "OSD_OUT" in checks
    assert mon.health_status(sim) == "HEALTH_WARN"


def test_failure_reports_need_quorum():
    sim = make_sim()
    mon = Monitor(sim.osdmap, failure_reports_needed=2)
    sim.fail_osd(3)                       # dead, map doesn't know
    assert sim.osdmap.is_up(3)
    assert not mon.report_failure(3, reporter=1)   # one report: no
    assert mon.report_failure(3, reporter=2)       # second: marked down
    assert not sim.osdmap.is_up(3)
    # duplicate reporters don't double-count
    sim.fail_osd(4)
    assert not mon.report_failure(4, reporter=7)
    assert not mon.report_failure(4, reporter=7)
    assert sim.osdmap.is_up(4)


# ------------------------------------------------------------ heartbeat ---

def test_heartbeat_detects_and_marks_down():
    sim = make_sim()
    mon = Monitor(sim.osdmap, failure_reports_needed=2)
    hb = HeartbeatMonitor(sim, mon, HeartbeatConfig(n_peers=3,
                                                    grace_ticks=2))
    sim.fail_osd(6)
    down = []
    for _ in range(5):
        down += hb.tick()
    assert down == [6]
    assert not sim.osdmap.is_up(6)
    # detection recorded an epoch consumers can fetch
    assert any(6 in i.new_up and i.new_up[6] is False
               for i in mon.incrementals)


def test_heartbeat_ignores_healthy():
    sim = make_sim()
    mon = Monitor(sim.osdmap)
    hb = HeartbeatMonitor(sim, mon)
    for _ in range(4):
        assert hb.tick() == []


# ------------------------------------------------------------- objecter ---

def test_objecter_plain_io():
    sim = make_sim()
    mon = Monitor(sim.osdmap)
    client = Objecter(sim, mon)
    data = bytes(range(256)) * 40
    client.put(2, "obj", data)
    assert client.get(2, "obj") == data


def test_objecter_resends_after_map_change():
    sim = make_sim()
    mon = Monitor(sim.osdmap, failure_reports_needed=1)
    client = Objecter(sim, mon)
    data = np.random.default_rng(3).integers(0, 256, 20000) \
        .astype(np.uint8).tobytes()
    placed = client.put(2, "hot", data)
    e0 = client.osdmap.epoch
    # primary dies; mon learns via a failure report; client is stale
    victim = placed[0]
    sim.fail_osd(victim)
    mon.report_failure(victim, reporter=placed[1])
    assert client.osdmap.epoch == e0          # still stale
    got = client.get(2, "hot")                # resend path catches up
    assert got == data
    assert client.osdmap.epoch > e0
    assert (_ := client._pc.get("op_resends") or 0) >= 0


def test_objecter_gives_up_without_map_progress():
    sim = make_sim()
    mon = Monitor(sim.osdmap)
    client = Objecter(sim, mon, max_retries=3)
    client.put(2, "x", b"payload")
    pool = sim.osdmap.pools[2]
    pg = sim.object_pg(pool, "x")
    # kill the real primary but never tell the mon: the op cannot land
    real_up = sim.pg_up(pool, pg)
    sim.fail_osd(real_up[0])
    with pytest.raises(TooManyRetries):
        client.put(2, "x", b"payload2")


def test_osd_boot_reaches_clients():
    """fail -> report -> restart -> boot: every map change flows as an
    incremental, so a cached-map client keeps working end to end."""
    sim = make_sim()
    mon = Monitor(sim.osdmap, failure_reports_needed=1)
    client = Objecter(sim, mon)
    data = b"lifecycle" * 300
    placed = client.put(2, "lc", data)
    victim = placed[0]
    sim.fail_osd(victim)
    mon.report_failure(victim, reporter=placed[1])
    assert client.get(2, "lc") == data        # degraded, via catch-up
    sim.restart_osd(victim)
    assert mon.osd_boot(victim)
    assert sim.osdmap.is_up(victim)
    sim.recover_delta(2)
    assert client.get(2, "lc") == data        # post-boot, via catch-up
    assert client.osdmap.epoch == sim.osdmap.epoch


def test_boot_cancels_pending_failure_reports():
    sim = make_sim()
    mon = Monitor(sim.osdmap, failure_reports_needed=2)
    sim.fail_osd(5)
    mon.report_failure(5, reporter=1)      # 1/2 pending
    sim.restart_osd(5)
    assert mon.osd_boot(5)
    sim.fail_osd(5)
    # one NEW report must not tip a threshold of two
    assert not mon.report_failure(5, reporter=2)
    assert sim.osdmap.is_up(5)
    assert mon.report_failure(5, reporter=3)
    assert not sim.osdmap.is_up(5)
