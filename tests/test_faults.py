"""FaultRegistry + faultpoint fire sites + deterministic backoff.

The ISSUE-3 injection substrate: declaration/arming contracts, seeded
schedule determinism, the dict-miss fast path, the per-daemon
``fault_injection`` admin command, the wire frame faultpoints
(drop/truncate/bit-flip over a socketpair), the device-store EIO and
corruption points, the in-process messenger drop, and mon map churn.
"""
import socket

import numpy as np
import pytest

from ceph_tpu.common import faults
from ceph_tpu.common.admin import AdminServer
from ceph_tpu.common.backoff import ExpBackoff, TickClock
from ceph_tpu.common.faults import FaultError
from ceph_tpu.common.perf_counters import perf
from ceph_tpu.msg import wire
from ceph_tpu.msg.queue import Envelope

# scratch faultpoints for the registry unit tests (module-scope
# declares, like production fire sites)
faults.declare("test.scratch", "registry unit-test point")
faults.declare("test.sched", "schedule determinism point")
faults.declare("test.params", "params pass-through point")


@pytest.fixture(autouse=True)
def _clean_registry():
    """Armed points are process-global state: never leak one into the
    next test."""
    yield
    faults.reset()


# ------------------------------------------------------------ registry ---

def test_declare_is_idempotent_but_collision_raises():
    faults.declare("test.scratch", "registry unit-test point")  # same
    with pytest.raises(FaultError, match="different docstring"):
        faults.declare("test.scratch", "some other doc")


def test_arm_requires_declaration_and_valid_mode():
    with pytest.raises(FaultError, match="unknown faultpoint"):
        faults.arm("test.never_declared")
    with pytest.raises(FaultError, match="unknown fault mode"):
        faults.arm("test.scratch", mode="sometimes")
    with pytest.raises(FaultError, match="one_in needs"):
        faults.arm("test.scratch", mode="one_in", n=0)
    with pytest.raises(FaultError, match="match must be a dict"):
        # a stringly match (un-parsed CLI JSON) must be refused at arm
        # time, not poison every later fire with an AttributeError
        faults.arm("test.scratch", match='{"cmd": "put_shard"}')


def test_disarmed_fire_is_none_and_counts_nothing():
    before = faults.fire_counts().get("test.scratch", 0)
    for _ in range(100):
        assert faults.fire("test.scratch") is None
    assert faults.fire_counts().get("test.scratch", 0) == before


def test_always_nth_count_and_params():
    faults.arm("test.scratch", mode="always", count=2)
    assert faults.fire("test.scratch") == {}
    assert faults.fire("test.scratch") == {}
    assert faults.fire("test.scratch") is None     # count exhausted
    assert faults.fire_counts()["test.scratch"] == 2

    faults.arm("test.params", mode="nth", n=3, seconds=0.25)
    assert faults.fire("test.params") is None
    assert faults.fire("test.params") is None
    assert faults.fire("test.params") == {"seconds": 0.25}
    assert faults.fire("test.params") is None      # nth fires once


def test_one_in_schedule_is_seed_deterministic():
    def pattern(seed):
        faults.arm("test.sched", mode="one_in", n=3, seed=seed)
        out = [faults.fire("test.sched") is not None
               for _ in range(30)]
        faults.disarm("test.sched")
        return out
    a, b, c = pattern(42), pattern(42), pattern(43)
    assert a == b                         # same seed: same schedule
    assert a != c                         # decorrelated seeds
    assert any(a) and not all(a)          # it is a schedule, not a knob


def test_predicate_and_match_gate_on_context():
    fired = []
    faults.arm("test.scratch", mode="predicate",
               predicate=lambda ctx: ctx.get("cmd") == "put_shard")
    assert faults.fire("test.scratch", cmd="get_shard") is None
    assert faults.fire("test.scratch", cmd="put_shard") is not None
    faults.arm("test.scratch", mode="always",
               match={"cmd": "put_shard"})
    assert faults.fire("test.scratch", cmd="get_shard") is None
    assert faults.fire("test.scratch", cmd="put_shard") is not None
    del fired


def test_fire_counts_survive_disarm_and_export_to_perf():
    pc_before = perf("faults").get("test.scratch") or 0
    faults.arm("test.scratch", mode="always")
    faults.fire("test.scratch")
    faults.disarm("test.scratch")
    assert faults.fire_counts()["test.scratch"] >= 1
    assert (perf("faults").get("test.scratch") or 0) == pc_before + 1


def test_disarmed_fast_path_is_cheap():
    """The acceptance bound: a disarmed faultpoint must be a single
    dict-miss check.  100k disarmed fires in well under a second is a
    very generous ceiling for that shape (it measures the guard, not
    the machine)."""
    import time
    t0 = time.perf_counter()
    for _ in range(100_000):
        faults.fire("test.scratch")
    assert time.perf_counter() - t0 < 1.0


# ------------------------------------------------------ admin command ---

def test_fault_injection_admin_command_round_trip():
    srv = AdminServer()
    st = srv.handle({"prefix": "fault_injection"})["result"]
    assert "test.scratch" in st["declared"]
    r = srv.handle({"prefix": "fault_injection", "action": "arm",
                    "name": "test.scratch", "mode": "one_in",
                    "n": 1, "seed": 7})["result"]
    assert r["armed"] == "test.scratch"
    assert faults.fire("test.scratch") is not None   # n=1: every call
    st = srv.handle({"prefix": "fault_injection"})["result"]
    assert st["armed"]["test.scratch"]["fires"] >= 1
    assert st["fire_counts"]["test.scratch"] >= 1
    r = srv.handle({"prefix": "fault_injection",
                    "action": "disarm"})["result"]
    assert r["disarmed"] == "all"
    assert faults.fire("test.scratch") is None
    # bad requests come back as errors, not tracebacks
    assert "error" in srv.handle({"prefix": "fault_injection",
                                  "action": "arm",
                                  "name": "test.never_declared"})
    assert "error" in srv.handle({"prefix": "fault_injection",
                                  "action": "bogus"})


# ------------------------------------------------------ wire faults ---

def _frame_pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_wire_drop_frame_raises_before_any_byte():
    a, b = _frame_pair()
    try:
        faults.arm("wire.drop_frame", mode="nth", n=1)
        with pytest.raises(wire.WireClosed, match="dropped"):
            wire.send_frame(a, Envelope(0x10, 1, -1, b"payload"))
        # nothing hit the socket; the next frame flows normally
        wire.send_frame(a, Envelope(0x10, 2, -1, b"second"))
        env = wire.recv_frame(b)
        assert env.id == 2 and env.payload == b"second"
    finally:
        a.close()
        b.close()


def test_wire_truncate_frame_peer_sees_closed():
    a, b = _frame_pair()
    try:
        faults.arm("wire.truncate_frame", mode="nth", n=1)
        with pytest.raises(wire.WireClosed, match="truncated"):
            wire.send_frame(a, Envelope(0x10, 1, -1, b"x" * 64))
        a.close()            # connection torn down after the half-send
        with pytest.raises(wire.WireClosed):
            wire.recv_frame(b)
    finally:
        b.close()


def test_wire_flip_bit_is_rejected_never_delivered():
    a, b = _frame_pair()
    try:
        faults.arm("wire.flip_bit", mode="nth", n=1)
        wire.send_frame(a, Envelope(0x10, 1, -1, b"y" * 64))
        with pytest.raises(wire.WireError):
            wire.recv_frame(b)             # crc mismatch: rejected
        assert faults.fire_counts()["wire.flip_bit"] == 1
    finally:
        a.close()
        b.close()


def test_wire_flip_bit_rejected_in_secure_mode_too():
    a, b = _frame_pair()
    key = bytes(range(32))
    try:
        faults.arm("wire.flip_bit", mode="nth", n=1)
        wire.send_frame(a, Envelope(0x10, 1, -1, b"z" * 64),
                        session_key=key)
        with pytest.raises(wire.WireError):
            wire.recv_frame(b, session_key=key)   # MAC rejected
    finally:
        a.close()
        b.close()


# --------------------------------------------------- device faults ---

def test_device_eio_and_read_corruption_on_simosd():
    from ceph_tpu.cluster.simulator import SimOSD
    osd = SimOSD(0)
    key = (1, 0, "obj", 0)
    payload = np.frombuffer(b"intact-bytes", dtype=np.uint8)
    osd.put(key, payload)

    faults.arm("device.eio", mode="nth", n=1)
    assert osd.get(key) is None                  # injected EIO
    assert bytes(osd.get(key)) == b"intact-bytes"   # next read fine

    faults.arm("device.read_corruption", mode="nth", n=1)
    got = bytes(osd.get(key))
    assert got != b"intact-bytes" and len(got) == len(b"intact-bytes")
    # the durable bytes were never touched: only the served copy lied
    assert bytes(osd.get(key)) == b"intact-bytes"


def test_device_staging_drop_evicts_clean_entry_only():
    from ceph_tpu.cluster.device_store import DeviceShardCache, as_ref
    cache = DeviceShardCache()
    key = (1, 0, "o", 0)
    ref = as_ref(np.arange(8, dtype=np.int32))
    cache.put(key, ref, csum=123)                # clean
    faults.arm("device.staging_drop", mode="nth", n=1)
    assert cache.get(key, 123) is None           # injected eviction
    assert not cache.has(key)
    # dirty entries are the only copy: the injection must not touch them
    cache.put(key, ref, csum=None)               # dirty
    faults.arm("device.staging_drop", mode="always")
    assert cache.dirty_get(key) is not None
    assert cache.get(key, None) is not None


# ------------------------------------------------- messenger faults ---

def test_msg_drop_op_raises_and_failover_reads_survive():
    from ceph_tpu.cluster.osd_service import OSDService
    from ceph_tpu.cluster.simulator import SimOSD
    svc = OSDService(SimOSD(3))
    try:
        key = (1, 0, "m", 0)
        svc.put(key, np.frombuffer(b"abc", dtype=np.uint8))
        faults.arm("msg.drop_op", mode="nth", n=1)
        with pytest.raises(IOError, match="dropped"):
            svc.get(key)
        assert bytes(svc.get(key)) == b"abc"     # next op flows
        assert faults.fire_counts()["msg.drop_op"] == 1
    finally:
        svc.stop()


# ------------------------------------------------------- mon churn ---

def test_mon_map_churn_bumps_an_extra_epoch():
    from ceph_tpu.cluster.monitor import Monitor
    from ceph_tpu.cluster.osdmap import OSDMap
    from ceph_tpu.placement.builder import build_flat_cluster
    cmap, _root = build_flat_cluster(n_hosts=2, osds_per_host=1,
                                     seed=0)
    om = OSDMap(cmap)
    om.mark_all_in_up()
    mon = Monitor(om)
    e0 = mon.osdmap.epoch
    inc = mon.next_incremental()
    inc.new_weight[0] = 0
    assert mon.commit_incremental(inc)
    assert mon.osdmap.epoch == e0 + 1            # disarmed: one epoch

    faults.arm("mon.map_churn", mode="nth", n=1)
    inc = mon.next_incremental()
    inc.new_weight[0] = 0x10000
    assert mon.commit_incremental(inc)
    # the committed mutation PLUS the injected empty churn epoch, and
    # both ride the incremental stream subscribers consume
    assert mon.osdmap.epoch == e0 + 3
    assert len(mon.get_incrementals(e0)) == 3


# ----------------------------------------------------------- backoff ---

def test_exp_backoff_is_seed_deterministic_and_capped():
    a = ExpBackoff(base=0.05, factor=2.0, cap=0.4, jitter=0.5, seed=9,
                   sleep=lambda s: None)
    b = ExpBackoff(base=0.05, factor=2.0, cap=0.4, jitter=0.5, seed=9,
                   sleep=lambda s: None)
    da = [a.delay(i) for i in range(8)]
    db = [b.delay(i) for i in range(8)]
    assert da == db
    assert all(0 < d <= 0.4 for d in da)
    # the envelope grows until the cap bites
    assert max(da) > min(da)
    c = ExpBackoff(seed=10, sleep=lambda s: None)
    assert [c.delay(i) for i in range(8)] != da


def test_tick_clock_never_wall_sleeps():
    import time
    clk = TickClock()
    bo = ExpBackoff(base=0.5, cap=8.0, jitter=0.0, seed=0,
                    sleep=clk.sleep)
    t0 = time.perf_counter()
    for i in range(6):
        bo.sleep(i)
    assert time.perf_counter() - t0 < 0.1        # no wall time passed
    assert clk.sleeps == 6
    assert clk.now == sum(min(8.0, 0.5 * 2 ** i) for i in range(6))


def test_objecter_backoff_rides_the_tick_clock():
    from ceph_tpu.cluster.monitor import Monitor
    from ceph_tpu.cluster.objecter import Objecter, TooManyRetries
    from tests.test_simulator import make_sim
    sim = make_sim()
    try:
        mon = Monitor(sim.osdmap)
        client = Objecter(sim, mon, max_retries=4)
        client.put(2, "bk", b"payload")
        pool = sim.osdmap.pools[2]
        pg = sim.object_pg(pool, "bk")
        sim.fail_osd(sim.pg_up(pool, pg)[0])     # mon never learns
        import time
        t0 = time.perf_counter()
        with pytest.raises(TooManyRetries):
            client.put(2, "bk", b"payload2")
        # the retry loop backed off on SIM TICKS, not the wall
        assert time.perf_counter() - t0 < 2.0
        assert client.clock.sleeps >= 1
        assert client.clock.now > 0.0
    finally:
        sim.shutdown()
