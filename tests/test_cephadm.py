"""cephadm analog: declarative deploy + health-gated rolling ops
(VERDICT r4 next #7).  Reference roles: src/cephadm/cephadm
(bootstrap/apply/upgrade sequencing), src/ceph-volume (store
provisioning — played by build_cluster_dir inside deploy).
"""
import threading
import time

import numpy as np
import pytest

from ceph_tpu.tools.cephadm import CephAdm, ClusterSpec


def _spec(n_mons=1):
    return ClusterSpec(
        name="t", version="1.0", mons=n_mons,
        hosts=[{"name": f"h{i}", "osds": 2} for i in range(2)],
        pools=[{"id": 1, "name": "rep", "type": 1, "size": 3,
                "pg_num": 8, "crush_rule": 0}])


def test_spec_driven_deploy_and_status(tmp_path):
    d = str(tmp_path / "c")
    adm = CephAdm.deploy(_spec(), d)
    try:
        st = adm.status()
        assert st["health_ok"]
        assert st["n_up"] == 4
        assert st["spec"]["version"] == "1.0"
        assert set(st["versions"]) == {f"osd.{i}" for i in range(4)}
        assert all(v == "1.0" for v in st["versions"].values())
        # the spec round-trips from committed mon state
        spec = adm.spec()
        assert spec.n_osds == 4 and spec.osds_per_host == 2
    finally:
        adm.stop()


def test_rolling_upgrade_under_io(tmp_path):
    """The rolling-restart-under-IO contract: client writes/reads run
    THROUGH the whole upgrade; every daemon cycles exactly once,
    health-gated; versions flip per daemon; no acknowledged write is
    lost."""
    d = str(tmp_path / "c")
    adm = CephAdm.deploy(_spec(), d)
    stop = threading.Event()
    acked = {}
    errors = []

    def workload():
        from ceph_tpu.client.remote import RemoteCluster
        rc = None
        rng = np.random.default_rng(9)
        i = 0
        while not stop.is_set():
            if rc is None:
                try:
                    rc = RemoteCluster(d)
                except IOError:
                    # the mon itself may be mid-cycle: reconnect
                    time.sleep(0.2)
                    continue
            name = f"w{i}"
            data = rng.integers(0, 256, 2000,
                                dtype=np.uint8).tobytes()
            try:
                rc.put(1, name, data)
                acked[name] = data
            except IOError:
                pass          # unacked writes carry no promise
            except Exception as e:     # anything else is a TEST bug
                errors.append(e)
                return
            i += 1
            time.sleep(0.05)
        if rc is not None:
            rc.close()

    t = threading.Thread(target=workload)
    t.start()
    try:
        res = adm.upgrade("2.0", timeout=120)
        assert set(res["restarted"]) >= {f"osd.{i}" for i in range(4)}
    finally:
        stop.set()
        t.join(timeout=30)
    assert not errors, f"workload thread died: {errors[0]!r}"
    try:
        st = adm.status()
        assert st["health_ok"]
        assert all(v == "2.0" for v in st["versions"].values())
        assert st["spec"]["version"] == "2.0"
        # every acknowledged write survived the full rolling cycle
        from ceph_tpu.client.remote import RemoteCluster
        rc = RemoteCluster(d)
        assert len(acked) > 0, "workload never acked a write"
        for name, data in acked.items():
            assert rc.get(1, name) == data, name
        rc.close()
    finally:
        adm.stop()


def test_multi_mon_rolling_restart(tmp_path):
    """Mons cycle first and one at a time; the quorum survives every
    single-mon outage (majority stays up)."""
    d = str(tmp_path / "c3")
    adm = CephAdm.deploy(_spec(n_mons=3), d, timeout=90)
    try:
        res = adm.rolling_restart(timeout=120)
        assert [r for r in res["restarted"]
                if r.startswith("mon")] == [f"mon.{r}"
                                            for r in range(3)]
        assert adm.health_ok()
    finally:
        adm.stop()
