"""Bit-sliced GF(2) region layout + masked-XOR kernel tests.

Covers the algebra (plane layout == GF(2^8) on bit-sliced symbols), the
device kernel against the NumPy oracle (shared and per-batch masks, pad
paths), and the jax codec's layout=bitsliced encode/decode round trips.
Reference roles: jerasure packet/bitmatrix coding
(src/erasure-code/jerasure/ErasureCodeJerasure.cc:162,274).
"""
import numpy as np
import pytest

from ceph_tpu.ops import gf, gf2, xor_kernel


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(1234)


def test_layout_reshapes_roundtrip(rng):
    ch = rng.integers(0, 256, size=(5, 3, 64), dtype=np.uint8)
    pl = gf2.chunks_to_planes(ch)
    assert pl.shape == (5, 24, 8)
    back = gf2.planes_to_chunks(pl)
    assert np.array_equal(back, ch)


def test_region_xor_equals_gf_matmul_on_sliced_symbols(rng):
    """Parity planes by region XOR == GF(2^8) matmul of the bit-sliced
    symbol view — the correctness contract of the whole layout."""
    k, m, L = 6, 3, 48
    data = rng.integers(0, 256, size=(k, L), dtype=np.uint8)
    P = gf.vandermonde_parity(k, m)
    B = gf.gf8_bitmatrix(P)
    parity_chunks = gf2.planes_to_chunks(
        gf2.region_xor_matmul_np(B, gf2.chunks_to_planes(data)))
    got = gf2.bitsliced_symbols(parity_chunks)
    want = gf.gf_matmul(P, gf2.bitsliced_symbols(data))
    assert np.array_equal(got, want)


def test_device_kernel_matches_oracle(rng):
    B = gf.gf8_bitmatrix(gf.isa_cauchy_parity(8, 3))
    masks = gf2.bitmatrix_masks(B)
    pl = rng.integers(0, 256, size=(4, 64, 128), dtype=np.uint8)
    out = np.asarray(xor_kernel.xor_matmul(masks, pl))
    assert np.array_equal(out, gf2.region_xor_matmul_np(B, pl))


def test_device_kernel_unaligned_tail(rng):
    """Lane counts that don't fill a kernel tile exercise the pad path."""
    B = gf.gf8_bitmatrix(gf.vandermonde_parity(4, 2))
    masks = gf2.bitmatrix_masks(B)
    pl = rng.integers(0, 256, size=(3, 32, 52), dtype=np.uint8)
    out = np.asarray(xor_kernel.xor_matmul(masks, pl))
    assert np.array_equal(out, gf2.region_xor_matmul_np(B, pl))


def test_device_kernel_per_batch_masks(rng):
    """Each batch element combines under its OWN bit-matrix — the
    per-stripe-signature decode shape (ECBackend recovery)."""
    mats = [gf.gf8_bitmatrix(gf.vandermonde_parity(4, 2)),
            gf.gf8_bitmatrix(gf.isa_cauchy_parity(4, 2)),
            gf.gf8_bitmatrix(gf.cauchy_good_parity(4, 2))]
    masks = np.stack([gf2.bitmatrix_masks(b) for b in mats])
    pl = rng.integers(0, 256, size=(3, 32, 64), dtype=np.uint8)
    out = np.asarray(xor_kernel.xor_matmul(masks, pl))
    for i, b in enumerate(mats):
        assert np.array_equal(out[i], gf2.region_xor_matmul_np(b, pl[i]))


def test_mask_batch_mismatch_raises(rng):
    masks = np.zeros((2, 16, 32), dtype=np.int32)
    pl = np.zeros((3, 32, 64), dtype=np.uint8)
    with pytest.raises(ValueError):
        xor_kernel.xor_matmul(masks, pl)


def test_w32_domain_matches_u8(rng):
    B = gf.gf8_bitmatrix(gf.vandermonde_parity(8, 3))
    masks = gf2.bitmatrix_masks(B)
    pl = rng.integers(0, 256, size=(2, 64, 256), dtype=np.uint8)
    via_u8 = np.asarray(xor_kernel.xor_matmul(masks, pl))
    import jax.numpy as jnp
    w = xor_kernel._u8_to_i32(jnp.asarray(pl))
    via_w32 = np.asarray(xor_kernel._i32_to_u8(
        xor_kernel.xor_matmul_w32(masks, w)))
    assert np.array_equal(via_u8, via_w32)


# ---------------------------------------------------------- codec level ---

@pytest.fixture(scope="module")
def bitsliced_codec():
    from ceph_tpu.ec import instance
    return instance().factory(
        "jax", {"k": "8", "m": "3", "layout": "bitsliced"})


def test_bitsliced_encode_decode_roundtrip(bitsliced_codec, rng):
    codec = bitsliced_codec
    chunk = codec.get_chunk_size(1 << 12)
    data = rng.integers(0, 256, size=(8, chunk), dtype=np.uint8)
    parity = np.asarray(codec.encode_chunks(data))
    full = np.concatenate([data, parity], axis=0)
    for erased in ([0], [10], [1, 5], [2, 8, 10], [0, 1, 2]):
        avail = [c for c in range(11) if c not in erased][:8]
        out = np.asarray(codec.decode_chunks(avail, full[avail], erased))
        assert np.array_equal(out, full[sorted(erased)]), erased


def test_bitsliced_batched_matches_single(bitsliced_codec, rng):
    codec = bitsliced_codec
    chunk = codec.get_chunk_size(1 << 12)
    data = rng.integers(0, 256, size=(4, 8, chunk), dtype=np.uint8)
    batched = np.asarray(codec.encode_chunks_batch(data))
    for s in range(4):
        single = np.asarray(codec.encode_chunks(data[s]))
        assert np.array_equal(batched[s], single)


def test_bitsliced_differs_from_bytes_layout_but_same_code(rng):
    """Parity bytes differ between layouts (like reed_sol_van vs the
    bitmatrix techniques in jerasure) while both remain MDS over their
    own layout."""
    from ceph_tpu.ec import instance
    b = instance().factory("jax", {"k": "4", "m": "2"})
    s = instance().factory("jax", {"k": "4", "m": "2",
                                   "layout": "bitsliced"})
    chunk = b.get_chunk_size(1 << 10)
    data = rng.integers(0, 256, size=(4, chunk), dtype=np.uint8)
    pb = np.asarray(b.encode_chunks(data))
    ps = np.asarray(s.encode_chunks(data))
    assert not np.array_equal(pb, ps)


def test_bitsliced_profile_surface():
    from ceph_tpu.ec import instance
    codec = instance().factory(
        "jax", {"k": "8", "m": "3", "layout": "bitsliced"})
    assert codec.get_profile()["layout"] == "bitsliced"
    from ceph_tpu.ec.interface import ErasureCodeError
    with pytest.raises(ErasureCodeError):
        instance().factory("jax", {"k": "8", "m": "3", "layout": "bogus"})
